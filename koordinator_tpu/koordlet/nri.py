"""NRI-mode hook delivery: the runtime-initiated event subscription path.

Reference: ``pkg/koordlet/runtimehooks/nri/server.go`` — koordlet runs as
an NRI plugin: it DIALS the runtime's NRI socket, registers a
subscription (plugin name/index + event set), and from then on the
RUNTIME calls the plugin over that same connection (reverse RPC over
ttrpc): ``Synchronize`` replays existing pods/containers,
``CreateContainer`` (server.go:165) returns a ContainerAdjustment the
runtime applies, ``UpdateContainer``/``RemoveContainer`` follow the
container lifecycle.  This is the modern delivery mode beside the CRI
proxy (runtimeproxy_server.py) and the standalone reconciler
(runtimehooks.Reconciler).

This module reproduces that structure over the repo's framed-JSON UDS
transport (runtimeproxy_server send_frame/recv_frame standing in for
ttrpc):

* ``NriPlugin`` — koordlet side: dials, registers, then serves runtime
  events from the SAME connection, running the shared ``HookRegistry``
  and replying ContainerAdjustment-style documents.
* ``NriRuntime`` — runtime side (containerd's role; used by tests and
  the e2e smoke): owns the socket, accepts one plugin registration,
  emits lifecycle events, and applies returned adjustments to cgroup
  parameters via ``apply_adjustment``.

All three delivery modes feed the SAME registry, so a container created
through NRI mode gets byte-identical cgroup mutations to one handled by
the reconciler (tests/test_nri.py asserts it).
"""

from __future__ import annotations

import socket
import threading
from typing import Callable, Dict, List, Optional

from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdate,
    ResourceUpdateExecutor,
)
from koordinator_tpu.koordlet.runtimehooks import (
    ContainerContext,
    HookRegistry,
    POST_STOP_POD_SANDBOX,
    PRE_CREATE_CONTAINER,
    PRE_UPDATE_CONTAINER,
)
from koordinator_tpu.runtimeproxy_server import recv_frame, send_frame

# event names mirror the NRI stub callbacks the reference subscribes to
# (nri/server.go Subscribe mask)
EVENT_RUN_POD_SANDBOX = "RunPodSandbox"
EVENT_STOP_POD_SANDBOX = "StopPodSandbox"
EVENT_CREATE_CONTAINER = "CreateContainer"
EVENT_UPDATE_CONTAINER = "UpdateContainer"
EVENT_REMOVE_CONTAINER = "RemoveContainer"
EVENT_SYNCHRONIZE = "Synchronize"

DEFAULT_EVENTS = (
    EVENT_RUN_POD_SANDBOX,
    EVENT_STOP_POD_SANDBOX,
    EVENT_CREATE_CONTAINER,
    EVENT_UPDATE_CONTAINER,
    EVENT_REMOVE_CONTAINER,
)


def _adjustment_from_ctx(ctx: ContainerContext) -> Dict:
    """ContainerAdjustment-style reply document (the shape of NRI's
    api.ContainerAdjustment: linux resources + env), carrying only the
    fields hooks actually set."""
    linux: Dict = {}
    cpu: Dict = {}
    if ctx.cfs_quota_us is not None:
        cpu["quota"] = ctx.cfs_quota_us
    if ctx.cpu_shares is not None:
        cpu["shares"] = ctx.cpu_shares
    if ctx.cpuset_cpus is not None:
        cpu["cpus"] = ctx.cpuset_cpus
    if cpu:
        linux["cpu"] = cpu
    if ctx.memory_limit_bytes is not None:
        linux["memory"] = {"limit": ctx.memory_limit_bytes}
    if ctx.bvt_warp_ns is not None:
        # koord-specific cgroup knob rides the adjustment like the
        # reference's bvt writes ride its protocol objects
        linux["bvt_warp_ns"] = ctx.bvt_warp_ns
    out: Dict = {}
    if linux:
        out["linux"] = {"resources": linux}
    if ctx.env:
        out["env"] = [{"key": k, "value": v} for k, v in ctx.env.items()]
    return out


def apply_adjustment(
    adjustment: Dict,
    cgroup_dir: str,
    executor: ResourceUpdateExecutor,
    now: float = 0.0,
) -> int:
    """Runtime-side application of a ContainerAdjustment to cgroup
    parameters (what containerd does with the NRI reply).  Uses the same
    ResourceUpdate names as the reconciler so the two delivery modes are
    directly comparable."""
    res = (adjustment.get("linux") or {}).get("resources") or {}
    cpu = res.get("cpu") or {}
    updates: List[ResourceUpdate] = []
    if "quota" in cpu:
        updates.append(ResourceUpdate("cpu.cfs_quota", cgroup_dir, str(cpu["quota"])))
    if "shares" in cpu:
        updates.append(ResourceUpdate("cpu.shares", cgroup_dir, str(cpu["shares"])))
    if "bvt_warp_ns" in res:
        updates.append(
            ResourceUpdate("cpu.bvt_warp_ns", cgroup_dir, str(res["bvt_warp_ns"]))
        )
    if "cpus" in cpu:
        updates.append(ResourceUpdate("cpuset.cpus", cgroup_dir, cpu["cpus"]))
    if "memory" in res and "limit" in res["memory"]:
        updates.append(
            ResourceUpdate("memory.limit", cgroup_dir, str(res["memory"]["limit"]))
        )
    return executor.update_batch(updates, now)


class NriPlugin:
    """koordlet as an NRI plugin: dial, register, serve runtime events
    from the same connection (reference nri/server.go)."""

    def __init__(
        self,
        socket_path: str,
        registry: HookRegistry,
        plugin_name: str = "koordlet",
        plugin_index: str = "00",
        events: tuple = DEFAULT_EVENTS,
        register_timeout: float = 10.0,
    ):
        self.registry = registry
        self.plugin_name = plugin_name
        self.plugin_index = plugin_index
        self.events = tuple(events)
        self.pods: Dict[str, Dict] = {}  # pod uid -> sandbox doc
        self._conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # bounded registration: connect() can succeed via the listen
        # backlog while nothing is accepting — an unbounded recv here
        # would hang the whole constructor (and the koordlet daemon)
        self._conn.settimeout(register_timeout)
        try:
            self._conn.connect(socket_path)
            send_frame(
                self._conn,
                {
                    "type": "register",
                    "plugin_name": plugin_name,
                    "plugin_index": plugin_index,
                    "events": list(self.events),
                },
            )
            ack = recv_frame(self._conn)
        except socket.timeout as exc:
            self._conn.close()
            raise RuntimeError(
                f"NRI registration timed out after {register_timeout}s"
            ) from exc
        except OSError:
            self._conn.close()
            raise
        if not ack or not ack.get("ok"):
            self._conn.close()
            raise RuntimeError(f"NRI registration rejected: {ack!r}")
        self._conn.settimeout(None)  # event loop blocks until close()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            self._conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._conn.close()

    # -- event loop (runtime -> plugin reverse RPC) --
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                doc = recv_frame(self._conn)
            except OSError:
                return  # close() raced the blocking recv (EBADF/shutdown)
            if doc is None:
                return
            try:
                reply = self._dispatch(doc)
            except Exception as exc:  # surfaced to the runtime, not lost
                reply = {"error": str(exc)}
            try:
                send_frame(self._conn, reply)
            except OSError:
                return  # runtime dropped the connection mid-reply

    def _ctx_for(self, pod_uid: str, container: Dict) -> ContainerContext:
        pod = self.pods.get(pod_uid, {})
        return ContainerContext(
            pod_name=pod.get("name", ""),
            pod_uid=pod_uid,
            container_name=container.get("name", ""),
            qos=pod.get("labels", {}).get("koordinator.sh/qosClass", ""),
            priority_class=pod.get("priority_class", ""),
            pod_annotations=pod.get("annotations", {}),
            pod_labels=pod.get("labels", {}),
            requests=pod.get("requests", {}),
            limits=pod.get("limits", {}),
            cgroup_dir=container.get("cgroup_dir", ""),
        )

    def _dispatch(self, doc: Dict) -> Dict:
        event = doc.get("event", "")
        if event not in self.events and event != EVENT_SYNCHRONIZE:
            return {}
        if event == EVENT_RUN_POD_SANDBOX:
            pod = doc.get("pod", {})
            self.pods[pod.get("uid", "")] = pod
            return {}
        if event == EVENT_STOP_POD_SANDBOX:
            pod = doc.get("pod", {})
            ctx = self._ctx_for(pod.get("uid", ""), {})
            self.registry.run(POST_STOP_POD_SANDBOX, ctx)
            self.pods.pop(pod.get("uid", ""), None)
            return {}
        if event == EVENT_CREATE_CONTAINER:
            ctx = self._ctx_for(
                doc.get("pod", {}).get("uid", ""), doc.get("container", {})
            )
            self.registry.run(PRE_CREATE_CONTAINER, ctx)
            return {"adjustment": _adjustment_from_ctx(ctx)}
        if event == EVENT_UPDATE_CONTAINER:
            ctx = self._ctx_for(
                doc.get("pod", {}).get("uid", ""), doc.get("container", {})
            )
            self.registry.run(PRE_UPDATE_CONTAINER, ctx)
            return {"update": _adjustment_from_ctx(ctx)}
        if event == EVENT_REMOVE_CONTAINER:
            return {}
        if event == EVENT_SYNCHRONIZE:
            # replay of existing state on (re)connect: rebuild the pod
            # store and return updates for running containers
            # (reference Synchronize returns []*ContainerUpdate)
            updates = []
            for pod in doc.get("pods", []):
                self.pods[pod.get("uid", "")] = pod
            for c in doc.get("containers", []):
                ctx = self._ctx_for(c.get("pod_uid", ""), c)
                self.registry.run(PRE_UPDATE_CONTAINER, ctx)
                adj = _adjustment_from_ctx(ctx)
                if adj:
                    updates.append({"container": c.get("name", ""), "update": adj})
            return {"updates": updates}
        return {}


class NriRuntime:
    """The runtime's side of the NRI socket (containerd's role): owns the
    listener, accepts one plugin registration, emits lifecycle events and
    returns the plugin's adjustments.  Production containerd speaks real
    NRI; this server exists for tests, the e2e smoke, and any
    CRI-implementation that wants to drive the plugin directly."""

    def __init__(self, socket_path: str):
        import os

        self.path = socket_path
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        os.makedirs(os.path.dirname(socket_path) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(socket_path)
        self._sock.listen(2)
        self._conn: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self.plugin: Optional[Dict] = None

    def accept_plugin(self, timeout: float = 5.0) -> Dict:
        self._sock.settimeout(timeout)
        conn, _ = self._sock.accept()
        reg = recv_frame(conn)
        if not reg or reg.get("type") != "register":
            send_frame(conn, {"ok": False, "error": "expected registration"})
            conn.close()
            raise RuntimeError(f"bad NRI registration: {reg!r}")
        send_frame(conn, {"ok": True})
        self._conn = conn
        self.plugin = reg
        return reg

    def event(self, doc: Dict) -> Dict:
        """Send one lifecycle event; returns the plugin's reply.  Serialized
        under a lock: NRI replies are matched by order on the stream."""
        with self._lock:
            assert self._conn is not None, "no plugin registered"
            send_frame(self._conn, doc)
            reply = recv_frame(self._conn)
            if reply is None:
                raise RuntimeError("NRI plugin connection closed")
            if "error" in reply:
                raise RuntimeError(f"NRI plugin error: {reply['error']}")
            return reply

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
        self._sock.close()
        import os

        if os.path.exists(self.path):
            os.unlink(self.path)
