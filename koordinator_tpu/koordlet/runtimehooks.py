"""Runtime hooks: mutate container resources at runtime events.

Reference: ``pkg/koordlet/runtimehooks`` — hook plugins registered by stage
(``hooks/hooks.go:44 Register``) mutate a container protocol object at
PreRunPodSandbox / PreCreateContainer / PreUpdateContainerResources, and
three delivery modes carry them: NRI (``nri/server.go:165``), the
runtime-proxy gRPC server (``proxyserver/``), and a standalone reconciler
polling cgroups (``reconciler/reconciler.go``).

Plugins here: groupidentity (bvt by QoS), cpuset (from scheduler
annotation), batchresource (cfs quota from batch resources), device env,
cpunormalization (quota scaling by the normalization ratio).
"""

from __future__ import annotations

import dataclasses
import json

from koordinator_tpu.model import resources as res
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.koordlet.qosmanager import BVT_BY_QOS, CFS_PERIOD_US
from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdate,
    ResourceUpdateExecutor,
)

# hook stages (reference runtimehooks/protocol + hooks registry)
PRE_RUN_POD_SANDBOX = "PreRunPodSandbox"
PRE_CREATE_CONTAINER = "PreCreateContainer"
PRE_UPDATE_CONTAINER = "PreUpdateContainerResources"
POST_STOP_POD_SANDBOX = "PostStopPodSandbox"


@dataclasses.dataclass
class ContainerContext:
    """Protocol object passed through hooks (reference
    runtimehooks/protocol/container_context.go): request view + response
    mutations the runtime applies."""

    pod_name: str = ""
    pod_uid: str = ""
    container_name: str = ""
    qos: str = ""  # koordinator QoS LSE/LSR/LS/BE
    priority_class: str = ""
    pod_annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    pod_labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    requests: Dict[str, int] = dataclasses.field(default_factory=dict)
    limits: Dict[str, int] = dataclasses.field(default_factory=dict)
    cgroup_dir: str = ""
    # response / mutations
    cpuset_cpus: Optional[str] = None
    cfs_quota_us: Optional[int] = None
    cpu_shares: Optional[int] = None
    bvt_warp_ns: Optional[int] = None
    memory_limit_bytes: Optional[int] = None
    env: Dict[str, str] = dataclasses.field(default_factory=dict)


HookFn = Callable[[ContainerContext], None]


class HookRegistry:
    """hooks.go:44 Register/RunHooks."""

    def __init__(self):
        self._hooks: Dict[str, List[tuple]] = {}

    def register(self, stage: str, name: str, fn: HookFn) -> None:
        self._hooks.setdefault(stage, []).append((name, fn))

    def run(self, stage: str, ctx: ContainerContext) -> List[str]:
        ran = []
        for name, fn in self._hooks.get(stage, []):
            fn(ctx)
            ran.append(name)
        return ran


# ---------------------------------------------------------------------------
# Hook plugins
# ---------------------------------------------------------------------------


def group_identity_hook(ctx: ContainerContext) -> None:
    """bvt value by QoS class (reference hooks/groupidentity/rule.go:
    BE -> -1, LS/LSR/LSE -> 2, SYSTEM -> 0)."""
    ctx.bvt_warp_ns = BVT_BY_QOS.get(ctx.qos, 0)


CPUSET_ANNOTATION = "scheduling.koordinator.sh/resource-status"


def cpuset_hook(ctx: ContainerContext) -> None:
    """Apply the scheduler-allocated cpuset (reference hooks/cpuset:
    reads the resource-status annotation written at PreBind)."""
    raw = ctx.pod_annotations.get(CPUSET_ANNOTATION)
    if not raw:
        return
    status = raw if isinstance(raw, dict) else json.loads(raw)
    cpuset = status.get("cpuset")
    if cpuset:
        ctx.cpuset_cpus = cpuset


def batch_resource_hook(ctx: ContainerContext) -> None:
    """BE pods sized by batch resources get cfs quota / shares / memory
    from kubernetes.io/batch-* (reference hooks/batchresource/plugin.go):
    quota = batch-cpu(milli) * period / 1000, shares = milli*1024/1000."""
    milli = ctx.requests.get("kubernetes.io/batch-cpu")
    if milli:
        # batch-cpu quantities are already milli; accept string quantities
        milli = res.parse_quantity(milli, "kubernetes.io/batch-cpu")
        ctx.cfs_quota_us = milli * CFS_PERIOD_US // 1000
        ctx.cpu_shares = max(2, milli * 1024 // 1000)
    mem = ctx.limits.get("kubernetes.io/batch-memory") or ctx.requests.get(
        "kubernetes.io/batch-memory"
    )
    if mem:
        # webhook-mutated pods carry "<n>Mi" strings; raw numbers are bytes
        ctx.memory_limit_bytes = res.parse_quantity_bytes(
            mem, "kubernetes.io/batch-memory"
        )


DEVICE_ALLOCATED_ANNOTATION = "scheduling.koordinator.sh/device-allocated"


def device_env_hook(ctx: ContainerContext) -> None:
    """Expose allocated accelerator minors to the container (reference
    hooks/gpu/gpu.go InjectContainerGPUEnv: parses the DeviceAllocations
    annotation — apis/extension/device_share.go:56-66, type name ->
    [{"minor", "resources"}] — and sets NVIDIA_VISIBLE_DEVICES;
    TPU_VISIBLE_CHIPS here).  Only accelerator (gpu) minors are joined —
    an RDMA NIC id in the visible-devices list would expose the wrong
    device."""
    raw = ctx.pod_annotations.get(DEVICE_ALLOCATED_ANNOTATION)
    if not raw:
        return
    alloc = raw if isinstance(raw, dict) else json.loads(raw)
    entries = alloc.get("gpu")
    if entries is not None:
        minors = [e["minor"] for e in entries]
    else:
        # pre-round-5 rebuild payloads carried a flat accelerator list
        minors = alloc.get("minors")
    if minors:
        visible = ",".join(str(m) for m in minors)
        ctx.env["TPU_VISIBLE_CHIPS"] = visible
        ctx.env["NVIDIA_VISIBLE_DEVICES"] = visible


def make_cpu_normalization_hook(ratio_fn: Callable[[], float]) -> HookFn:
    """Scale cfs quota by the node's cpu-normalization ratio (reference
    hooks/cpunormalization: quota *= ratio for LS pods on amplified
    nodes)."""

    def hook(ctx: ContainerContext) -> None:
        ratio = ratio_fn()
        if ratio and ratio != 1.0 and ctx.cfs_quota_us and ctx.cfs_quota_us > 0:
            ctx.cfs_quota_us = int(ctx.cfs_quota_us * ratio)

    return hook


def default_registry(cpu_normalization_ratio: Optional[Callable[[], float]] = None):
    """Standard plugin set (reference runtimehooks.go:81 registered
    plugins)."""
    reg = HookRegistry()
    for stage in (PRE_CREATE_CONTAINER, PRE_UPDATE_CONTAINER):
        reg.register(stage, "groupidentity", group_identity_hook)
        reg.register(stage, "cpuset", cpuset_hook)
        reg.register(stage, "batchresource", batch_resource_hook)
        if cpu_normalization_ratio is not None:
            reg.register(
                stage,
                "cpunormalization",
                make_cpu_normalization_hook(cpu_normalization_ratio),
            )
    reg.register(PRE_CREATE_CONTAINER, "device", device_env_hook)
    return reg


# ---------------------------------------------------------------------------
# Reconciler delivery mode
# ---------------------------------------------------------------------------


class Reconciler:
    """Standalone reconciler (reference runtimehooks/reconciler): applies
    the hook mutations straight to cgroups for running containers, for
    runtimes without NRI/proxy."""

    def __init__(self, registry: HookRegistry, executor: ResourceUpdateExecutor):
        self.registry = registry
        self.executor = executor

    def reconcile_container(self, ctx: ContainerContext, now: float = 0.0) -> int:
        self.registry.run(PRE_UPDATE_CONTAINER, ctx)
        updates: List[ResourceUpdate] = []
        if ctx.cfs_quota_us is not None:
            updates.append(
                ResourceUpdate("cpu.cfs_quota", ctx.cgroup_dir, str(ctx.cfs_quota_us))
            )
        if ctx.cpu_shares is not None:
            updates.append(
                ResourceUpdate("cpu.shares", ctx.cgroup_dir, str(ctx.cpu_shares))
            )
        if ctx.bvt_warp_ns is not None:
            updates.append(
                ResourceUpdate(
                    "cpu.bvt_warp_ns", ctx.cgroup_dir, str(ctx.bvt_warp_ns)
                )
            )
        if ctx.cpuset_cpus is not None:
            updates.append(
                ResourceUpdate("cpuset.cpus", ctx.cgroup_dir, ctx.cpuset_cpus)
            )
        if ctx.memory_limit_bytes is not None:
            updates.append(
                ResourceUpdate(
                    "memory.limit", ctx.cgroup_dir, str(ctx.memory_limit_bytes)
                )
            )
        return self.executor.update_batch(updates, now)
