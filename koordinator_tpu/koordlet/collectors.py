"""Metrics advisor: collector framework + the standard collector set.

Reference: ``pkg/koordlet/metricsadvisor`` — a plugin framework
(``framework/plugin.go:28 Collector``) running each collector on its own
tick (``metrics_advisor.go:102``), registry at ``plugins_profile.go:36-52``:
noderesource, podresource, beresource, sysresource, performance (CPI/PSI),
coldmemoryresource, and the device collector (NVML there; TPU enumeration
via JAX here).

Collectors are deterministic functions of the SysFS + prior state so tests
drive them against a temp-dir fake fs (the reference fakes cgroupfs the
same way, ``util_test_tool.go``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.sysfs import (
    KUBEPODS_BESTEFFORT,
    SysFS,
    pod_cgroup_dir,
)


class Collector:
    """Collector plugin interface (framework/plugin.go:28): Enabled/Setup/
    Run condensed to a ``collect(now)`` tick."""

    name = "collector"
    interval_seconds = 10.0

    def collect(self, now: float) -> None:
        raise NotImplementedError

    def enabled(self) -> bool:
        return True


@dataclasses.dataclass
class PodMeta:
    """statesinformer pod description consumed by collectors."""

    name: str
    uid: str
    qos: str = "Burstable"  # kubelet QoS class
    koord_qos: str = ""  # LSE/LSR/LS/BE
    namespace: str = "default"


class NodeResourceCollector(Collector):
    """Node cpu (cores) + memory (bytes) usage from /proc
    (collectors/noderesource).  CPU usage derives from /proc/stat tick
    deltas between collections."""

    name = "noderesource"

    def __init__(self, fs: SysFS, cache: MetricCache, *, ticks_per_second: int = 100):
        self.fs = fs
        self.cache = cache
        self.ticks_per_second = ticks_per_second
        self._last: Optional[tuple] = None  # (used, total, wall)

    def collect(self, now: float) -> None:
        used, total = self.fs.proc_stat_cpu()
        if self._last is not None:
            last_used, last_total, last_now = self._last
            dt = now - last_now
            if dt > 0 and total > last_total:
                cores = (used - last_used) / self.ticks_per_second / dt
                self.cache.append(mc.NODE_CPU_USAGE, max(0.0, cores), ts=now)
        self._last = (used, total, now)
        self.cache.append(
            mc.NODE_MEMORY_USAGE, float(self.fs.memory_usage_bytes()), ts=now
        )


class PodResourceCollector(Collector):
    """Per-pod cpu/memory from the pod cgroup (collectors/podresource)."""

    name = "podresource"

    def __init__(self, fs: SysFS, cache: MetricCache, pods_fn):
        self.fs = fs
        self.cache = cache
        self.pods_fn = pods_fn  # () -> Sequence[PodMeta]
        self._last_cpu: Dict[str, tuple] = {}  # uid -> (usage_ns, wall)

    def collect(self, now: float) -> None:
        for pod in self.pods_fn():
            cgdir = pod_cgroup_dir(pod.qos, pod.uid)
            usage_ns = self.fs.cpuacct_usage_ns(cgdir)
            last = self._last_cpu.get(pod.uid)
            if last is not None:
                last_ns, last_now = last
                dt = now - last_now
                if dt > 0 and usage_ns >= last_ns:
                    cores = (usage_ns - last_ns) / 1e9 / dt
                    self.cache.append(
                        mc.POD_CPU_USAGE, cores, ts=now, labels={"pod": pod.uid}
                    )
            self._last_cpu[pod.uid] = (usage_ns, now)
            self.cache.append(
                mc.POD_MEMORY_USAGE,
                float(self.fs.memory_usage_cgroup(cgdir)),
                ts=now,
                labels={"pod": pod.uid},
            )


class BEResourceCollector(Collector):
    """Aggregate BestEffort-tree usage (collectors/beresource): the
    cpusuppress strategy consumes this."""

    name = "beresource"

    def __init__(self, fs: SysFS, cache: MetricCache):
        self.fs = fs
        self.cache = cache
        self._last: Optional[tuple] = None

    def collect(self, now: float) -> None:
        usage_ns = self.fs.cpuacct_usage_ns(KUBEPODS_BESTEFFORT)
        if self._last is not None:
            last_ns, last_now = self._last
            dt = now - last_now
            if dt > 0 and usage_ns >= last_ns:
                self.cache.append(
                    mc.BE_CPU_USAGE, (usage_ns - last_ns) / 1e9 / dt, ts=now
                )
        self._last = (usage_ns, now)


class SysResourceCollector(Collector):
    """System (non-pod) usage = node usage - sum(pod usage)
    (collectors/sysresource)."""

    name = "sysresource"

    def __init__(self, cache: MetricCache):
        self.cache = cache

    def collect(self, now: float) -> None:
        node = self.cache.query(
            mc.NODE_CPU_USAGE, start=now - 60, end=now, agg=mc.AGG_LATEST
        )
        if node is None:
            return
        pod_total = 0.0
        for labels in self.cache.series_labels(mc.POD_CPU_USAGE):
            v = self.cache.query(
                mc.POD_CPU_USAGE,
                start=now - 60,
                end=now,
                agg=mc.AGG_LATEST,
                labels=labels,
            )
            pod_total += v or 0.0
        self.cache.append(mc.SYS_CPU_USAGE, max(0.0, node - pod_total), ts=now)


class PSICollector(Collector):
    """Node PSI cpu/mem/io some-avg10 (collectors/performance PSI path)."""

    name = "psi"

    def __init__(self, fs: SysFS, cache: MetricCache):
        self.fs = fs
        self.cache = cache

    def collect(self, now: float) -> None:
        for resource, metric in (
            ("cpu.pressure", mc.NODE_PSI_CPU_SOME_AVG10),
            ("memory.pressure", mc.NODE_PSI_MEM_SOME_AVG10),
            ("io.pressure", mc.NODE_PSI_IO_SOME_AVG10),
        ):
            psi = self.fs.psi(resource)
            if psi is not None:
                self.cache.append(metric, psi.some.avg10, ts=now)


class PerformanceCollector(Collector):
    """Container CPI via the native perf shim (collectors/performance
    collectContainerCPI, cgo libpfm4 there; ``native.perf`` here).  Falls
    back to disabled when the shim or perf_event_open is unavailable."""

    name = "performance"

    def __init__(self, cache: MetricCache, pods_fn, perf_reader=None):
        self.cache = cache
        self.pods_fn = pods_fn
        self.perf_reader = perf_reader

    def enabled(self) -> bool:
        return self.perf_reader is not None

    def collect(self, now: float) -> None:
        if self.perf_reader is None:
            return
        for pod in self.pods_fn():
            sample = self.perf_reader(pod)
            if not sample:
                continue
            cycles, instructions = sample
            self.cache.append(
                mc.CONTAINER_CPI_CYCLES, cycles, ts=now, labels={"pod": pod.uid}
            )
            self.cache.append(
                mc.CONTAINER_CPI_INSTRUCTIONS,
                instructions,
                ts=now,
                labels={"pod": pod.uid},
            )


def make_native_perf_reader(fs: SysFS):
    """Perf reader backed by the native CPI shim
    (``koordinator_tpu.native.PerfCPIGroup``; reference cgo path
    ``perf_group_linux.go collectContainerCPI``): opens the pod cgroup dir
    as a perf cgroup target.  Returns None when perf is unavailable so the
    PerformanceCollector disables itself (feature-gate semantics)."""
    import os

    from koordinator_tpu import native

    if not native.available() or native.read_self_cpi() is None:
        return None

    def reader(pod: "PodMeta"):
        cgdir = os.path.join(
            fs.root, "sys/fs/cgroup/perf_event", pod_cgroup_dir(pod.qos, pod.uid)
        )
        try:
            fd = os.open(cgdir, os.O_RDONLY)
        except OSError:
            return None
        try:
            with native.PerfCPIGroup(fd, is_cgroup=True) as g:
                return g.read()
        except OSError:
            return None
        finally:
            os.close(fd)

    return reader


class ColdMemoryCollector(Collector):
    """kidled cold-page accounting (collectors/coldmemoryresource
    cold_page_kidled.go): reads idle-page stats to size reclaimable
    memory."""

    name = "coldmemoryresource"

    def __init__(self, fs: SysFS, cache: MetricCache):
        self.fs = fs
        self.cache = cache

    def enabled(self) -> bool:
        return (
            self.fs.read(self.fs.proc_path("sys/vm/kidled_scan_period_in_seconds"))
            is not None
        )

    def collect(self, now: float) -> None:
        text = self.fs.read(
            self.fs.proc_path("kidled_cold_pages")
        )
        if text is None:
            return
        try:
            cold_bytes = int(text.strip())
        except ValueError:
            return
        self.cache.append(mc.COLD_PAGE_BYTES, float(cold_bytes), ts=now)


class DeviceCollector(Collector):
    """Accelerator enumeration + utilization (reference NVML GPU collector,
    ``metricsadvisor/devices/gpu/collector_gpu_linux.go``; here the device
    list comes from JAX/libtpu)."""

    name = "device"

    def __init__(self, cache: MetricCache, devices_fn=None):
        self.cache = cache
        self.devices_fn = devices_fn or _jax_devices

    def collect(self, now: float) -> None:
        for dev in self.devices_fn():
            labels = {"minor": str(dev.get("minor", 0))}
            if "util" in dev:
                self.cache.append(
                    mc.DEVICE_UTIL, float(dev["util"]), ts=now, labels=labels
                )
            if "memory_used" in dev:
                self.cache.append(
                    mc.DEVICE_MEMORY_USED,
                    float(dev["memory_used"]),
                    ts=now,
                    labels=labels,
                )


_JAX_UNAVAILABLE = False


def _jax_devices() -> List[Dict]:
    global _JAX_UNAVAILABLE
    if _JAX_UNAVAILABLE:
        # failed imports are not cached by Python: without this flag a
        # jax-less host would re-walk the import machinery every tick
        return []
    try:
        import jax

        return [
            {"minor": i, "platform": d.platform}
            for i, d in enumerate(jax.devices())
        ]
    except ImportError:
        _JAX_UNAVAILABLE = True
        return []
    except Exception:
        # an unhealthy backend (dead TPU tunnel) must read as "no
        # devices", but not invisibly: the scrape path keeps running and
        # the reason lands in the debug log
        import logging

        logging.getLogger(__name__).debug(
            "jax device enumeration failed", exc_info=True
        )
        return []


class MetricsAdvisor:
    """Collector scheduler (metrics_advisor.go): each collector ticks on
    its own interval; ``run_once`` advances every due collector — the
    production loop calls it from a timer, tests call it directly."""

    def __init__(self, collectors: Sequence[Collector]):
        self.collectors = [c for c in collectors if c.enabled()]
        self._next_due: Dict[str, float] = {}

    def run_once(self, now: Optional[float] = None) -> List[str]:
        now = time.time() if now is None else now
        ran = []
        for c in self.collectors:
            if now >= self._next_due.get(c.name, 0):
                c.collect(now)
                self._next_due[c.name] = now + c.interval_seconds
                ran.append(c.name)
        return ran
