"""PLEG: pod lifecycle events from cgroup directory changes.

Reference: ``pkg/koordlet/pleg`` — inotify watches on the kubepods cgroup
trees (``watcher_linux.go:30``) emit PodAdded/PodDeleted/ContainerAdded…
events to subscribed handlers (``pleg.go:75,81``).  This rebuild scans the
same directory layout; ``poll_once`` diffs against the previous scan (tests
and non-inotify platforms), which is semantically the event stream the
reference derives from inotify.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Set

from koordinator_tpu.koordlet.sysfs import SysFS

POD_ADDED = "PodAdded"
POD_DELETED = "PodDeleted"
CONTAINER_ADDED = "ContainerAdded"
CONTAINER_DELETED = "ContainerDeleted"

_POD_DIR = re.compile(r"^pod([0-9a-f-]+)$")


@dataclasses.dataclass(frozen=True)
class PlegEvent:
    kind: str
    pod_uid: str
    container_id: str = ""


class Pleg:
    """Directory-diff PLEG over the kubepods trees."""

    QOS_DIRS = ("kubepods", "kubepods/besteffort", "kubepods/burstable")

    def __init__(self, fs: SysFS):
        self.fs = fs
        self._handlers: List[Callable[[PlegEvent], None]] = []
        self._known: Dict[str, Set[str]] = {}  # pod uid -> container ids

    def subscribe(self, handler: Callable[[PlegEvent], None]) -> None:
        self._handlers.append(handler)

    def _emit(self, event: PlegEvent) -> None:
        for h in self._handlers:
            h(event)

    def _scan(self) -> Dict[str, Set[str]]:
        base = os.path.join(self.fs.root, self.fs.cgroup_mount)
        pods: Dict[str, Set[str]] = {}
        for qos_dir in self.QOS_DIRS:
            d = os.path.join(base, qos_dir)
            try:
                entries = os.listdir(d)
            except OSError:
                continue
            for entry in entries:
                m = _POD_DIR.match(entry)
                if not m:
                    continue
                uid = m.group(1)
                pod_path = os.path.join(d, entry)
                containers = {
                    c
                    for c in os.listdir(pod_path)
                    if os.path.isdir(os.path.join(pod_path, c))
                }
                pods[uid] = containers
        return pods

    def poll_once(self) -> List[PlegEvent]:
        """Diff the cgroup trees against the last poll; emit + return
        events in a stable order (pods added, containers added, containers
        deleted, pods deleted)."""
        current = self._scan()
        events: List[PlegEvent] = []
        for uid in sorted(current.keys() - self._known.keys()):
            events.append(PlegEvent(POD_ADDED, uid))
            for c in sorted(current[uid]):
                events.append(PlegEvent(CONTAINER_ADDED, uid, c))
        for uid in sorted(current.keys() & self._known.keys()):
            for c in sorted(current[uid] - self._known[uid]):
                events.append(PlegEvent(CONTAINER_ADDED, uid, c))
            for c in sorted(self._known[uid] - current[uid]):
                events.append(PlegEvent(CONTAINER_DELETED, uid, c))
        for uid in sorted(self._known.keys() - current.keys()):
            for c in sorted(self._known[uid]):
                events.append(PlegEvent(CONTAINER_DELETED, uid, c))
            events.append(PlegEvent(POD_DELETED, uid))
        self._known = current
        for e in events:
            self._emit(e)
        return events
