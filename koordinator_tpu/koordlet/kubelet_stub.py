"""Kubelet stub: the statesinformer's pod-list sync surface.

Reference ``pkg/koordlet/statesinformer/impl/kubelet_stub.go``: the
koordlet reads the authoritative pod list straight from the kubelet's
(secure) endpoint — ``GET /pods`` with a bearer token over HTTPS (or the
read-only HTTP port) — rather than watching the apiserver, so the node
agent sees exactly what the kubelet is running.
"""

from __future__ import annotations

import json
import ssl
import urllib.request
from typing import Dict, List, Mapping, Optional


class KubeletStub:
    def __init__(
        self,
        address: str = "127.0.0.1",
        port: int = 10250,
        scheme: str = "https",
        token: Optional[str] = None,
        token_path: Optional[str] = None,
        insecure_skip_verify: bool = True,
        ca_path: Optional[str] = None,
        timeout_seconds: float = 10.0,
    ):
        self.base = f"{scheme}://{address}:{port}"
        self.timeout = timeout_seconds
        self._token = token
        self._token_path = token_path
        if scheme == "https":
            # create_default_context loads the system trust store, so the
            # verifying mode actually works; ca_path pins the cluster CA
            ctx = ssl.create_default_context(cafile=ca_path)
            if insecure_skip_verify:
                # kubelet serving certs are cluster-internal; the reference
                # defaults to InsecureSkipVerify for the same reason
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl = ctx
        else:
            self._ssl = None

    def _bearer(self) -> Optional[str]:
        if self._token:
            return self._token
        if self._token_path:
            try:
                with open(self._token_path) as fh:
                    return fh.read().strip()
            except OSError:
                return None
        return None

    def get_all_pods(self) -> List[Dict]:
        """GET /pods -> the pod list (kubelet PodList .items)."""
        doc = self._get("/pods")
        return list(doc.get("items", []))

    def get_node_config(self) -> Mapping:
        """GET /configz -> kubelet configuration (cpu manager policy etc.,
        consumed by the NUMA topology reporter)."""
        return self._get("/configz")

    def _get(self, path: str) -> Dict:
        req = urllib.request.Request(self.base + path)
        token = self._bearer()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        kwargs = {"timeout": self.timeout}
        if self._ssl is not None:
            kwargs["context"] = self._ssl
        with urllib.request.urlopen(req, **kwargs) as resp:
            return json.loads(resp.read())
