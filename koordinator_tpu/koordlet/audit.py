"""Audit: ring-file log of agent actuations + reader.

Reference: ``pkg/koordlet/audit`` — every actuation (cgroup write, evict,
suppress) appends a structured record to size-rotated files
(``auditor.go:38``), readable via the ``/events`` HTTP handler
(``cmd/koordlet/main.go:64-67,86``).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterator, List, Optional


class Auditor:
    """Size-rotated JSONL audit log."""

    def __init__(
        self,
        directory: str,
        *,
        max_file_bytes: int = 1 << 20,
        max_files: int = 8,
    ):
        self.directory = directory
        self.max_file_bytes = max_file_bytes
        self.max_files = max_files
        os.makedirs(directory, exist_ok=True)
        self._active = os.path.join(directory, "audit.log")

    def log(self, event: str, **fields) -> None:
        record = {"ts": time.time(), "event": event, **fields}
        line = json.dumps(record, sort_keys=True) + "\n"
        self._rotate_if_needed(len(line))
        with open(self._active, "a") as f:
            f.write(line)

    def _rotate_if_needed(self, incoming: int) -> None:
        try:
            size = os.path.getsize(self._active)
        except OSError:
            return
        if size + incoming <= self.max_file_bytes:
            return
        # shift audit.log.N -> .N+1, drop the oldest
        for i in range(self.max_files - 1, 0, -1):
            src = f"{self._active}.{i}"
            if os.path.exists(src):
                if i + 1 >= self.max_files:
                    os.remove(src)
                else:
                    os.replace(src, f"{self._active}.{i + 1}")
        os.replace(self._active, f"{self._active}.1")

    def read_events(
        self, *, limit: int = 256, event: Optional[str] = None
    ) -> List[Dict]:
        """Newest-first event records (the /events handler's view)."""
        out: List[Dict] = []
        files = [self._active] + [
            f"{self._active}.{i}" for i in range(1, self.max_files)
        ]
        for path in files:
            try:
                with open(path) as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in reversed(lines):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if event is not None and rec.get("event") != event:
                    continue
                out.append(rec)
                if len(out) >= limit:
                    return out
        return out


    # -- HTTP /events handler (cmd/koordlet/main.go:64-67,86) --
    def wsgi_app(self, environ, start_response):
        from urllib.parse import parse_qs

        try:
            query = parse_qs(environ.get("QUERY_STRING", ""))
            try:
                limit = int(query.get("limit", ["256"])[0])
            except ValueError:
                limit = 256
            event = query.get("event", [None])[0]
            events = self.read_events(limit=limit, event=event)
            status, body = "200 OK", json.dumps(events).encode()
        except Exception as exc:  # never crash the scrape path
            status, body = "500 Internal", json.dumps({"error": str(exc)}).encode()
        start_response(
            status,
            [("Content-Type", "application/json"),
             ("Content-Length", str(len(body)))],
        )
        return [body]
