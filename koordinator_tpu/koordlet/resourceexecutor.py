"""Resource update executor: cache-diffed, leveled cgroup writes.

Reference: ``pkg/koordlet/resourceexecutor`` — ``executor.go:32
ResourceUpdateExecutor`` skips writes whose value already matches the
cache (``UpdateBatch`` with cacheable updaters), and **leveled** updaters
order parent/child cgroup updates so limits never transiently violate the
hierarchy (``updater.go`` merge semantics: when shrinking a parent cgroup,
children update first; when growing, parent first).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.koordlet.sysfs import SysFS


@dataclasses.dataclass
class ResourceUpdate:
    """One desired cgroup write."""

    resource: str  # CGROUP_FILES key
    cgroup_dir: str
    value: str


@dataclasses.dataclass
class _CacheEntry:
    value: str
    ts: float


class ResourceUpdateExecutor:
    """Cache-diffed executor (executor.go:59 NewResourceUpdateExecutor)."""

    def __init__(self, fs: SysFS, *, cache_expire_seconds: float = 1800.0, audit=None):
        self.fs = fs
        self.cache_expire_seconds = cache_expire_seconds
        self._cache: Dict[Tuple[str, str], _CacheEntry] = {}
        self.audit = audit  # optional koordlet.audit.Auditor

    def _cached_same(self, key: Tuple[str, str], value: str, now: float) -> bool:
        e = self._cache.get(key)
        return (
            e is not None
            and e.value == value
            and now - e.ts < self.cache_expire_seconds
        )

    def update(self, update: ResourceUpdate, now: Optional[float] = None) -> bool:
        """Write one value unless the cache already holds it.  Returns
        whether a write happened."""
        now = time.time() if now is None else now
        key = (update.resource, update.cgroup_dir)
        if self._cached_same(key, update.value, now):
            return False
        ok = self.fs.write_cgroup(update.resource, update.cgroup_dir, update.value)
        if ok:
            self._cache[key] = _CacheEntry(update.value, now)
            if self.audit is not None:
                self.audit.log(
                    "cgroup_write",
                    resource=update.resource,
                    cgroup=update.cgroup_dir,
                    value=update.value,
                )
        return ok

    def update_batch(
        self, updates: Sequence[ResourceUpdate], now: Optional[float] = None
    ) -> int:
        return sum(1 for u in updates if self.update(u, now))

    def leveled_update_batch(
        self, levels: Sequence[Sequence[ResourceUpdate]], now: Optional[float] = None
    ) -> int:
        """Apply level-ordered updates (updater.go LeveledUpdateBatch):
        callers pass levels root-first; growth applies root-first and
        shrink leaf-first per level pair, which the caller encodes by
        ordering — this executor just honors the level sequence."""
        done = 0
        for level in levels:
            done += self.update_batch(level, now)
        return done


class CgroupReader:
    """Typed read face (resourceexecutor/reader.go CgroupReader)."""

    def __init__(self, fs: SysFS):
        self.fs = fs

    def read_int(self, resource: str, cgroup_dir: str = "") -> Optional[int]:
        v = self.fs.read_cgroup(resource, cgroup_dir)
        if v is None:
            return None
        try:
            return int(v.split()[0])
        except (ValueError, IndexError):
            return None

    def read_cpuset(self, cgroup_dir: str = "") -> Optional[List[int]]:
        v = self.fs.read_cgroup("cpuset.cpus", cgroup_dir)
        if v is None or not v.strip():
            return None
        out: List[int] = []
        for part in v.strip().split(","):
            if "-" in part:
                a, b = part.split("-")
                out.extend(range(int(a), int(b) + 1))
            else:
                out.append(int(part))
        return out


def format_cpuset(cpus: Sequence[int]) -> str:
    """Canonical ranges string ('0-3,8,10-11'), the kernel's cpuset format
    (reference pkg/util/cpuset)."""
    cpus = sorted(set(cpus))
    if not cpus:
        return ""
    runs = []
    start = prev = cpus[0]
    for c in cpus[1:]:
        if c == prev + 1:
            prev = c
            continue
        runs.append((start, prev))
        start = prev = c
    runs.append((start, prev))
    return ",".join(f"{a}-{b}" if a != b else str(a) for a, b in runs)
