"""Prometheus-style metrics registry with text exposition.

Reference: ``pkg/koordlet/metrics`` (CPI ``cpi.go``, PSI ``psi.go``,
cpu_suppress / cpu_burst / prediction gauges, common node labels
``common.go:26,79``) exposed on ``/metrics``
(``cmd/koordlet/main.go:82-90``).  No prometheus_client dependency: the
registry renders the text exposition format directly, which is all the
scrape path needs.

Family registration is IDEMPOTENT and kind-checked: every metric name
maps to exactly one family (counter, gauge or histogram), so a daemon
restart that re-registers its families cannot emit duplicate
``# HELP``/``# TYPE`` lines (the pre-fix render walked the counter and
gauge tables independently, and a name that had landed in both — e.g. a
family re-registered under a different kind across restarts — rendered
twice, which Prometheus rejects as a duplicate family).  Re-registering
the same name with a conflicting kind raises instead of silently
splitting the series.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

# cycle latencies span sub-ms warm cycles to multi-second cold compiles
DEFAULT_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, float("inf"),
)


def _key(labels: Optional[Mapping[str, str]]) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


def _render_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else f"{bound:g}"


def _norm_buckets(buckets: Sequence[float]) -> Tuple[float, ...]:
    """Prometheus requires the +Inf bucket (it must equal _count);
    custom bucket lists that omit it would silently drop over-top
    observations from every bucket and render an invalid histogram."""
    out = tuple(float(b) for b in buckets)
    if not out or not math.isinf(out[-1]):
        out = out + (float("inf"),)
    return out


class _Family:
    """One metric family: a kind, help text, and its labeled series."""

    __slots__ = ("kind", "help", "series", "buckets")

    def __init__(self, kind: str, help_text: str = "",
                 buckets: Optional[Sequence[float]] = None):
        self.kind = kind
        self.help = help_text
        # counter/gauge: LabelKey -> float
        # histogram:     LabelKey -> [bucket_counts..., sum, count]
        self.series: Dict[LabelKey, object] = {}
        self.buckets: Optional[Tuple[float, ...]] = (
            tuple(buckets) if buckets is not None else None
        )


class MetricsRegistry:
    """Counters, gauges and histograms with labels; render() emits the
    Prometheus text exposition format."""

    def __init__(self, common_labels: Optional[Mapping[str, str]] = None):
        # common node labels (common.go:26: node name merged into every
        # series)
        self.common = dict(common_labels or {})
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # -- registration (idempotent; the duplicate-family fix) --
    def _family_locked(self, name: str, kind: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            fam = _Family(
                kind,
                buckets=_norm_buckets(buckets) if buckets is not None else None,
            )
            self._families[name] = fam
        elif fam.kind == "":
            fam.kind = kind  # describe() created a kindless placeholder
        elif fam.kind != kind:
            raise ValueError(
                f"metric family {name!r} already registered as "
                f"{fam.kind}; cannot re-register as {kind} (duplicate "
                "# TYPE lines are invalid exposition)"
            )
        return fam

    def register(self, name: str, kind: str, help_text: str = "",
                 buckets: Optional[Sequence[float]] = None) -> None:
        """Declare a family up front.  Safe to call any number of times
        (daemon restarts re-register); a kind conflict raises."""
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown metric kind {kind!r}")
        with self._lock:
            fam = self._family_locked(
                name, kind,
                buckets=buckets if kind == "histogram" else None,
            )
            if help_text:
                fam.help = help_text
            if kind == "histogram" and buckets is not None:
                want = _norm_buckets(buckets)
                if fam.buckets is None:
                    fam.buckets = want
                elif fam.buckets != want and fam.series:
                    raise ValueError(
                        f"histogram {name!r} re-registered with different "
                        "buckets while series exist"
                    )
                else:
                    fam.buckets = want

    def describe(self, name: str, help_text: str) -> None:
        """Attach help text; kind is bound at first write/register."""
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                fam.help = help_text
            else:
                # remembered until the first write binds a kind
                self._families[name] = _Family("", help_text)

    def _bind_locked(self, name: str, kind: str) -> _Family:
        return self._family_locked(name, kind)

    # -- writes --
    def counter_add(
        self, name: str, value: float, labels: Optional[Mapping[str, str]] = None
    ) -> None:
        with self._lock:
            fam = self._bind_locked(name, "counter")
            k = _key({**self.common, **(labels or {})})
            fam.series[k] = fam.series.get(k, 0.0) + value

    def gauge_set(
        self, name: str, value: float, labels: Optional[Mapping[str, str]] = None
    ) -> None:
        with self._lock:
            self._bind_locked(name, "gauge").series[
                _key({**self.common, **(labels or {})})
            ] = value

    def histogram_observe(
        self, name: str, value: float, labels: Optional[Mapping[str, str]] = None
    ) -> None:
        with self._lock:
            fam = self._bind_locked(name, "histogram")
            if fam.buckets is None:
                fam.buckets = DEFAULT_BUCKETS_MS
            k = _key({**self.common, **(labels or {})})
            state = fam.series.get(k)
            if state is None:
                state = [0] * len(fam.buckets) + [0.0, 0]
                fam.series[k] = state
            for i, bound in enumerate(fam.buckets):
                if value <= bound:
                    state[i] += 1
            state[-2] += value  # _sum
            state[-1] += 1  # _count

    # -- reads (test/introspection seam) --
    def get(
        self, name: str, labels: Optional[Mapping[str, str]] = None
    ) -> Optional[float]:
        k = _key({**self.common, **(labels or {})})
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind not in ("counter", "gauge"):
                return None
            return fam.series.get(k)

    def get_histogram(
        self, name: str, labels: Optional[Mapping[str, str]] = None
    ) -> Optional[Tuple[int, float]]:
        """(count, sum) of one histogram series, or None."""
        k = _key({**self.common, **(labels or {})})
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind != "histogram":
                return None
            state = fam.series.get(k)
            if state is None:
                return None
            return int(state[-1]), float(state[-2])

    def histogram_series(
        self, name: str
    ) -> List[Tuple[Dict[str, str], Tuple[float, ...], Tuple[int, ...], float, int]]:
        """Every series of one histogram family as
        ``(labels, bucket_bounds, cumulative_counts, sum, count)`` —
        the read seam obs/slo.py's quantile estimator consumes.
        ``cumulative_counts[i]`` is observations ``<= bucket_bounds[i]``
        (the exposition's ``_bucket{le=...}`` semantics).  Copies are
        returned, so callers can diff windows without racing writers."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind != "histogram" or fam.buckets is None:
                return []
            n = len(fam.buckets)
            return [
                (dict(k), fam.buckets, tuple(state[:n]),
                 float(state[-2]), int(state[-1]))
                for k, state in fam.series.items()
            ]

    # -- the koordlet metric families (metrics/*.go) --
    def record_container_cpi(
        self, pod: str, container: str, cycles: float, instructions: float
    ) -> None:
        labels = {"pod": pod, "container": container}
        self.gauge_set("koordlet_container_cpi_cycles", cycles, labels)
        self.gauge_set("koordlet_container_cpi_instructions", instructions, labels)

    def record_psi(
        self, resource: str, level: str, avg10: float, labels=None
    ) -> None:
        self.gauge_set(
            "koordlet_psi_avg10",
            avg10,
            {**(labels or {}), "resource": resource, "level": level},
        )

    def record_be_suppress(self, cpu_cores_milli: float) -> None:
        self.gauge_set("koordlet_be_suppress_cpu_cores", cpu_cores_milli / 1000.0)

    def record_cpu_burst(self, pod: str, container: str, burst_us: float) -> None:
        self.gauge_set(
            "koordlet_container_cpu_burst_us",
            burst_us,
            {"pod": pod, "container": container},
        )

    def record_prediction(self, key: str, peak: float) -> None:
        self.gauge_set("koordlet_prediction_peak", peak, {"key": key})

    def render(self) -> str:
        """Prometheus text exposition (the /metrics body).  Every family
        renders exactly once — one # HELP, one # TYPE — regardless of
        how many times it was registered."""
        out: List[str] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                if not fam.kind or not fam.series:
                    continue  # described but never written
                if fam.help:
                    out.append(f"# HELP {name} {fam.help}")
                out.append(f"# TYPE {name} {fam.kind}")
                if fam.kind == "histogram":
                    for k in sorted(fam.series):
                        state = fam.series[k]
                        for i, bound in enumerate(fam.buckets):
                            lk = k + (("le", _fmt_le(bound)),)
                            out.append(
                                f"{name}_bucket{_render_labels(lk)} "
                                f"{state[i]}"
                            )
                        out.append(
                            f"{name}_sum{_render_labels(k)} {state[-2]:g}"
                        )
                        out.append(
                            f"{name}_count{_render_labels(k)} {state[-1]}"
                        )
                else:
                    for k in sorted(fam.series):
                        out.append(
                            f"{name}{_render_labels(k)} {fam.series[k]:g}"
                        )
        return "\n".join(out) + "\n"

    # -- WSGI /metrics endpoint (main.go:82-90) --
    def wsgi_app(self, environ, start_response):
        body = self.render().encode()
        start_response(
            "200 OK",
            [("Content-Type", "text/plain; version=0.0.4"),
             ("Content-Length", str(len(body)))],
        )
        return [body]
