"""Prometheus-style metrics registry with text exposition.

Reference: ``pkg/koordlet/metrics`` (CPI ``cpi.go``, PSI ``psi.go``,
cpu_suppress / cpu_burst / prediction gauges, common node labels
``common.go:26,79``) exposed on ``/metrics``
(``cmd/koordlet/main.go:82-90``).  No prometheus_client dependency: the
registry renders the text exposition format directly, which is all the
scrape path needs.
"""

from __future__ import annotations

import threading
from typing import Dict, Mapping, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _key(labels: Optional[Mapping[str, str]]) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


def _render_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class MetricsRegistry:
    """Counters and gauges with labels; render() emits exposition text."""

    def __init__(self, common_labels: Optional[Mapping[str, str]] = None):
        # common node labels (common.go:26: node name merged into every
        # series)
        self.common = dict(common_labels or {})
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[LabelKey, float]] = {}
        self._gauges: Dict[str, Dict[LabelKey, float]] = {}
        self._help: Dict[str, str] = {}

    def describe(self, name: str, help_text: str) -> None:
        self._help[name] = help_text

    def counter_add(
        self, name: str, value: float, labels: Optional[Mapping[str, str]] = None
    ) -> None:
        with self._lock:
            series = self._counters.setdefault(name, {})
            k = _key({**self.common, **(labels or {})})
            series[k] = series.get(k, 0.0) + value

    def gauge_set(
        self, name: str, value: float, labels: Optional[Mapping[str, str]] = None
    ) -> None:
        with self._lock:
            self._gauges.setdefault(name, {})[
                _key({**self.common, **(labels or {})})
            ] = value

    def get(
        self, name: str, labels: Optional[Mapping[str, str]] = None
    ) -> Optional[float]:
        k = _key({**self.common, **(labels or {})})
        with self._lock:
            for table in (self._counters, self._gauges):
                if name in table and k in table[name]:
                    return table[name][k]
        return None

    # -- the koordlet metric families (metrics/*.go) --
    def record_container_cpi(
        self, pod: str, container: str, cycles: float, instructions: float
    ) -> None:
        labels = {"pod": pod, "container": container}
        self.gauge_set("koordlet_container_cpi_cycles", cycles, labels)
        self.gauge_set("koordlet_container_cpi_instructions", instructions, labels)

    def record_psi(
        self, resource: str, level: str, avg10: float, labels=None
    ) -> None:
        self.gauge_set(
            "koordlet_psi_avg10",
            avg10,
            {**(labels or {}), "resource": resource, "level": level},
        )

    def record_be_suppress(self, cpu_cores_milli: float) -> None:
        self.gauge_set("koordlet_be_suppress_cpu_cores", cpu_cores_milli / 1000.0)

    def record_cpu_burst(self, pod: str, container: str, burst_us: float) -> None:
        self.gauge_set(
            "koordlet_container_cpu_burst_us",
            burst_us,
            {"pod": pod, "container": container},
        )

    def record_prediction(self, key: str, peak: float) -> None:
        self.gauge_set("koordlet_prediction_peak", peak, {"key": key})

    def render(self) -> str:
        """Prometheus text exposition (the /metrics body)."""
        out = []
        with self._lock:
            for kind, table in (("counter", self._counters), ("gauge", self._gauges)):
                for name in sorted(table):
                    if name in self._help:
                        out.append(f"# HELP {name} {self._help[name]}")
                    out.append(f"# TYPE {name} {kind}")
                    for k in sorted(table[name]):
                        out.append(f"{name}{_render_labels(k)} {table[name][k]:g}")
        return "\n".join(out) + "\n"

    # -- WSGI /metrics endpoint (main.go:82-90) --
    def wsgi_app(self, environ, start_response):
        body = self.render().encode()
        start_response(
            "200 OK",
            [("Content-Type", "text/plain; version=0.0.4"),
             ("Content-Length", str(len(body)))],
        )
        return [body]
