"""States informer: node/pod/NodeSLO state + NodeMetric reporting.

Reference: ``pkg/koordlet/statesinformer`` — plugin-registered informers
sync apiserver state into the agent and report back ``NodeMetric.Status``
(``impl/states_nodemetric.go:237 sync``, ``:324 collectMetric``: windowed
AVG node/pod usage plus P50/P90/P95/P99 aggregated usage) and the
NodeResourceTopology CR (``impl/states_noderesourcetopology.go``).

This rebuild keeps the informer as plain state + callbacks (no apiserver in
the loop); the report dicts are the CR payloads the manager controllers
(``koordinator_tpu.manager``) consume directly.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.collectors import PodMeta
from koordinator_tpu.koordlet.metriccache import MetricCache

DEFAULT_AGGREGATE_DURATION_SECONDS = 300.0  # collect policy default
DEFAULT_REPORT_INTERVAL_SECONDS = 60.0


@dataclasses.dataclass
class CollectPolicy:
    """NodeMetric spec collect policy (reference
    slo-controller/nodemetric/collect_policy.go defaults)."""

    aggregate_duration_seconds: float = DEFAULT_AGGREGATE_DURATION_SECONDS
    report_interval_seconds: float = DEFAULT_REPORT_INTERVAL_SECONDS


class StatesInformer:
    """Holds node/pods/NodeSLO state; thread-safe snapshot accessors
    (states_informer.go:105 GetAllPods/GetNode/GetNodeSLO)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._node: Dict = {}
        self._pods: List[PodMeta] = []
        self._pod_specs: Dict[str, Dict] = {}
        self._node_slo: Dict = {}
        self._node_topo: Dict = {}
        self._callbacks: List[Callable[[str], None]] = []

    def register_callback(self, cb: Callable[[str], None]) -> None:
        """reference statesinformer RegisterCallbacks: qosmanager and
        runtimehooks react to state changes."""
        self._callbacks.append(cb)

    def _notify(self, what: str) -> None:
        for cb in self._callbacks:
            cb(what)

    def set_node(self, node: Mapping) -> None:
        with self._lock:
            self._node = dict(node)
        self._notify("node")

    def get_node(self) -> Dict:
        with self._lock:
            return dict(self._node)

    def set_pods(self, pods: Sequence[PodMeta], specs: Optional[Mapping] = None) -> None:
        with self._lock:
            self._pods = list(pods)
            if specs is not None:
                self._pod_specs = dict(specs)
        self._notify("pods")

    def get_all_pods(self) -> List[PodMeta]:
        with self._lock:
            return list(self._pods)

    def sync_from_kubelet(self, stub) -> int:
        """Pull the authoritative pod list from the kubelet endpoint
        (reference ``impl/states_pods.go`` syncPods via the kubelet stub)
        and refresh the informer's pod view.  Returns the pod count."""
        items = stub.get_all_pods()
        pods: List[PodMeta] = []
        specs: Dict[str, Dict] = {}
        for item in items:
            meta = item.get("metadata") or {}
            status = item.get("status") or {}
            labels = meta.get("labels") or {}
            uid = meta.get("uid", meta.get("name", ""))
            pods.append(
                PodMeta(
                    name=meta.get("name", ""),
                    uid=uid,
                    qos=status.get("qosClass", "Burstable"),
                    koord_qos=labels.get("koordinator.sh/qosClass", ""),
                    namespace=meta.get("namespace", "default"),
                )
            )
            specs[uid] = item.get("spec") or {}
        self.set_pods(pods, specs)
        return len(pods)

    def get_pod_spec(self, uid: str) -> Dict:
        with self._lock:
            return dict(self._pod_specs.get(uid, {}))

    def set_node_slo(self, slo: Mapping) -> None:
        with self._lock:
            self._node_slo = dict(slo)
        self._notify("nodeslo")

    def get_node_slo(self) -> Dict:
        with self._lock:
            return dict(self._node_slo)

    def set_node_topo(self, topo: Mapping) -> None:
        with self._lock:
            self._node_topo = dict(topo)
        self._notify("nodetopo")

    def get_node_topo(self) -> Dict:
        with self._lock:
            return dict(self._node_topo)


class NodeMetricReporter:
    """Builds the NodeMetric.Status payload (states_nodemetric.go:324
    collectMetric): window AVG node/system/pod usage plus the aggregated
    P50/P90/P95/P99 node usage the LoadAware plugin's aggregated mode and
    the prod-usage estimator consume."""

    def __init__(
        self,
        cache: MetricCache,
        informer: StatesInformer,
        policy: Optional[CollectPolicy] = None,
    ):
        self.cache = cache
        self.informer = informer
        self.policy = policy or CollectPolicy()

    def _node_usage(self, start: float, end: float, agg: str) -> Optional[Dict]:
        cpu = self.cache.query(mc.NODE_CPU_USAGE, start=start, end=end, agg=agg)
        memory = self.cache.query(mc.NODE_MEMORY_USAGE, start=start, end=end, agg=agg)
        if cpu is None and memory is None:
            return None
        return {
            "cpu": f"{int(round((cpu or 0.0) * 1000))}m",
            "memory": str(int(memory or 0)),
        }

    def collect(self, now: float) -> Optional[Dict]:
        """One NodeMetric.Status dict, or None when metrics are absent
        (the manager then degrades, noderesource degradeCalculate)."""
        start = now - self.policy.aggregate_duration_seconds
        node_usage = self._node_usage(start, now, mc.AGG_AVG)
        if node_usage is None:
            return None

        pods_usage = []
        for pod in self.informer.get_all_pods():
            labels = {"pod": pod.uid}
            cpu = self.cache.query(
                mc.POD_CPU_USAGE, start=start, end=now, agg=mc.AGG_AVG, labels=labels
            )
            memory = self.cache.query(
                mc.POD_MEMORY_USAGE,
                start=start,
                end=now,
                agg=mc.AGG_AVG,
                labels=labels,
            )
            if cpu is None and memory is None:
                continue
            pods_usage.append(
                {
                    "namespace": pod.namespace,
                    "name": pod.name,
                    "uid": pod.uid,
                    "usage": {
                        "cpu": f"{int(round((cpu or 0.0) * 1000))}m",
                        "memory": str(int(memory or 0)),
                    },
                }
            )

        sys_cpu = self.cache.query(
            mc.SYS_CPU_USAGE, start=start, end=now, agg=mc.AGG_AVG
        )
        aggregated = {
            name: usage
            for name, agg in (
                ("p50", mc.AGG_P50),
                ("p90", mc.AGG_P90),
                ("p95", mc.AGG_P95),
                ("p99", mc.AGG_P99),
            )
            if (usage := self._node_usage(start, now, agg)) is not None
        }
        return {
            "updateTime": now,
            "nodeMetric": {
                "nodeUsage": node_usage,
                "systemUsage": (
                    {"cpu": f"{int(round((sys_cpu or 0.0) * 1000))}m"}
                    if sys_cpu is not None
                    else {}
                ),
                "aggregatedNodeUsages": aggregated,
            },
            "podsMetric": pods_usage,
        }
