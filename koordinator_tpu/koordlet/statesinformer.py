"""States informer: node/pod/NodeSLO state + NodeMetric reporting.

Reference: ``pkg/koordlet/statesinformer`` — plugin-registered informers
sync apiserver state into the agent and report back ``NodeMetric.Status``
(``impl/states_nodemetric.go:237 sync``, ``:324 collectMetric``: windowed
AVG node/pod usage plus P50/P90/P95/P99 aggregated usage) and the
NodeResourceTopology CR (``impl/states_noderesourcetopology.go``).

This rebuild keeps the informer as plain state + callbacks (no apiserver in
the loop); the report dicts are the CR payloads the manager controllers
(``koordinator_tpu.manager``) consume directly.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.collectors import PodMeta
from koordinator_tpu.koordlet.metriccache import MetricCache

DEFAULT_AGGREGATE_DURATION_SECONDS = 300.0  # collect policy default
DEFAULT_REPORT_INTERVAL_SECONDS = 60.0


@dataclasses.dataclass
class CollectPolicy:
    """NodeMetric spec collect policy (reference
    slo-controller/nodemetric/collect_policy.go defaults)."""

    aggregate_duration_seconds: float = DEFAULT_AGGREGATE_DURATION_SECONDS
    report_interval_seconds: float = DEFAULT_REPORT_INTERVAL_SECONDS


class StatesInformer:
    """Holds node/pods/NodeSLO state; thread-safe snapshot accessors
    (states_informer.go:105 GetAllPods/GetNode/GetNodeSLO)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._node: Dict = {}
        self._pods: List[PodMeta] = []
        self._pod_specs: Dict[str, Dict] = {}
        self._node_slo: Dict = {}
        self._node_topo: Dict = {}
        self._devices: List[Dict] = []
        self._plugins: List = []
        self._callbacks: List[Callable[[str], None]] = []

    def register_callback(self, cb: Callable[[str], None]) -> None:
        """reference statesinformer RegisterCallbacks: qosmanager and
        runtimehooks react to state changes."""
        self._callbacks.append(cb)

    def _notify(self, what: str) -> None:
        for cb in self._callbacks:
            cb(what)

    def set_node(self, node: Mapping) -> None:
        with self._lock:
            self._node = dict(node)
        self._notify("node")

    def get_node(self) -> Dict:
        with self._lock:
            return dict(self._node)

    def set_pods(self, pods: Sequence[PodMeta], specs: Optional[Mapping] = None) -> None:
        with self._lock:
            self._pods = list(pods)
            if specs is not None:
                self._pod_specs = dict(specs)
        self._notify("pods")

    def get_all_pods(self) -> List[PodMeta]:
        with self._lock:
            return list(self._pods)

    def sync_from_kubelet(self, stub) -> int:
        """Pull the authoritative pod list from the kubelet endpoint
        (reference ``impl/states_pods.go`` syncPods via the kubelet stub)
        and refresh the informer's pod view.  Returns the pod count."""
        items = stub.get_all_pods()
        pods: List[PodMeta] = []
        specs: Dict[str, Dict] = {}
        for item in items:
            meta = item.get("metadata") or {}
            status = item.get("status") or {}
            labels = meta.get("labels") or {}
            uid = meta.get("uid", meta.get("name", ""))
            pods.append(
                PodMeta(
                    name=meta.get("name", ""),
                    uid=uid,
                    qos=status.get("qosClass", "Burstable"),
                    koord_qos=labels.get("koordinator.sh/qosClass", ""),
                    namespace=meta.get("namespace", "default"),
                )
            )
            specs[uid] = item.get("spec") or {}
        self.set_pods(pods, specs)
        return len(pods)

    def get_pod_spec(self, uid: str) -> Dict:
        with self._lock:
            return dict(self._pod_specs.get(uid, {}))

    def set_node_slo(self, slo: Mapping) -> None:
        with self._lock:
            self._node_slo = dict(slo)
        self._notify("nodeslo")

    def get_node_slo(self) -> Dict:
        with self._lock:
            return dict(self._node_slo)

    def set_node_topo(self, topo: Mapping) -> None:
        with self._lock:
            self._node_topo = dict(topo)
        self._notify("nodetopo")

    def get_node_topo(self) -> Dict:
        with self._lock:
            return dict(self._node_topo)

    def set_devices(self, devices: Sequence[Mapping]) -> None:
        with self._lock:
            self._devices = [dict(d) for d in devices]
        self._notify("devices")

    def get_devices(self) -> List[Dict]:
        with self._lock:
            return [dict(d) for d in self._devices]

    # -- plugin registry (reference impl/registry.go: informer plugins
    # registered by name, set up once, synced by the informer loop) --
    def register_plugin(self, plugin) -> None:
        with self._lock:
            self._plugins.append(plugin)

    def sync_plugins(self, now: float) -> Dict[str, object]:
        """Run every registered informer plugin once; returns name ->
        report (None when a plugin had nothing to publish).  A failing
        plugin is logged and skipped — the reference koordlet continues
        past informer-plugin errors rather than killing the daemon."""
        import logging

        with self._lock:
            plugins = list(self._plugins)
        out: Dict[str, object] = {}
        for p in plugins:
            try:
                out[p.name] = p.sync(now)
            except Exception:
                logging.getLogger(__name__).exception(
                    "informer plugin %s sync failed", p.name
                )
                out[p.name] = None
        return out


class NodeMetricReporter:
    """Builds the NodeMetric.Status payload (states_nodemetric.go:324
    collectMetric): window AVG node/system/pod usage plus the aggregated
    P50/P90/P95/P99 node usage the LoadAware plugin's aggregated mode and
    the prod-usage estimator consume."""

    def __init__(
        self,
        cache: MetricCache,
        informer: StatesInformer,
        policy: Optional[CollectPolicy] = None,
    ):
        self.cache = cache
        self.informer = informer
        self.policy = policy or CollectPolicy()

    def _node_usage(self, start: float, end: float, agg: str) -> Optional[Dict]:
        cpu = self.cache.query(mc.NODE_CPU_USAGE, start=start, end=end, agg=agg)
        memory = self.cache.query(mc.NODE_MEMORY_USAGE, start=start, end=end, agg=agg)
        if cpu is None and memory is None:
            return None
        return {
            "cpu": f"{int(round((cpu or 0.0) * 1000))}m",
            "memory": str(int(memory or 0)),
        }

    def collect(self, now: float) -> Optional[Dict]:
        """One NodeMetric.Status dict, or None when metrics are absent
        (the manager then degrades, noderesource degradeCalculate)."""
        start = now - self.policy.aggregate_duration_seconds
        node_usage = self._node_usage(start, now, mc.AGG_AVG)
        if node_usage is None:
            return None

        pods_usage = []
        for pod in self.informer.get_all_pods():
            labels = {"pod": pod.uid}
            cpu = self.cache.query(
                mc.POD_CPU_USAGE, start=start, end=now, agg=mc.AGG_AVG, labels=labels
            )
            memory = self.cache.query(
                mc.POD_MEMORY_USAGE,
                start=start,
                end=now,
                agg=mc.AGG_AVG,
                labels=labels,
            )
            if cpu is None and memory is None:
                continue
            pods_usage.append(
                {
                    "namespace": pod.namespace,
                    "name": pod.name,
                    "uid": pod.uid,
                    "usage": {
                        "cpu": f"{int(round((cpu or 0.0) * 1000))}m",
                        "memory": str(int(memory or 0)),
                    },
                }
            )

        sys_cpu = self.cache.query(
            mc.SYS_CPU_USAGE, start=start, end=now, agg=mc.AGG_AVG
        )
        aggregated = {
            name: usage
            for name, agg in (
                ("p50", mc.AGG_P50),
                ("p90", mc.AGG_P90),
                ("p95", mc.AGG_P95),
                ("p99", mc.AGG_P99),
            )
            if (usage := self._node_usage(start, now, agg)) is not None
        }
        return {
            "updateTime": now,
            "nodeMetric": {
                "nodeUsage": node_usage,
                "systemUsage": (
                    {"cpu": f"{int(round((sys_cpu or 0.0) * 1000))}m"}
                    if sys_cpu is not None
                    else {}
                ),
                "aggregatedNodeUsages": aggregated,
            },
            "podsMetric": pods_usage,
        }


class NodeTopoReporter:
    """NodeResourceTopology producer (reference
    ``impl/states_noderesourcetopology.go``): reads the host CPU/NUMA
    layout from sysfs, builds the NRT report — per-NUMA-zone allocatable
    resources plus the CPU topology detail the scheduler's cpuset
    accumulator consumes (``scheduler/topology_options.go``) — and
    publishes it through the informer store.

    The report dict IS the CR payload: the scheduler side turns a set of
    them into the NodeNUMAResource plugin's ZoneBatch extras via
    ``zones_from_node_topos`` + ``model.topology.encode_zones``.
    """

    name = "nodetopo"

    def __init__(self, fs, informer: StatesInformer, node_name: str = ""):
        self.fs = fs
        self.informer = informer
        self.node_name = node_name
        self._last: Optional[Dict] = None

    def build(self) -> Optional[Dict]:
        detail = self.fs.cpu_topology()
        if not detail:
            return None
        zones = []
        for numa in sorted({node for _, _, node, _ in detail}):
            cpus = [c for c, _, node, _ in detail if node == numa]
            zones.append(
                {
                    "name": f"node-{numa}",
                    "type": "Node",
                    "resources": {
                        "cpu": f"{len(cpus) * 1000}m",
                        "memory": self.fs.numa_node_memory_bytes(numa),
                    },
                    "cpus": cpus,
                }
            )
        return {
            "name": self.node_name,
            "zones": zones,
            "cpuTopology": {
                "detail": [
                    {"cpu": c, "core": core, "node": node, "socket": sock}
                    for c, core, node, sock in detail
                ]
            },
        }

    def sync(self, now: float) -> Optional[Dict]:
        report = self.build()
        # publish (and fire informer callbacks) only on change: the
        # topology is static, so every tick re-notifying qosmanager /
        # runtimehooks reactions would be pure churn
        if report is not None and report != self._last:
            self.informer.set_node_topo(report)
            self._last = report
        return report


class DeviceReporter:
    """Device CR producer (reference ``impl/states_device.go``: the GPU
    device informer reports the Device CR the DeviceShare plugin
    consumes; here accelerators come from JAX/libtpu enumeration)."""

    name = "device"

    def __init__(self, informer: StatesInformer, devices_fn=None):
        self.informer = informer
        if devices_fn is None:
            from koordinator_tpu.koordlet.collectors import _jax_devices

            devices_fn = _jax_devices
        self.devices_fn = devices_fn
        self._last: Optional[List[Dict]] = None

    def sync(self, now: float) -> List[Dict]:
        devices = []
        for dev in self.devices_fn():
            # the default enumeration (collectors._jax_devices) yields
            # {"minor", "platform"}; only accelerators become CR entries —
            # a CPU-only host must not publish phantom devices
            dev_type = dev.get("type") or dev.get("platform", "")
            if dev_type in ("", "cpu"):
                continue
            devices.append(
                {
                    "type": dev_type,
                    "minor": int(dev.get("minor", 0)),
                    "health": bool(dev.get("health", True)),
                    "resources": dev.get("resources", {}),
                    "topology": {"numaNode": int(dev.get("numa_node", 0))},
                }
            )
        # publish (and fire informer callbacks) only on change, like
        # NodeTopoReporter — device lists are near-static
        if devices != self._last:
            self.informer.set_devices(devices)
            self._last = devices
        return devices


def zones_from_node_topos(topos: Sequence[Mapping]) -> List[Dict]:
    """Adapt published NRT reports into the node-dict shape
    ``model.topology.encode_zones`` consumes — the producer half feeding
    the scheduler's NodeNUMAResource zone tensors, replacing hand-built
    test fixtures (round-3 review #6)."""
    out: List[Dict] = []
    for topo in topos:
        out.append(
            {
                "name": topo.get("name", ""),
                "zones": [
                    {
                        "allocatable": z.get("resources", {}),
                        "requested": z.get("requested", {}),
                    }
                    for z in topo.get("zones", ())
                ],
                "cpu_amplification": topo.get("cpu_amplification"),
            }
        )
    return out


def device_nodes_from_informers(
    device_lists: Sequence[Sequence[Mapping]],
) -> List[Dict]:
    """Adapt published Device CRs (DeviceReporter output, one list per
    node) into the node-dict shape ``model.device.encode_devices``
    consumes — the producer half feeding the DeviceShare plugin's
    tensors, mirroring ``zones_from_node_topos`` for NRT.

    Unhealthy devices stay IN the list (``encode_devices`` keeps their
    minor slot with ``valid=False``) — dropping one would renumber its
    neighbors, and slot index is the device identity the Reserve path
    reports back."""
    out: List[Dict] = []
    for devices in device_lists:
        out.append(
            {
                "devices": [
                    {
                        "type": d.get("type", "gpu"),
                        "minor": d.get("minor", 0),
                        "total": d.get("resources", {}),
                        "topology": d.get("topology", {}),
                        "health": bool(d.get("health", True)),
                    }
                    for d in devices
                ]
            }
        )
    return out
