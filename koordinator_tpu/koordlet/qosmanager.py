"""QoS manager: strategy framework + the standard strategy set.

Reference: ``pkg/koordlet/qosmanager`` — ``framework/strategy.go:21
QOSStrategy`` plugins on independent ticks (``qosmanager.go:92``), registry
``plugins/register.go``: cpusuppress, cpuevict, memoryevict, cpuburst,
cgreconcile, resctrl, blkio, sysreconcile.

Every strategy is a pure-ish function of (statesinformer, metriccache,
NodeSLO strategy config) emitting writes through the
ResourceUpdateExecutor, so the whole actuation path is testable against a
fake fs.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.collectors import PodMeta
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.resourceexecutor import (
    ResourceUpdate,
    ResourceUpdateExecutor,
    format_cpuset,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.sysfs import (
    KUBEPODS_BESTEFFORT,
    pod_cgroup_dir,
)

CFS_PERIOD_US = 100_000  # kernel default, the reference assumes it too


class QOSStrategy:
    """framework/strategy.go:21 — Enabled + periodic tick."""

    name = "strategy"
    interval_seconds = 1.0

    def enabled(self) -> bool:
        return True

    def tick(self, now: float) -> None:
        raise NotImplementedError


@dataclasses.dataclass
class Evicted:
    pod: PodMeta
    reason: str


class Evictor:
    """Eviction sink (the reference calls the apiserver eviction API;
    here a callback records/performs it).  The ledger is bounded: a
    strategy that keeps re-selecting the same victim (its condition only
    clears once the pod is really gone) must not grow memory without
    bound in the live loop."""

    def __init__(
        self,
        evict_fn: Optional[Callable[[PodMeta, str], bool]] = None,
        max_ledger: int = 1024,
    ):
        self.evict_fn = evict_fn
        self.max_ledger = max_ledger
        self.evicted: List[Evicted] = []

    def evict(self, pod: PodMeta, reason: str) -> bool:
        if self.evict_fn is not None and not self.evict_fn(pod, reason):
            return False
        self.evicted.append(Evicted(pod, reason))
        if len(self.evicted) > self.max_ledger:
            # explicit length arithmetic: a [-max_ledger:] slice would be
            # a no-op at max_ledger=0 (negative zero slicing)
            del self.evicted[: len(self.evicted) - max(self.max_ledger, 0)]
        return True


# ---------------------------------------------------------------------------
# CPUSuppress
# ---------------------------------------------------------------------------


def calculate_be_suppress_cpu(
    node_capacity_milli: int,
    node_usage_cores: float,
    pod_usages_cores: Mapping[str, float],
    pod_is_be: Mapping[str, bool],
    be_cpu_used_threshold_percent: int,
    *,
    node_anno_reserved_milli: int = 0,
    kubelet_reserved_milli: int = 0,
) -> int:
    """Milli-CPUs BE pods may use (reference cpu_suppress.go:139
    calculateBESuppressCPU):

    ``suppress(BE) = capacity * SLOPercent - pod(non-BE).used
    - max(system.used, node.anno.reserved, kubelet.reserved)``
    where ``system.used = max(0, nodeUsed - sum(podUsed))``.
    """
    pod_all = sum(pod_usages_cores.values())
    pod_none_be = sum(
        u for uid, u in pod_usages_cores.items() if not pod_is_be.get(uid, False)
    )
    system_used = max(0.0, node_usage_cores - pod_all)
    system_used_milli = max(
        int(system_used * 1000), node_anno_reserved_milli, kubelet_reserved_milli
    )
    return (
        node_capacity_milli * be_cpu_used_threshold_percent // 100
        - int(pod_none_be * 1000)
        - system_used_milli
    )


class CPUSuppressStrategy(QOSStrategy):
    """Suppress the BestEffort tree to the SLO-allowed CPU share
    (cpu_suppress.go:269 suppressBECPU): by cpuset (count of cpus) or by
    cfs quota on the BE root."""

    name = "cpusuppress"

    def __init__(
        self,
        informer: StatesInformer,
        cache: MetricCache,
        executor: ResourceUpdateExecutor,
        *,
        policy: str = "cfsQuota",  # or "cpuset"
        metric_window_seconds: float = 60.0,
    ):
        self.informer = informer
        self.cache = cache
        self.executor = executor
        self.policy = policy
        self.window = metric_window_seconds

    def enabled(self) -> bool:
        slo = self.informer.get_node_slo()
        be = (slo.get("resourceUsedThresholdWithBE") or {})
        return bool(be.get("enable", False))

    def tick(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        be_cfg = slo.get("resourceUsedThresholdWithBE") or {}
        threshold = int(be_cfg.get("cpuSuppressThresholdPercent", 65))
        node = self.informer.get_node()
        capacity_milli = int(node.get("capacity_milli_cpu", 0))
        if capacity_milli <= 0:
            return
        node_usage = self.cache.query(
            mc.NODE_CPU_USAGE, start=now - self.window, end=now, agg=mc.AGG_AVG
        )
        if node_usage is None:
            return
        pods = self.informer.get_all_pods()
        pod_usages: Dict[str, float] = {}
        pod_is_be: Dict[str, bool] = {}
        for pod in pods:
            u = self.cache.query(
                mc.POD_CPU_USAGE,
                start=now - self.window,
                end=now,
                agg=mc.AGG_AVG,
                labels={"pod": pod.uid},
            )
            if u is not None:
                pod_usages[pod.uid] = u
            pod_is_be[pod.uid] = pod.koord_qos == "BE" or pod.qos == "BestEffort"

        suppress_milli = calculate_be_suppress_cpu(
            capacity_milli,
            node_usage,
            pod_usages,
            pod_is_be,
            threshold,
            node_anno_reserved_milli=int(node.get("anno_reserved_milli_cpu", 0)),
            kubelet_reserved_milli=int(node.get("kubelet_reserved_milli_cpu", 0)),
        )
        suppress_milli = max(suppress_milli, 0)

        if self.policy == "cpuset":
            # round up to whole cpus, at least 1 (cpu_suppress.go
            # calculateBESuppressCPUSetPolicy keeps BE pods schedulable)
            num_cpus = max(1, math.ceil(suppress_milli / 1000))
            all_cpus = list(range(capacity_milli // 1000))
            chosen = all_cpus[-num_cpus:] if num_cpus <= len(all_cpus) else all_cpus
            self.executor.update(
                ResourceUpdate(
                    "cpuset.cpus", KUBEPODS_BESTEFFORT, format_cpuset(chosen)
                ),
                now,
            )
        else:
            quota = max(suppress_milli * CFS_PERIOD_US // 1000, 1000)
            self.executor.update(
                ResourceUpdate("cpu.cfs_quota", KUBEPODS_BESTEFFORT, str(quota)), now
            )


# ---------------------------------------------------------------------------
# CPUBurst
# ---------------------------------------------------------------------------


class CPUBurstStrategy(QOSStrategy):
    """Set cfs burst for LS pods (reference
    qosmanager/plugins/cpuburst/cpu_burst.go): burst quota =
    limit * cpuBurstPercent / 100, written to cpu.cfs_burst_us."""

    name = "cpuburst"

    def __init__(
        self,
        informer: StatesInformer,
        executor: ResourceUpdateExecutor,
    ):
        self.informer = informer
        self.executor = executor

    def enabled(self) -> bool:
        slo = self.informer.get_node_slo()
        return (slo.get("cpuBurstStrategy") or {}).get("policy", "none") != "none"

    def tick(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        cfg = slo.get("cpuBurstStrategy") or {}
        burst_percent = int(cfg.get("cpuBurstPercent", 1000))
        for pod in self.informer.get_all_pods():
            if pod.koord_qos not in ("LS", ""):
                continue
            spec = self.informer.get_pod_spec(pod.uid)
            limit_milli = int(spec.get("limit_milli_cpu", 0))
            if limit_milli <= 0:
                continue
            burst_us = limit_milli * CFS_PERIOD_US // 1000 * burst_percent // 100
            cgdir = pod_cgroup_dir(pod.qos, pod.uid)
            self.executor.update(
                ResourceUpdate("cpu.cfs_burst", cgdir, str(burst_us)), now
            )


# ---------------------------------------------------------------------------
# CPU / memory eviction
# ---------------------------------------------------------------------------


class CPUEvictStrategy(QOSStrategy):
    """Evict BE pods when their CPU satisfaction stays below threshold
    (reference qosmanager/plugins/cpuevict/cpu_evict.go): satisfaction =
    realLimit / request; below ``lowPercent`` for the window -> evict by
    priority until the gap clears."""

    name = "cpuevict"

    def __init__(
        self,
        informer: StatesInformer,
        cache: MetricCache,
        evictor: Evictor,
        *,
        window_seconds: float = 60.0,
    ):
        self.informer = informer
        self.cache = cache
        self.evictor = evictor
        self.window = window_seconds

    def enabled(self) -> bool:
        slo = self.informer.get_node_slo()
        be = slo.get("resourceUsedThresholdWithBE") or {}
        return be.get("cpuEvictPolicy", "none") != "none"

    def tick(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        be = slo.get("resourceUsedThresholdWithBE") or {}
        low = int(be.get("cpuEvictBESatisfactionLowerPercent", 60))
        be_usage = self.cache.query(
            mc.BE_CPU_USAGE, start=now - self.window, end=now, agg=mc.AGG_AVG
        )
        if be_usage is None:
            return
        be_pods = [
            p
            for p in self.informer.get_all_pods()
            if p.koord_qos == "BE" or p.qos == "BestEffort"
        ]
        request_milli = sum(
            int(self.informer.get_pod_spec(p.uid).get("request_milli_cpu", 0))
            for p in be_pods
        )
        if request_milli <= 0:
            return
        satisfaction = be_usage * 1000 * 100 / request_milli
        if satisfaction >= low:
            return
        # evict the lowest-priority BE pods first until the shortfall clears
        shortfall = request_milli * (low - satisfaction) / 100
        for pod in sorted(
            be_pods,
            key=lambda p: int(self.informer.get_pod_spec(p.uid).get("priority", 0)),
        ):
            if shortfall <= 0:
                break
            if self.evictor.evict(pod, "cpu satisfaction below threshold"):
                shortfall -= int(
                    self.informer.get_pod_spec(pod.uid).get("request_milli_cpu", 0)
                )


class MemoryEvictStrategy(QOSStrategy):
    """Evict BE pods when node memory usage exceeds the threshold
    (reference qosmanager/plugins/memoryevict/memory_evict.go), lowest
    priority first, until below the lower percent."""

    name = "memoryevict"

    def __init__(
        self,
        informer: StatesInformer,
        cache: MetricCache,
        evictor: Evictor,
        *,
        window_seconds: float = 60.0,
    ):
        self.informer = informer
        self.cache = cache
        self.evictor = evictor
        self.window = window_seconds

    def enabled(self) -> bool:
        slo = self.informer.get_node_slo()
        be = slo.get("resourceUsedThresholdWithBE") or {}
        return be.get("memoryEvictThresholdPercent") is not None

    def tick(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        be = slo.get("resourceUsedThresholdWithBE") or {}
        threshold = int(be.get("memoryEvictThresholdPercent", 70))
        lower = int(be.get("memoryEvictLowerPercent", threshold - 2))
        node = self.informer.get_node()
        capacity = int(node.get("capacity_memory_bytes", 0))
        if capacity <= 0:
            return
        usage = self.cache.query(
            mc.NODE_MEMORY_USAGE, start=now - self.window, end=now, agg=mc.AGG_LATEST
        )
        if usage is None or usage * 100 / capacity < threshold:
            return
        to_release = usage - capacity * lower / 100
        be_pods = [
            p
            for p in self.informer.get_all_pods()
            if p.koord_qos == "BE" or p.qos == "BestEffort"
        ]
        for pod in sorted(
            be_pods,
            key=lambda p: int(self.informer.get_pod_spec(p.uid).get("priority", 0)),
        ):
            if to_release <= 0:
                break
            mem = self.cache.query(
                mc.POD_MEMORY_USAGE,
                start=now - self.window,
                end=now,
                agg=mc.AGG_LATEST,
                labels={"pod": pod.uid},
            )
            if self.evictor.evict(pod, "node memory usage above threshold"):
                to_release -= mem or 0


# ---------------------------------------------------------------------------
# Reconcilers: cgroup QoS params / resctrl / blkio / sysctl
# ---------------------------------------------------------------------------

# QoS-class cgroup parameters (reference runtimehooks/hooks/groupidentity
# bvt values; cgreconcile cpu shares)
BVT_BY_QOS = {"LSE": 2, "LSR": 2, "LS": 2, "BE": -1, "SYSTEM": 0, "": 0}


class CgroupReconcileStrategy(QOSStrategy):
    """Keep per-QoS-tree cgroup params converged (reference
    qosmanager/plugins/cgreconcile): BE tree gets minimal cpu shares and
    bvt -1; burstable keeps defaults."""

    name = "cgreconcile"

    def __init__(self, informer: StatesInformer, executor: ResourceUpdateExecutor):
        self.informer = informer
        self.executor = executor

    def tick(self, now: float) -> None:
        updates = [
            ResourceUpdate("cpu.shares", KUBEPODS_BESTEFFORT, "2"),
            ResourceUpdate("cpu.bvt_warp_ns", KUBEPODS_BESTEFFORT, "-1"),
        ]
        self.executor.update_batch(updates, now)


def calculate_cat_l3_mask(cbm: int, start_percent: int, end_percent: int) -> str:
    """reference ``util/system/resctrl.go:558 CalculateCatL3MaskValue``:
    contiguous way mask covering [start% * ways, end% * ways), hex.

    The root cbm must be a full mask (all ones): x86 CAT requires
    contiguous '1' bits and the root group exposes every way."""
    if cbm <= 0 or bin(cbm + 1).count("1") != 1:
        raise ValueError(f"illegal cbm {cbm:#x}")
    if start_percent < 0 or end_percent > 100 or end_percent <= start_percent:
        raise ValueError(
            f"illegal l3 cat percent: start {start_percent}, end {end_percent}"
        )
    ways = cbm.bit_length()
    start_way = math.ceil(ways * start_percent / 100)
    end_way = math.ceil(ways * end_percent / 100)
    if end_way <= start_way:
        # a narrow interval rounding to the same way boundary would yield
        # an empty CBM the kernel rejects with EINVAL
        raise ValueError(
            f"empty l3 way interval: start {start_percent}%, end "
            f"{end_percent}% both round to way {start_way} of {ways}"
        )
    return format((1 << end_way) - (1 << start_way), "x")


class ResctrlStrategy(QOSStrategy):
    """L3 cache / memory-bandwidth isolation groups (reference
    qosmanager/plugins/resctrl + resourceexecutor/resctrl_updater.go):
    create the LS/BE/LSR groups, write L3 way-interval + MB percent
    schemata from NodeSLO, and bind each QoS class's tasks into its
    group's tasks file (appending one pid per write, duplicates dropped —
    ``resctrl_updater.go:143-146``)."""

    name = "resctrl"

    # QoS class -> resctrl group (reference init: LSR/LS share LS by default)
    GROUPS = ("LSR", "LS", "BE")

    def __init__(
        self, informer: StatesInformer, executor: ResourceUpdateExecutor, *,
        cbm: int = 0xFFF, num_l3: int = 1
    ):
        self.informer = informer
        self.executor = executor
        self.cbm = cbm
        self.num_l3 = num_l3

    def enabled(self) -> bool:
        slo = self.informer.get_node_slo()
        return (slo.get("resctrlQOS") or {}).get("enable", False)

    def _root(self) -> str:
        return f"{self.executor.fs.root}/sys/fs/resctrl"

    def _schemata(self, qos_cfg: Mapping) -> str:
        start = int(qos_cfg.get("catRangeStartPercent", 0))
        end = int(qos_cfg.get("catRangeEndPercent", 100))
        mask = calculate_cat_l3_mask(self.cbm, start, end)
        l3 = ";".join(f"{i}={mask}" for i in range(self.num_l3))
        lines = [f"L3:{l3}"]
        mba = qos_cfg.get("mbaPercent")
        if mba is not None:
            mb = ";".join(f"{i}={int(mba)}" for i in range(self.num_l3))
            lines.append(f"MB:{mb}")
        return "\n".join(lines) + "\n"

    def tick(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        cfg = slo.get("resctrlQOS") or {}
        class_key = {"LSR": "lsrClass", "LS": "lsClass", "BE": "beClass"}
        for group in self.GROUPS:
            qos_cfg = (cfg.get(class_key[group]) or {}).get("resctrlQOS")
            if qos_cfg is None and group == "LSR":
                # LSR falls back to the LS class config (reference default)
                qos_cfg = (cfg.get("lsClass") or {}).get("resctrlQOS")
            if qos_cfg is None:
                qos_cfg = {}
            gdir = f"{self._root()}/{group}"
            os.makedirs(gdir, exist_ok=True)  # resctrl group = mkdir
            try:
                schemata = self._schemata(qos_cfg)
            except ValueError:
                # malformed NodeSLO percentages must not kill the daemon
                # loop: skip this group's update, keep the others running
                continue
            self.executor.fs.write(f"{gdir}/schemata", schemata)
            # task binding: one pid per appending write() call — the
            # kernel interface binds per write (resctrl_updater.go:143-146).
            # Membership truth lives in the group's tasks file (the kernel
            # drops dead pids itself), so re-reading it each tick handles
            # pid recycling with no cache to go stale.
            pids = set(self._group_tasks(group))
            tasks_path = f"{gdir}/tasks"
            bound = set()
            current = self.executor.fs.read(tasks_path)
            if current:
                bound = {int(t) for t in current.split() if t.isdigit()}
            for pid in sorted(pids - bound):
                self._append_task(tasks_path, pid)

    @staticmethod
    def _append_task(path: str, pid: int) -> bool:
        """One pid per O_APPEND write, never a truncate-rewrite; a failed
        write (task exited mid-bind, EPERM) is retried next tick."""
        try:
            with open(path, "a") as fh:
                fh.write(f"{pid}\n")
            return True
        except OSError:
            return False

    def _group_tasks(self, group: str):
        """All pids of pods in the group's koord QoS class, read from each
        pod's cgroup.procs (the reference walks the pod cgroup dirs the
        same way, ``resctrl.go`` task collection)."""
        out = []
        for pod in self.informer.get_all_pods():
            koord_qos = pod.koord_qos or "LS"
            if koord_qos == "LSE":  # LSE never shares a CAT group
                continue
            target = "LSR" if koord_qos == "LSR" else (
                "BE" if koord_qos == "BE" else "LS"
            )
            if target != group:
                continue
            fs = self.executor.fs
            procs = fs.read(
                f"{fs.root}/{fs.cgroup_mount}/"
                f"{pod_cgroup_dir(pod.qos, pod.uid)}/cgroup.procs"
            )
            if procs:
                out.extend(
                    int(line) for line in procs.split() if line.isdigit()
                )
        return out


class BlkIOReconcileStrategy(QOSStrategy):
    """Throttle BE block IO (reference qosmanager/plugins/blkio): write
    read/write bps limits from NodeSLO blkioQOS config."""

    name = "blkio"

    def __init__(self, informer: StatesInformer, executor: ResourceUpdateExecutor):
        self.informer = informer
        self.executor = executor

    def enabled(self) -> bool:
        slo = self.informer.get_node_slo()
        return bool(slo.get("blkioQOS"))

    def tick(self, now: float) -> None:
        slo = self.informer.get_node_slo()
        for blk in slo.get("blkioQOS") or []:
            dev = blk.get("device", "253:0")
            if blk.get("readBPS"):
                self.executor.update(
                    ResourceUpdate(
                        "blkio.throttle.read_bps",
                        KUBEPODS_BESTEFFORT,
                        f"{dev} {blk['readBPS']}",
                    ),
                    now,
                )
            if blk.get("writeBPS"):
                self.executor.update(
                    ResourceUpdate(
                        "blkio.throttle.write_bps",
                        KUBEPODS_BESTEFFORT,
                        f"{dev} {blk['writeBPS']}",
                    ),
                    now,
                )


class SystemReconcileStrategy(QOSStrategy):
    """Node-level sysctl knobs (reference qosmanager/plugins/sysreconcile):
    min_free_kbytes / watermark_scale_factor from NodeSLO systemStrategy."""

    name = "sysreconcile"

    def __init__(self, informer: StatesInformer, executor: ResourceUpdateExecutor):
        self.informer = informer
        self.executor = executor

    def enabled(self) -> bool:
        return bool(self.informer.get_node_slo().get("systemStrategy"))

    def tick(self, now: float) -> None:
        cfg = self.informer.get_node_slo().get("systemStrategy") or {}
        fs = self.executor.fs
        if "minFreeKbytesFactor" in cfg:
            node = self.informer.get_node()
            total_kb = int(node.get("capacity_memory_bytes", 0)) // 1024
            v = total_kb * int(cfg["minFreeKbytesFactor"]) // 10000
            fs.write(fs.proc_path("sys/vm/min_free_kbytes"), str(v))
        if "watermarkScaleFactor" in cfg:
            fs.write(
                fs.proc_path("sys/vm/watermark_scale_factor"),
                str(cfg["watermarkScaleFactor"]),
            )


def default_qos_strategies(
    informer: StatesInformer,
    cache: MetricCache,
    executor: ResourceUpdateExecutor,
    evictor: Evictor,
) -> List[QOSStrategy]:
    """The reference's full battery (plugins/register.go) — the ONE
    wiring both daemon builders share, so they cannot drift."""
    return [
        CPUSuppressStrategy(informer, cache, executor),
        CPUBurstStrategy(informer, executor),
        CPUEvictStrategy(informer, cache, evictor),
        MemoryEvictStrategy(informer, cache, evictor),
        CgroupReconcileStrategy(informer, executor),
        ResctrlStrategy(informer, executor),
        BlkIOReconcileStrategy(informer, executor),
        SystemReconcileStrategy(informer, executor),
    ]


class QOSManager:
    """Strategy scheduler (qosmanager.go:51): independent per-strategy
    ticks, enable-gated by NodeSLO."""

    def __init__(self, strategies: Sequence[QOSStrategy]):
        self.strategies = list(strategies)
        self._next_due: Dict[str, float] = {}

    def run_once(self, now: Optional[float] = None) -> List[str]:
        now = time.time() if now is None else now
        ran = []
        for s in self.strategies:
            # enabled() reads user-supplied NodeSLO and can throw on
            # malformed config just like tick() — one failing strategy
            # must not stop the rest of the battery or kill the daemon
            # loop (the reference runs each strategy in its own goroutine)
            try:
                if not s.enabled():
                    continue
                if now >= self._next_due.get(s.name, 0):
                    self._next_due[s.name] = now + s.interval_seconds
                    s.tick(now)
                    ran.append(s.name)
            except Exception:
                import logging

                # a throw in enabled() skips the interval update (cheap
                # recheck next tick); a throw in tick() already consumed
                # its interval slot, so no hot loop either way
                logging.getLogger(__name__).exception(
                    "qos strategy %s failed", s.name
                )
        return ran
