"""Remote runtime-hook dispatch: the koordlet-side hook server.

Reference: the runtime proxy does not run hooks in-process — it forwards
each CRI event to koordlet's hook gRPC server (the proto at
``apis/runtime/v1alpha1/api.proto:148 RuntimeHookService``, served by
``pkg/koordlet/runtimehooks/proxyserver``), and merges the returned
mutations into the request.  This module provides that process split
over the repo's framed-UDS transport:

* ``HookServer`` — runs in the koordlet process, owns the real
  ``HookRegistry``; serves framed JSON ContainerContext requests.
* ``RemoteHookRegistry`` — runs in the proxy process; a ``HookRegistry``
  look-alike whose ``run`` ships the context to the koordlet socket and
  applies the returned mutations, with the reference's failure-policy
  semantics left to the caller (an unreachable hook server raises, and
  ``RuntimeProxy``'s Ignore policy forwards the original request).
"""

from __future__ import annotations

import dataclasses
import socket
import threading
from typing import Dict, List, Optional

from koordinator_tpu.koordlet.runtimehooks import ContainerContext, HookRegistry
from koordinator_tpu.runtimeproxy_server import (
    _UdsServer,
    recv_frame,
    send_frame,
)

# context fields the wire protocol carries (mutations flow back for the
# writable subset, mirroring the proto's ContainerResourceHookResponse)
_MUTABLE = (
    "cfs_quota_us",
    "cpu_shares",
    "cpuset_cpus",
    "bvt_warp_ns",
    "memory_limit_bytes",
)


def _ctx_to_doc(stage: str, ctx: ContainerContext) -> Dict:
    doc = dataclasses.asdict(ctx)
    doc["__stage__"] = stage
    return doc


def _doc_to_ctx(doc: Dict) -> ContainerContext:
    fields = {f.name for f in dataclasses.fields(ContainerContext)}
    return ContainerContext(**{k: v for k, v in doc.items() if k in fields})


class HookServer(_UdsServer):
    """koordlet-side hook service (proxyserver role)."""

    def __init__(self, path: str, registry: HookRegistry):
        self.registry = registry

        def handle(doc: Dict) -> Dict:
            stage = doc.pop("__stage__", "")
            ctx = _doc_to_ctx(doc)
            ran = self.registry.run(stage, ctx)
            out = dataclasses.asdict(ctx)
            out["__ran__"] = ran
            return out

        super().__init__(path, handle)


class RemoteHookRegistry:
    """Proxy-side stand-in for HookRegistry: dispatches over UDS.

    One connection PER SERVING THREAD (threading.local, the same scheme
    as CRIProxyServer._backend_conn): replies on a stream socket are
    matched by read order, so a connection shared across the proxy's
    concurrent serving threads would hand one container another
    container's mutations."""

    def __init__(self, path: str):
        self.path = path
        self._local = threading.local()
        self._conns: List[socket.socket] = []
        self._conns_lock = threading.Lock()

    def _connect(self) -> socket.socket:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.connect(self.path)
            self._local.conn = conn
            with self._conns_lock:
                self._conns.append(conn)
        return conn

    def run(self, stage: str, ctx: ContainerContext) -> List[str]:
        try:
            conn = self._connect()
            send_frame(conn, _ctx_to_doc(stage, ctx))
            reply = recv_frame(conn)
        except OSError:
            self._drop_thread_conn()
            raise ConnectionError(
                f"hook server unreachable at {self.path}"
            ) from None
        if reply is None:
            self._drop_thread_conn()
            raise ConnectionError("hook server closed the connection")
        if "error" in reply and "__ran__" not in reply:
            raise RuntimeError(reply["error"])
        # apply the returned mutations onto the caller's context
        for field in _MUTABLE:
            setattr(ctx, field, reply.get(field))
        ctx.env.update(reply.get("env") or {})
        return list(reply.get("__ran__") or [])

    def _drop_thread_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            finally:
                self._local.conn = None
                with self._conns_lock:
                    if conn in self._conns:
                        self._conns.remove(conn)

    def close(self) -> None:
        """Close every thread's connection (proxy shutdown)."""
        with self._conns_lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
        self._local = threading.local()
