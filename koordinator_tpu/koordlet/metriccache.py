"""Metric cache: in-process time-series store with percentile aggregation.

Reference: ``pkg/koordlet/metriccache`` — an embedded Prometheus TSDB plus
an in-memory KV (``metric_cache.go:56``, ``tsdb_storage.go:105``), queried
with AVG/P50/P90/P95/P99/latest/count aggregations by the nodemetric
reporter and the qos strategies.

TPU-first shape: samples land in flat numpy ring buffers per (metric,
labels) series — aggregation over a window is one vectorized reduction, and
whole series can be handed to the batched kernels without per-sample
boxing.  Durability mirrors the TSDB directory with an optional npz
snapshot (``save``/``load``).
"""

from __future__ import annotations

import ast
import dataclasses
import glob
import os
import struct
import threading
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

import numpy as np

# Metric names (reference metriccache/metric_resources.go)
NODE_CPU_USAGE = "node_cpu_usage"  # cores
NODE_MEMORY_USAGE = "node_memory_usage"  # bytes
POD_CPU_USAGE = "pod_cpu_usage"
POD_MEMORY_USAGE = "pod_memory_usage"
CONTAINER_CPU_USAGE = "container_cpu_usage"
CONTAINER_MEMORY_USAGE = "container_memory_usage"
CONTAINER_CPI_CYCLES = "container_cpi_cycles"
CONTAINER_CPI_INSTRUCTIONS = "container_cpi_instructions"
NODE_PSI_CPU_SOME_AVG10 = "node_psi_cpu_some_avg10"
NODE_PSI_MEM_SOME_AVG10 = "node_psi_mem_some_avg10"
NODE_PSI_IO_SOME_AVG10 = "node_psi_io_some_avg10"
BE_CPU_USAGE = "be_cpu_usage"
SYS_CPU_USAGE = "sys_cpu_usage"
COLD_PAGE_BYTES = "cold_page_bytes"
DEVICE_UTIL = "device_util"
DEVICE_MEMORY_USED = "device_memory_used"

AGG_AVG = "AVG"
AGG_P50 = "P50"
AGG_P90 = "P90"
AGG_P95 = "P95"
AGG_P99 = "P99"
AGG_LATEST = "latest"
AGG_COUNT = "count"
AGG_MAX = "max"
AGG_MIN = "min"


def _series_key(metric: str, labels: Mapping[str, str]) -> Tuple:
    return (metric,) + tuple(sorted(labels.items()))


@dataclasses.dataclass
class _Series:
    ts: np.ndarray  # f64[cap]
    values: np.ndarray  # f64[cap]
    head: int = 0  # next write index
    count: int = 0

    def append(self, ts: float, value: float) -> None:
        cap = len(self.ts)
        self.ts[self.head] = ts
        self.values[self.head] = value
        self.head = (self.head + 1) % cap
        self.count = min(self.count + 1, cap)

    def window(self, start: float, end: float) -> np.ndarray:
        ts = self.ts[: self.count]
        vals = self.values[: self.count]
        sel = (ts >= start) & (ts <= end)
        return vals[sel], ts[sel]


class MetricCache:
    """Thread-safe ring-buffer TSDB analog."""

    def __init__(self, capacity_per_series: int = 4096):
        self._cap = capacity_per_series
        self._series: Dict[Tuple, _Series] = {}
        self._kv: Dict[str, object] = {}
        self._lock = threading.Lock()

    # -- TSDB face --

    def append(
        self,
        metric: str,
        value: float,
        *,
        ts: float,
        labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        key = _series_key(metric, labels or {})
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = _Series(
                    ts=np.zeros(self._cap), values=np.zeros(self._cap)
                )
                self._series[key] = s
            s.append(ts, value)

    def query(
        self,
        metric: str,
        *,
        start: float,
        end: float,
        agg: str = AGG_AVG,
        labels: Optional[Mapping[str, str]] = None,
    ) -> Optional[float]:
        """Aggregate one series over [start, end]; None when empty
        (the reference degrades on missing metrics, e.g. LoadAware
        score-0 and noderesource degradeCalculate)."""
        key = _series_key(metric, labels or {})
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return None
            vals, ts = s.window(start, end)
        if len(vals) == 0:
            return None
        if agg == AGG_AVG:
            return float(vals.mean())
        if agg == AGG_LATEST:
            return float(vals[np.argmax(ts)])
        if agg == AGG_COUNT:
            return float(len(vals))
        if agg == AGG_MAX:
            return float(vals.max())
        if agg == AGG_MIN:
            return float(vals.min())
        if agg in (AGG_P50, AGG_P90, AGG_P95, AGG_P99):
            q = {AGG_P50: 50, AGG_P90: 90, AGG_P95: 95, AGG_P99: 99}[agg]
            # lower-interpolation percentile matches the Prometheus
            # histogram-free quantile the reference effectively computes
            return float(np.percentile(vals, q, method="lower"))
        raise ValueError(f"unknown aggregation {agg}")

    def series_labels(self, metric: str) -> List[Dict[str, str]]:
        """All label sets currently stored for ``metric``."""
        with self._lock:
            return [
                dict(key[1:])
                for key in self._series
                if key[0] == metric
            ]

    # -- in-memory KV face (metric_cache.go Get/Set) --

    def set(self, key: str, value: object) -> None:
        with self._lock:
            self._kv[key] = value

    def get(self, key: str) -> Optional[object]:
        with self._lock:
            return self._kv.get(key)

    # -- persistence (tsdb_storage.go directory analog) --

    def save(self, path: str) -> None:
        with self._lock:
            arrays = {}
            index = []
            for i, (key, s) in enumerate(self._series.items()):
                arrays[f"ts_{i}"] = s.ts[: s.count]
                arrays[f"v_{i}"] = s.values[: s.count]
                index.append(repr(key))
            # host-only string array for the npz index — no device
            # value ever enters this cache, so nothing can block here
            arrays["index"] = np.array(index)  # koordlint: disable=lock-held-dispatch
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez_compressed(path, **arrays)

    def load(self, path: str) -> bool:
        try:
            data = np.load(path, allow_pickle=False)
        except OSError:
            return False
        import ast

        with self._lock:
            for i, key_repr in enumerate(data["index"]):
                key = ast.literal_eval(str(key_repr))
                ts = data[f"ts_{i}"]
                vals = data[f"v_{i}"]
                s = _Series(ts=np.zeros(self._cap), values=np.zeros(self._cap))
                for t, v in zip(ts[-self._cap :], vals[-self._cap :]):
                    s.append(float(t), float(v))
                self._series[tuple(key)] = s
        return True


# ---------------------------------------------------------------------------
# Durable storage: WAL segments (tsdb_storage.go analog)
# ---------------------------------------------------------------------------

# record layout: u32 key-id, f64 ts, f64 value (little-endian)
_REC = struct.Struct("<Iqd")  # ts stored as int64 milliseconds
_KEYDEF = 0xFFFFFFFF  # key-id sentinel: record body is a key definition


class PersistentMetricCache(MetricCache):
    """MetricCache whose appends land in append-only WAL segments and whose
    constructor replays them — a koordlet restart keeps the NodeMetric
    aggregation window intact (the role the reference's embedded Prometheus
    TSDB directory plays, ``metriccache/tsdb_storage.go:105``).

    Segments rotate at ``segment_bytes``; on rotation, segments whose
    newest sample is older than ``retention_seconds`` are deleted (TSDB
    block retention).  Records are fixed-width binary; series keys are
    interned once per segment stream via key-definition records, so the
    steady-state write is 20 bytes per sample.

    Durability contract: every append is flushed (survives process
    restart); sealed segments are fsync'd at rotation (survive host
    crash).  The tail of the *active* segment rides the page cache and
    can lose recent samples to power loss — same trade the reference's
    head-block WAL makes before TSDB block cut.
    """

    def __init__(
        self,
        directory: str,
        capacity_per_series: int = 4096,
        segment_bytes: int = 4 << 20,
        retention_seconds: float = 24 * 3600.0,
    ):
        super().__init__(capacity_per_series=capacity_per_series)
        self.directory = directory
        self.segment_bytes = segment_bytes
        self.retention_seconds = retention_seconds
        self._key_ids: Dict[Tuple, int] = {}
        self._next_key = 0
        self._segment_newest: Dict[str, float] = {}
        self._segment_valid_bytes: Dict[str, int] = {}
        os.makedirs(directory, exist_ok=True)
        self._replay()
        # startup retention sweep: a crash-looping daemon that never fills
        # a segment would otherwise accumulate WAL files forever
        newest_any = max(self._segment_newest.values(), default=0.0)
        self._sweep(newest_any)
        existing = self._segments()
        last_index = (
            int(existing[-1].rsplit("-", 1)[1].split(".")[0])
            if existing
            else -1
        )
        if existing and os.path.getsize(existing[-1]) >= segment_bytes:
            # the last segment is full but the writer died before rotating:
            # it is being sealed implicitly here, so give it the same fsync
            # a normal rotation would have
            with open(existing[-1], "rb") as fh:
                os.fsync(fh.fileno())
        if (
            existing
            and os.path.getsize(existing[-1]) < segment_bytes
        ):
            # reuse the under-sized active segment (its key table is
            # already interned and its ids match the replayed _key_ids).
            # A torn tail from a crash mid-write MUST be truncated first:
            # appending after partial-record garbage would shift the
            # fixed-stride replay off alignment on the next restart.
            valid = self._segment_valid_bytes.get(existing[-1])
            if valid is not None and valid < os.path.getsize(existing[-1]):
                with open(existing[-1], "r+b") as fh:
                    fh.truncate(valid)
            self._seg_index = last_index
            self._fh = open(existing[-1], "ab")
        else:
            self._seg_index = last_index + 1
            self._fh = open(self._segment_path(self._seg_index), "ab")
            # re-intern the key table into the fresh segment so every
            # segment is self-describing (replay never needs another one)
            for key, kid in sorted(
                self._key_ids.items(), key=lambda kv: kv[1]
            ):
                self._fh.write(self._keydef_record(kid, key))
            self._fh.flush()

    # -- write path --
    def append(self, metric, value, *, ts, labels=None):
        super().append(metric, value, ts=ts, labels=labels)
        key = _series_key(metric, labels or {})
        with self._lock:
            kid = self._key_ids.get(key)
            if kid is None:
                kid = self._next_key
                self._next_key += 1
                self._key_ids[key] = kid
                self._fh.write(self._keydef_record(kid, key))
            self._fh.write(_REC.pack(kid, int(ts * 1000), float(value)))
            self._fh.flush()
            seg = self._segment_path(self._seg_index)
            self._segment_newest[seg] = max(
                self._segment_newest.get(seg, 0.0), float(ts)
            )
            if self._fh.tell() >= self.segment_bytes:
                self._rotate(float(ts))

    def close(self):
        with self._lock:
            self._fh.close()

    # -- internals --
    def _segment_path(self, index: int) -> str:
        return os.path.join(self.directory, f"segment-{index:08d}.wal")

    def _segments(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.directory, "segment-*.wal")))

    @staticmethod
    def _keydef_record(kid: int, key: Tuple) -> bytes:
        blob = repr(key).encode()
        return _REC.pack(_KEYDEF, kid, float(len(blob))) + blob

    def _rotate(self, now: float):
        # fsync before sealing: flush() alone leaves the segment in the
        # page cache, so a host crash (not just a process restart) could
        # drop the tail of an otherwise "durable" sealed segment.
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._seg_index += 1
        self._fh = open(self._segment_path(self._seg_index), "ab")
        for key, kid in sorted(self._key_ids.items(), key=lambda kv: kv[1]):
            self._fh.write(self._keydef_record(kid, key))
        self._fh.flush()
        # fsync the directory AFTER creating the new segment so its dirent
        # (and the sealed predecessor's) survives a host crash
        dirfd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        self._sweep(now)

    def _sweep(self, now: float):
        """Drop whole segments whose newest sample has aged out (TSDB
        block retention)."""
        active = self._segment_path(getattr(self, "_seg_index", -1))
        for seg in self._segments():
            if seg == active:
                continue
            newest = self._segment_newest.get(seg)
            if newest is not None and now - newest > self.retention_seconds:
                os.unlink(seg)
                self._segment_newest.pop(seg, None)

    def _replay(self):
        for seg in self._segments():
            keymap: Dict[int, Tuple] = {}
            newest = 0.0
            try:
                with open(seg, "rb") as fh:
                    data = fh.read()
            except OSError:
                continue
            off = 0
            valid_off = 0
            while off + _REC.size <= len(data):
                kid, ts_ms, value = _REC.unpack_from(data, off)
                off += _REC.size
                if kid == _KEYDEF:
                    blob_len = int(value)
                    blob = data[off : off + blob_len]
                    off += blob_len
                    try:
                        key = tuple(ast.literal_eval(blob.decode()))
                    except (ValueError, SyntaxError):
                        break  # torn key record: stop at the tear
                    keymap[ts_ms] = key  # ts field carries the key id here
                    if key not in self._key_ids:
                        self._key_ids[key] = self._next_key
                        self._next_key += 1
                    valid_off = off
                    continue
                key = keymap.get(kid)
                valid_off = off
                if key is None:
                    continue  # unknown id (foreign tear): skip
                ts = ts_ms / 1000.0
                newest = max(newest, ts)
                metric = key[0]
                labels = dict(key[1:])
                MetricCache.append(self, metric, value, ts=ts, labels=labels)
            self._segment_newest[seg] = newest
            self._segment_valid_bytes[seg] = valid_off
