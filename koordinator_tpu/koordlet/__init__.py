"""koordlet — the node agent.

Reference: ``pkg/koordlet`` (``koordlet.go:68 NewDaemon``, ``:123 Run``)
wires six subsystems: metriccache -> statesinformer -> metricsadvisor ->
predictserver -> qosmanager -> runtimehooks.  ``Daemon`` here wires the
same set over the fake-able SysFS layer; ``run_once`` advances every
subsystem one tick (production loops call it from timers; tests drive it
directly, the same seam the reference's gomock harness fakes).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.collectors import (
    BEResourceCollector,
    Collector,
    MetricsAdvisor,
    NodeResourceCollector,
    PodMeta,
    PodResourceCollector,
    PSICollector,
    SysResourceCollector,
)
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.pleg import Pleg
from koordinator_tpu.koordlet.prediction import FileCheckpointer, PeakPredictServer
from koordinator_tpu.koordlet.qosmanager import (
    Evictor,
    QOSManager,
    default_qos_strategies,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks import Reconciler, default_registry
from koordinator_tpu.koordlet.statesinformer import (
    NodeMetricReporter,
    StatesInformer,
)
from koordinator_tpu.koordlet.sysfs import SysFS


class Daemon:
    """koordlet.go:68 NewDaemon analog."""

    def __init__(
        self,
        fs: Optional[SysFS] = None,
        *,
        audit_dir: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        evictor: Optional[Evictor] = None,
    ):
        self.fs = fs or SysFS()
        self.cache = MetricCache()
        self.informer = StatesInformer()
        self.audit = Auditor(audit_dir) if audit_dir else None
        self.executor = ResourceUpdateExecutor(self.fs, audit=self.audit)
        self.evictor = evictor or Evictor()
        self.pleg = Pleg(self.fs)
        self.advisor = MetricsAdvisor(
            [
                NodeResourceCollector(self.fs, self.cache),
                PodResourceCollector(self.fs, self.cache, self.informer.get_all_pods),
                BEResourceCollector(self.fs, self.cache),
                SysResourceCollector(self.cache),
                PSICollector(self.fs, self.cache),
            ]
        )
        self.predictor = PeakPredictServer(
            FileCheckpointer(checkpoint_dir) if checkpoint_dir else None
        )
        self.reporter = NodeMetricReporter(self.cache, self.informer)
        self.qos = QOSManager(
            default_qos_strategies(
                self.informer, self.cache, self.executor, self.evictor
            )
        )
        self.hooks = default_registry()
        self.reconciler = Reconciler(self.hooks, self.executor)

    def run_once(self, now: Optional[float] = None) -> dict:
        """One tick of every subsystem; returns what ran."""
        now = time.time() if now is None else now
        pleg_events = self.pleg.poll_once()
        collected = self.advisor.run_once(now)
        qos_ran = self.qos.run_once(now)
        report = self.reporter.collect(now)
        return {
            "pleg": pleg_events,
            "collectors": collected,
            "qos": qos_ran,
            "node_metric": report,
        }
