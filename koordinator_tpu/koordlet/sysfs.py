"""Node OS interface: cgroup v1/v2 + /proc readers behind a fake-able root.

Reference L0 (``pkg/koordlet/util/system``): cgroup driver for both
hierarchies (``cgroup_driver_linux.go``, ``cgroup2.go``), resource registry
(``cgroup_resource.go``), PSI parsing
(``pkg/koordlet/resourceexecutor/psi.go``), proc parsing
(``util/system`` meminfo/cpuinfo helpers).  Everything resolves under a
configurable root so tests run against a temp-dir fake fs (the reference's
``util_test_tool.go`` pattern).
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Dict, List, Mapping, Optional, Tuple


class CgroupVersion(enum.IntEnum):
    V1 = 1
    V2 = 2


# Cgroup resource registry (reference util/system/cgroup_resource.go):
# logical resource -> (v1 subsystem relative file, v2 file)
CGROUP_FILES = {
    "cpu.cfs_quota": ("cpu/cpu.cfs_quota_us", "cpu.max"),
    "cpu.cfs_period": ("cpu/cpu.cfs_period_us", "cpu.max"),
    "cpu.cfs_burst": ("cpu/cpu.cfs_burst_us", "cpu.max.burst"),
    "cpu.shares": ("cpu/cpu.shares", "cpu.weight"),
    "cpu.bvt_warp_ns": ("cpu/cpu.bvt_warp_ns", "cpu.bvt_warp_ns"),
    "cpu.idle": ("cpu/cpu.idle", "cpu.idle"),
    "cpuset.cpus": ("cpuset/cpuset.cpus", "cpuset.cpus"),
    "cpuacct.usage": ("cpuacct/cpuacct.usage", "cpu.stat"),
    "memory.limit": ("memory/memory.limit_in_bytes", "memory.max"),
    "memory.usage": ("memory/memory.usage_in_bytes", "memory.current"),
    "memory.wmark_ratio": ("memory/memory.wmark_ratio", "memory.wmark_ratio"),
    "memory.priority": ("memory/memory.priority", "memory.priority"),
    "memory.oom_group": ("memory/memory.use_priority_oom", "memory.oom.group"),
    "cpu.pressure": ("cpuacct/cpu.pressure", "cpu.pressure"),
    "memory.pressure": ("cpuacct/memory.pressure", "memory.pressure"),
    "io.pressure": ("cpuacct/io.pressure", "io.pressure"),
    "blkio.throttle.read_bps": (
        "blkio/blkio.throttle.read_bps_device",
        "io.max",
    ),
    "blkio.throttle.write_bps": (
        "blkio/blkio.throttle.write_bps_device",
        "io.max",
    ),
}


@dataclasses.dataclass
class PSILine:
    """One parsed PSI record (resourceexecutor/psi.go)."""

    avg10: float
    avg60: float
    avg300: float
    total: int


@dataclasses.dataclass
class PSI:
    some: PSILine
    full: Optional[PSILine]


@dataclasses.dataclass
class SysFS:
    """Filesystem accessor rooted at ``root`` ('/' in production)."""

    root: str = "/"
    cgroup_version: CgroupVersion = CgroupVersion.V2
    cgroup_mount: str = "sys/fs/cgroup"

    # -- path helpers --

    def proc_path(self, *parts: str) -> str:
        return os.path.join(self.root, "proc", *parts)

    def cgroup_path(self, resource: str, cgroup_dir: str = "") -> str:
        v1_rel, v2_rel = CGROUP_FILES[resource]
        base = os.path.join(self.root, self.cgroup_mount)
        if self.cgroup_version == CgroupVersion.V1:
            subsystem, _, fname = v1_rel.partition("/")
            return os.path.join(base, subsystem, cgroup_dir, fname)
        return os.path.join(base, cgroup_dir, v2_rel)

    # -- raw io --

    def read(self, path: str) -> Optional[str]:
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            return None

    def write(self, path: str, value: str) -> bool:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                f.write(value)
            return True
        except OSError:
            return False

    def read_cgroup(self, resource: str, cgroup_dir: str = "") -> Optional[str]:
        v = self.read(self.cgroup_path(resource, cgroup_dir))
        return v.strip() if v is not None else None

    def write_cgroup(self, resource: str, cgroup_dir: str, value: str) -> bool:
        return self.write(self.cgroup_path(resource, cgroup_dir), value)

    # -- /proc parsers (reference util/system) --

    def meminfo(self) -> Dict[str, int]:
        """Parse /proc/meminfo into bytes."""
        out: Dict[str, int] = {}
        text = self.read(self.proc_path("meminfo")) or ""
        for line in text.splitlines():
            if ":" not in line:
                continue
            key, _, rest = line.partition(":")
            fields = rest.split()
            if not fields:
                continue
            value = int(fields[0])
            if len(fields) > 1 and fields[1] == "kB":
                value *= 1024
            out[key.strip()] = value
        return out

    def memory_usage_bytes(self) -> int:
        """Node memory usage = MemTotal - MemAvailable (the reference's
        node memory accounting, util/meminfo)."""
        mi = self.meminfo()
        return max(0, mi.get("MemTotal", 0) - mi.get("MemAvailable", 0))

    # -- CPU / NUMA topology (reference util/system + koordlet nodeinfo
    # collectors read the same sysfs files to build the
    # NodeResourceTopology CR, states_noderesourcetopology.go) --

    def sys_path(self, *parts: str) -> str:
        return os.path.join(self.root, "sys", *parts)

    @staticmethod
    def _parse_cpulist(text: str) -> List[int]:
        """"0-3,8,10-11" -> [0, 1, 2, 3, 8, 10, 11]."""
        cpus: List[int] = []
        for part in text.strip().split(","):
            if not part:
                continue
            if "-" in part:
                lo, hi = part.split("-")
                cpus.extend(range(int(lo), int(hi) + 1))
            else:
                cpus.append(int(part))
        return cpus

    def numa_nodes(self) -> List[int]:
        """NUMA node ids from /sys/devices/system/node/node*/."""
        base = self.sys_path("devices", "system", "node")
        out: List[int] = []
        try:
            for name in os.listdir(base):
                if name.startswith("node") and name[4:].isdigit():
                    out.append(int(name[4:]))
        except OSError:
            return []
        return sorted(out)

    def numa_node_cpus(self, node: int) -> List[int]:
        text = self.read(
            self.sys_path("devices", "system", "node", f"node{node}", "cpulist")
        )
        return self._parse_cpulist(text) if text else []

    def numa_node_memory_bytes(self, node: int) -> int:
        """Node-local MemTotal from node<X>/meminfo ("Node 0 MemTotal: N kB")."""
        text = (
            self.read(
                self.sys_path(
                    "devices", "system", "node", f"node{node}", "meminfo"
                )
            )
            or ""
        )
        for line in text.splitlines():
            if "MemTotal:" in line:
                fields = line.split()
                try:
                    idx = fields.index("MemTotal:")
                    value = int(fields[idx + 1])
                except (ValueError, IndexError):
                    return 0
                if len(fields) > idx + 2 and fields[idx + 2] == "kB":
                    value *= 1024
                return value
        return 0

    def cpu_topology(self) -> List[Tuple[int, int, int, int]]:
        """(cpu, core, numa_node, socket) per online logical CPU, from
        cpu<N>/topology/{core_id,physical_package_id} + the NUMA cpulists."""
        cpu_node: Dict[int, int] = {}
        for n in self.numa_nodes():
            for c in self.numa_node_cpus(n):
                cpu_node[c] = n
        base = self.sys_path("devices", "system", "cpu")
        out: List[Tuple[int, int, int, int]] = []
        try:
            names = os.listdir(base)
        except OSError:
            return []
        for name in sorted(names):
            if not (name.startswith("cpu") and name[3:].isdigit()):
                continue
            cpu = int(name[3:])
            core = self.read(os.path.join(base, name, "topology", "core_id"))
            sock = self.read(
                os.path.join(base, name, "topology", "physical_package_id")
            )
            if core is None or sock is None:
                continue
            out.append(
                (cpu, int(core), cpu_node.get(cpu, 0), int(sock))
            )
        return out

    def proc_stat_cpu(self) -> Tuple[int, int]:
        """(used_ticks, total_ticks) from the aggregate /proc/stat cpu line."""
        text = self.read(self.proc_path("stat")) or ""
        for line in text.splitlines():
            if line.startswith("cpu "):
                vals = [int(v) for v in line.split()[1:]]
                # user nice system idle iowait irq softirq steal [guest ...]
                total = sum(vals[:8])
                idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
                return total - idle, total
        return 0, 0

    def psi(self, resource: str, cgroup_dir: str = "") -> Optional[PSI]:
        """Parse a PSI file (resourceexecutor/psi.go readPSI)."""
        text = self.read_cgroup(resource, cgroup_dir)
        if text is None:
            return None
        lines: Dict[str, PSILine] = {}
        for line in text.splitlines():
            fields = line.split()
            if not fields:
                continue
            kind = fields[0]
            kv = dict(f.split("=", 1) for f in fields[1:])
            lines[kind] = PSILine(
                avg10=float(kv.get("avg10", 0)),
                avg60=float(kv.get("avg60", 0)),
                avg300=float(kv.get("avg300", 0)),
                total=int(kv.get("total", 0)),
            )
        if "some" not in lines:
            return None
        return PSI(some=lines["some"], full=lines.get("full"))

    def cpuacct_usage_ns(self, cgroup_dir: str = "") -> int:
        """Container/pod cpu usage in nanoseconds (v1 cpuacct.usage; v2
        cpu.stat usage_usec)."""
        if self.cgroup_version == CgroupVersion.V1:
            v = self.read_cgroup("cpuacct.usage", cgroup_dir)
            return int(v) if v else 0
        text = self.read_cgroup("cpuacct.usage", cgroup_dir) or ""
        for line in text.splitlines():
            if line.startswith("usage_usec"):
                return int(line.split()[1]) * 1000
        return 0

    def memory_usage_cgroup(self, cgroup_dir: str = "") -> int:
        v = self.read_cgroup("memory.usage", cgroup_dir)
        return int(v) if v and v.isdigit() else 0


# Well-known koordinator cgroup layout (reference util/koordlet cgroup
# paths): besteffort pods live under a dedicated QoS tree.
KUBEPODS = "kubepods"
KUBEPODS_BESTEFFORT = "kubepods/besteffort"
KUBEPODS_BURSTABLE = "kubepods/burstable"


def pod_cgroup_dir(qos: str, pod_uid: str) -> str:
    """Pod dir by k8s QoS class (reference util/pod.go GetPodCgroupParentDir)."""
    if qos == "Guaranteed":
        return f"{KUBEPODS}/pod{pod_uid}"
    if qos == "BestEffort":
        return f"{KUBEPODS_BESTEFFORT}/pod{pod_uid}"
    return f"{KUBEPODS_BURSTABLE}/pod{pod_uid}"
