"""Peak prediction: decaying histograms + file checkpointing.

Reference: ``pkg/koordlet/prediction`` — ``predict_server.go:65`` feeds
node/priority/QoS usage samples into sliding-window histograms
(``peak_predictor.go:42-59``; VPA-style exponentially-decaying geometric
buckets), reads p95/p98 peaks to compute ProdReclaimable, and checkpoints
histograms to files reloaded on restart (``checkpoint.go:46,53``).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Dict, List, Mapping, Optional, Sequence

DEFAULT_HALF_LIFE_SECONDS = 12 * 3600.0
DEFAULT_FIRST_BUCKET = 0.01  # cores (or GiB-scale for memory users)
DEFAULT_BUCKET_RATIO = 1.05
DEFAULT_NUM_BUCKETS = 176
# safety margin applied to peaks (predict_server.go defaultModelFactor)
DEFAULT_SAFETY_MARGIN_PERCENT = 10


class DecayHistogram:
    """Exponentially-decaying geometric-bucket histogram
    (peak_predictor.go histogram semantics)."""

    def __init__(
        self,
        *,
        first_bucket: float = DEFAULT_FIRST_BUCKET,
        ratio: float = DEFAULT_BUCKET_RATIO,
        num_buckets: int = DEFAULT_NUM_BUCKETS,
        half_life_seconds: float = DEFAULT_HALF_LIFE_SECONDS,
    ):
        self.first_bucket = first_bucket
        self.ratio = ratio
        self.num_buckets = num_buckets
        self.half_life = half_life_seconds
        self.weights = [0.0] * num_buckets
        self.total = 0.0
        self.ref_ts = 0.0

    def _bucket_of(self, value: float) -> int:
        if value <= self.first_bucket:
            return 0
        i = int(math.log(value / self.first_bucket) / math.log(self.ratio)) + 1
        return min(i, self.num_buckets - 1)

    def bucket_start(self, i: int) -> float:
        return 0.0 if i == 0 else self.first_bucket * self.ratio ** (i - 1)

    def _decay_factor(self, ts: float) -> float:
        return 2 ** ((ts - self.ref_ts) / self.half_life)

    def add(self, value: float, ts: float, weight: float = 1.0) -> None:
        w = weight * self._decay_factor(ts)
        i = self._bucket_of(value)
        self.weights[i] += w
        self.total += w
        # renormalize when factors grow large (same trick as VPA histograms)
        if self._decay_factor(ts) > 2**40:
            self._shift_ref(ts)

    def _shift_ref(self, ts: float) -> None:
        f = self._decay_factor(ts)
        self.weights = [w / f for w in self.weights]
        self.total /= f
        self.ref_ts = ts

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket at the p-quantile (0..100)."""
        if self.total <= 0:
            return 0.0
        target = self.total * p / 100.0
        acc = 0.0
        for i, w in enumerate(self.weights):
            acc += w
            if acc >= target:
                return self.bucket_start(min(i + 1, self.num_buckets - 1))
        return self.bucket_start(self.num_buckets - 1)

    def to_dict(self) -> Dict:
        return {
            "first_bucket": self.first_bucket,
            "ratio": self.ratio,
            "num_buckets": self.num_buckets,
            "half_life": self.half_life,
            "weights": self.weights,
            "total": self.total,
            "ref_ts": self.ref_ts,
        }

    @classmethod
    def from_dict(cls, d: Mapping) -> "DecayHistogram":
        h = cls(
            first_bucket=d["first_bucket"],
            ratio=d["ratio"],
            num_buckets=d["num_buckets"],
            half_life_seconds=d["half_life"],
        )
        h.weights = list(d["weights"])
        h.total = float(d["total"])
        h.ref_ts = float(d["ref_ts"])
        return h


class FileCheckpointer:
    """checkpoint.go:53 NewFileCheckpointer: one json file per key."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_")
        return os.path.join(self.directory, f"{safe}.json")

    def save(self, key: str, hist: DecayHistogram) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(hist.to_dict(), f)
        os.replace(tmp, self._path(key))

    def load(self, key: str) -> Optional[DecayHistogram]:
        try:
            with open(self._path(key)) as f:
                return DecayHistogram.from_dict(json.load(f))
        except (OSError, ValueError, KeyError):
            return None

    def keys(self) -> List[str]:
        return [
            f[: -len(".json")]
            for f in os.listdir(self.directory)
            if f.endswith(".json")
        ]


class PeakPredictServer:
    """predict_server.go:65 — histogram per key (node / priority band /
    QoS class / pod), peak = p95 with a safety margin."""

    def __init__(
        self,
        checkpointer: Optional[FileCheckpointer] = None,
        *,
        safety_margin_percent: int = DEFAULT_SAFETY_MARGIN_PERCENT,
        cold_start_seconds: float = 15 * 60,
    ):
        self.hists: Dict[str, DecayHistogram] = {}
        self.checkpointer = checkpointer
        self.safety_margin = safety_margin_percent
        self.cold_start = cold_start_seconds
        self._first_sample_ts: Dict[str, float] = {}
        if checkpointer is not None:
            for key in checkpointer.keys():
                h = checkpointer.load(key)
                if h is not None:
                    self.hists[key] = h
                    self._first_sample_ts[key] = 0.0

    def update(self, key: str, value: float, ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        h = self.hists.get(key)
        if h is None:
            h = DecayHistogram()
            self.hists[key] = h
            self._first_sample_ts[key] = ts
        h.add(value, ts)

    def peak(self, key: str, *, p: float = 95.0, now: Optional[float] = None) -> Optional[float]:
        """Predicted peak, or None during cold start (predict_server
        returns no result until the model warmed up)."""
        h = self.hists.get(key)
        if h is None:
            return None
        now = time.time() if now is None else now
        if now - self._first_sample_ts.get(key, 0.0) < self.cold_start:
            return None
        return h.percentile(p) * (100 + self.safety_margin) / 100.0

    def prod_reclaimable(
        self,
        *,
        prod_allocated: float,
        prod_peak_key: str = "prod",
        now: Optional[float] = None,
    ) -> Optional[float]:
        """ProdReclaimable = allocated - predicted prod peak (the
        MidResource plugin's input, reference noderesource MidResource)."""
        peak = self.peak(prod_peak_key, now=now)
        if peak is None:
            return None
        return max(0.0, prod_allocated - peak)

    def checkpoint_all(self) -> None:
        if self.checkpointer is None:
            return
        for key, h in self.hists.items():
            self.checkpointer.save(key, h)
