"""Node-agent daemon wiring: one object owning every koordlet subsystem.

Reference: ``pkg/koordlet/koordlet.go:68 NewDaemon`` wires metriccache ->
statesinformer -> metricsadvisor -> predictserver -> qosmanager ->
runtimehooks and ``:123 Run`` starts them as goroutines
(``koordlet.go:126-178``).  Here the same wiring with explicit tick
methods (``run_once``) so tests drive it with a fake clock, plus a
``run`` loop with threads for live deployment.  Prometheus-style metrics
and the audit /events handler hang off the daemon the way
``cmd/koordlet/main.go:64-90`` mounts them.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.collectors import Collector, MetricsAdvisor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.metrics import MetricsRegistry
from koordinator_tpu.koordlet.pleg import Pleg
from koordinator_tpu.koordlet.prediction import PeakPredictServer
from koordinator_tpu.koordlet.qosmanager import QOSManager, QOSStrategy
from koordinator_tpu.koordlet.statesinformer import NodeMetricReporter, StatesInformer
from koordinator_tpu.koordlet.sysfs import SysFS


class Daemon:
    """Wires the six koordlet subsystems (koordlet.go:126-178 order)."""

    def __init__(
        self,
        *,
        fs: Optional[SysFS] = None,
        cache: Optional[MetricCache] = None,
        informer: Optional[StatesInformer] = None,
        collectors: Sequence[Collector] = (),
        strategies: Sequence[QOSStrategy] = (),
        predict: Optional[PeakPredictServer] = None,
        reporter: Optional[NodeMetricReporter] = None,
        auditor: Optional[Auditor] = None,
        metrics: Optional[MetricsRegistry] = None,
        report_interval_seconds: float = 60.0,
        storage_dir: Optional[str] = None,
    ):
        self.fs = fs or SysFS()
        if cache is not None:
            self.cache = cache
        elif storage_dir:
            # durable metrics (the reference embeds a Prometheus TSDB,
            # tsdb_storage.go:105): a koordlet restart replays the WAL so
            # the NodeMetric aggregation window survives
            from koordinator_tpu.koordlet.metriccache import (
                PersistentMetricCache,
            )

            self.cache = PersistentMetricCache(storage_dir)
        else:
            self.cache = MetricCache()
        self.informer = informer or StatesInformer()
        self.advisor = MetricsAdvisor(list(collectors))
        self.qos = QOSManager(list(strategies))
        self.predict = predict
        self.reporter = reporter
        self.auditor = auditor
        self.metrics = metrics or MetricsRegistry()
        self.pleg = Pleg(self.fs)
        self.report_interval = report_interval_seconds
        self._next_report = 0.0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- single tick (test- and fake-clock-friendly) --
    def run_once(self, now: Optional[float] = None) -> dict:
        """One pass over every subsystem, in the reference's start order."""
        now = time.time() if now is None else now
        events = self.pleg.poll_once()
        collected = self.advisor.run_once(now)
        reported = None
        if self.reporter is not None and now >= self._next_report:
            reported = self.reporter.collect(now)
            self._next_report = now + self.report_interval
        strategies = self.qos.run_once(now)
        if self.auditor is not None and strategies:
            self.auditor.log("qos-tick", strategies=",".join(strategies))
        self.metrics.counter_add("koordlet_ticks_total", 1)
        self.metrics.gauge_set("koordlet_collectors_last_run", len(collected))
        return {
            "pleg_events": events,
            "collectors": collected,
            "strategies": strategies,
            "node_metric": reported,
        }

    # -- live loop --
    def run(
        self,
        interval_seconds: float = 1.0,
        stop: Optional[Callable[[], bool]] = None,
    ) -> None:
        while not self._stop.is_set() and not (stop and stop()):
            self.run_once()
            self._stop.wait(interval_seconds)

    def start(self, interval_seconds: float = 1.0) -> None:
        t = threading.Thread(
            target=self.run, args=(interval_seconds,), daemon=True
        )
        t.start()
        self._threads.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        if self.predict is not None:
            self.predict.checkpoint_all()
