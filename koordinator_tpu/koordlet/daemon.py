"""Node-agent daemon wiring: one object owning every koordlet subsystem.

Reference: ``pkg/koordlet/koordlet.go:68 NewDaemon`` wires metriccache ->
statesinformer -> metricsadvisor -> predictserver -> qosmanager ->
runtimehooks and ``:123 Run`` starts them as goroutines
(``koordlet.go:126-178``).  Here the same wiring with explicit tick
methods (``run_once``) so tests drive it with a fake clock, plus a
``run`` loop with threads for live deployment.  Prometheus-style metrics
and the audit /events handler hang off the daemon the way
``cmd/koordlet/main.go:64-90`` mounts them.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.collectors import Collector, MetricsAdvisor
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.koordlet.metrics import MetricsRegistry
from koordinator_tpu.koordlet.pleg import Pleg
from koordinator_tpu.koordlet.prediction import PeakPredictServer
from koordinator_tpu.koordlet.qosmanager import QOSManager, QOSStrategy
from koordinator_tpu.koordlet.statesinformer import NodeMetricReporter, StatesInformer
from koordinator_tpu.koordlet.sysfs import SysFS


class Daemon:
    """Wires the six koordlet subsystems (koordlet.go:126-178 order)."""

    def __init__(
        self,
        *,
        fs: Optional[SysFS] = None,
        cache: Optional[MetricCache] = None,
        informer: Optional[StatesInformer] = None,
        collectors: Sequence[Collector] = (),
        strategies: Sequence[QOSStrategy] = (),
        predict: Optional[PeakPredictServer] = None,
        reporter: Optional[NodeMetricReporter] = None,
        auditor: Optional[Auditor] = None,
        metrics: Optional[MetricsRegistry] = None,
        report_interval_seconds: float = 60.0,
        storage_dir: Optional[str] = None,
        nri_socket: Optional[str] = None,
        hook_registry=None,
        evictor=None,
    ):
        self.fs = fs or SysFS()
        if cache is not None:
            self.cache = cache
        elif storage_dir:
            # durable metrics (the reference embeds a Prometheus TSDB,
            # tsdb_storage.go:105): a koordlet restart replays the WAL so
            # the NodeMetric aggregation window survives
            from koordinator_tpu.koordlet.metriccache import (
                PersistentMetricCache,
            )

            self.cache = PersistentMetricCache(storage_dir)
        else:
            self.cache = MetricCache()
        self.informer = informer or StatesInformer()
        self.advisor = MetricsAdvisor(list(collectors))
        self.qos = QOSManager(list(strategies))
        self.predict = predict
        self.reporter = reporter
        self.auditor = auditor
        self.metrics = metrics or MetricsRegistry()
        self.pleg = Pleg(self.fs)
        self.evictor = evictor
        # NRI delivery mode (reference runtimehooks/nri/server.go): when a
        # runtime NRI socket is configured, register as a plugin on it —
        # the runtime then drives the shared HookRegistry through
        # CreateContainer/UpdateContainer events; proxy and reconciler
        # modes keep working beside it
        self.nri = None
        if nri_socket is not None:
            import logging

            from koordinator_tpu.koordlet.nri import NriPlugin
            from koordinator_tpu.koordlet.runtimehooks import default_registry

            try:
                self.nri = NriPlugin(
                    nri_socket, hook_registry or default_registry()
                )
            except (OSError, RuntimeError):
                # NRI is one of three delivery modes; an absent/unready
                # runtime socket must degrade to proxy/reconciler, not
                # fail the whole daemon (reference runtimehooks.go falls
                # back the same way when NRI registration fails)
                logging.getLogger(__name__).exception(
                    "NRI registration on %s failed; continuing with "
                    "proxy/reconciler delivery only",
                    nri_socket,
                )
        self.report_interval = report_interval_seconds
        self._next_report = 0.0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- single tick (test- and fake-clock-friendly) --
    def run_once(self, now: Optional[float] = None) -> dict:
        """One pass over every subsystem, in the reference's start order."""
        now = time.time() if now is None else now
        events = self.pleg.poll_once()
        # informer plugin sync (reference states_informer.go:146 Run):
        # NRT/device producers publish through the informer store each tick
        informer_reports = self.informer.sync_plugins(now)
        collected = self.advisor.run_once(now)
        reported = None
        if self.reporter is not None and now >= self._next_report:
            reported = self.reporter.collect(now)
            self._next_report = now + self.report_interval
        strategies = self.qos.run_once(now)
        if self.auditor is not None and strategies:
            self.auditor.log("qos-tick", strategies=",".join(strategies))
        self.metrics.counter_add("koordlet_ticks_total", 1)
        self.metrics.gauge_set("koordlet_collectors_last_run", len(collected))
        return {
            "pleg_events": events,
            "collectors": collected,
            "strategies": strategies,
            "node_metric": reported,
            "informer_reports": informer_reports,
        }

    # -- live loop --
    def run(
        self,
        interval_seconds: float = 1.0,
        stop: Optional[Callable[[], bool]] = None,
    ) -> None:
        while not self._stop.is_set() and not (stop and stop()):
            self.run_once()
            self._stop.wait(interval_seconds)

    def start(self, interval_seconds: float = 1.0) -> None:
        t = threading.Thread(
            target=self.run, args=(interval_seconds,), daemon=True
        )
        t.start()
        self._threads.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        if self.nri is not None:
            self.nri.close()
        for t in self._threads:
            t.join(timeout=5)
        if self.predict is not None:
            self.predict.checkpoint_all()
        close = getattr(self.cache, "close", None)
        if close is not None:
            close()  # the WAL handle belongs to the Daemon that built it


def build_default_daemon(
    *,
    cgroup_root: str = "/",
    storage_dir: Optional[str] = None,
    audit_dir: Optional[str] = None,
    nri_socket: Optional[str] = None,
    node_name: str = "",
    evict_fn=None,
) -> Daemon:
    """Wire the reference's default module set (koordlet.go:126-178):
    metriccache -> statesinformer -> the metricsadvisor collector battery
    -> qosmanager strategies -> audit/metrics, against the host sysfs.
    Everything goes through the Daemon constructor so MetricsAdvisor's
    enabled() gate applies to the default battery too."""
    from koordinator_tpu.koordlet.collectors import (
        BEResourceCollector,
        DeviceCollector,
        NodeResourceCollector,
        PSICollector,
        SysResourceCollector,
    )
    from koordinator_tpu.koordlet.qosmanager import (
        Evictor,
        default_qos_strategies,
    )
    from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
    from koordinator_tpu.koordlet.statesinformer import (
        DeviceReporter,
        NodeTopoReporter,
    )

    if not node_name:
        # reference koordlet resolves the node name from NODE_NAME; an
        # empty name would publish an NRT no scheduler could match
        import os
        import socket as _socket

        node_name = os.environ.get("NODE_NAME") or _socket.gethostname()
    fs = SysFS(root=cgroup_root)
    informer = StatesInformer()
    executor = ResourceUpdateExecutor(fs)
    # the eviction sink: production passes evict_fn (the reference calls
    # the apiserver eviction API); it rides on the returned Daemon so
    # callers can inspect the ledger
    evictor = Evictor(evict_fn)
    if storage_dir:
        from koordinator_tpu.koordlet.metriccache import PersistentMetricCache

        cache = PersistentMetricCache(storage_dir)
    else:
        cache = MetricCache()
    daemon = Daemon(
        fs=fs,
        cache=cache,
        informer=informer,
        collectors=[
            NodeResourceCollector(fs, cache),
            PSICollector(fs, cache),
            BEResourceCollector(fs, cache),
            SysResourceCollector(cache),
            DeviceCollector(cache),
        ],
        strategies=default_qos_strategies(informer, cache, executor, evictor),
        reporter=NodeMetricReporter(cache, informer),
        auditor=Auditor(audit_dir) if audit_dir else None,
        nri_socket=nri_socket,
        evictor=evictor,
    )
    # informer producer plugins (reference impl/registry.go): publish
    # NodeResourceTopology and the Device CR each tick
    informer.register_plugin(NodeTopoReporter(fs, informer, node_name))
    informer.register_plugin(DeviceReporter(informer))
    return daemon


def main(argv=None) -> int:
    """koordlet CLI (cmd/koordlet/main.go): the node agent + /metrics
    and /events HTTP exposition."""
    import argparse
    from wsgiref.simple_server import make_server

    from koordinator_tpu.httpserving import HTTPLifecycle

    ap = argparse.ArgumentParser(prog="koordlet")
    ap.add_argument("--cgroup-root", default="/")
    ap.add_argument(
        "--storage-dir", default=None,
        help="durable metric WAL dir (restart keeps aggregation windows)",
    )
    ap.add_argument("--audit-dir", default=None)
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument(
        "--nri-socket", default=None,
        help="runtime NRI socket; when set koordlet registers as an NRI "
        "plugin (third hook delivery mode beside proxy/reconciler)",
    )
    ap.add_argument("--node-name", default="")
    ap.add_argument("--http-host", default="127.0.0.1")
    ap.add_argument("--http-port", type=int, default=9316)
    args = ap.parse_args(argv)

    daemon = build_default_daemon(
        cgroup_root=args.cgroup_root,
        storage_dir=args.storage_dir,
        audit_dir=args.audit_dir,
        nri_socket=args.nri_socket,
        node_name=args.node_name,
    )

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "")
        if path == "/metrics":
            return daemon.metrics.wsgi_app(environ, start_response)
        if path == "/events" and daemon.auditor is not None:
            return daemon.auditor.wsgi_app(environ, start_response)
        start_response("404 Not Found", [("Content-Type", "text/plain")])
        return [b"not found"]

    # bind BEFORE the tick loop starts: a port conflict must be a clean
    # no-op, never a daemon left mutating cgroups with no teardown path
    http = HTTPLifecycle(make_server(args.http_host, args.http_port, app))
    daemon.start(args.interval)
    http.start()
    try:
        threading.Event().wait()  # koordlint: disable=unbounded-wait(main thread parks forever by design; the daemon threads own the work and KeyboardInterrupt unparks)
    except KeyboardInterrupt:
        pass
    finally:
        daemon.shutdown()
        http.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
