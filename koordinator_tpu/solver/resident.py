"""On-device sparse updates for the resident ClusterSnapshot.

The warm-cycle fast path (bridge/state.py) keeps the committed snapshot's
``jax.Array`` tensors alive across Syncs.  A warm Sync's sparse delta
frame is applied here as a jitted scatter straight into the resident
device buffer — the old buffer is DONATED (it is dead the moment the new
generation commits), so the update is in-place on backends that support
aliasing and the warm path never re-uploads the full table.

Exactness contract: a scatter of (idx, val) onto the resident array is
bit-identical to re-encoding the updated host mirror, because the flat
index space of the unpadded [N, ...] mirror embeds prefix-wise into the
row-padded [N_bucket, ...] device array (same trailing dims, row-major);
tests/test_resident_warm.py fuzzes this against cold re-encodes.

Compile economics: delta sizes vary per cycle, so (idx, val) are padded
to power-of-two buckets (pad slots carry an out-of-range index dropped
by ``mode="drop"``) — one compiled scatter per (shape, dtype, bucket)
instead of one per delta length.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from koordinator_tpu.model.snapshot import pad_bucket
from koordinator_tpu.obs import devprof


@devprof.boundary("solver.resident._scatter_flat")
@partial(jax.jit, donate_argnums=(0,))
def _scatter_flat(arr, idx, val):
    """arr.flat[idx] = val (OOB indices dropped), preserving arr's dtype.

    ``arr`` is donated: the pre-delta buffer backs the post-delta array
    where the backend supports input/output aliasing, so a warm update
    costs one small (idx, val) upload instead of a full-table transfer.
    """
    flat = arr.reshape(-1)
    flat = flat.at[idx].set(val.astype(arr.dtype), mode="drop")
    return flat.reshape(arr.shape)


@devprof.boundary("solver.resident._scatter_flat_sharded")
@partial(jax.jit, static_argnames=("mesh",), donate_argnums=(0,))
def _scatter_flat_sharded(arr, idx, val, *, mesh):
    """Shard-LOCAL scatter into a mesh-resident node tensor (ISSUE 7).

    ``arr`` is sharded along its leading (node) axis over the cluster
    mesh; ``idx``/``val`` replicate.  Each device rebases the global
    flat indices against its own shard's flat offset and scatters only
    the cells it owns — indices outside the shard (including the pad
    slots, which target ``arr.size`` globally) rebase out of the local
    range and are dropped.  NO collective runs: a delta for node *j*
    lands on the one device holding *j*'s rows, every other shard's
    program is a no-op scatter, and the donated pre-delta buffers alias
    in place per shard exactly like the single-chip path.

    Shardings are preserved (in_specs == out_specs), so the warm path
    never silently regathers the snapshot; one compiled program per
    (shape, dtype, bucket, mesh), same sticky-bucket economics as
    ``_scatter_flat``.
    """
    from koordinator_tpu.parallel.mesh import CLUSTER_AXIS, shard_map_compat
    from jax.sharding import PartitionSpec as P

    spec = P(CLUSTER_AXIS, *([None] * (arr.ndim - 1)))

    def body(a, idx, val):
        # contiguous leading-axis sharding: shard s owns the global flat
        # range [s * a.size, (s + 1) * a.size)
        start = jax.lax.axis_index(CLUSTER_AXIS).astype(idx.dtype) * a.size
        loc = idx - start
        owned = (loc >= 0) & (loc < a.size)
        loc = jnp.where(owned, loc, a.size)  # not-mine -> dropped
        flat = a.reshape(-1)
        flat = flat.at[loc].set(val.astype(a.dtype), mode="drop")
        return flat.reshape(a.shape)

    return shard_map_compat(
        body, mesh=mesh, in_specs=(spec, P(), P()), out_specs=spec
    )(arr, idx, val)


def apply_flat_delta(arr: "jax.Array", idx, val, mesh=None) -> "jax.Array":
    """Apply a sparse (flat-index, value) delta to a resident device array.

    ``idx``/``val`` are host arrays in the UNPADDED mirror's flat index
    space; because padding only appends rows, the same flat indices address
    the same cells in the row-padded resident array.  Returns the updated
    array; the input array is donated (dead) afterwards — callers must
    re-bind or drop their reference (the koordlint ``donation-safety``
    rule enforces this for module-local call sites; cross-module callers
    own the contract, see docs/ANALYSIS.md).

    Cross-THREAD donation contract (ISSUE 5): since the bridge daemon
    stopped serializing RPCs under one lock, a concurrent Score batch
    may hold a captured reference to the pre-delta snapshot.  Callers
    must launch this scatter under the device-dispatch lock
    (bridge/coalesce.py ``run_exclusive``) so the donation only
    invalidates buffers no in-flight launch can still read back; the
    scatter itself is a non-blocking async launch, which is what lets
    the next Sync's decode overlap it (docs/PIPELINE.md).

    ``mesh``: a cluster mesh (parallel/mesh.py) routes the scatter
    through the shard-local program — ``arr`` must be node-sharded over
    it; only the shard owning each index writes, nothing regathers.
    """
    idx = np.asarray(idx, np.int64)
    val = np.asarray(val, np.int64)
    bucket = pad_bucket(max(len(idx), 1))
    if len(idx) < bucket:
        # pad slots target arr.size, which mode="drop" discards
        pad = bucket - len(idx)
        idx = np.concatenate([idx, np.full(pad, arr.size, np.int64)])
        val = np.concatenate([val, np.zeros(pad, np.int64)])
    if mesh is not None and mesh.size > 1:
        scatter, kw = _scatter_flat_sharded, {"mesh": mesh}
    else:
        scatter, kw = _scatter_flat, {}
    return scatter(arr, jnp.asarray(idx), jnp.asarray(val), **kw)
