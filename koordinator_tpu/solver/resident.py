"""On-device sparse updates for the resident ClusterSnapshot.

The warm-cycle fast path (bridge/state.py) keeps the committed snapshot's
``jax.Array`` tensors alive across Syncs.  A warm Sync's sparse delta
frame is applied here as a jitted scatter straight into the resident
device buffer — the old buffer is DONATED (it is dead the moment the new
generation commits), so the update is in-place on backends that support
aliasing and the warm path never re-uploads the full table.

Exactness contract: a scatter of (idx, val) onto the resident array is
bit-identical to re-encoding the updated host mirror, because the flat
index space of the unpadded [N, ...] mirror embeds prefix-wise into the
row-padded [N_bucket, ...] device array (same trailing dims, row-major);
tests/test_resident_warm.py fuzzes this against cold re-encodes.

Compile economics: delta sizes vary per cycle, so (idx, val) are padded
to power-of-two buckets (pad slots carry an out-of-range index dropped
by ``mode="drop"``) — one compiled scatter per (shape, dtype, bucket)
instead of one per delta length.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from koordinator_tpu.model.snapshot import pad_bucket


@partial(jax.jit, donate_argnums=(0,))
def _scatter_flat(arr, idx, val):
    """arr.flat[idx] = val (OOB indices dropped), preserving arr's dtype.

    ``arr`` is donated: the pre-delta buffer backs the post-delta array
    where the backend supports input/output aliasing, so a warm update
    costs one small (idx, val) upload instead of a full-table transfer.
    """
    flat = arr.reshape(-1)
    flat = flat.at[idx].set(val.astype(arr.dtype), mode="drop")
    return flat.reshape(arr.shape)


def apply_flat_delta(arr: "jax.Array", idx, val) -> "jax.Array":
    """Apply a sparse (flat-index, value) delta to a resident device array.

    ``idx``/``val`` are host arrays in the UNPADDED mirror's flat index
    space; because padding only appends rows, the same flat indices address
    the same cells in the row-padded resident array.  Returns the updated
    array; the input array is donated (dead) afterwards — callers must
    re-bind or drop their reference (the koordlint ``donation-safety``
    rule enforces this for module-local call sites; cross-module callers
    own the contract, see docs/ANALYSIS.md).

    Cross-THREAD donation contract (ISSUE 5): since the bridge daemon
    stopped serializing RPCs under one lock, a concurrent Score batch
    may hold a captured reference to the pre-delta snapshot.  Callers
    must launch this scatter under the device-dispatch lock
    (bridge/coalesce.py ``run_exclusive``) so the donation only
    invalidates buffers no in-flight launch can still read back; the
    scatter itself is a non-blocking async launch, which is what lets
    the next Sync's decode overlap it (docs/PIPELINE.md).
    """
    idx = np.asarray(idx, np.int64)
    val = np.asarray(val, np.int64)
    bucket = pad_bucket(max(len(idx), 1))
    if len(idx) < bucket:
        # pad slots target arr.size, which mode="drop" discards
        pad = bucket - len(idx)
        idx = np.concatenate([idx, np.full(pad, arr.size, np.int64)])
        val = np.concatenate([val, np.zeros(pad, np.int64)])
    return _scatter_flat(arr, jnp.asarray(idx), jnp.asarray(val))
