"""Wave/top-M certification: the shared math of the round-based cycle.

``greedy_assign`` replays the reference's one-pod-at-a-time cycle, so any
batched variant must prove each pod's choice equals what the sequential
scan would have picked.  Both round-based paths — the multi-chip
``parallel/shard_assign.py greedy_assign_waves`` and the single-chip
``wave_assign`` below — share that proof, so its primitives live here
exactly once:

* the packed (score, node) key: ``score * N + (N - 1 - node)``.  One
  integer max selects the highest score with the LOWEST node index — the
  same tie-break as ``jnp.argmax`` in the scan path — and keys are unique
  (the index term), which the certification argument leans on;
* the in-wave resolution ``resolve_wave``: every pod of a wave froze its
  global top-M candidate keys against round-start state; pods resolve in
  queue order, replaying earlier in-wave commits (node requested /
  estimated deltas, quota deltas) onto the candidates and certifying the
  winner against the frozen M-th key ``k_M``.  The first pod that cannot
  be certified ends the commit prefix — it and everything after rerun
  next round against fresh state.

Certification, in full (the part a maintainer can silently break; also
docs/KERNEL.md "Wave batching"):

* under LeastAllocated scoring keys are non-increasing as load commits,
  so any node outside a pod's frozen top-M stays strictly below the
  frozen ``k_M`` forever within the wave — re-keying the M candidates is
  enough, and the choice is EXACT whenever the best current candidate
  key is still >= ``k_M``;
* under MostAllocated keys INCREASE with committed load, which inverts
  that bound.  The symmetric certificate rides the CLOSED candidate
  universe: every in-wave commit lands on some wave pod's candidate, so
  the union of all wave pods' top-M rows is the only set of nodes whose
  keys can move within the round.  Each pod re-keys that whole universe
  exactly and certifies when the universe best >= its own frozen
  ``k_M``; packed-key uniqueness turns the boundary case into candidate
  membership.  Pod 0 of a round has no earlier in-wave commits, so it
  always commits — liveness holds for both strategies;
* quota admission is node-invariant, so it is rechecked exactly against
  the in-wave quota state: a blocked pod commits as unschedulable with
  no rescan.  A ``-1`` outcome certifies ONLY when it is
  node-independent or when ``k_M`` sits at the sentinel (fewer than M
  frozen-feasible nodes exist, and committed load never turns an
  infeasible node feasible under either strategy).

The Pallas kernel (solver/pallas_cycle.py) mirrors this resolution in
i32 with an unpacked (score, index) lexicographic compare — the packed
key would overflow i32 — and tests/test_parity_fuzz.py holds all three
implementations bit-identical to the scan.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from koordinator_tpu.config import (
    CycleConfig,
    DEFAULT_CYCLE_CONFIG,
    MOST_ALLOCATED,
)
from koordinator_tpu.constraints.gang import gang_satisfaction
from koordinator_tpu.model.snapshot import ClusterSnapshot, PriorityClass
from koordinator_tpu.obs import devprof
from koordinator_tpu.ops.fit import nonzero_requests
from koordinator_tpu.ops.loadaware import (
    loadaware_node_masks,
    select_score_usage,
)
from koordinator_tpu.solver.greedy import (
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    CycleResult,
    queue_order,
    step_feasible_scores,
)

# scores are bounded by plugin weights * MAX_NODE_SCORE (tiny); this
# sentinel for infeasible nodes leaves the packed key far from i64 limits
SENTINEL_SCORE = jnp.int64(-(2**40))


def is_most_allocated(cfg: CycleConfig) -> bool:
    """True when the fit strategy needs the closed-universe certificate
    (scores increase with committed load) instead of the k_M bound."""
    return bool(cfg.enable_fit_score) and (
        cfg.fit_scoring_strategy == MOST_ALLOCATED
    )


def sentinel_threshold(n_total: int):
    """Packed keys at or below this decode as infeasible."""
    return SENTINEL_SCORE * n_total // 2


def pack_keys(total, feasible, node_index, n_total: int):
    """(score, node) -> packed i64 key; infeasible slots take the
    sentinel score but KEEP their index term, so sentinel keys stay
    unique and order by node index like feasible ones."""
    idx_term = n_total - 1 - node_index
    return (
        jnp.where(feasible, total, SENTINEL_SCORE) * n_total + idx_term
    )


def decode_key(key, n_total: int):
    """Packed key -> (score, node i32).  Floor division decodes the
    negative sentinel range too."""
    score = key // n_total
    node = (n_total - 1 - (key - score * n_total)).astype(jnp.int32)
    return score, node


def score_feasible(score):
    """True when a DECODED score (not a packed key) is a real score
    rather than the infeasible sentinel."""
    return score > SENTINEL_SCORE // 2


def flatten_shards(a):
    """Align an ``all_gather``'d per-shard candidate payload by wave
    lane: ``[S, W, M, ...] -> [W, S*M, ...]``.  The flattened axis is
    the pool the cross-shard top-M merge selects from."""
    a = jnp.moveaxis(a, 0, 1)
    return a.reshape((a.shape[0], -1) + a.shape[3:])


def merge_topm_keys(gathered_key, top_m: int):
    """Key-only cross-shard merge: the frozen global top-M packed keys
    per wave pod (ONE ``lax.top_k`` over the flattened ``[W, S*M]``
    pool).  The packed-key tie-break (highest score, lowest node index)
    rides the key encoding itself, so this merge orders identically to
    the scan path's ``pmax``/``argmax`` — the MostAllocated universe
    certificate needs only the resulting ``k_M`` bar."""
    cand_key, _ = lax.top_k(flatten_shards(gathered_key), top_m)
    return cand_key


def merge_topm(gathered: dict, top_m: int):
    """The full cross-shard top-M merge for the k_M (LeastAllocated)
    path: flatten every gathered row ``[S, W, M, ...] -> [W, S*M, ...]``,
    select the global top-M by packed ``key``, and gather each winner's
    state rows along.  Returns ``(cand_key i64[W, M], cand dict)`` in
    exactly the shape :func:`resolve_wave` consumes — the one merge
    collective's worth of data every shard reduces identically, keeping
    the round bit-identical to the single-chip oracle."""
    g = {k: flatten_shards(v) for k, v in gathered.items()}
    gkeys, gsel = lax.top_k(g["key"], top_m)

    def take(a):
        sel = gsel
        while sel.ndim < a.ndim:
            sel = sel[..., None]
        return jnp.take_along_axis(a, sel, axis=1)

    cand = {k: take(v) for k, v in g.items() if k != "key"}
    return gkeys, cand


def resolve_wave(
    cand_key,  # i64[W, M] frozen global top-M keys per wave pod
    *,
    cand: Optional[dict] = None,  # k_M path candidate rows (see below)
    universe: Optional[dict] = None,  # closed-universe rows (MostAllocated)
    preq_wave,  # i64[W, R] pod requests, wave order
    pest_wave,  # i64[W, R]
    psreq_wave,  # i64[W, R] nonzero-default score requests
    pqid_wave,  # i32[W]
    pvalid_wave,  # bool[W]
    pprod_wave,  # bool[W]
    wvalid,  # bool[W] lane addresses a real pod slot
    qrt,  # i64[Q, R] quota runtime
    qlim,  # bool[Q, R]
    quse,  # i64[Q, R] quota used at round start
    cfg: CycleConfig,
    n_total: int,
    prod_sensitive: bool,
):
    """Deterministic in-wave resolution + certification (module docstring).

    ``cand`` (LeastAllocated-style k_M path) carries per-pod candidate
    rows, each ``[W, M, ...]``: ``gid`` (i64 node ids), ``alloc``,
    ``nreq``, ``nest``, ``usage`` (prod-selected), ``ok``, ``fresh``,
    ``xval``, ``xfeas``.  ``universe`` (MostAllocated) carries the
    node-keyed closed candidate set, ``[U, ...]``: ``gid``, ``alloc``,
    ``nreq``, ``nest``, ``usage``, ``okd``, ``fresh``, plus per-pod
    ``xval``/``xfeas`` ``[W, U]`` and, when ``prod_sensitive``,
    ``uprod``/``okp``.  Duplicated nodes are harmless — identical rows
    produce identical keys.

    Returns ``(choices i64[W], committed bool[W], done bool[W],
    quota_used, ncommit i64)``; ``done`` marks the committed prefix
    (including -1 commits), ``committed`` the subset that took a node.
    """
    W, M = cand_key.shape
    N = n_total
    most_alloc = is_most_allocated(cfg)
    if most_alloc and universe is None:
        raise ValueError(
            "MostAllocated wave resolution needs the closed candidate "
            "universe (scores rise with committed load; the k_M bound "
            "alone is not exact)"
        )
    if not most_alloc and cand is None:
        raise ValueError("wave resolution needs the candidate rows")
    SENT_TH = sentinel_threshold(N)
    iota_w = jnp.arange(W)
    if most_alloc:
        u_gid = universe["gid"]

    def resolve(i, st):
        choices, committed, active, done, quse_w, ncommit = st
        req = preq_wave[i]
        est = pest_wave[i]
        sreq = psreq_wave[i]
        qid = pqid_wave[i]
        qi = jnp.maximum(qid, 0)
        earlier = committed & (iota_w < i)

        k_m = cand_key[i, M - 1]
        # k_M at sentinel: fewer than M nodes were feasible at frozen
        # state, so ALL feasible nodes are candidates — and committed
        # load never turns an infeasible node feasible under either
        # strategy
        sentinel_m = k_m <= SENT_TH

        if most_alloc:
            # universe certificate (module docstring): re-key the WHOLE
            # closed candidate universe exactly for this pod — frozen
            # rows + the in-wave commit deltas — then certify against
            # the frozen k_M
            hit_u = earlier[:, None] & (
                choices[:, None] == u_gid[None, :]
            )  # [W, U]
            dreq_u = jnp.einsum(
                "wu,wr->ur", hit_u.astype(jnp.int64), preq_wave
            )
            dest_u = jnp.einsum(
                "wu,wr->ur", hit_u.astype(jnp.int64), pest_wave
            )
            if prod_sensitive:
                usage_u = jnp.where(
                    pprod_wave[i], universe["uprod"], universe["usage"]
                )
                ok_u = jnp.where(
                    pprod_wave[i], universe["okp"], universe["okd"]
                )
            else:
                usage_u = universe["usage"]
                ok_u = universe["okd"]
            re_feas, re_total = step_feasible_scores(
                universe["nreq"] + dreq_u,
                universe["nest"] + dest_u,
                quse_w,
                universe["alloc"],
                usage_u,
                universe["fresh"],
                ok_u,
                req,
                sreq,
                est,
                jnp.int32(-1),
                jnp.bool_(True),
                qrt,
                qlim,
                cfg,
            )
            re_total = re_total + jnp.where(
                universe["xfeas"][i], universe["xval"][i], 0
            )
            re_feas = re_feas & universe["xfeas"][i]
            cur = pack_keys(re_total, re_feas, u_gid, N)  # [U]
            best_key = jnp.max(cur)
            best_node = u_gid[jnp.argmax(cur)]
            # pod 0 has no earlier in-wave commits: frozen keys are
            # current, its frozen top-1 is in the universe (liveness:
            # every round commits at least one pod)
            certified = (best_key >= k_m) | sentinel_m | (i == 0)
        else:
            # candidate current keys (recomputed when dirtied in-wave)
            c_nodes = cand["gid"][i]  # [M]
            hit = earlier[:, None] & (
                choices[:, None] == c_nodes[None, :]
            )  # [W, M]
            dreq = jnp.einsum(
                "wm,wr->mr", hit.astype(jnp.int64), preq_wave
            )
            dest = jnp.einsum(
                "wm,wr->mr", hit.astype(jnp.int64), pest_wave
            )
            dirty = jnp.any(hit, axis=0)  # [M]
            # re-key dirtied candidates with the SAME step semantics the
            # scan path and the frozen wave scoring use — the candidate
            # rows stand in as an M-node block, quota disabled (qid=-1;
            # admission is the node-invariant recheck below).  No third
            # copy of Filter+Score exists here.
            re_feas, re_total = step_feasible_scores(
                cand["nreq"][i] + dreq,
                cand["nest"][i] + dest,
                quse_w,
                cand["alloc"][i],
                cand["usage"][i],
                cand["fresh"][i],
                cand["ok"][i],
                req,
                sreq,
                est,
                jnp.int32(-1),
                jnp.bool_(True),
                qrt,
                qlim,
                cfg,
            )
            re_total = re_total + jnp.where(
                cand["xfeas"][i], cand["xval"][i], 0
            )
            re_feas = re_feas & cand["xfeas"][i]
            rekeys = pack_keys(re_total, re_feas, c_nodes, N)
            cur = jnp.where(dirty, rekeys, cand_key[i])  # [M]
            best_key = jnp.max(cur)
            best_node = c_nodes[jnp.argmax(cur)]
            certified = (best_key >= k_m) | sentinel_m
        feas = best_key > SENT_TH

        qblocked = (qid >= 0) & jnp.any(
            qlim[qi] & (quse_w[qi] + req > qrt[qi])
        )
        usable = pvalid_wave[i] & ~qblocked & wvalid[i]
        choice = jnp.where(feas & usable, best_node, -1)
        # a -1 outcome is exact only when it is node-INDEPENDENT
        # (quota-blocked / invalid pod / padding lane) or when
        # sentinel_m says every frozen-feasible node is already a
        # candidate (infeasible stays infeasible under commits).  With
        # k_M > sentinel, "no candidate feasible" proves nothing about
        # nodes OUTSIDE the gathered set — feasible frozen nodes below
        # k_M may remain, so the pod must end the commit prefix and
        # rerun next round against fresh state (certification via
        # sentinel_m is already in `certified`; adding ~feas here would
        # wrongly commit schedulable pods as unschedulable).
        certified = certified | ~usable

        commit = active & certified
        take_node = commit & (choice >= 0)
        choices = choices.at[i].set(jnp.where(take_node, choice, -1))
        committed = committed.at[i].set(take_node)
        done = done.at[i].set(commit)
        quse_w = jnp.where(
            take_node & (qid >= 0),
            quse_w.at[qi].add(req),
            quse_w,
        )
        ncommit = ncommit + jnp.where(commit, 1, 0)
        active = active & certified
        return (choices, committed, active, done, quse_w, ncommit)

    st0 = (
        jnp.full((W,), -1, jnp.int64),
        jnp.zeros((W,), bool),
        jnp.bool_(True),
        jnp.zeros((W,), bool),
        quse,
        jnp.int64(0),
    )
    choices, committed, _, done, quse_new, ncommit = lax.fori_loop(
        0, W, resolve, st0
    )
    return choices, committed, done, quse_new, ncommit


@devprof.boundary("solver.wave._wave_assign")
@partial(
    jax.jit,
    static_argnames=("cfg", "wave", "top_m", "has_mask", "has_scores"),
)
def _wave_assign(
    snapshot: ClusterSnapshot,
    extra_mask,
    extra_scores,
    *,
    cfg: CycleConfig,
    wave: int,
    top_m: int,
    has_mask: bool,
    has_scores: bool,
):
    """Single-chip round-based cycle: O(P / commit-prefix) sequential
    rounds instead of O(P) scan steps.

    Each round scores the next ``wave`` pods against the frozen node
    table as ONE ``[W, N]`` tensor op (vmapped ``step_feasible_scores``
    — VPU/MXU-friendly instead of ``[N]`` vector ops), freezes each
    pod's global top-``top_m`` packed keys via ``lax.top_k``, and runs
    the shared ``resolve_wave`` certification; the committed prefix
    lands on the carried node/quota state and the pointer advances by
    its length.  Bit-identical with ``greedy_assign`` (same packed-key
    tie-break, same WAIT_GANG semantics, same ElasticQuota admission
    order); parity fuzzed in tests/test_parity_fuzz.py.
    """
    pods, nodes, gangs, quotas = (
        snapshot.pods,
        snapshot.nodes,
        snapshot.gangs,
        snapshot.quotas,
    )
    PCAP = pods.capacity
    N = nodes.allocatable.shape[0]
    W = wave
    M = max(1, min(top_m, N))

    order = queue_order(pods.priority, pods.valid)
    order_pad = jnp.concatenate([order, jnp.zeros((W,), order.dtype)])
    score_requests = nonzero_requests(pods.requests)

    mask_default, mask_prod = loadaware_node_masks(nodes, cfg)
    if not cfg.enable_loadaware:
        mask_default = jnp.ones_like(mask_default)
        mask_prod = mask_default
    node_ok_default = nodes.valid & mask_default
    node_ok_prod = nodes.valid & mask_prod
    usage_np, usage_prod = select_score_usage(nodes, cfg)
    prod_sensitive = cfg.enable_loadaware and (
        usage_prod is not None
        or bool(dict(cfg.loadaware.prod_usage_thresholds))
    )
    uprod = usage_prod if usage_prod is not None else usage_np
    is_prod_pods = pods.priority_class == int(PriorityClass.PROD)

    alloc = nodes.allocatable
    fresh = nodes.metric_fresh
    gidx = jnp.arange(N, dtype=jnp.int64)
    iota_w = jnp.arange(W)
    qrt, qlim = quotas.runtime, quotas.limited
    most_alloc = is_most_allocated(cfg)

    def one_pod_keys(nreq, nest, p):
        """Frozen [N] packed keys for pod p (quota handled in the
        resolution, so qid=-1 here)."""
        if prod_sensitive:
            ok_p = jnp.where(is_prod_pods[p], node_ok_prod, node_ok_default)
            usage_p = jnp.where(is_prod_pods[p], uprod, usage_np)
        else:
            ok_p = node_ok_default
            usage_p = usage_np
        feasible, total = step_feasible_scores(
            nreq, nest, quotas.used, alloc, usage_p, fresh, ok_p,
            pods.requests[p], score_requests[p], pods.estimated[p],
            jnp.int32(-1), pods.valid[p], qrt, qlim, cfg,
        )
        if has_mask:
            feasible = feasible & extra_mask[p]
        if has_scores:
            total = total + extra_scores[p]
        return pack_keys(total, feasible, gidx, N)

    def wave_round(carry):
        ptr, nreq, nest, quse, chosen_buf, nrounds = carry
        ps = lax.dynamic_slice(order_pad, (ptr,), (W,))
        wvalid = (ptr + iota_w) < PCAP
        # ONE [W, N] scoring op for the whole wave
        keys = jax.vmap(lambda p: one_pod_keys(nreq, nest, p))(ps)
        cand_key, lidx = lax.top_k(keys, M)  # [W, M]

        preq_wave = pods.requests[ps]
        pest_wave = pods.estimated[ps]
        psreq_wave = score_requests[ps]
        pqid_wave = pods.quota_id[ps]
        pvalid_wave = pods.valid[ps]
        pprod_wave = is_prod_pods[ps]

        if most_alloc:
            # the closed candidate universe: union of the wave's top-M
            # rows, keyed by node (duplicates harmless)
            uni = lidx.reshape(-1)  # [W*M]
            universe = dict(
                gid=uni.astype(jnp.int64),
                alloc=alloc[uni],
                nreq=nreq[uni],
                nest=nest[uni],
                usage=usage_np[uni],
                okd=node_ok_default[uni],
                fresh=fresh[uni],
                xval=(
                    extra_scores[ps[:, None], uni[None, :]]
                    if has_scores
                    else jnp.zeros((W, W * M), jnp.int64)
                ),
                xfeas=(
                    extra_mask[ps[:, None], uni[None, :]]
                    if has_mask
                    else jnp.ones((W, W * M), bool)
                ),
            )
            if prod_sensitive:
                universe["uprod"] = uprod[uni]
                universe["okp"] = node_ok_prod[uni]
            cand = None
        else:
            universe = None
            if prod_sensitive:
                usage_rows = jnp.where(
                    pprod_wave[:, None, None], uprod[lidx], usage_np[lidx]
                )
                ok_rows = jnp.where(
                    pprod_wave[:, None],
                    node_ok_prod[lidx],
                    node_ok_default[lidx],
                )
            else:
                usage_rows = usage_np[lidx]
                ok_rows = node_ok_default[lidx]
            cand = dict(
                gid=lidx.astype(jnp.int64),
                alloc=alloc[lidx],
                nreq=nreq[lidx],
                nest=nest[lidx],
                usage=usage_rows,
                ok=ok_rows,
                fresh=fresh[lidx],
                xval=(
                    extra_scores[ps[:, None], lidx]
                    if has_scores
                    else jnp.zeros((W, M), jnp.int64)
                ),
                xfeas=(
                    extra_mask[ps[:, None], lidx]
                    if has_mask
                    else jnp.ones((W, M), bool)
                ),
            )

        choices, committed, done, quse_new, ncommit = resolve_wave(
            cand_key,
            cand=cand,
            universe=universe,
            preq_wave=preq_wave,
            pest_wave=pest_wave,
            psreq_wave=psreq_wave,
            pqid_wave=pqid_wave,
            pvalid_wave=pvalid_wave,
            pprod_wave=pprod_wave,
            wvalid=wvalid,
            qrt=qrt,
            qlim=qlim,
            quse=quse,
            cfg=cfg,
            n_total=N,
            prod_sensitive=prod_sensitive,
        )

        # apply the committed prefix to the carried node state
        onehot = (
            (choices[:, None] == jnp.arange(N, dtype=choices.dtype)[None, :])
            & committed[:, None]
        ).astype(jnp.int64)
        nreq = nreq + jnp.einsum("wn,wr->nr", onehot, preq_wave)
        nest = nest + jnp.einsum("wn,wr->nr", onehot, pest_wave)

        write = jnp.where(done, choices.astype(jnp.int32), jnp.int32(-1))
        # positions not committed this round keep their buffer value
        # (they will be rewritten when their round comes)
        window = lax.dynamic_slice(chosen_buf, (ptr,), (W,))
        window = jnp.where(done, write, window)
        chosen_buf = lax.dynamic_update_slice(chosen_buf, window, (ptr,))

        return (ptr + ncommit, nreq, nest, quse_new, chosen_buf, nrounds + 1)

    def cond(carry):
        return carry[0] < PCAP

    init = (
        jnp.int64(0),
        nodes.requested,
        jnp.zeros_like(nodes.requested),
        quotas.used,
        jnp.full((PCAP + W,), -1, jnp.int32),
        jnp.int64(0),
    )
    _, node_requested, node_estimated, quota_used, chosen_buf, nrounds = (
        lax.while_loop(cond, wave_round, init)
    )

    assignment = (
        jnp.full((PCAP,), -1, jnp.int32).at[order].set(chosen_buf[:PCAP])
    )
    status = jnp.where(assignment >= 0, STATUS_ASSIGNED, STATUS_UNSCHEDULABLE)
    assigned = (assignment >= 0) & pods.valid
    _, pod_gang_ok = gang_satisfaction(
        assignment, pods.valid, pods.gang_id, gangs.min_member
    )
    status = jnp.where(assigned & ~pod_gang_ok, STATUS_WAIT_GANG, status)
    return CycleResult(
        assignment=assignment,
        status=status.astype(jnp.int32),
        node_requested=node_requested,
        node_estimated=node_estimated,
        quota_used=quota_used,
        rounds=nrounds,
        path="wave",
    )


def wave_assign(
    snapshot: ClusterSnapshot,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    extra_mask: Optional[jnp.ndarray] = None,
    extra_scores: Optional[jnp.ndarray] = None,
    wave: Optional[int] = None,
    top_m: Optional[int] = None,
    scores_hi: Optional[int] = None,
) -> CycleResult:
    """Wave-batched drop-in for ``greedy_assign``: bit-identical
    placements, ~W pods committed per sequential round.

    ``wave``/``top_m`` default from the ``CycleConfig`` knobs; both are
    STATIC jit arguments (a traced wave width would retrace every cycle
    — the koordlint retrace-hazard rule enforces this at every jit
    boundary).  Returns a ``CycleResult`` with ``rounds`` set to the
    number of sequential wave rounds and ``path="wave"``.

    ``scores_hi``: callers that already reduced ``extra_scores`` to its
    max magnitude (the run_cycle dispatcher does, for its kernel bound)
    pass it to skip a second blocking device->host reduction per cycle
    — the ``i32_ok`` pattern.
    """
    W = int(cfg.wave if wave is None else wave)
    M = int(cfg.top_m if top_m is None else top_m)
    if W < 1 or M < 1:
        raise ValueError(f"wave ({W}) and top_m ({M}) must be >= 1")
    if extra_scores is not None:
        # the packed key multiplies scores by N; plugin scores are tiny
        # by construction, but extra_scores is caller-supplied — values
        # at the sentinel's magnitude would decode as infeasible (or
        # overflow the key), silently breaking parity
        hi = (
            int(jnp.max(jnp.abs(extra_scores)))
            if scores_hi is None
            else int(scores_hi)
        )
        if hi >= 2**31:
            raise ValueError(
                f"extra_scores magnitude {hi} too large for the packed "
                "key (must be < 2^31); use solver.greedy_assign"
            )
    return _wave_assign(
        snapshot,
        extra_mask,
        extra_scores,
        cfg=cfg,
        wave=W,
        top_m=M,
        has_mask=extra_mask is not None,
        has_scores=extra_scores is not None,
    )
