"""Batched assignment solver: one device program replaces the per-pod cycle.

The reference schedules one pod at a time: PreFilter -> parallel Filter ->
parallel Score -> Reserve mutates plugin caches (assign-cache
``plugins/loadaware/pod_assign_cache.go``; NodeInfo requested) so the next
pod sees the updated world.  ``greedy_assign`` reproduces those sequential
semantics exactly with a ``lax.scan`` over pods in queue order, carrying
(node_requested, node_estimated, quota_used) as scan state — so its
placements match the reference pod-for-pod — while ``score_cycle`` is the
stateless "score every pending pod at once" tensor program for score-only
parity and for the descheduler's candidate ranking.

Queue order follows the Coscheduling QueueSort (``coscheduling.go:118``):
higher priority first, then stable by submission index.

Gang all-or-nothing (Permit, ``coscheduling/core/core.go:308``): after the
scan, gangs whose assigned-member count is below minMember have their pods
marked WAIT_GANG — resources stay reserved within the cycle, exactly like
waiting pods hold their reservations in the reference's Permit stage.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG, MOST_ALLOCATED
from koordinator_tpu.constraints.gang import gang_satisfaction
from koordinator_tpu.model.snapshot import ClusterSnapshot
from koordinator_tpu.obs import devprof
from koordinator_tpu.ops.fit import fit_mask, nonzero_requests
from koordinator_tpu.ops.loadaware import (
    loadaware_node_masks,
    select_score_usage,
)
from koordinator_tpu.model.snapshot import PriorityClass
from koordinator_tpu.ops.scoring import (
    least_requested_score,
    most_requested_score,
    weighted_resource_score,
)

STATUS_ASSIGNED = 0
STATUS_UNSCHEDULABLE = 1
STATUS_WAIT_GANG = 2


@dataclasses.dataclass
class CycleResult:
    assignment: jnp.ndarray  # i32[P] node index, -1 = none
    status: jnp.ndarray  # i32[P]
    scores: Optional[jnp.ndarray] = None  # i64[P, N] (score_cycle only)
    node_requested: Optional[jnp.ndarray] = None  # i64[N, R] post-cycle
    node_estimated: Optional[jnp.ndarray] = None  # i64[N, R] post-cycle
    quota_used: Optional[jnp.ndarray] = None  # i64[Q, R] post-cycle
    # sequential round count of the wave-batched paths (solver/wave.py,
    # the wave Pallas kernel, parallel/shard_assign.py): ~P/wave-prefix
    # rounds vs P scan steps — surfaced so bench.py can publish the win;
    # None on the per-pod paths
    rounds: Optional[jnp.ndarray] = None
    # which code path produced the result ("pallas" single-kernel cycle,
    # "scan" lax.scan, "wave" round-based single chip, "shard" multi-chip
    # shard_map) — static metadata so callers (bridge AssignReply, bench)
    # can surface degraded-path runs; VERDICT r2 flagged the
    # silent-fallback invisibility
    path: Optional[str] = None


jax.tree_util.register_dataclass(
    CycleResult,
    data_fields=[
        "assignment",
        "status",
        "scores",
        "node_requested",
        "node_estimated",
        "quota_used",
        "rounds",
    ],
    meta_fields=["path"],
)


def queue_order(priority: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Pod visit order: priority desc, stable by index; padding last."""
    key = jnp.where(valid, -priority.astype(jnp.int64), jnp.iinfo(jnp.int64).max)
    return jnp.argsort(key, stable=True)


def _fit_score_requests(requests: jnp.ndarray) -> jnp.ndarray:
    return nonzero_requests(requests)


def _combined_scores(
    snapshot: ClusterSnapshot,
    node_requested: jnp.ndarray,
    node_estimated: jnp.ndarray,
    cfg: CycleConfig,
    pod_requests: jnp.ndarray,
    pod_score_requests: jnp.ndarray,
    pod_estimated: jnp.ndarray,
):
    """Weighted sum of enabled plugin scores; broadcasting over [P?, N]."""
    nodes = snapshot.nodes
    total = jnp.zeros(
        pod_requests.shape[:-1] + (nodes.allocatable.shape[0],), jnp.int64
    )
    if cfg.enable_fit_score:
        t = node_requested + pod_score_requests[..., None, :]
        if cfg.fit_scoring_strategy == MOST_ALLOCATED:
            per_res = most_requested_score(t, nodes.allocatable)
        else:
            per_res = least_requested_score(t, nodes.allocatable)
        total = total + cfg.fit_plugin_weight * weighted_resource_score(
            per_res, cfg.fit_weights_arr()
        )
    if cfg.enable_loadaware:
        usage_np, usage_prod = select_score_usage(nodes, cfg)
        usage_sel = usage_np[None, :, :]
        if usage_prod is not None:
            is_prod = (
                snapshot.pods.priority_class == int(PriorityClass.PROD)
            )
            usage_sel = jnp.where(
                is_prod[:, None, None], usage_prod[None, :, :], usage_sel
            )
        est_used = usage_sel + node_estimated + pod_estimated[..., None, :]
        per_res = least_requested_score(est_used, nodes.allocatable)
        la = weighted_resource_score(per_res, cfg.loadaware_weights_arr())
        la = jnp.where(nodes.metric_fresh, la, 0)
        total = total + cfg.loadaware_plugin_weight * la
    return total


def step_feasible_scores(
    node_requested: jnp.ndarray,  # i64[N, R] carried
    node_estimated: jnp.ndarray,  # i64[N, R] carried
    quota_used: jnp.ndarray,  # i64[Q, R] carried
    alloc: jnp.ndarray,  # i64[N, R]
    usage: jnp.ndarray,  # i64[N, R]
    fresh: jnp.ndarray,  # bool[N]
    node_ok: jnp.ndarray,  # bool[N] valid & loadaware filter
    req: jnp.ndarray,  # i64[R] one pod
    sreq: jnp.ndarray,  # i64[R]
    est: jnp.ndarray,  # i64[R]
    qid: jnp.ndarray,  # i32 scalar
    is_valid: jnp.ndarray,  # bool scalar
    qrt: jnp.ndarray,  # i64[Q, R]
    qlim: jnp.ndarray,  # bool[Q, R]
    cfg: CycleConfig,
):
    """One pod's Filter+Score against a node-state block -> (feasible[N],
    scores[N]).  The single source of the sequential-cycle step semantics,
    shared by ``greedy_assign`` and the shard_map variant
    (parallel/shard_assign.py); the Pallas kernel mirrors it in i32."""
    q = jnp.maximum(qid, 0)
    need = req > 0
    fits = jnp.all(
        jnp.where(need[None, :], node_requested + req[None, :] <= alloc, True),
        axis=-1,
    )
    quota_ok = jnp.where(
        qid >= 0,
        jnp.all(jnp.where(qlim[q], quota_used[q] + req <= qrt[q], True)),
        True,
    )
    feasible = fits & node_ok & quota_ok & is_valid

    total = jnp.zeros((alloc.shape[0],), jnp.int64)
    if cfg.enable_fit_score:
        t = node_requested + sreq[None, :]
        if cfg.fit_scoring_strategy == MOST_ALLOCATED:
            per_res = most_requested_score(t, alloc)
        else:
            per_res = least_requested_score(t, alloc)
        total = total + cfg.fit_plugin_weight * weighted_resource_score(
            per_res, cfg.fit_weights_arr()
        )
    if cfg.enable_loadaware:
        est_used = usage + node_estimated + est[None, :]
        per_res = least_requested_score(est_used, alloc)
        la = jnp.where(fresh, weighted_resource_score(per_res, cfg.loadaware_weights_arr()), 0)
        total = total + cfg.loadaware_plugin_weight * la
    return feasible, total


def feasibility_mask(
    snapshot: ClusterSnapshot, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG
) -> jnp.ndarray:
    """The MASK half of :func:`score_all`, standalone (ISSUE 16).

    Requests-fit + node-validity + loadaware freshness/threshold masks +
    every enabled term's feasibility mask, with zero scoring arithmetic
    — the cheap feasibility pre-mask the sparse candidate engine
    (solver/candidates.py) evaluates blockwise to pick each pod's
    candidate set without ever materializing the [P, N] score tensor.
    Cellwise in (pod row, node row) like everything else in the body,
    so it is shape-polymorphic over gathered sub-snapshots.

    Exactness: ``score_all`` composes this mask with the score half;
    masks only AND together and scores only add, so factoring changes
    no bits — the bool this returns at (p, n) is the very ``feasible``
    bit a full ``score_cycle`` would produce.
    """
    pods, nodes = snapshot.pods, snapshot.nodes
    feasible = fit_mask(
        pods.requests, nodes.requested, nodes.allocatable, nodes.valid, pods.valid
    )
    if cfg.enable_loadaware:
        mask_default, mask_prod = loadaware_node_masks(nodes, cfg)
        is_prod = pods.priority_class == int(PriorityClass.PROD)
        la_mask = jnp.where(
            is_prod[:, None], mask_prod[None, :], mask_default[None, :]
        )
        feasible = feasible & la_mask
    from koordinator_tpu.solver.terms import apply_term_masks

    return apply_term_masks(snapshot, cfg, feasible)


def score_all(snapshot: ClusterSnapshot, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG):
    """The scoring math of :func:`score_cycle`, un-jitted.

    The ONE statement of the stateless Filter+Score semantics, shared by
    the jitted full rescore (``score_cycle``) and the incremental
    column/row rescore (solver/incremental.py, ISSUE 9) — every term is
    cellwise in (pod row, node row), which is exactly what makes
    "gather rows, score, scatter back" bit-identical to a full rescore,
    and sharing the body is what keeps the two engines from drifting.

    The fused scoring-term registry (ISSUE 15, solver/terms.py) rides
    the same body: heterogeneity / sensitivity / packing contributions
    are added INSIDE this one tensor program — cellwise by contract, so
    the incremental exactness argument extends to them unchanged and a
    three-term Score still costs exactly one launch.

    Composed (ISSUE 16) from :func:`feasibility_mask` (the mask half —
    the sparse engine's standalone pre-mask) and the score half; the
    halves commute, so the factoring is bitwise free.
    """
    pods, nodes = snapshot.pods, snapshot.nodes
    feasible = feasibility_mask(snapshot, cfg)
    zero_nr = jnp.zeros_like(nodes.requested)
    scores = _combined_scores(
        snapshot,
        nodes.requested,
        zero_nr,
        cfg,
        pods.requests,
        _fit_score_requests(pods.requests),
        pods.estimated,
    )
    from koordinator_tpu.solver.terms import apply_term_scores

    return apply_term_scores(snapshot, cfg, scores), feasible


@devprof.boundary("solver.greedy.score_cycle")
@partial(jax.jit, static_argnames=("cfg",))
def score_cycle(snapshot: ClusterSnapshot, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG):
    """Stateless batch scoring: scores + feasibility for every (pod, node).

    Equivalent to running the reference's Filter+Score for each pending pod
    against the *initial* snapshot (no intra-batch Reserve effects).
    Returns (scores i64[P, N], feasible bool[P, N]).
    """
    return score_all(snapshot, cfg)


@devprof.boundary("solver.greedy.greedy_assign")
@partial(jax.jit, static_argnames=("cfg",))
def greedy_assign(
    snapshot: ClusterSnapshot,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    extra_mask: Optional[jnp.ndarray] = None,  # bool[P, N] extended-plugin Filter
    extra_scores: Optional[jnp.ndarray] = None,  # i64[P, N] extended-plugin Score
) -> CycleResult:
    """Sequential-parity greedy assignment of the whole pending batch.

    ``extra_mask``/``extra_scores`` carry the extended plugins' (NUMA,
    reservation, device-share) stateless Filter/Score tensors into the
    sequential scan; their intra-batch allocation state is settled exactly
    at Reserve on the host (scheduler.framework), like the reference's
    Reserve phase caches.
    """
    pods, nodes, gangs, quotas = (
        snapshot.pods,
        snapshot.nodes,
        snapshot.gangs,
        snapshot.quotas,
    )
    P = pods.capacity
    N = nodes.allocatable.shape[0]

    order = queue_order(pods.priority, pods.valid)
    score_requests = _fit_score_requests(pods.requests)

    mask_default, mask_prod = loadaware_node_masks(nodes, cfg)
    if not cfg.enable_loadaware:
        mask_default = jnp.ones_like(mask_default)
        mask_prod = mask_default
    node_ok_default = nodes.valid & mask_default
    node_ok_prod = nodes.valid & mask_prod
    usage_np, usage_prod = select_score_usage(nodes, cfg)
    prod_sensitive = cfg.enable_loadaware and (
        usage_prod is not None
        or bool(dict(cfg.loadaware.prod_usage_thresholds))
    )

    def step(state, p):
        node_requested, node_estimated, quota_used = state
        req = pods.requests[p]
        est = pods.estimated[p]
        qid = pods.quota_id[p]
        q = jnp.maximum(qid, 0)
        if prod_sensitive:
            is_prod_p = pods.priority_class[p] == int(PriorityClass.PROD)
            node_ok_p = jnp.where(is_prod_p, node_ok_prod, node_ok_default)
            usage_p = (
                jnp.where(is_prod_p, usage_prod, usage_np)
                if usage_prod is not None
                else usage_np
            )
        else:
            node_ok_p = node_ok_default
            usage_p = usage_np

        feasible, scores = step_feasible_scores(
            node_requested,
            node_estimated,
            quota_used,
            nodes.allocatable,
            usage_p,
            nodes.metric_fresh,
            node_ok_p,
            req,
            score_requests[p],
            est,
            qid,
            pods.valid[p],
            quotas.runtime,
            quotas.limited,
            cfg,
        )
        if extra_mask is not None:
            feasible = feasible & extra_mask[p]
        if extra_scores is not None:
            scores = scores + extra_scores[p]
        masked = jnp.where(feasible, scores, jnp.iinfo(jnp.int64).min)
        best = jnp.argmax(masked).astype(jnp.int32)
        any_feasible = jnp.any(feasible)
        chosen = jnp.where(any_feasible, best, -1)

        assign_onehot = (jnp.arange(N) == chosen) & any_feasible
        node_requested = node_requested + jnp.where(
            assign_onehot[:, None], req[None, :], 0
        )
        node_estimated = node_estimated + jnp.where(
            assign_onehot[:, None], est[None, :], 0
        )
        quota_used = jnp.where(
            any_feasible & (qid >= 0),
            quota_used.at[q].add(req),
            quota_used,
        )
        return (node_requested, node_estimated, quota_used), chosen

    init = (nodes.requested, jnp.zeros_like(nodes.requested), quotas.used)
    (node_requested, node_estimated, quota_used), chosen_in_order = lax.scan(
        step, init, order
    )

    assignment = jnp.full((P,), -1, jnp.int32).at[order].set(chosen_in_order)
    status = jnp.where(assignment >= 0, STATUS_ASSIGNED, STATUS_UNSCHEDULABLE)

    # Gang all-or-nothing: a gang below minMember keeps its pods WAITing.
    assigned = (assignment >= 0) & pods.valid
    _, pod_gang_ok = gang_satisfaction(
        assignment, pods.valid, pods.gang_id, gangs.min_member
    )
    status = jnp.where(assigned & ~pod_gang_ok, STATUS_WAIT_GANG, status)

    return CycleResult(
        assignment=assignment,
        status=status.astype(jnp.int32),
        node_requested=node_requested,
        node_estimated=node_estimated,
        quota_used=quota_used,
        path="scan",
    )
