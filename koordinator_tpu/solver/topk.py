"""Serving top-k over the masked [P, N] score tensor.

The bridge's Score reply is the k-prefix of ``lax.top_k`` over
``where(feasible, scores, i64.min)`` — descending scores, ties broken
by lower node index.  On CPU, XLA's top-k on i64 (or f64) falls back
to a comparator-based sort (measured ~5-7 s at 10k x 2k — it DWARFS
the scoring math, for the full and the incremental engine alike),
while F32 takes the fast TopK path (~0.2 s).

``masked_top_k`` exploits a static bound: every scoring term clamps to
``[0, MAX_NODE_SCORE]`` per resource (ops/scoring.py — the cap==0 /
req>cap branches included), so the combined score is non-negative and
bounded by ``hi = MAX_NODE_SCORE * (enabled plugin weights)`` — a
bound derived from the STATIC CycleConfig, not from data
(:func:`score_upper_bound`).  When ``hi + 1 < 2^24`` every rank value
is an exactly-representable f32 integer, and the selection runs as::

    rank = (feasible ? score + 1 : 0)   # infeasible below every score
    ti   = lax.top_k(rank.astype(f32), k)[1]
    ts   = take_along_axis(masked_i64, ti)

Ordering parity with ``lax.top_k`` on the masked i64 tensor:

* feasible beats infeasible (rank 0 < any score + 1), and the masked
  tensor's infeasible entries are all-equal (i64.min) exactly as the
  rank's are all-equal (0);
* equal values break toward the LOWER index — ``lax.top_k``'s own
  documented contract, dtype-independent (the prefix-memo slicing
  already relies on it);
* the returned VALUES are gathered from the masked i64 tensor at the
  winning indices, so the reply bytes (and the ScoreMemo contents) are
  bit-identical to the integer path's.

A config whose bound does not fit f32's exact-integer range
(plugin weights summing past ~167k) takes the integer path unchanged —
the decision is static, so the jit cache never keys on data.  The
static bound is additionally VERIFIED on device: the scorers clamp to
``[0, MAX_NODE_SCORE]`` per term for in-contract inputs, but the wire
accepts arbitrary int64 (a negative ``node_requested`` pushes
``least_requested_score`` past the clamp), so the fast path runs under
a ``lax.cond`` on ``all(feasible -> 0 <= score <= hi)`` — one cheap
reduction, and an out-of-bound tensor takes the integer branch of the
SAME compiled program instead of silently mis-ordering.  A future
scoring term with a different range should still widen
:func:`score_upper_bound` so the fast path stays the one that runs
(tests/test_score_incremental.py pins the parity both in and out of
bound, and the bound itself on fuzzed snapshots).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from koordinator_tpu.model.snapshot import MAX_NODE_SCORE
from koordinator_tpu.obs import devprof

# f32 represents every integer up to 2^24 exactly; ranks at or past it
# would collapse distinct scores onto one float (wrong order, silently)
_F32_EXACT = 1 << 24


def score_upper_bound(cfg) -> int:
    """Static upper bound of ``score_cycle``'s combined scores under
    ``cfg`` (scores are >= 0: every term clamps at zero).  Term-aware
    (ISSUE 15): every fused scoring term's registry entry declares its
    own config-derived bound (solver/terms.py ``terms_upper_bound`` —
    each term clamps its device contribution to
    ``[0, weight * MAX_NODE_SCORE]``), so the f32-exact fast path keeps
    running with terms enabled instead of silently picking the wrong
    rank path."""
    from koordinator_tpu.solver.terms import terms_upper_bound

    hi = 0
    if cfg.enable_fit_score:
        hi += MAX_NODE_SCORE * int(cfg.fit_plugin_weight)
    if cfg.enable_loadaware:
        hi += MAX_NODE_SCORE * int(cfg.loadaware_plugin_weight)
    return hi + terms_upper_bound(cfg)


@devprof.boundary("solver.topk.masked_top_k")
@partial(jax.jit, static_argnames=("k", "hi"))
def masked_top_k(scores, feasible, *, k, hi):
    """(top_scores i64[..., k], top_idx i32[..., k]) of the masked
    score tensor — bit-identical to ``lax.top_k(where(feasible,
    scores, i64.min), k)``, via the f32 fast path when the static
    ``hi`` bound permits AND the tensor actually honors it (module
    docstring)."""
    masked = jnp.where(feasible, scores, jnp.iinfo(jnp.int64).min)
    if hi is None or hi < 0 or hi + 1 >= _F32_EXACT:
        return lax.top_k(masked, k)
    # only feasible cells participate in the f32 ranking; infeasible
    # cells map to rank 0 regardless of their (possibly wild) values
    in_bound = jnp.all(
        jnp.where(feasible, (scores >= 0) & (scores <= hi), True)
    )

    def _fast(args):
        m, f, s = args
        rank = jnp.where(f, s + 1, 0).astype(jnp.float32)
        _, ti = lax.top_k(rank, k)
        return jnp.take_along_axis(m, ti, axis=-1), ti

    def _exact(args):
        m, _f, _s = args
        ts, ti = lax.top_k(m, k)
        return ts, ti  # normalized: top_k's multi-result is a list

    return lax.cond(in_bound, _fast, _exact, (masked, feasible, scores))


def masked_top_k_host(scores_np, feasible_np, k: int):
    """Host-numpy twin of :func:`masked_top_k` — bit-identical values,
    indices and tie-breaks, no device involved.

    Used by the brownout cache (ISSUE 13 / ROADMAP 6(a)): while the
    circuit breaker is open the server answers from the last launch's
    cached [P, N] readback, and a request wanting a WIDER top-k than
    that launch computed must be ranked on host — touching the failing
    device is the one thing the brownout path must never do.

    Exactness: ``lax.top_k`` orders descending with ties broken toward
    the LOWER index.  A descending stable sort with that tie-break is an
    ASCENDING stable argsort of the order-reversed key; i64 negation
    overflows at i64.min (the masked infeasible sentinel), so the key is
    built order-preservingly in uint64 (``x ^ 2^63``) and reversed
    bitwise (``~``) — no overflow, exact total order.  Returned values
    are gathered from the masked tensor, exactly like the device paths.
    """
    scores_np = np.asarray(scores_np, np.int64)
    feasible_np = np.asarray(feasible_np, bool)
    masked = np.where(
        feasible_np, scores_np, np.iinfo(np.int64).min
    )
    biased = masked.view(np.uint64) ^ np.uint64(1 << 63)
    ti = np.argsort(~biased, axis=-1, kind="stable")[..., :k]
    ts = np.take_along_axis(masked, ti, axis=-1)
    return ts, ti.astype(np.int32)
