"""The whole scheduling cycle as ONE Pallas TPU kernel.

``greedy_assign`` (solver/greedy.py) is semantically a 10k-step ``lax.scan``
whose per-step tensors are tiny ([nodes, resources]); on TPU its cost is
pure sequential dispatch latency (~55us/step), not FLOPs or bandwidth.  The
fix is TPU-native: the full cycle state — node requested/estimated tensors,
quota usage — is ~100 KB at 2k nodes, so it lives in VMEM for the whole
cycle and the per-pod loop runs *inside* a single kernel, eliminating the
inter-step overhead entirely (~10x on the 10k x 2k benchmark).

Layout: resources ride the 128-lane axis (R=13 used), nodes ride sublanes
([N, 128] i32 blocks); per-pod vectors stream in as (B, 128) blocks with a
grid over pod batches, and per-pod scalars (quota id, validity) arrive via
scalar prefetch in SMEM.  All score math is the same exact integer
arithmetic as ops/scoring.py — MiB resource units (model/resources.py)
guarantee every intermediate, including ``free * MaxNodeScore``, fits i32,
so no i64 emulation on the VPU.

Reference semantics mirrored (all paths under /root/reference): the per-pod
Filter/Score/Reserve cycle of ``pkg/scheduler/frameworkext`` with
NodeResourcesFit + LoadAware scoring and ElasticQuota admission; see
solver/greedy.py for the per-line citations — this kernel is bit-identical
with that scan (tests/test_pallas_cycle.py asserts it).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG, MOST_ALLOCATED
from koordinator_tpu.constraints.gang import gang_satisfaction
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE, ClusterSnapshot
from koordinator_tpu.obs import devprof
from koordinator_tpu.ops.fit import nonzero_requests
from koordinator_tpu.ops.loadaware import (
    loadaware_node_masks,
    select_score_usage,
)
from koordinator_tpu.model.snapshot import PriorityClass
from koordinator_tpu.solver.greedy import (
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    CycleResult,
    queue_order,
)

LANES = 128
I32_MIN = np.int32(np.iinfo(np.int32).min)

# node flags ride the usage buffer's spare lanes (resources occupy only
# the first NUM_RESOURCES of 128; the LoadAware weight rows are zero
# beyond that, so flag lanes never contribute to any score)
FLAG_LANE_OK = 120  # valid & loadaware default mask
FLAG_LANE_FRESH = 121  # metric_fresh
FLAG_LANE_PROD_OK = 122  # valid & prod-threshold mask
# the initial node-requested vector rides alloc's spare lanes (one roll
# at init recovers it) — a dedicated req0 buffer cost 1MB of scoped VMEM
REQ0_LANE_OFFSET = 32
# the packing scheme silently corrupts real lanes if the resource axis
# ever grows into the borrowed regions — fail loudly instead
assert res.NUM_RESOURCES <= REQ0_LANE_OFFSET
assert REQ0_LANE_OFFSET + res.NUM_RESOURCES <= FLAG_LANE_OK
# combined extended-plugin tensor: score where feasible, sentinel where
# masked out (scores are magnitude-guarded < 2^29, far from the sentinel)
XCOMB_INFEASIBLE = I32_MIN


def _pad_rows(a: jnp.ndarray, rows: int) -> jnp.ndarray:
    return jnp.pad(a, ((0, rows - a.shape[0]),) + ((0, 0),) * (a.ndim - 1))


def _lanes(a: jnp.ndarray) -> jnp.ndarray:
    """[M, R] -> [M, 128] i32, resources on the lane axis."""
    return jnp.pad(a.astype(jnp.int32), ((0, 0), (0, LANES - a.shape[1])))


# In-kernel i32 scalar constants.  With x64 enabled, a bare Python scalar
# in jnp.where/floor_divide/etc. enters the traced sub-jaxpr as a weak
# i64[] argument whose i64->i32 convert has NO Mosaic lowering (it
# recurses forever in _convert_helper).  Every scalar that reaches kernel
# math must therefore be a strong i32.
def _i32(v) -> jnp.ndarray:
    return jnp.int32(v)


def _exact_div(v, safe, recip):
    """Exact nonnegative i32 floor division via f32 reciprocal.

    The VPU has no integer divide — Mosaic emulates ``lax.div`` in
    software, and ablation on v5e measured it at HALF the whole kernel's
    runtime (a [N,1] column div costs the same per-vreg as a full
    [N,128] one).  Every quotient in the score math is bounded by
    MAX_NODE_SCORE (=100): free/clamped <= cap and weighted sums divide
    by their weight total, so ``v/safe <= 100`` and the f32 rounding
    error (rel ~2^-22) is far below the +-1 a single correction step
    absorbs.  Exactness at floor boundaries is restored by the two
    corrections; parity tests assert bit-identity with ``//``.
    """
    q = (v.astype(jnp.float32) * recip).astype(jnp.int32)
    r = v - q * safe
    q = q + jnp.where(r >= safe, _i32(1), _i32(0))
    q = q - jnp.where(v - q * safe < _i32(0), _i32(1), _i32(0))
    return q


def _least_requested(t, cap, recip):
    """Exact ops/scoring.py least_requested_score in i32 (free pre-clamped
    so free * MAX_NODE_SCORE never overflows)."""
    safe = jnp.maximum(cap, _i32(1))
    # jnp.maximum, not jnp.clip: clip's asarray(0) bound is a strong i64
    # under x64 and i64 does not lower on Mosaic
    free = jnp.maximum(cap - t, _i32(0))
    score = _exact_div(free * _i32(MAX_NODE_SCORE), safe, recip)
    return jnp.where((cap == _i32(0)) | (t > cap), _i32(0), score)


def _most_requested(t, cap, recip):
    safe = jnp.maximum(cap, _i32(1))
    clamped = jnp.minimum(t, cap)
    score = _exact_div(clamped * _i32(MAX_NODE_SCORE), safe, recip)
    return jnp.where(cap == _i32(0), _i32(0), score)


def _weighted(per_res, w_row, w_sum: int):
    if w_sum == 0:
        return jnp.zeros(per_res.shape[:-1] + (1,), jnp.int32)
    # dtype=i32: under x64 jnp.sum accumulates i32 into i64 (numpy
    # semantics) and i64 does not lower on Mosaic
    s = jnp.sum(per_res * w_row, axis=-1, keepdims=True, dtype=jnp.int32)
    return _exact_div(s, _i32(w_sum), np.float32(1.0 / w_sum))


def _kernel_filter_fit(nreq, req, alloc):
    """NodeResourcesFit over a [rows, 128] state block: only requested
    resources constrain.  i32 violation count, not jnp.all: a bool lane
    reduction lowers to an i1 reduce_min Mosaic rejects ("Unsupported
    element type for the selected reduction")."""
    need = req > _i32(0)
    fviol = jnp.where(need & (nreq + req > alloc), _i32(1), _i32(0))
    return jnp.max(fviol, axis=-1, keepdims=True) == _i32(0)


def _kernel_scores(
    nreq, nest, alloc, usage, fresh, sreq, est, recip,
    fit_w_row, la_w_row, fit_w_sum, la_w_sum, cfg: CycleConfig,
):
    """The plugin Score sum in exact i32 over a [rows, 128] state block
    — the ONE in-kernel mirror of solver.greedy.step_feasible_scores,
    shared by the per-pod and wave kernels (flag lanes in ``usage``
    never contribute: the weight rows are zero beyond the resources)."""
    total = jnp.zeros((alloc.shape[0], 1), jnp.int32)
    if cfg.enable_fit_score:
        t = nreq + sreq
        if cfg.fit_scoring_strategy == MOST_ALLOCATED:
            per_res = _most_requested(t, alloc, recip)
        else:
            per_res = _least_requested(t, alloc, recip)
        total = total + _i32(cfg.fit_plugin_weight) * _weighted(
            per_res, fit_w_row, fit_w_sum
        )
    if cfg.enable_loadaware:
        est_used = usage + nest + est
        per_res = _least_requested(est_used, alloc, recip)
        la = _weighted(per_res, la_w_row, la_w_sum)
        total = total + _i32(cfg.loadaware_plugin_weight) * jnp.where(
            fresh, la, _i32(0)
        )
    return total


def _cycle_kernel(
    # scalar prefetch (SMEM)
    qid_ref,  # i32[P] quota id per sorted pod (-1 = none)
    pvalid_ref,  # i32[P]
    pprod_ref,  # i32[P] 1 = PriorityProd pod (prod filter/score selection)
    # inputs (VMEM)
    preq_ref,  # i32[B, 128] pod requests (sorted)
    psreq_ref,  # i32[B, 128] nonzero-default score requests
    pest_ref,  # i32[B, 128] estimator output
    alloc_ref,  # i32[N, 128]
    usage_ref,  # i32[N, 128] score usage (aggregated pre-selected on host);
    # spare lanes carry the node flags (FLAG_LANE_OK/FRESH/PROD_OK) — a
    # dedicated flags buffer cost 1MB of the 16MB scoped-VMEM budget for
    # three booleans per node
    qrt_ref,  # i32[Q, 128] quota runtime
    qlim_ref,  # i32[Q, 128] quota limited mask
    quse0_ref,  # i32[Q, 128] initial quota used
    w_ref,  # i32[8, 128] row0 = fit weights, row1 = loadaware weights
    *rest,  # optional: uprod_ref i32[N, 128] (prod-pods usage, has_prod);
    # optional: xcomb_ref i32[N, B] — the combined extended-plugin tensor
    # (NUMA/reservation/deviceshare): score where feasible,
    # XCOMB_INFEASIBLE where masked, pods on the lane axis so each step
    # extracts a [N, 1] column — then outputs
    block: int,
    cfg: CycleConfig,
    has_extras: bool,
    has_prod: bool,
):
    if has_prod:
        uprod_ref = rest[0]
        rest = rest[1:]
    else:
        uprod_ref = None
    if has_extras:
        xcomb_ref = rest[0]
        rest = rest[1:]
    else:
        xcomb_ref = None
    # the node/quota state carries IN the output refs (constant index
    # maps persist across grid steps): no separate scratch copies — at
    # benchmark scale the duplicated state alone overflowed the 16MB
    # scoped-VMEM limit once the extended-plugin tiles joined
    (chosen_ref, nreq_ref, nest_ref, quse_ref) = rest

    i = pl.program_id(0)

    @pl.when(i == _i32(0))
    def _init():
        # output buffers are NOT initialized on hardware (the standard
        # revisited-block contract: only what the kernel wrote persists),
        # so EVERY carried state needs an explicit i==0 init.  The initial
        # requested state rides alloc's spare lanes: one roll brings lanes
        # [REQ0_LANE_OFFSET, +R) down to [0, R), the rest zeroes.
        lane = lax.broadcasted_iota(jnp.int32, alloc_ref.shape, 1)
        rolled = pltpu.roll(alloc_ref[:], _i32(LANES - REQ0_LANE_OFFSET), 1)
        nreq_ref[:] = jnp.where(
            lane < _i32(res.NUM_RESOURCES), rolled, _i32(0)
        )
        nest_ref[:] = jnp.zeros_like(nest_ref)
        quse_ref[:] = quse0_ref[:]

    alloc = alloc_ref[:]
    n_rows = alloc.shape[0]
    node_ok = usage_ref[:, FLAG_LANE_OK : FLAG_LANE_OK + 1] != _i32(0)
    fresh = usage_ref[:, FLAG_LANE_FRESH : FLAG_LANE_FRESH + 1] != _i32(0)
    row_iota = lax.broadcasted_iota(jnp.int32, (n_rows, 1), 0)

    fit_w_row = w_ref[0:1, :]
    la_w_row = w_ref[1:2, :]
    # weight sums over the AXIS-MAPPED weights (names not on RESOURCE_AXIS
    # are dropped by weights_vector; the divisor must match the scan path)
    fit_w_sum = sum(res.weights_vector(dict(cfg.fit_resource_weights)))
    la_w_sum = sum(res.weights_vector(dict(cfg.loadaware.resource_weights)))
    # loop-invariant f32 reciprocal of node capacity for _exact_div
    recip = 1.0 / jnp.maximum(alloc, _i32(1)).astype(jnp.float32)

    def step(j, _):
        # j MUST stay i32: Mosaic has no i64 lowering, and with x64
        # enabled an int-typed fori_loop counter arrives as i64 — any
        # promotion it causes (p, pl.ds indices, lane compares) recurses
        # forever in _convert_helper at kernel-lowering time.
        p = i * block + j
        req = preq_ref[pl.ds(j, 1), :]  # [1, 128]
        sreq = psreq_ref[pl.ds(j, 1), :]
        est = pest_ref[pl.ds(j, 1), :]
        qid = qid_ref[p]
        is_valid = pvalid_ref[p] != _i32(0)
        qidx = jnp.maximum(qid, _i32(0))
        if has_prod:
            is_prod = pprod_ref[p] != _i32(0)
            # select the i32 flag lanes, compare after: a select over i1
            # vectors has no Mosaic legalization ('arith.select')
            node_ok_p = (
                jnp.where(
                    is_prod,
                    usage_ref[:, FLAG_LANE_PROD_OK : FLAG_LANE_PROD_OK + 1],
                    usage_ref[:, FLAG_LANE_OK : FLAG_LANE_OK + 1],
                )
                != _i32(0)
            )
            usage_p = jnp.where(is_prod, uprod_ref[:], usage_ref[:])
        else:
            node_ok_p = node_ok
            usage_p = usage_ref[:]

        nreq = nreq_ref[:]
        # Filter: Fit (only requested resources constrain) + node flags
        fits = _kernel_filter_fit(nreq, req, alloc)
        # ElasticQuota admission on limited dimensions
        quse_row = quse_ref[pl.ds(qidx, 1), :]
        # scalar reduce in i32 (a scalar bool `jnp.all` does not lower on
        # Mosaic: only 32-bit element types squeeze to scalars)
        qviol = jnp.where(
            (qlim_ref[pl.ds(qidx, 1), :] != _i32(0))
            & (quse_row + req > qrt_ref[pl.ds(qidx, 1), :]),
            jnp.int32(1),
            jnp.int32(0),
        )
        qok = jnp.max(qviol) == _i32(0)
        feasible = fits & node_ok_p & ((qid < _i32(0)) | qok) & is_valid
        if has_extras:
            # extract this pod's [N, 1] column by one-hot lane reduction
            # (dynamic lane slicing is costly on the VPU; a masked lane
            # sum is a single vector op); the sentinel encodes the mask
            lane = lax.broadcasted_iota(jnp.int32, (1, block), 1) == j
            xv = jnp.sum(
                jnp.where(lane, xcomb_ref[:], _i32(0)),
                axis=1,
                keepdims=True,
                dtype=jnp.int32,
            )
            feasible = feasible & (xv != _i32(XCOMB_INFEASIBLE))

        # Score: NodeResourcesFit + LoadAware, exact integer math
        total = _kernel_scores(
            nreq, nest_ref[:], alloc, usage_p, fresh, sreq, est, recip,
            fit_w_row, la_w_row, fit_w_sum, la_w_sum, cfg,
        )
        if has_extras:
            total = total + jnp.where(
                xv == _i32(XCOMB_INFEASIBLE), _i32(0), xv
            )

        masked = jnp.where(feasible, total, I32_MIN)
        best = jnp.max(masked)
        any_feasible = best > I32_MIN
        # first index achieving the max == jnp.argmax tie-break
        chosen = jnp.min(jnp.where(masked == best, row_iota, _i32(n_rows)))
        chosen = jnp.where(any_feasible, chosen, _i32(-1))

        # Reserve: commit the pod's resources to the chosen node / quota
        cidx = jnp.maximum(chosen, _i32(0))
        take = jnp.where(any_feasible, req, _i32(0))
        nreq_ref[pl.ds(cidx, 1), :] = nreq_ref[pl.ds(cidx, 1), :] + take
        nest_ref[pl.ds(cidx, 1), :] = nest_ref[pl.ds(cidx, 1), :] + jnp.where(
            any_feasible, est, _i32(0)
        )
        quse_ref[pl.ds(qidx, 1), :] = quse_row + jnp.where(
            any_feasible & (qid >= _i32(0)), req, _i32(0)
        )

        chosen_ref[pl.ds(j, 1), :] = jnp.full((1, LANES), chosen, jnp.int32)
        return jnp.int32(0)

    lax.fori_loop(jnp.int32(0), jnp.int32(block), step, jnp.int32(0))


def _wave_cycle_kernel(
    # scalar prefetch (SMEM)
    qid_ref,  # i32[P] quota id per sorted pod (-1 = none)
    pvalid_ref,  # i32[P]
    pprod_ref,  # i32[P]
    # inputs (VMEM) — same layout as _cycle_kernel
    preq_ref,
    psreq_ref,
    pest_ref,
    alloc_ref,
    usage_ref,
    qrt_ref,
    qlim_ref,
    quse0_ref,
    w_ref,
    *rest,
    block: int,
    cfg: CycleConfig,
    has_extras: bool,
    has_prod: bool,
    wave: int,
    top_m: int,
):
    """Wave-batched inner loop: the solver/wave.py rounds, in VMEM.

    Instead of one Filter/Score/argmax/Reserve dispatch per pod, each
    sequential round freezes the next ``wave`` pods' top-``top_m``
    candidate (score, node) pairs against round-start state, then
    resolves the wave in queue order with the SAME certification the
    jnp paths use — re-keyed candidates vs the frozen M-th key, queue
    prefix commits, node-invariant quota recheck.  Differences from the
    i64 resolver, both exactness-preserving:

    * keys stay UNPACKED (score, index) with a lexicographic compare —
      the packed ``score * N + idx`` key would overflow i32;
    * Reserve lands on the state refs LIVE during resolution, so a
      later pod's re-key reads frozen rows + earlier in-wave deltas
      directly (the frozen candidate keys were captured before any
      commit of the round);
    * the MostAllocated closed universe is refined to {own top-M} ∪
      {nodes committed-to earlier in the round}: commits land only on
      wave candidates, every other node's key-for-this-pod is frozen
      below its k_M, so re-keying that union bounds the true best
      exactly (docs/KERNEL.md "Wave batching").

    Waves never cross the 128-pod grid blocks the existing streaming
    provides (``wvalid`` masks the tail); wave segmentation does not
    affect placements, only round counts.  The round total accumulates
    in the stats output so callers can surface the sequential-round win.
    """
    if has_prod:
        uprod_ref = rest[0]
        rest = rest[1:]
    else:
        uprod_ref = None
    if has_extras:
        xcomb_ref = rest[0]
        rest = rest[1:]
    else:
        xcomb_ref = None
    (chosen_ref, nreq_ref, nest_ref, quse_ref, rounds_ref,
     cand_s_ref, cand_i_ref) = rest

    i = pl.program_id(0)
    W = wave
    n_rows = alloc_ref.shape[0]
    # the frozen candidate (score, index) slots live one-per-lane in the
    # 128-lane scratch rows, so M is capped at LANES as well as the node
    # count — a shallower M changes round counts, never placements (any
    # M >= 1 certifies exactly)
    M = max(1, min(top_m, n_rows, LANES))
    most_alloc = cfg.enable_fit_score and (
        cfg.fit_scoring_strategy == MOST_ALLOCATED
    )

    @pl.when(i == _i32(0))
    def _init():
        lane = lax.broadcasted_iota(jnp.int32, alloc_ref.shape, 1)
        rolled = pltpu.roll(alloc_ref[:], _i32(LANES - REQ0_LANE_OFFSET), 1)
        nreq_ref[:] = jnp.where(
            lane < _i32(res.NUM_RESOURCES), rolled, _i32(0)
        )
        nest_ref[:] = jnp.zeros_like(nest_ref)
        quse_ref[:] = quse0_ref[:]
        rounds_ref[:] = jnp.zeros_like(rounds_ref)

    alloc = alloc_ref[:]
    row_iota = lax.broadcasted_iota(jnp.int32, (n_rows, 1), 0)
    lane_iota = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    sub_iota_w = lax.broadcasted_iota(jnp.int32, (W, 1), 0)
    fit_w_row = w_ref[0:1, :]
    la_w_row = w_ref[1:2, :]
    fit_w_sum = sum(res.weights_vector(dict(cfg.fit_resource_weights)))
    la_w_sum = sum(res.weights_vector(dict(cfg.loadaware.resource_weights)))
    recip = 1.0 / jnp.maximum(alloc, _i32(1)).astype(jnp.float32)

    def _lane(row, m):
        """Lane m (traced) of a [1, 128] row -> i32 scalar (dynamic lane
        slicing is costly on the VPU; a masked lane sum is one vector op)."""
        return jnp.sum(
            jnp.where(lane_iota == m, row, _i32(0)), dtype=jnp.int32
        )

    def frozen_masked(j):
        """Frozen masked scores [n_rows, 1] for block-row j.  Quota is
        handled at resolution (node-invariant), matching one_pod_keys in
        the jnp wave paths."""
        p = i * block + j
        req = preq_ref[pl.ds(j, 1), :]
        sreq = psreq_ref[pl.ds(j, 1), :]
        est = pest_ref[pl.ds(j, 1), :]
        is_valid = pvalid_ref[p] != _i32(0)
        if has_prod:
            is_prod = pprod_ref[p] != _i32(0)
            node_ok_p = (
                jnp.where(
                    is_prod,
                    usage_ref[:, FLAG_LANE_PROD_OK : FLAG_LANE_PROD_OK + 1],
                    usage_ref[:, FLAG_LANE_OK : FLAG_LANE_OK + 1],
                )
                != _i32(0)
            )
            usage_p = jnp.where(is_prod, uprod_ref[:], usage_ref[:])
        else:
            node_ok_p = (
                usage_ref[:, FLAG_LANE_OK : FLAG_LANE_OK + 1] != _i32(0)
            )
            usage_p = usage_ref[:]
        fresh = usage_ref[:, FLAG_LANE_FRESH : FLAG_LANE_FRESH + 1] != _i32(0)
        feasible = (
            _kernel_filter_fit(nreq_ref[:], req, alloc)
            & node_ok_p
            & is_valid
        )
        total = _kernel_scores(
            nreq_ref[:], nest_ref[:], alloc, usage_p, fresh, sreq, est,
            recip, fit_w_row, la_w_row, fit_w_sum, la_w_sum, cfg,
        )
        if has_extras:
            xv = jnp.sum(
                jnp.where(lane_iota == j, xcomb_ref[:], _i32(0)),
                axis=1,
                keepdims=True,
                dtype=jnp.int32,
            )
            feasible = feasible & (xv != _i32(XCOMB_INFEASIBLE))
            total = total + jnp.where(
                xv == _i32(XCOMB_INFEASIBLE), _i32(0), xv
            )
        return jnp.where(feasible, total, I32_MIN)

    def rekey(c, j, req, sreq, est, is_prod):
        """Current score of node c for the pod at block-row j, or
        I32_MIN when infeasible.  The state refs already carry this
        round's earlier commits (live Reserve), so the read IS frozen
        rows + in-wave deltas — the same quantity the i64 resolver
        reconstructs from gathered rows."""
        a = alloc_ref[pl.ds(c, 1), :]
        nr = nreq_ref[pl.ds(c, 1), :]
        ne = nest_ref[pl.ds(c, 1), :]
        u_row = usage_ref[pl.ds(c, 1), :]
        fresh = u_row[:, FLAG_LANE_FRESH : FLAG_LANE_FRESH + 1] != _i32(0)
        if has_prod:
            ok_col = (
                jnp.where(
                    is_prod,
                    u_row[:, FLAG_LANE_PROD_OK : FLAG_LANE_PROD_OK + 1],
                    u_row[:, FLAG_LANE_OK : FLAG_LANE_OK + 1],
                )
                != _i32(0)
            )
            usage_row = jnp.where(is_prod, uprod_ref[pl.ds(c, 1), :], u_row)
        else:
            ok_col = u_row[:, FLAG_LANE_OK : FLAG_LANE_OK + 1] != _i32(0)
            usage_row = u_row
        recip_c = 1.0 / jnp.maximum(a, _i32(1)).astype(jnp.float32)
        feas = _kernel_filter_fit(nr, req, a) & ok_col  # [1, 1]
        total = _kernel_scores(
            nr, ne, a, usage_row, fresh, sreq, est, recip_c,
            fit_w_row, la_w_row, fit_w_sum, la_w_sum, cfg,
        )
        feas_s = jnp.sum(
            jnp.where(feas, _i32(1), _i32(0)), dtype=jnp.int32
        ) != _i32(0)
        score = jnp.sum(total, dtype=jnp.int32)
        if has_extras:
            xv = _lane(xcomb_ref[pl.ds(c, 1), :], j)
            feas_s = feas_s & (xv != _i32(XCOMB_INFEASIBLE))
            score = score + jnp.where(
                xv == _i32(XCOMB_INFEASIBLE), _i32(0), xv
            )
        return jnp.where(feas_s, score, I32_MIN)

    def wave_round(carry):
        ptr, rounds = carry

        # Phase A: freeze the wave's top-M (score, node) pairs against
        # round-start state (no ref is written until resolution below)
        def score_one(w, _):
            j = ptr + w
            in_block = j < _i32(block)
            j_eff = jnp.minimum(j, _i32(block - 1))
            masked = jnp.where(in_block, frozen_masked(j_eff), I32_MIN)
            srow = jnp.full((1, LANES), I32_MIN, jnp.int32)
            irow = jnp.zeros((1, LANES), jnp.int32)

            def pick(m, st):
                rem, srow, irow = st
                best = jnp.max(rem)
                # first index achieving the max == jnp.argmax tie-break
                bidx = jnp.min(
                    jnp.where(rem == best, row_iota, _i32(n_rows))
                )
                srow = jnp.where(lane_iota == m, best, srow)
                irow = jnp.where(lane_iota == m, bidx, irow)
                rem = jnp.where(row_iota == bidx, I32_MIN, rem)
                return (rem, srow, irow)

            _, srow, irow = lax.fori_loop(
                jnp.int32(0), jnp.int32(M), pick, (masked, srow, irow)
            )
            cand_s_ref[pl.ds(w, 1), :] = srow
            cand_i_ref[pl.ds(w, 1), :] = irow
            return jnp.int32(0)

        lax.fori_loop(jnp.int32(0), jnp.int32(W), score_one, jnp.int32(0))

        # Phase B: resolve the wave in queue order (solver/wave.py
        # resolve_wave semantics, i32)
        def resolve(i_w, st):
            choices_col, committed_col, active, ncommit = st
            j = ptr + i_w
            in_block = j < _i32(block)
            j_eff = jnp.minimum(j, _i32(block - 1))
            p = i * block + j_eff
            req = preq_ref[pl.ds(j_eff, 1), :]
            sreq = psreq_ref[pl.ds(j_eff, 1), :]
            est = pest_ref[pl.ds(j_eff, 1), :]
            qid = qid_ref[p]
            qidx = jnp.maximum(qid, _i32(0))
            is_valid = pvalid_ref[p] != _i32(0)
            is_prod = (pprod_ref[p] != _i32(0)) if has_prod else None
            srow = cand_s_ref[pl.ds(i_w, 1), :]
            irow = cand_i_ref[pl.ds(i_w, 1), :]
            k_s = _lane(srow, _i32(M - 1))
            k_i = _lane(irow, _i32(M - 1))
            # k_M at sentinel: every frozen-feasible node is already a
            # candidate, and committed load never turns an infeasible
            # node feasible
            sentinel_m = k_s == I32_MIN

            # current best over the pod's own candidates — unpacked
            # (score, lowest-index) lexicographic max
            bs = I32_MIN
            bi = _i32(0)
            for m in range(M):  # static unroll, M is tiny
                c = _lane(irow, _i32(m))
                fs = _lane(srow, _i32(m))
                cs = rekey(c, j_eff, req, sreq, est, is_prod)
                # a sentinel slot (fewer than m+1 frozen-feasible nodes)
                # stays sentinel: its index is not a real candidate
                cs = jnp.where(fs == I32_MIN, I32_MIN, cs)
                better = (cs > bs) | ((cs == bs) & (c < bi))
                bs = jnp.where(better, cs, bs)
                bi = jnp.where(better, c, bi)

            if most_alloc:
                # refined closed universe (kernel docstring): nodes
                # committed-to earlier this round are the only
                # non-candidates whose keys moved
                def consider(w, st2):
                    bs2, bi2 = st2
                    cw = jnp.sum(
                        jnp.where(sub_iota_w == w, choices_col, _i32(0)),
                        dtype=jnp.int32,
                    )
                    comm = jnp.sum(
                        jnp.where(sub_iota_w == w, committed_col, _i32(0)),
                        dtype=jnp.int32,
                    ) != _i32(0)
                    live = comm & (w < i_w)
                    cw_eff = jnp.maximum(cw, _i32(0))
                    cs2 = jnp.where(
                        live,
                        rekey(cw_eff, j_eff, req, sreq, est, is_prod),
                        I32_MIN,
                    )
                    better2 = (cs2 > bs2) | ((cs2 == bs2) & (cw_eff < bi2))
                    return (
                        jnp.where(better2, cs2, bs2),
                        jnp.where(better2, cw_eff, bi2),
                    )

                bs, bi = lax.fori_loop(
                    jnp.int32(0), jnp.int32(W), consider, (bs, bi)
                )
                lex_ge = (bs > k_s) | ((bs == k_s) & (bi <= k_i))
                # pod 0 has no earlier in-wave commits: frozen keys ARE
                # current (liveness)
                certified = lex_ge | sentinel_m | (i_w == _i32(0))
            else:
                lex_ge = (bs > k_s) | ((bs == k_s) & (bi <= k_i))
                certified = lex_ge | sentinel_m
            feas = bs > I32_MIN

            # ElasticQuota admission against the LIVE in-wave quota state
            quse_row = quse_ref[pl.ds(qidx, 1), :]
            qviol = jnp.where(
                (qlim_ref[pl.ds(qidx, 1), :] != _i32(0))
                & (quse_row + req > qrt_ref[pl.ds(qidx, 1), :]),
                jnp.int32(1),
                jnp.int32(0),
            )
            qblocked = (qid >= _i32(0)) & (jnp.max(qviol) != _i32(0))
            usable = is_valid & ~qblocked & in_block
            choice = jnp.where(feas & usable, bi, _i32(-1))
            # a -1 outcome certifies only when node-independent or at
            # the sentinel (see solver/wave.py) — otherwise the pod ends
            # the commit prefix and reruns next round
            certified = certified | ~usable
            active_b = active != _i32(0)
            commit = active_b & certified
            take_node = commit & (choice >= _i32(0))

            # live Reserve: later pods re-key against these rows
            cidx = jnp.maximum(choice, _i32(0))
            take = jnp.where(take_node, req, _i32(0))
            nreq_ref[pl.ds(cidx, 1), :] = nreq_ref[pl.ds(cidx, 1), :] + take
            nest_ref[pl.ds(cidx, 1), :] = nest_ref[
                pl.ds(cidx, 1), :
            ] + jnp.where(take_node, est, _i32(0))
            quse_ref[pl.ds(qidx, 1), :] = quse_row + jnp.where(
                take_node & (qid >= _i32(0)), req, _i32(0)
            )

            # uncommitted rows keep their value: they rerun in a later
            # round (the committed set is always a queue prefix)
            prev = chosen_ref[pl.ds(j_eff, 1), :]
            chosen_ref[pl.ds(j_eff, 1), :] = jnp.where(
                commit & in_block, choice, prev
            )

            choices_col = jnp.where(
                sub_iota_w == i_w,
                jnp.where(take_node, choice, _i32(-1)),
                choices_col,
            )
            committed_col = jnp.where(
                sub_iota_w == i_w,
                jnp.where(take_node, _i32(1), _i32(0)),
                committed_col,
            )
            ncommit = ncommit + jnp.where(commit, _i32(1), _i32(0))
            active = jnp.where(commit, active, _i32(0))
            return (choices_col, committed_col, active, ncommit)

        st0 = (
            jnp.full((W, 1), -1, jnp.int32),
            jnp.zeros((W, 1), jnp.int32),
            jnp.int32(1),
            jnp.int32(0),
        )
        _, _, _, ncommit = lax.fori_loop(
            jnp.int32(0), jnp.int32(W), resolve, st0
        )
        return (ptr + ncommit, rounds + _i32(1))

    _, rounds = lax.while_loop(
        lambda c: c[0] < _i32(block),
        wave_round,
        (jnp.int32(0), jnp.int32(0)),
    )
    rounds_ref[:] = rounds_ref[:] + rounds


@devprof.boundary("solver.pallas_cycle._run_cycle")
@partial(jax.jit, static_argnames=("cfg", "block", "interpret", "wave", "top_m"))
def _run_cycle(
    preq, psreq, pest, qid, pvalid, pprod, alloc, usage, qrt,
    qlim, quse0, weights, uprod=None, xcomb=None, *,
    cfg: CycleConfig, block: int, interpret: bool,
    wave: int = 0, top_m: int = 0
):
    P = preq.shape[0]
    N = alloc.shape[0]
    Q = qrt.shape[0]
    has_extras = xcomb is not None
    has_prod = uprod is not None
    grid = (P // block,)
    # index maps return strong-i32 zeros: with x64 on, a literal 0 becomes
    # an i64 constant in the lowered index-map func, which Mosaic rejects
    # ("failed to legalize operation 'func.func'")
    _z = np.int32(0)
    node_spec = pl.BlockSpec((N, LANES), lambda i, *_: (_z, _z), memory_space=pltpu.VMEM)
    quota_spec = pl.BlockSpec((Q, LANES), lambda i, *_: (_z, _z), memory_space=pltpu.VMEM)
    pod_spec = pl.BlockSpec((block, LANES), lambda i, *_: (i, _z), memory_space=pltpu.VMEM)
    in_specs = (
        [pod_spec, pod_spec, pod_spec]
        + [node_spec] * 2
        + [quota_spec] * 3
        + [pl.BlockSpec((8, LANES), lambda i, *_: (_z, _z), memory_space=pltpu.VMEM)]
    )
    operands = [preq, psreq, pest, alloc, usage, qrt, qlim, quse0, weights]
    if has_prod:
        in_specs += [node_spec]
        operands += [uprod]
    if has_extras:
        # [N, P] with pods on lanes: each grid step streams a (N, block) tile
        xtra_spec = pl.BlockSpec(
            (N, block), lambda i, *_: (_z, i), memory_space=pltpu.VMEM
        )
        in_specs += [xtra_spec]
        operands += [xcomb]
    out_specs = [pod_spec, node_spec, node_spec, quota_spec]
    out_shape = [
        jax.ShapeDtypeStruct((P, LANES), jnp.int32),
        jax.ShapeDtypeStruct((N, LANES), jnp.int32),
        jax.ShapeDtypeStruct((N, LANES), jnp.int32),
        jax.ShapeDtypeStruct((Q, LANES), jnp.int32),
    ]
    if wave > 1:
        # wave-batched inner loop: the round-count stats row joins the
        # outputs and the frozen candidate tables ride scratch VMEM
        W_k = min(wave, block)  # waves never cross the 128-pod blocks
        kernel = partial(
            _wave_cycle_kernel,
            block=block,
            cfg=cfg,
            has_extras=has_extras,
            has_prod=has_prod,
            wave=W_k,
            top_m=top_m,
        )
        out_specs = out_specs + [
            pl.BlockSpec(
                (8, LANES), lambda i, *_: (_z, _z), memory_space=pltpu.VMEM
            )
        ]
        out_shape = out_shape + [jax.ShapeDtypeStruct((8, LANES), jnp.int32)]
        scratch_shapes = [
            pltpu.VMEM((W_k, LANES), jnp.int32),  # frozen cand scores
            pltpu.VMEM((W_k, LANES), jnp.int32),  # frozen cand indices
        ]
    else:
        kernel = partial(
            _cycle_kernel,
            block=block,
            cfg=cfg,
            has_extras=has_extras,
            has_prod=has_prod,
        )
        scratch_shapes = []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(qid, pvalid, pprod, *operands)


def greedy_assign_pallas(
    snapshot: ClusterSnapshot,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    interpret: bool = False,
    extra_mask=None,  # bool[P, N] extended-plugin Filter tensor
    extra_scores=None,  # i64[P, N] extended-plugin Score tensor
) -> CycleResult:
    """Drop-in replacement for solver.greedy.greedy_assign on TPU.

    ``cfg.wave > 1`` swaps the per-pod inner loop for the wave-batched
    rounds (``_wave_cycle_kernel``, docs/KERNEL.md "Wave batching") —
    bit-identical placements, ``rounds`` set on the result.

    Raises ValueError when ``extra_scores`` exceed the i32 headroom the
    kernel's accumulation needs — direct callers must not get silent
    wraparound and divergent placements (the run_cycle dispatcher checks the
    same bound before routing here; this guards everyone else).
    """
    if extra_scores is not None:
        import numpy as _np

        peak = int(jnp.max(jnp.abs(extra_scores)))
        if peak >= 2**29:
            raise ValueError(
                f"extra_scores magnitude {peak} >= 2^29: out of the Pallas "
                "kernel's i32 headroom; use the lax.scan path (greedy_assign)"
            )
    return _greedy_assign_pallas(
        snapshot, cfg, interpret, extra_mask, extra_scores
    )


@devprof.boundary("solver.pallas_cycle._greedy_assign_pallas")
@partial(jax.jit, static_argnames=("cfg", "interpret"))
def _greedy_assign_pallas(
    snapshot: ClusterSnapshot,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    interpret: bool = False,
    extra_mask=None,  # bool[P, N] extended-plugin Filter tensor
    extra_scores=None,  # i64[P, N] extended-plugin Score tensor
) -> CycleResult:
    """jit inner of greedy_assign_pallas (magnitude-checked wrapper above).

    Bit-identical placements (same queue order, same integer scores, same
    argmax tie-breaks); i32 internally — sound because MiB/milli units bound
    every intermediate (documented in model/resources.py).  The extended
    plugins' (NUMA/reservation/deviceshare — scheduler/plugins.py) stateless
    Filter/Score tensors ride the kernel as [N, P] tiles so the full plugin
    composition stays on the single-kernel path (reference analog: these
    plugins run inside the Score hot loop, ``nodenumaresource/scoring.go:55``).
    """
    pods, nodes, gangs, quotas = (
        snapshot.pods,
        snapshot.nodes,
        snapshot.gangs,
        snapshot.quotas,
    )
    P = pods.capacity
    N = nodes.allocatable.shape[0]

    order = queue_order(pods.priority, pods.valid)
    # pods always pad to 128-blocks: the extended-plugin tiles put pods on
    # the LANE axis ([N, block]), and a lane tile that is neither 128-wide
    # nor the full array does not lower on TPU
    P_pad = -(-P // 128) * 128
    block = 128
    N_pad = -(-N // 8) * 8

    def _pods(a):
        return _pad_rows(_lanes(a[order]), P_pad)

    preq = _pods(pods.requests)
    psreq = _pods(nonzero_requests(pods.requests))
    pest = _pods(pods.estimated)
    qid = jnp.pad(pods.quota_id[order].astype(jnp.int32), (0, P_pad - P))
    pvalid = jnp.pad(pods.valid[order].astype(jnp.int32), (0, P_pad - P))

    # LoadAware masks + score-usage selection (aggregated/prod profiles):
    # aggregated percentiles are selected host-side (static config), only
    # the prod-vs-default choice is per-pod and rides into the kernel
    mask_default, mask_prod = loadaware_node_masks(nodes, cfg)
    if not cfg.enable_loadaware:
        mask_default = jnp.ones_like(mask_default)
        mask_prod = mask_default
    usage_np, usage_prod = select_score_usage(nodes, cfg)
    prod_sensitive = cfg.enable_loadaware and (
        usage_prod is not None
        or bool(dict(cfg.loadaware.prod_usage_thresholds))
    )
    is_prod = pods.priority_class == int(PriorityClass.PROD)
    pprod = jnp.pad(is_prod[order].astype(jnp.int32), (0, P_pad - P))
    if prod_sensitive:
        uprod = _pad_rows(
            _lanes(usage_prod if usage_prod is not None else usage_np), N_pad
        )
    else:
        uprod = None

    Q = max(8, quotas.runtime.shape[0])
    Q = -(-Q // 8) * 8
    qrt = _pad_rows(_lanes(quotas.runtime), Q)
    qlim = _pad_rows(_lanes(quotas.limited.astype(jnp.int32)), Q)
    quse0 = _pad_rows(_lanes(quotas.used), Q)

    weights = jnp.zeros((8, LANES), jnp.int32)
    weights = weights.at[0, : res.NUM_RESOURCES].set(
        jnp.asarray(res.weights_vector(dict(cfg.fit_resource_weights)), jnp.int32)
    )
    weights = weights.at[1, : res.NUM_RESOURCES].set(
        jnp.asarray(
            res.weights_vector(dict(cfg.loadaware.resource_weights)), jnp.int32
        )
    )

    if extra_mask is not None or extra_scores is not None:
        # sorted pod order on the LANE axis, nodes on sublanes: [N_pad,
        # P_pad]; ONE combined tensor — score where feasible, sentinel
        # where masked (halves the streamed VMEM tiles)
        if extra_mask is None:
            extra_mask = jnp.ones((P, N), bool)
        if extra_scores is None:
            extra_scores = jnp.zeros((P, N), jnp.int64)
        comb = jnp.where(
            extra_mask,
            extra_scores.astype(jnp.int32),
            jnp.int32(XCOMB_INFEASIBLE),
        )
        xcomb = jnp.pad(
            comb[order].T,
            ((0, N_pad - N), (0, P_pad - P)),
            constant_values=np.int32(XCOMB_INFEASIBLE),
        )
    else:
        xcomb = None

    usage_with_flags = _pad_rows(_lanes(usage_np), N_pad)
    n_gap = N_pad - mask_default.shape[0]
    for flag_lane, vec in (
        (FLAG_LANE_OK, nodes.valid & mask_default),
        (FLAG_LANE_FRESH, nodes.metric_fresh),
        (FLAG_LANE_PROD_OK, nodes.valid & mask_prod),
    ):
        usage_with_flags = usage_with_flags.at[:, flag_lane].set(
            jnp.pad(vec.astype(jnp.int32), (0, n_gap))
        )
    # the initial requested vector rides alloc's spare lanes
    alloc_packed = _pad_rows(_lanes(nodes.allocatable), N_pad)
    req0 = _pad_rows(_lanes(nodes.requested), N_pad)
    alloc_packed = lax.dynamic_update_slice(
        alloc_packed,
        req0[:, : res.NUM_RESOURCES],
        (0, REQ0_LANE_OFFSET),
    )
    use_wave = cfg.wave > 1
    outs = _run_cycle(
        preq,
        psreq,
        pest,
        qid,
        pvalid,
        pprod,
        alloc_packed,
        usage_with_flags,
        qrt,
        qlim,
        quse0,
        weights,
        uprod,
        xcomb,
        cfg=cfg,
        block=block,
        interpret=interpret,
        wave=cfg.wave if use_wave else 0,
        top_m=cfg.top_m if use_wave else 0,
    )
    if use_wave:
        chosen, nreq, nest, quse, stats = outs
        rounds = stats[0, 0].astype(jnp.int64)
    else:
        chosen, nreq, nest, quse = outs
        rounds = None

    assignment = jnp.full((P,), -1, jnp.int32).at[order].set(chosen[:P, 0])
    status = jnp.where(assignment >= 0, STATUS_ASSIGNED, STATUS_UNSCHEDULABLE)
    assigned = (assignment >= 0) & pods.valid
    _, pod_gang_ok = gang_satisfaction(
        assignment, pods.valid, pods.gang_id, gangs.min_member
    )
    status = jnp.where(assigned & ~pod_gang_ok, STATUS_WAIT_GANG, status)

    R = res.NUM_RESOURCES
    nq = quotas.used.shape[0]
    return CycleResult(
        assignment=assignment,
        status=status.astype(jnp.int32),
        node_requested=nreq[:N, :R].astype(jnp.int64),
        node_estimated=nest[:N, :R].astype(jnp.int64),
        quota_used=quse[:nq, :R].astype(jnp.int64),
        rounds=rounds,
        path="pallas",
    )
