"""Incremental column/row rescore of the resident [P, N] score tensors.

The Score phase is one dense pods x nodes tensor program (ISSUE 9 —
the paper's whole premise), but a warm delta Sync touches a handful of
node rows: recomputing the entire [P, N] tensor for a 3-node delta
throws away 99.9% of the arithmetic.  The bridge keeps the last
launch's score/feasible tensors DEVICE-RESIDENT (bridge/state.py
``ScoreResidency``) and this module recomputes only what a batch of
committed deltas invalidated:

* **dirty columns** — node rows a delta scattered (or whose derived
  freshness flipped): gather those node rows, run the scoring math for
  every pod against just them (O(P x d)), scatter the [P, d] result
  into the resident tensors.
* **dirty rows** — pod rows that changed (requests/estimated deltas,
  priority-class flips): gather those pod rows, score them against
  every node (O(d_p x N)), scatter the [d_p, N] result in.

Exactness contract: every term of the scoring math
(solver/greedy.py ``score_all`` — the SHARED body, so the engines
cannot drift) is cellwise in (pod row, node row), so gather-compute-
scatter produces the very same bits a full ``score_cycle`` would put
in those cells; untouched cells keep the bits the last launch wrote.
tests/test_score_incremental.py fuzzes randomized warm streams against
the full-rescore oracle byte-for-byte.

Compile economics: dirty counts vary per cycle, so the index vectors
are padded to the same power-of-two buckets the delta scatter uses
(pad slots carry an out-of-range index dropped by ``mode="drop"``) —
one compiled rescore per (geometry, dirty-bucket pair), zero jit cache
misses on a steady warm stream.  The dirty COUNT itself never crosses
the jit boundary (a traced ``n_dirty`` would retrace per value — the
koordlint retrace-hazard rule rejects that shape statically).

Donation: the resident ``scores`` tensor ([P, N] i64, the big one) is
donated — the pre-rescore buffer is dead the moment the new tensor
exists, so the scatter aliases in place.  ``feasible`` is NOT donated:
coalesced Score readbacks ``device_get`` the feasible tensor they
captured at launch, and a non-donating warm commit (derived-column
only) does not drain the pipeline — donating feasible could delete a
buffer an in-flight batch still reads.

Mesh (ISSUE 7 geometry): the score tensor shards ``P(None, "nodes")``
— column j lives with node j's snapshot rows — so the sharded rescore
is a ``shard_map`` where each device rebases the global dirty-column
indices against its own shard, recomputes with its LOCAL node rows,
and scatters only the columns it owns.  NO collective runs; in/out
specs are equal, so no resharding program is ever minted.

The sparse candidate engine (ISSUE 16, solver/candidates.py) reuses
this module's gather/pad helpers (``_take_nodes``/``_take_pods``/
``_pad_rows``) for its own dirty-row refreshes — same bucketing, same
OOB-sentinel drop semantics, same retrace-free contract.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from koordinator_tpu.model.snapshot import pad_bucket
from koordinator_tpu.obs import devprof
from koordinator_tpu.solver.greedy import score_all


def _take_nodes(nodes, idx):
    """NodeBatch with rows gathered at ``idx`` (in-range by contract:
    callers clip).  Optional leaves stay None; names stay static."""
    take = lambda a: None if a is None else jnp.take(a, idx, axis=0)
    return dataclasses.replace(
        nodes,
        allocatable=take(nodes.allocatable),
        requested=take(nodes.requested),
        usage=take(nodes.usage),
        metric_fresh=take(nodes.metric_fresh),
        valid=take(nodes.valid),
        agg_usage=take(nodes.agg_usage),
        agg_fresh=take(nodes.agg_fresh),
        prod_usage=take(nodes.prod_usage),
        accel_type=take(nodes.accel_type),
    )


def _take_pods(pods, idx):
    take = lambda a: jnp.take(a, idx, axis=0)
    opt = lambda a: None if a is None else take(a)
    return dataclasses.replace(
        pods,
        requests=take(pods.requests),
        estimated=take(pods.estimated),
        priority_class=take(pods.priority_class),
        qos=take(pods.qos),
        priority=take(pods.priority),
        gang_id=take(pods.gang_id),
        quota_id=take(pods.quota_id),
        valid=take(pods.valid),
        workload_class=opt(pods.workload_class),
        sensitivity=opt(pods.sensitivity),
    )


def _rescore_body(snapshot, scores, feasible, node_idx, pod_idx, cfg):
    """Column pass then row pass over one (shard-local) block.  The two
    passes overlap on (dirty pod, dirty node) cells with identical
    values — both compute the full-rescore bits — so the order is
    immaterial; pad/foreign slots carry out-of-range targets that
    ``mode="drop"`` discards."""
    nodes, pods = snapshot.nodes, snapshot.pods
    n_rows = nodes.allocatable.shape[0]
    p_rows = pods.requests.shape[0]
    # dirty COLUMNS: every pod vs the gathered node rows -> [P, dB]
    sub_nodes = _take_nodes(nodes, jnp.clip(node_idx, 0, n_rows - 1))
    s_cols, f_cols = score_all(
        dataclasses.replace(snapshot, nodes=sub_nodes), cfg
    )
    scores = scores.at[:, node_idx].set(s_cols, mode="drop")
    feasible = feasible.at[:, node_idx].set(f_cols, mode="drop")
    # dirty ROWS: the gathered pod rows vs every node -> [dB_p, N]
    sub_pods = _take_pods(pods, jnp.clip(pod_idx, 0, p_rows - 1))
    s_rows, f_rows = score_all(
        dataclasses.replace(snapshot, pods=sub_pods), cfg
    )
    scores = scores.at[pod_idx, :].set(s_rows, mode="drop")
    feasible = feasible.at[pod_idx, :].set(f_rows, mode="drop")
    return scores, feasible


@devprof.boundary("solver.incremental._rescore")
@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _rescore(snapshot, scores, feasible, node_idx, pod_idx, *, cfg):
    """Single-chip incremental rescore; ``scores`` is donated (the
    pre-rescore buffer is dead), ``feasible`` is copied (module
    docstring: in-flight readbacks hold it)."""
    return _rescore_body(snapshot, scores, feasible, node_idx, pod_idx, cfg)


@devprof.boundary("solver.incremental._rescore_sharded")
@partial(jax.jit, static_argnames=("cfg", "mesh"), donate_argnums=(1,))
def _rescore_sharded(snapshot, scores, feasible, node_idx, pod_idx, *, cfg, mesh):
    """Shard-LOCAL incremental rescore over the cluster mesh: the score
    tensor is ``P(None, "nodes")`` (column j with node j's rows), the
    dirty-column indices replicate, and each device rebases them
    against its own shard's column offset — foreign and pad columns
    rebase out of local range and drop, so a dirty column writes on
    exactly the device owning it.  The row pass scores the dirty pod
    rows against each device's LOCAL node shard and scatters its own
    [dB_p, N_local] block.  In/out specs equal: nothing regathers."""
    from jax.sharding import PartitionSpec as P

    from koordinator_tpu.parallel.mesh import (
        CLUSTER_AXIS,
        shard_map_compat,
        snapshot_partition_specs,
    )

    score_spec = P(None, CLUSTER_AXIS)

    def body(snap_local, scores_l, feasible_l, nidx, pidx):
        n_local = snap_local.nodes.allocatable.shape[0]
        start = jax.lax.axis_index(CLUSTER_AXIS).astype(nidx.dtype) * n_local
        loc = nidx - start
        owned = (loc >= 0) & (loc < n_local)
        loc = jnp.where(owned, loc, n_local)  # not-mine/pad -> dropped
        return _rescore_body(snap_local, scores_l, feasible_l, loc, pidx, cfg)

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            snapshot_partition_specs(snapshot),
            score_spec, score_spec, P(), P(),
        ),
        out_specs=(score_spec, score_spec),
    )(snapshot, scores, feasible, node_idx, pod_idx)


def _pad_rows(rows, oob: int) -> np.ndarray:
    """Sorted unique row indices padded to the power-of-two bucket with
    the out-of-range sentinel ``oob`` (``mode="drop"`` discards it) —
    the apply_flat_delta bucket discipline, so dirty-count variance
    never mints new compiled shapes."""
    rows = np.asarray(sorted(int(r) for r in rows), np.int64)
    bucket = pad_bucket(max(len(rows), 1))
    out = np.full(bucket, oob, np.int64)
    out[: len(rows)] = rows
    return out


def rescore_dirty(snapshot, scores, feasible, node_rows, pod_rows,
                  cfg, mesh=None):
    """Recompute the dirty columns/rows of the resident score tensors.

    ``scores``/``feasible`` are the resident [P, N] tensors of the LAST
    certified launch; ``node_rows``/``pod_rows`` are the (unpadded,
    unique) row indices every warm commit since then invalidated.
    Returns the advanced ``(scores, feasible)`` pair — bit-identical to
    ``score_cycle(snapshot, cfg)`` by the gather/scatter exactness
    contract (module docstring).

    ``scores`` is DONATED: callers must re-bind or drop their reference
    (the koordlint ``donation-safety`` rule checks call sites of this
    helper cross-module).  ``feasible`` is never donated — in-flight
    coalesced readbacks hold it.

    ``mesh``: the cluster mesh routes the shard-local program;
    ``scores``/``feasible`` must be ``P(None, "nodes")``-sharded over it
    (parallel/mesh.py ``score_sharding``) and the snapshot mesh-resident.
    """
    node_idx = jnp.asarray(_pad_rows(node_rows, scores.shape[1]))
    pod_idx = jnp.asarray(_pad_rows(pod_rows, scores.shape[0]))
    if mesh is not None and mesh.size > 1:
        kernel, kw = _rescore_sharded, {"mesh": mesh}
    else:
        kernel, kw = _rescore, {}
    return kernel(snapshot, scores, feasible, node_idx, pod_idx, cfg=cfg, **kw)
