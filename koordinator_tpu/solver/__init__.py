from koordinator_tpu.solver.greedy import (  # noqa: F401
    CycleResult,
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    score_cycle,
    greedy_assign,
)


# (backend, node-bucket, pod-bucket) combos where the Pallas cycle kernel
# failed to lower/run; keyed by shape bucket so an oversized cycle (VMEM
# overflow) doesn't blacklist normal-sized cycles, while a broken combo
# pays the failed trace once, not once per scheduling cycle.
_PALLAS_UNSUPPORTED = set()

# The kernel's scoring multiplies clamped free capacity by MAX_NODE_SCORE
# (=100) in i32, so scored tensors need that much headroom below 2^31
# (model/resources.py documents the same ~20 TiB/node bound); quota rows
# are only added/compared, so they just need room for one more request.
_I32_SCORED_LIMIT = 2**31 // 100
_I32_QUOTA_LIMIT = 2**31 - 2**27


def check_i32_bounds(maxima) -> bool:
    """``maxima``: (scored_max, quota_max, est_sum_max, req_sum_max).

    Bounds the kernel's in-loop accumulators, not just its inputs: the
    LoadAware term sums usage + all assigned pods' estimates on one node,
    and a quota's used row sums every assigned request in the cycle, so
    the worst-case cycle-end values must themselves fit i32."""
    scored_max, quota_max, est_sum_max, req_sum_max = (int(v) for v in maxima)
    return (
        scored_max < _I32_SCORED_LIMIT
        and quota_max + req_sum_max < _I32_QUOTA_LIMIT
        and scored_max + est_sum_max < _I32_SCORED_LIMIT
    )


def pallas_inputs_fit_i32(snapshot) -> bool:
    """Node rows are bounded by design (MiB units) but quota rows are
    cluster-wide aggregates that can exceed i32 on very large clusters
    (> ~2 PiB memory).  Out-of-range inputs must take the i64 scan path —
    silent truncation would diverge placement with no error."""
    import jax.numpy as jnp
    import numpy as np

    scored = (
        snapshot.nodes.allocatable,
        snapshot.nodes.requested,
        snapshot.nodes.usage,
        snapshot.pods.requests,
        snapshot.pods.estimated,
    )
    quota = (snapshot.quotas.runtime, snapshot.quotas.used)
    # one fused device->host transfer for the whole check
    maxima = np.asarray(
        jnp.stack(
            [
                jnp.max(jnp.stack([jnp.max(jnp.abs(t)) for t in scored])),
                jnp.max(jnp.stack([jnp.max(jnp.abs(t)) for t in quota])),
                jnp.max(jnp.sum(jnp.abs(snapshot.pods.estimated), axis=0)),
                jnp.max(jnp.sum(jnp.abs(snapshot.pods.requests), axis=0)),
            ]
        )
    )
    return check_i32_bounds(maxima)


def run_cycle(snapshot, cfg=None, extra_mask=None, extra_scores=None, i32_ok=None):
    """Backend-dispatched scheduling cycle.

    On TPU the single-kernel Pallas cycle (solver/pallas_cycle.py) runs the
    per-pod loop in VMEM; elsewhere (and when extended-plugin tensors are
    composed in) the lax.scan path runs.  Both are bit-identical
    (tests/test_pallas_cycle.py).

    ``i32_ok``: callers that already know whether the snapshot fits the
    kernel's i32 arithmetic (e.g. the bridge server, which checks host-side
    numpy mirrors at Sync time) pass it to skip the per-cycle device check.
    """
    import jax

    from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG

    if cfg is None:
        cfg = DEFAULT_CYCLE_CONFIG
    backend = jax.default_backend()
    has_extras = extra_mask is not None or extra_scores is not None
    bucket = (
        backend,
        int(snapshot.nodes.allocatable.shape[0]),
        int(snapshot.pods.capacity),
        has_extras,
    )
    extras_ok = True
    if extra_scores is not None:
        import jax.numpy as jnp

        # extended-plugin scores join the kernel's i32 accumulation
        extras_ok = int(jnp.max(jnp.abs(extra_scores))) < 2**29
    if (
        backend != "cpu"
        and bucket not in _PALLAS_UNSUPPORTED
        # data-dependent, not shape-dependent: no blacklisting on failure
        and extras_ok
        and (i32_ok if i32_ok is not None else pallas_inputs_fit_i32(snapshot))
    ):
        import logging

        from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas

        try:
            result = greedy_assign_pallas(
                snapshot, cfg, extra_mask=extra_mask, extra_scores=extra_scores
            )
            # materialize before returning: with async dispatch (and lazy
            # materialization on tunneled platforms) a runtime fault would
            # otherwise surface at the caller, outside this fallback.  Hand
            # the host copy back in the result — on a tunneled platform a
            # device->host read costs a network round trip (~68ms measured),
            # and every caller's next move is np.asarray(assignment).
            import dataclasses

            import numpy as _np

            # np.asarray both forces execution and surfaces runtime faults;
            # an extra block_until_ready would cost a second round trip here
            return dataclasses.replace(
                result, assignment=_np.asarray(result.assignment)
            )
        except Exception:
            _PALLAS_UNSUPPORTED.add(bucket)
            logging.getLogger(__name__).exception(
                "pallas cycle kernel failed for %r; "
                "falling back to the lax.scan path for this shape bucket",
                bucket,
            )
    return greedy_assign(snapshot, cfg, extra_mask=extra_mask, extra_scores=extra_scores)
