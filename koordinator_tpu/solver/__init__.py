from koordinator_tpu.solver.greedy import (  # noqa: F401
    CycleResult,
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    score_cycle,
    greedy_assign,
)
