from koordinator_tpu.solver.greedy import (  # noqa: F401
    CycleResult,
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    score_all,
    score_cycle,
    greedy_assign,
)
from koordinator_tpu.solver.candidates import (  # noqa: F401
    CandidateOverflow,
    build_candidates,
    candidate_membership_mask,
    check_candidate_overflow,
    refresh_candidates,
    score_candidates,
    sparse_top_k,
)
from koordinator_tpu.solver.incremental import rescore_dirty  # noqa: F401
from koordinator_tpu.solver.topk import (  # noqa: F401
    masked_top_k,
    score_upper_bound,
)
from koordinator_tpu.solver.wave import wave_assign  # noqa: F401


# (variant, backend, node-bucket, pod-bucket, extras) combos where a Pallas
# cycle kernel failed to lower/run, with retry backoff state.  Keyed by
# shape bucket so an oversized cycle (VMEM overflow) doesn't demote
# normal-sized cycles.  Demotion is NOT process-lifetime (round-3 review):
# a transient backend error (e.g. a tunnel hiccup mid-trace) retries after
# an exponentially growing number of scan-path cycles, and the demotion
# state is inspectable via ``pallas_demotions()``.
import threading as _threading

_PALLAS_FAILURES = {}  # bucket -> [fail_count, cycles_until_retry]
_PALLAS_LOCK = _threading.Lock()  # HTTP surfacing reads race solver writes
_RETRY_BASE = 4  # first retry after 4 demoted cycles, then 16, 64, ... 256
_RETRY_CAP = 256


def pallas_demotions():
    """Snapshot of demoted kernel buckets -> (failures, cycles until the
    next retry).  Surfaced so daemons can export it as a metric instead of
    the demotion being visible only in a log line."""
    with _PALLAS_LOCK:
        return {k: tuple(v) for k, v in _PALLAS_FAILURES.items()}


def _demoted(bucket) -> bool:
    """True while the bucket should keep using the scan path; decrements
    the retry counter so the kernel is re-attempted periodically."""
    with _PALLAS_LOCK:
        state = _PALLAS_FAILURES.get(bucket)
        if state is None:
            return False
        if state[1] <= 0:
            return False  # retry window open: attempt the kernel again
        state[1] -= 1
        return True


# demotion observers (obs/ telemetry: counter bump + flight-recorder
# dump).  Weak references: a ScorerServicer built per test must not pin
# its telemetry alive — or keep firing — through this module-level list.
import weakref as _weakref

_DEMOTION_LISTENERS = []


def register_demotion_listener(cb):
    """``cb(bucket, failures)`` fires on every kernel demotion (after
    the backoff state updated).  Held weakly; returns an unregister
    callable.  Callbacks run on the scheduling path — keep them cheap
    and never raise (exceptions are swallowed and logged: a telemetry
    sink must not take the cycle's fallback path down)."""
    try:
        ref = _weakref.WeakMethod(cb)
    except TypeError:
        ref = _weakref.ref(cb)
    with _PALLAS_LOCK:
        _DEMOTION_LISTENERS.append(ref)

    def unregister() -> None:
        with _PALLAS_LOCK:
            if ref in _DEMOTION_LISTENERS:
                _DEMOTION_LISTENERS.remove(ref)

    return unregister


def _notify_demotion(bucket, failures) -> None:
    with _PALLAS_LOCK:
        live = [ref() for ref in _DEMOTION_LISTENERS]
        _DEMOTION_LISTENERS[:] = [
            ref for ref, cb in zip(_DEMOTION_LISTENERS, live) if cb is not None
        ]
        live = [cb for cb in live if cb is not None]
    for cb in live:
        try:
            cb(bucket, failures)
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "demotion listener failed for bucket %r", bucket
            )


def _record_failure(bucket) -> None:
    with _PALLAS_LOCK:
        state = _PALLAS_FAILURES.setdefault(bucket, [0, 0])
        state[0] += 1
        state[1] = min(_RETRY_CAP, _RETRY_BASE ** min(state[0], 4))
        failures = state[0]
    # outside the lock: a listener reading pallas_demotions() (or doing
    # anything slow) must not deadlock or serialize the solver
    _notify_demotion(bucket, failures)


def _record_success(bucket) -> None:
    with _PALLAS_LOCK:
        _PALLAS_FAILURES.pop(bucket, None)

# The kernel's scoring multiplies clamped free capacity by MAX_NODE_SCORE
# (=100) in i32, so scored tensors need that much headroom below 2^31
# (model/resources.py documents the same ~20 TiB/node bound); quota rows
# are only added/compared, so they just need room for one more request.
_I32_SCORED_LIMIT = 2**31 // 100
_I32_QUOTA_LIMIT = 2**31 - 2**27


def check_i32_bounds(maxima) -> bool:
    """``maxima``: (scored_max, quota_max, est_sum_max, req_sum_max).

    Bounds the kernel's in-loop accumulators, not just its inputs: the
    LoadAware term sums usage + all assigned pods' estimates on one node,
    and a quota's used row sums every assigned request in the cycle, so
    the worst-case cycle-end values must themselves fit i32."""
    scored_max, quota_max, est_sum_max, req_sum_max = (int(v) for v in maxima)
    return (
        scored_max < _I32_SCORED_LIMIT
        and quota_max + req_sum_max < _I32_QUOTA_LIMIT
        and scored_max + est_sum_max < _I32_SCORED_LIMIT
    )


def pallas_inputs_fit_i32(snapshot) -> bool:
    """Node rows are bounded by design (MiB units) but quota rows are
    cluster-wide aggregates that can exceed i32 on very large clusters
    (> ~2 PiB memory).  Out-of-range inputs must take the i64 scan path —
    silent truncation would diverge placement with no error."""
    import jax.numpy as jnp
    import numpy as np

    scored = (
        snapshot.nodes.allocatable,
        snapshot.nodes.requested,
        snapshot.nodes.usage,
        snapshot.pods.requests,
        snapshot.pods.estimated,
    )
    quota = (snapshot.quotas.runtime, snapshot.quotas.used)
    # one fused device->host transfer for the whole check
    maxima = np.asarray(
        jnp.stack(
            [
                jnp.max(jnp.stack([jnp.max(jnp.abs(t)) for t in scored])),
                jnp.max(jnp.stack([jnp.max(jnp.abs(t)) for t in quota])),
                jnp.max(jnp.sum(jnp.abs(snapshot.pods.estimated), axis=0)),
                jnp.max(jnp.sum(jnp.abs(snapshot.pods.requests), axis=0)),
            ]
        )
    )
    return check_i32_bounds(maxima)


def run_cycle(snapshot, cfg=None, extra_mask=None, extra_scores=None, i32_ok=None):
    """Backend-dispatched scheduling cycle.

    On TPU the dense-layout single-kernel Pallas cycle
    (solver/pallas_dense.py) runs the per-pod loop in VMEM, with the
    first-generation wide-layout kernel (solver/pallas_cycle.py) as a
    fallback; elsewhere the lax.scan path runs.  All are bit-identical
    (tests/test_pallas_cycle.py).

    ``cfg.wave > 1`` selects the wave-batched cycle: the wide kernel
    runs its in-VMEM wave rounds (and is tried FIRST — the dense kernel
    keeps its per-pod loop and ignores the knobs, placements identical
    either way), and the CPU path runs ``solver.wave.wave_assign``
    instead of the scan.  The knobs ride the static config, so a warm
    Sync/Assign stream stays retrace-free (tests/test_resident_warm.py).

    ``i32_ok``: callers that already know whether the snapshot fits the
    kernel's i32 arithmetic (e.g. the bridge server, which checks host-side
    numpy mirrors at Sync time) pass it to skip the per-cycle device check.

    Fused scoring terms (ISSUE 15): a ``cfg`` with term configs set
    materializes the registry's cellwise [P, N] tensors ONCE
    (solver/terms.py ``term_extras``, one async launch, no readback) and
    folds them into ``extra_mask``/``extra_scores`` — the scan, the wave
    path and the Pallas kernels all consume the fused total through the
    seam they already had.  A terms-only extra needs NO device
    reduction for its magnitude bound: the registry's bound is a config
    property (``terms_upper_bound``).
    """
    import jax

    from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG

    if cfg is None:
        cfg = DEFAULT_CYCLE_CONFIG
    backend = jax.default_backend()
    caller_scores = extra_scores
    from koordinator_tpu.solver.terms import term_extras, terms_upper_bound

    t_scores, t_mask = term_extras(snapshot, cfg)
    if t_scores is not None:
        extra_scores = (
            t_scores if extra_scores is None else extra_scores + t_scores
        )
    if t_mask is not None:
        extra_mask = t_mask if extra_mask is None else extra_mask & t_mask
    has_extras = extra_mask is not None or extra_scores is not None
    shape_key = (
        backend,
        int(snapshot.nodes.allocatable.shape[0]),
        int(snapshot.pods.capacity),
        has_extras,
        # the wave knobs compile distinct programs — a failing wave
        # kernel must not demote the per-pod bucket (or vice versa)
        int(cfg.wave),
        int(cfg.top_m),
    )
    extras_ok = True
    scores_hi = None
    if extra_scores is not None:
        # magnitude bound for the kernel's i32 accumulation headroom and
        # the wave path's packed-key range.  Terms-only extras take the
        # STATIC registry bound (no device sync on the warm Assign
        # path); caller extras still need the one device reduction, and
        # a composed total is bounded by the sum of the two bounds.
        if caller_scores is None:
            scores_hi = terms_upper_bound(cfg)
        else:
            import jax.numpy as jnp

            scores_hi = int(jnp.max(jnp.abs(caller_scores)))
            if t_scores is not None:
                scores_hi += terms_upper_bound(cfg)
        extras_ok = scores_hi < 2**29
    if (
        backend != "cpu"
        # data-dependent, not shape-dependent: no demotion on failure
        and extras_ok
        and (i32_ok if i32_ok is not None else pallas_inputs_fit_i32(snapshot))
    ):
        import dataclasses
        import logging

        import numpy as _np

        from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas
        from koordinator_tpu.solver.pallas_dense import greedy_assign_dense

        variants = (("dense", greedy_assign_dense),
                    ("wide", greedy_assign_pallas))
        if cfg.wave > 1:
            # the wave inner loop lives in the wide kernel; try it first
            # so the requested batching actually runs
            variants = (("wide", greedy_assign_pallas),
                        ("dense", greedy_assign_dense))
        for variant, fn in variants:
            bucket = (variant,) + shape_key
            if _demoted(bucket):
                continue
            try:
                result = fn(
                    snapshot,
                    cfg,
                    extra_mask=extra_mask,
                    extra_scores=extra_scores,
                )
                # materialize before returning: with async dispatch (and
                # lazy materialization on tunneled platforms) a runtime
                # fault would otherwise surface at the caller, outside this
                # fallback.  np.asarray both forces execution and surfaces
                # faults; the host copy rides back in the result because
                # every caller's next move is np.asarray(assignment) and a
                # tunneled device->host read costs a round trip (~68ms).
                result = dataclasses.replace(
                    result, assignment=_np.asarray(result.assignment)
                )
                _record_success(bucket)
                return result
            except Exception:
                _record_failure(bucket)
                logging.getLogger(__name__).exception(
                    "pallas %s cycle kernel failed for %r; demoting this "
                    "shape bucket (retry after %d cycles)",
                    variant,
                    bucket,
                    pallas_demotions().get(bucket, (0, 0))[1],
                )
    if cfg.wave > 1 and (scores_hi is None or scores_hi < 2**31):
        # run_cycle never raises for in-contract inputs: extra_scores
        # beyond the packed-key range take the bit-identical scan below
        # instead of tripping wave_assign's magnitude guard
        return wave_assign(
            snapshot, cfg, extra_mask=extra_mask, extra_scores=extra_scores,
            scores_hi=scores_hi,
        )
    return greedy_assign(snapshot, cfg, extra_mask=extra_mask, extra_scores=extra_scores)
