from koordinator_tpu.solver.greedy import (  # noqa: F401
    CycleResult,
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    score_cycle,
    greedy_assign,
)


# (backend, node-bucket, pod-bucket) combos where the Pallas cycle kernel
# failed to lower/run; keyed by shape bucket so an oversized cycle (VMEM
# overflow) doesn't blacklist normal-sized cycles, while a broken combo
# pays the failed trace once, not once per scheduling cycle.
_PALLAS_UNSUPPORTED = set()


def run_cycle(snapshot, cfg=None, extra_mask=None, extra_scores=None):
    """Backend-dispatched scheduling cycle.

    On TPU the single-kernel Pallas cycle (solver/pallas_cycle.py) runs the
    per-pod loop in VMEM; elsewhere (and when extended-plugin tensors are
    composed in) the lax.scan path runs.  Both are bit-identical
    (tests/test_pallas_cycle.py).
    """
    import jax

    from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG

    if cfg is None:
        cfg = DEFAULT_CYCLE_CONFIG
    backend = jax.default_backend()
    bucket = (
        backend,
        int(snapshot.nodes.allocatable.shape[0]),
        int(snapshot.pods.capacity),
    )
    if (
        extra_mask is None
        and extra_scores is None
        and backend != "cpu"
        and bucket not in _PALLAS_UNSUPPORTED
    ):
        import logging

        from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas

        try:
            result = greedy_assign_pallas(snapshot, cfg)
            # materialize before returning: with async dispatch (and lazy
            # materialization on tunneled platforms) a runtime fault would
            # otherwise surface at the caller, outside this fallback
            jax.block_until_ready(result.assignment)
            import numpy as _np

            _np.asarray(result.assignment)
            return result
        except Exception:
            _PALLAS_UNSUPPORTED.add(bucket)
            logging.getLogger(__name__).exception(
                "pallas cycle kernel failed for %r; "
                "falling back to the lax.scan path for this shape bucket",
                bucket,
            )
    return greedy_assign(snapshot, cfg, extra_mask=extra_mask, extra_scores=extra_scores)
