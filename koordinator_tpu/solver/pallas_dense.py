"""Dense-layout Pallas cycle kernel: nodes on lanes, resources on sublanes.

The first-generation kernel (solver/pallas_cycle.py) puts RESOURCES on the
128-lane axis, so every per-pod vector op touches [N, 128] i32 tiles (256
vregs at 2k nodes) while only ~13 lanes carry data — measured ~12us/pod on
v5e, entirely VPU-occupancy-bound on padding.  This kernel transposes the
whole state to ``[RP=16, N]`` — resources (13) plus three node-flag rows on
the SUBLANE axis, nodes riding the lane axis — so the same math touches 32
vregs instead of 256:

* per-pod column extraction (requests / estimates / quota row) is a one-hot
  lane reduction ([16, 128] ops — 2 vregs);
* Filter violations reduce over the 16 sublanes to a [1, N] row;
* argmax over nodes is a native lane reduction of a [1, N] row with the
  same first-index tie-break (min over matching lane iota);
* Reserve commits are full-tensor one-hot-lane adds on [16, N].

Semantics are bit-identical with solver/greedy.py's lax.scan (the parity
oracle mirroring the reference's sequential cycle,
``pkg/scheduler/frameworkext/framework_extender.go:192,216``); the same
i32-soundness contract as the wide kernel applies (model/resources.py MiB
units; dispatcher gates via pallas_inputs_fit_i32).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG, MOST_ALLOCATED
from koordinator_tpu.constraints.gang import gang_satisfaction
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE, ClusterSnapshot
from koordinator_tpu.obs import devprof
from koordinator_tpu.model.snapshot import PriorityClass
from koordinator_tpu.ops.fit import nonzero_requests
from koordinator_tpu.ops.loadaware import (
    loadaware_node_masks,
    select_score_usage,
)
from koordinator_tpu.solver.greedy import (
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    CycleResult,
    queue_order,
)
from koordinator_tpu.solver.pallas_cycle import (
    I32_MIN,
    LANES,
    XCOMB_INFEASIBLE,
    _i32,
)

# sublane rows: resources occupy [0, NUM_RESOURCES); flags ride the spare
# rows of the usage tensor (their weight rows are zero, their request rows
# are zero, so they can never contribute to a score or a Filter violation)
RP = 16
FLAG_ROW_OK = RP - 3  # valid & loadaware default mask
FLAG_ROW_FRESH = RP - 2  # metric_fresh
FLAG_ROW_PROD_OK = RP - 1  # valid & prod-threshold mask
assert res.NUM_RESOURCES <= FLAG_ROW_OK, (
    "resource axis grew into the dense kernel's flag rows; bump RP"
)


def _exact_div(v, safe, recip):
    """Exact nonnegative i32 floor division via f32 reciprocal (see
    pallas_cycle._exact_div for the ablation and soundness argument)."""
    q = (v.astype(jnp.float32) * recip).astype(jnp.int32)
    r = v - q * safe
    q = q + jnp.where(r >= safe, _i32(1), _i32(0))
    q = q - jnp.where(v - q * safe < _i32(0), _i32(1), _i32(0))
    return q


def _least_requested(t, cap, recip):
    safe = jnp.maximum(cap, _i32(1))
    free = jnp.maximum(cap - t, _i32(0))
    score = _exact_div(free * _i32(MAX_NODE_SCORE), safe, recip)
    return jnp.where((cap == _i32(0)) | (t > cap), _i32(0), score)


def _most_requested(t, cap, recip):
    safe = jnp.maximum(cap, _i32(1))
    clamped = jnp.minimum(t, cap)
    score = _exact_div(clamped * _i32(MAX_NODE_SCORE), safe, recip)
    return jnp.where(cap == _i32(0), _i32(0), score)


def _weighted_rows(per_res, w_col, w_sum: int):
    """[RP, N] per-resource scores x [RP, 1] weights -> [1, N]."""
    if w_sum == 0:
        return jnp.zeros((1, per_res.shape[1]), jnp.int32)
    s = jnp.sum(per_res * w_col, axis=0, keepdims=True, dtype=jnp.int32)
    return _exact_div(s, _i32(w_sum), np.float32(1.0 / w_sum))


def _onehot_col(tile, j, width):
    """Extract lane column ``j`` of ``tile`` [RP, width] -> [RP, 1] via a
    masked lane reduction (dynamic lane slicing is costly on the VPU)."""
    lane = lax.broadcasted_iota(jnp.int32, (1, width), 1) == j
    return jnp.sum(
        jnp.where(lane, tile, _i32(0)), axis=1, keepdims=True, dtype=jnp.int32
    )


def _cycle_kernel_dense(
    # scalar prefetch (SMEM)
    qid_ref,  # i32[P]
    pvalid_ref,  # i32[P]
    pprod_ref,  # i32[P]
    # inputs (VMEM) — all [RP, *] with nodes/pods/quotas on lanes
    preq_ref,  # i32[RP, B]
    psreq_ref,  # i32[RP, B]
    pest_ref,  # i32[RP, B]
    alloc_ref,  # i32[RP, N]
    req0_ref,  # i32[RP, N] initial node-requested
    usage_ref,  # i32[RP, N]; flag rows OK/FRESH/PROD_OK
    qrt_ref,  # i32[RP, Qp]
    qlim_ref,  # i32[RP, Qp]
    quse0_ref,  # i32[RP, Qp]
    w_ref,  # i32[RP, 128]: col 0 = fit weights, col 1 = loadaware weights
    *rest,  # optional uprod_ref i32[RP, N]; optional xcomb_ref i32[B, N];
    # then outputs (chosen_ref, nreq_ref, nest_ref, quse_ref)
    block: int,
    cfg: CycleConfig,
    has_extras: bool,
    has_prod: bool,
):
    if has_prod:
        uprod_ref = rest[0]
        rest = rest[1:]
    else:
        uprod_ref = None
    if has_extras:
        xcomb_ref = rest[0]
        rest = rest[1:]
    else:
        xcomb_ref = None
    (chosen_ref, nreq_ref, nest_ref, quse_ref) = rest

    i = pl.program_id(0)

    @pl.when(i == _i32(0))
    def _init():
        nreq_ref[:] = req0_ref[:]
        nest_ref[:] = jnp.zeros_like(nest_ref)
        quse_ref[:] = quse0_ref[:]

    alloc = alloc_ref[:]
    n_lanes = alloc.shape[1]
    q_lanes = quse0_ref.shape[1]
    node_ok = usage_ref[FLAG_ROW_OK : FLAG_ROW_OK + 1, :] != _i32(0)
    fresh = usage_ref[FLAG_ROW_FRESH : FLAG_ROW_FRESH + 1, :] != _i32(0)
    lane_iota = lax.broadcasted_iota(jnp.int32, (1, n_lanes), 1)
    qlane_iota = lax.broadcasted_iota(jnp.int32, (1, q_lanes), 1)

    fit_w_col = w_ref[:, 0:1]
    la_w_col = w_ref[:, 1:2]
    fit_w_sum = sum(res.weights_vector(dict(cfg.fit_resource_weights)))
    la_w_sum = sum(res.weights_vector(dict(cfg.loadaware.resource_weights)))
    recip = 1.0 / jnp.maximum(alloc, _i32(1)).astype(jnp.float32)

    def step(j, _):
        p = i * block + j
        req = _onehot_col(preq_ref[:], j, block)  # [RP, 1]
        sreq = _onehot_col(psreq_ref[:], j, block)
        est = _onehot_col(pest_ref[:], j, block)
        qid = qid_ref[p]
        is_valid = pvalid_ref[p] != _i32(0)
        qidx = jnp.maximum(qid, _i32(0))
        if has_prod:
            is_prod = pprod_ref[p] != _i32(0)
            node_ok_p = (
                jnp.where(
                    is_prod,
                    usage_ref[FLAG_ROW_PROD_OK : FLAG_ROW_PROD_OK + 1, :],
                    usage_ref[FLAG_ROW_OK : FLAG_ROW_OK + 1, :],
                )
                != _i32(0)
            )
            usage_p = jnp.where(is_prod, uprod_ref[:], usage_ref[:])
        else:
            node_ok_p = node_ok
            usage_p = usage_ref[:]

        nreq = nreq_ref[:]
        # Filter: Fit (only requested resources constrain) + node flags
        need = req > _i32(0)  # [RP, 1] broadcasts over lanes
        fviol = jnp.where(need & (nreq + req > alloc), _i32(1), _i32(0))
        fits = jnp.max(fviol, axis=0, keepdims=True) == _i32(0)  # [1, N]
        # ElasticQuota admission on limited dimensions
        qlane = qlane_iota == qidx
        quse_col = jnp.sum(
            jnp.where(qlane, quse_ref[:], _i32(0)),
            axis=1,
            keepdims=True,
            dtype=jnp.int32,
        )
        qrt_col = jnp.sum(
            jnp.where(qlane, qrt_ref[:], _i32(0)),
            axis=1,
            keepdims=True,
            dtype=jnp.int32,
        )
        qlim_col = jnp.sum(
            jnp.where(qlane, qlim_ref[:], _i32(0)),
            axis=1,
            keepdims=True,
            dtype=jnp.int32,
        )
        qviol = jnp.where(
            (qlim_col != _i32(0)) & (quse_col + req > qrt_col),
            _i32(1),
            _i32(0),
        )
        qok = jnp.max(qviol) == _i32(0)
        feasible = fits & node_ok_p & ((qid < _i32(0)) | qok) & is_valid
        if has_extras:
            xv = xcomb_ref[pl.ds(j, 1), :]  # [1, N]
            feasible = feasible & (xv != _i32(XCOMB_INFEASIBLE))

        # Score: NodeResourcesFit + LoadAware, exact integer math
        total = jnp.zeros((1, n_lanes), jnp.int32)
        if cfg.enable_fit_score:
            t = nreq + sreq
            if cfg.fit_scoring_strategy == MOST_ALLOCATED:
                per_res = _most_requested(t, alloc, recip)
            else:
                per_res = _least_requested(t, alloc, recip)
            total = total + _i32(cfg.fit_plugin_weight) * _weighted_rows(
                per_res, fit_w_col, fit_w_sum
            )
        if cfg.enable_loadaware:
            est_used = usage_p + nest_ref[:] + est
            per_res = _least_requested(est_used, alloc, recip)
            la = _weighted_rows(per_res, la_w_col, la_w_sum)
            total = total + _i32(cfg.loadaware_plugin_weight) * jnp.where(
                fresh, la, _i32(0)
            )
        if has_extras:
            total = total + jnp.where(
                xv == _i32(XCOMB_INFEASIBLE), _i32(0), xv
            )

        masked = jnp.where(feasible, total, I32_MIN)
        best = jnp.max(masked)
        any_feasible = best > I32_MIN
        chosen = jnp.min(jnp.where(masked == best, lane_iota, _i32(n_lanes)))
        chosen = jnp.where(any_feasible, chosen, _i32(-1))

        # Reserve: one-hot-lane adds on the [RP, N] state
        commit_lane = (lane_iota == chosen) & any_feasible  # [1, N]
        nreq_ref[:] = nreq + jnp.where(commit_lane, req, _i32(0))
        nest_ref[:] = nest_ref[:] + jnp.where(commit_lane, est, _i32(0))
        quse_commit = qlane & any_feasible & (qid >= _i32(0))
        quse_ref[:] = quse_ref[:] + jnp.where(quse_commit, req, _i32(0))

        chosen_ref[pl.ds(j, 1), :] = jnp.full((1, LANES), chosen, jnp.int32)
        return jnp.int32(0)

    lax.fori_loop(jnp.int32(0), jnp.int32(block), step, jnp.int32(0))


@devprof.boundary("solver.pallas_dense._run_cycle_dense")
@partial(jax.jit, static_argnames=("cfg", "block", "interpret"))
def _run_cycle_dense(
    preq, psreq, pest, qid, pvalid, pprod, alloc, req0, usage, qrt,
    qlim, quse0, weights, uprod=None, xcomb=None, *,
    cfg: CycleConfig, block: int, interpret: bool
):
    P = preq.shape[1]
    N = alloc.shape[1]
    Qp = qrt.shape[1]
    has_extras = xcomb is not None
    has_prod = uprod is not None
    grid = (P // block,)
    _z = np.int32(0)
    node_spec = pl.BlockSpec(
        (RP, N), lambda i, *_: (_z, _z), memory_space=pltpu.VMEM
    )
    quota_spec = pl.BlockSpec(
        (RP, Qp), lambda i, *_: (_z, _z), memory_space=pltpu.VMEM
    )
    pod_spec = pl.BlockSpec(
        (RP, block), lambda i, *_: (_z, i), memory_space=pltpu.VMEM
    )
    in_specs = (
        [pod_spec] * 3
        + [node_spec] * 3
        + [quota_spec] * 3
        + [
            pl.BlockSpec(
                (RP, LANES), lambda i, *_: (_z, _z), memory_space=pltpu.VMEM
            )
        ]
    )
    operands = [preq, psreq, pest, alloc, req0, usage, qrt, qlim, quse0, weights]
    if has_prod:
        in_specs += [node_spec]
        operands += [uprod]
    if has_extras:
        # [P, N] with nodes on lanes: each grid step streams a (block, N)
        # tile; the per-pod row is a cheap dynamic sublane slice
        in_specs += [
            pl.BlockSpec((block, N), lambda i, *_: (i, _z), memory_space=pltpu.VMEM)
        ]
        operands += [xcomb]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (block, LANES), lambda i, *_: (i, _z), memory_space=pltpu.VMEM
            ),
            node_spec,
            node_spec,
            quota_spec,
        ],
    )
    kernel = partial(
        _cycle_kernel_dense,
        block=block,
        cfg=cfg,
        has_extras=has_extras,
        has_prod=has_prod,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((P, LANES), jnp.int32),
            jax.ShapeDtypeStruct((RP, N), jnp.int32),
            jax.ShapeDtypeStruct((RP, N), jnp.int32),
            jax.ShapeDtypeStruct((RP, Qp), jnp.int32),
        ],
        interpret=interpret,
    )(qid, pvalid, pprod, *operands)


def _rows(a: jnp.ndarray, lanes: int) -> jnp.ndarray:
    """[M, R] -> [RP, lanes] i32: transpose, resources on sublanes."""
    t = a.astype(jnp.int32).T
    return jnp.pad(t, ((0, RP - t.shape[0]), (0, lanes - t.shape[1])))


def greedy_assign_dense(
    snapshot: ClusterSnapshot,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    interpret: bool = False,
    extra_mask=None,  # bool[P, N] extended-plugin Filter tensor
    extra_scores=None,  # i64[P, N] extended-plugin Score tensor
) -> CycleResult:
    """Dense-layout drop-in for greedy_assign on TPU (path="pallas").

    Same i32-headroom guard as the wide kernel: extended scores must stay
    under 2^29 so the accumulation cannot wrap.
    """
    if extra_scores is not None:
        peak = int(jnp.max(jnp.abs(extra_scores)))
        if peak >= 2**29:
            raise ValueError(
                f"extra_scores magnitude {peak} >= 2^29: out of the Pallas "
                "kernel's i32 headroom; use the lax.scan path (greedy_assign)"
            )
    return _greedy_assign_dense(snapshot, cfg, interpret, extra_mask, extra_scores)


@devprof.boundary("solver.pallas_dense._greedy_assign_dense")
@partial(jax.jit, static_argnames=("cfg", "interpret"))
def _greedy_assign_dense(
    snapshot: ClusterSnapshot,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    interpret: bool = False,
    extra_mask=None,
    extra_scores=None,
) -> CycleResult:
    pods, nodes, gangs, quotas = (
        snapshot.pods,
        snapshot.nodes,
        snapshot.gangs,
        snapshot.quotas,
    )
    P = pods.capacity
    N = nodes.allocatable.shape[0]

    order = queue_order(pods.priority, pods.valid)
    P_pad = -(-P // 128) * 128
    block = 128
    N_pad = -(-N // LANES) * LANES  # nodes ride the lane axis now

    def _pods(a):
        return _rows(a[order], P_pad)

    preq = _pods(pods.requests)
    psreq = _pods(nonzero_requests(pods.requests))
    pest = _pods(pods.estimated)
    qid = jnp.pad(pods.quota_id[order].astype(jnp.int32), (0, P_pad - P))
    pvalid = jnp.pad(pods.valid[order].astype(jnp.int32), (0, P_pad - P))

    mask_default, mask_prod = loadaware_node_masks(nodes, cfg)
    if not cfg.enable_loadaware:
        mask_default = jnp.ones_like(mask_default)
        mask_prod = mask_default
    usage_np, usage_prod = select_score_usage(nodes, cfg)
    prod_sensitive = cfg.enable_loadaware and (
        usage_prod is not None
        or bool(dict(cfg.loadaware.prod_usage_thresholds))
    )
    is_prod = pods.priority_class == int(PriorityClass.PROD)
    pprod = jnp.pad(is_prod[order].astype(jnp.int32), (0, P_pad - P))
    if prod_sensitive:
        uprod = _rows(usage_prod if usage_prod is not None else usage_np, N_pad)
    else:
        uprod = None

    Q = max(quotas.runtime.shape[0], 1)
    Qp = -(-Q // LANES) * LANES
    qrt = _rows(quotas.runtime, Qp)
    qlim = _rows(quotas.limited.astype(jnp.int32), Qp)
    quse0 = _rows(quotas.used, Qp)

    weights = jnp.zeros((RP, LANES), jnp.int32)
    weights = weights.at[: res.NUM_RESOURCES, 0].set(
        jnp.asarray(res.weights_vector(dict(cfg.fit_resource_weights)), jnp.int32)
    )
    weights = weights.at[: res.NUM_RESOURCES, 1].set(
        jnp.asarray(
            res.weights_vector(dict(cfg.loadaware.resource_weights)), jnp.int32
        )
    )

    if extra_mask is not None or extra_scores is not None:
        if extra_mask is None:
            extra_mask = jnp.ones((P, N), bool)
        if extra_scores is None:
            extra_scores = jnp.zeros((P, N), jnp.int64)
        comb = jnp.where(
            extra_mask,
            extra_scores.astype(jnp.int32),
            jnp.int32(XCOMB_INFEASIBLE),
        )
        # sorted pod order on SUBLANES, nodes on lanes: [P_pad, N_pad]
        xcomb = jnp.pad(
            comb[order],
            ((0, P_pad - P), (0, N_pad - N)),
            constant_values=np.int32(XCOMB_INFEASIBLE),
        )
    else:
        xcomb = None

    usage_rows = _rows(usage_np, N_pad)
    n_gap = N_pad - mask_default.shape[0]
    for flag_row, vec in (
        (FLAG_ROW_OK, nodes.valid & mask_default),
        (FLAG_ROW_FRESH, nodes.metric_fresh),
        (FLAG_ROW_PROD_OK, nodes.valid & mask_prod),
    ):
        usage_rows = usage_rows.at[flag_row, :].set(
            jnp.pad(vec.astype(jnp.int32), (0, n_gap))
        )
    alloc_rows = _rows(nodes.allocatable, N_pad)
    req0_rows = _rows(nodes.requested, N_pad)

    chosen, nreq, nest, quse = _run_cycle_dense(
        preq,
        psreq,
        pest,
        qid,
        pvalid,
        pprod,
        alloc_rows,
        req0_rows,
        usage_rows,
        qrt,
        qlim,
        quse0,
        weights,
        uprod,
        xcomb,
        cfg=cfg,
        block=block,
        interpret=interpret,
    )

    assignment = jnp.full((P,), -1, jnp.int32).at[order].set(chosen[:P, 0])
    status = jnp.where(assignment >= 0, STATUS_ASSIGNED, STATUS_UNSCHEDULABLE)
    assigned = (assignment >= 0) & pods.valid
    _, pod_gang_ok = gang_satisfaction(
        assignment, pods.valid, pods.gang_id, gangs.min_member
    )
    status = jnp.where(assigned & ~pod_gang_ok, STATUS_WAIT_GANG, status)

    R = res.NUM_RESOURCES
    nq = quotas.used.shape[0]
    return CycleResult(
        assignment=assignment,
        status=status.astype(jnp.int32),
        node_requested=nreq[:R, :N].T.astype(jnp.int64),
        node_estimated=nest[:R, :N].T.astype(jnp.int64),
        quota_used=quse[:R, :nq].T.astype(jnp.int64),
        path="pallas",
    )
