"""Sparse candidate-set scoring: [P, C] instead of [P, N] (ISSUE 16).

Every dense engine — ``score_cycle``, the incremental rescore, the
sharded rescore — materializes the full pods x nodes tensor, which at
true production scale (1M pods x 100k nodes) no chip can hold.  Real
schedulers never score every node (upstream K8s samples via
``percentageOfNodesToScore``; the constraint-packing term's source
scores feasibility-filtered subsets), so this module serves Score from
a per-pod CANDIDATE LIST instead:

* **feasibility pre-mask** — ``solver/greedy.py feasibility_mask``
  (the ``score_all`` mask half factored standalone: requests-fit +
  node validity + loadaware freshness/thresholds + term masks) is
  swept over the node axis in power-of-two BLOCKS, so the only dense
  tensor ever materialized is [P, B] for one block — never [P, N].
* **candidate gather** — each pod keeps the C LOWEST-INDEXED feasible
  nodes (C = ``cfg.candidate_width``, a power of two, static in every
  jit signature; pad slots carry the sentinel N).  Lists are
  ascending, which makes index-map-back preserve ``lax.top_k``'s
  lower-index tie-break exactly.
* **sparse scoring** — the existing cellwise ``score_all`` body (fit +
  loadaware + the full term stack) evaluated over the gathered [P, C]
  cells via a vmap of per-pod sub-snapshots; ``sparse_top_k`` maps
  winners back through the index map to real node ids.

Exactness contract (the reason top-C-by-INDEX, not top-C-by-score): a
candidate list is exact only if it contains EVERY feasible node for
its pod.  ``count`` tracks the true per-pod feasible total; whenever
``count > C`` for any pod the engine must raise
:class:`CandidateOverflow` — REFUSE rather than silently serve a
truncated node set (the brownout path's refusal precedent).  Under
that invariant the sparse reply is byte-identical to the dense
engine's: same feasible set, same scores (the cellwise term contract),
same tie-breaks (ascending lists).  A score-ranked top-C could not
offer this: a low-ranked node can enter the true top-k after a delta
without ever being in the list — silent wrongness, the one thing the
engine ladder never does.

Dirty attribution (bridge/state.py ``CandidateResidency``): a dirty
node invalidates only the candidate lists containing it —
``refresh_candidates`` evicts the dirty nodes from every list,
re-evaluates just their feasibility columns, and sort-merges them
back; dirty pod rows rebuild from scratch.  Counts stay exact through
the merge, so overflow detection survives any delta stream.  Dirty
index vectors ride the same power-of-two pad buckets as the
incremental rescore (``_pad_rows``) — the dirty COUNT never crosses a
jit boundary, and neither does C (koordlint retrace-hazard shape 6
rejects traced candidate widths statically).

Pod-axis sharding (parallel/mesh.py ``POD_AXIS``): the [P, C] tensors
split over pod rows, node tables replicate, and the build / refresh /
score kernels run as ``shard_map`` bodies with zero collectives — the
sparse engine's scale axis is pods, the transpose of the dense
residency's ``P(None, "nodes")``.
"""

from __future__ import annotations

import dataclasses
import os
from collections import deque
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from koordinator_tpu.obs import devprof
from koordinator_tpu.solver.greedy import feasibility_mask, score_all
from koordinator_tpu.solver.incremental import (
    _pad_rows,
    _take_nodes,
    _take_pods,
)

# Node-axis sweep width of the blocked feasibility pass.  Powers of two
# only: node buckets are powers of two, so any power-of-two block <= N
# divides the axis exactly and the scan length is static per geometry.
_SWEEP_BLOCK = 1024

# Cold-build routing (ISSUE 20): below this many blocks the serial
# lax.scan build wins (one dispatch, no host round-trips); at or above
# it the pipelined build overlaps device feasibility compute with host
# merges and prunes blocks that cannot reach any pod's C-prefix.
# KOORD_PARALLEL_BUILD=0 forces serial, =1 forces pipelined, anything
# else (the default, "auto") applies this threshold.
_PARALLEL_MIN_BLOCKS = 16

# extract launches kept in flight ahead of the host merge: enough to
# hide one merge behind device compute, small enough that a refused
# build (overflow raises at readback) never queued the whole sweep
_PIPELINE_DEPTH = 4


class CandidateOverflow(RuntimeError):
    """A pod's feasible-node count exceeds the candidate width C.

    The exactness contract requires every feasible node to be IN its
    pod's candidate list; a list that cannot hold them all would serve
    a silently truncated node set, so the engine refuses instead
    (servers map this to a clean RPC error advising a wider
    ``--candidate-width``)."""

    def __init__(self, width: int, max_feasible: int, pods: int):
        self.width = int(width)
        self.max_feasible = int(max_feasible)
        self.pods = int(pods)
        super().__init__(
            f"sparse candidate width {self.width} cannot hold every "
            f"feasible node: {self.pods} pod(s) have up to "
            f"{self.max_feasible} feasible nodes; raise "
            "--candidate-width (power of two) — the sparse engine "
            "refuses rather than silently degrade to a truncated "
            "candidate set"
        )


def check_candidate_overflow(count, width: int) -> None:
    """Raise :class:`CandidateOverflow` if any pod's exact feasible
    count exceeds ``width``.  ``count`` is the host readback of a
    build/refresh ``count`` vector — callers fold it into the one
    stacked ``device_get`` they already pay."""
    count = np.asarray(count)
    over = count > int(width)
    if bool(np.any(over)):
        raise CandidateOverflow(
            width, int(count.max()), int(np.count_nonzero(over))
        )


def _sweep_block(n: int, c: int) -> int:
    return min(int(n), max(int(c), _SWEEP_BLOCK))


def _merge_lowest(cand: jnp.ndarray, new_idx: jnp.ndarray) -> jnp.ndarray:
    """Keep the C lowest node indices of ``cand ∪ new_idx`` (both carry
    the sentinel N in empty slots; sort pushes sentinels past every
    real index, so the C-prefix is the merged ascending list)."""
    C = cand.shape[1]
    merged = jnp.sort(jnp.concatenate([cand, new_idx], axis=1), axis=1)
    return merged[:, :C]


def _build_carry(snapshot, cfg):
    """Blocked feasibility sweep over the whole node axis ->
    (cand i32[P, C] ascending with sentinel N, count i64[P] exact).
    Never materializes more than [P, B] feasibility bits at once."""
    nodes, pods = snapshot.nodes, snapshot.pods
    n = nodes.allocatable.shape[0]
    p = pods.requests.shape[0]
    c = int(cfg.candidate_width)
    b = _sweep_block(n, c)

    def step(carry, block):
        cand, count = carry
        gidx = block * b + jnp.arange(b, dtype=jnp.int32)
        sub = dataclasses.replace(snapshot, nodes=_take_nodes(nodes, gidx))
        feas = feasibility_mask(sub, cfg)  # [P, B]
        count = count + jnp.sum(feas, axis=-1, dtype=jnp.int64)
        new_idx = jnp.where(feas, gidx[None, :], jnp.int32(n))
        return (_merge_lowest(cand, new_idx), count), None

    init = (
        jnp.full((p, c), n, jnp.int32),
        jnp.zeros((p,), jnp.int64),
    )
    (cand, count), _ = lax.scan(
        step, init, jnp.arange(n // b, dtype=jnp.int32)
    )
    return cand, count


@devprof.boundary("solver.candidates._build")
@partial(jax.jit, static_argnames=("cfg",))
def _build(snapshot, *, cfg):
    return _build_carry(snapshot, cfg)


@devprof.boundary("solver.candidates._build_sharded")
@partial(jax.jit, static_argnames=("cfg", "mesh"))
def _build_sharded(snapshot, *, cfg, mesh):
    from koordinator_tpu.parallel.mesh import (
        POD_AXIS,
        shard_map_compat,
        snapshot_pod_partition_specs,
    )

    return shard_map_compat(
        lambda snap: _build_carry(snap, cfg),
        mesh=mesh,
        in_specs=(snapshot_pod_partition_specs(snapshot),),
        out_specs=(P(POD_AXIS, None), P(POD_AXIS)),
    )(snapshot)


def _count_carry(snapshot, cfg):
    """Counts-only sweep: i64[NB, P] exact per-block feasible counts.
    No merges, no sorts — the cheap pass whose readback drives the
    pipelined build's block pruning."""
    nodes = snapshot.nodes
    n = nodes.allocatable.shape[0]
    b = _sweep_block(n, int(cfg.candidate_width))

    def step(carry, block):
        gidx = block * b + jnp.arange(b, dtype=jnp.int32)
        sub = dataclasses.replace(snapshot, nodes=_take_nodes(nodes, gidx))
        feas = feasibility_mask(sub, cfg)  # [P, B]
        return carry, jnp.sum(feas, axis=-1, dtype=jnp.int64)

    _, counts = lax.scan(
        step, 0, jnp.arange(n // b, dtype=jnp.int32)
    )
    return counts


@devprof.boundary("solver.candidates._count_blocks")
@partial(jax.jit, static_argnames=("cfg",))
def _count_blocks(snapshot, *, cfg):
    return _count_carry(snapshot, cfg)


@devprof.boundary("solver.candidates._count_blocks_sharded")
@partial(jax.jit, static_argnames=("cfg", "mesh"))
def _count_blocks_sharded(snapshot, *, cfg, mesh):
    """Counts pass with the BLOCK axis sharded over a node-axis mesh:
    each device sweeps its local node shard's blocks (feasibility is
    index-free, so local node tables suffice; pod tables replicate
    under ``snapshot_partition_specs``), and the stacked [NB, P]
    output lands in global block order because device d's shard IS
    blocks [d*NB/D, (d+1)*NB/D)."""
    from koordinator_tpu.parallel.mesh import (
        shard_map_compat,
        snapshot_partition_specs,
    )
    from koordinator_tpu.parallel.mesh import CLUSTER_AXIS

    return shard_map_compat(
        lambda snap: _count_carry(snap, cfg),
        mesh=mesh,
        in_specs=(snapshot_partition_specs(snapshot),),
        out_specs=P(CLUSTER_AXIS, None),
    )(snapshot)


@devprof.boundary("solver.candidates._extract_block")
@partial(jax.jit, static_argnames=("cfg",))
def _extract_block(snapshot, block, *, cfg):
    """One node block's candidate columns: i32[P, B] global node
    indices where feasible, sentinel N elsewhere.  ``block`` is
    TRACED (an i32 scalar), so ONE compiled program serves every
    block of a geometry and the pipelined build never retraces."""
    nodes = snapshot.nodes
    n = nodes.allocatable.shape[0]
    b = _sweep_block(n, int(cfg.candidate_width))
    gidx = block * b + jnp.arange(b, dtype=jnp.int32)
    sub = dataclasses.replace(snapshot, nodes=_take_nodes(nodes, gidx))
    feas = feasibility_mask(sub, cfg)  # [P, B]
    return jnp.where(feas, gidx[None, :], jnp.int32(n))


def _merge_lowest_host(cand: np.ndarray, new_idx: np.ndarray) -> np.ndarray:
    """Host-side twin of :func:`_merge_lowest`: exact integer sort, so
    the merged C-prefix is bit-identical to the device merge."""
    c = cand.shape[1]
    merged = np.sort(np.concatenate([cand, new_idx], axis=1), axis=1)
    return merged[:, :c]


def _build_pipelined(snapshot, cfg, node_mesh=None):
    """Pipelined cold build (ISSUE 20): byte-identical to
    :func:`_build`, ≥2x faster at large N.  Three legs:

    1. **counts pass** — one jitted sweep (block-axis sharded over
       ``node_mesh`` when one is configured) yields exact per-block
       feasible counts, no merge work;
    2. **block pruning** — block j can reach pod p's C-prefix only if
       it holds a feasible node for p AND fewer than C feasible nodes
       precede it (every preceding index is smaller, so a C-full
       prefix is final).  Any valid (non-overflowing) geometry has
       count <= C per pod, which makes every feasible-holding block
       needed for ITS pods but lets the sweep skip the (typically
       vast) feasibility deserts a 2^21-node axis is mostly made of;
    3. **pipelined extraction** — per-block feasibility launches (one
       traced-block program, no retraces) dispatched
       ``_PIPELINE_DEPTH`` ahead while the host sort-merges the
       previous block's readback: device compute for block i+1
       overlaps the merge of block i.

    Parity argument: the final lists are the C lowest feasible node
    indices per pod; pruned blocks provably cannot contribute to any
    C-prefix, int64 count sums are exact in any order, and the host
    integer sort is bit-identical to the device sort."""
    nodes, pods = snapshot.nodes, snapshot.pods
    n = nodes.allocatable.shape[0]
    p = pods.requests.shape[0]
    c = int(cfg.candidate_width)
    b = _sweep_block(n, c)
    if (
        node_mesh is not None and node_mesh.size > 1
        and n % node_mesh.size == 0 and (n // node_mesh.size) % b == 0
    ):
        counts = _count_blocks_sharded(snapshot, cfg=cfg, mesh=node_mesh)
    else:
        counts = _count_blocks(snapshot, cfg=cfg)
    counts_np = np.asarray(counts)  # [NB, P]
    count = counts_np.sum(axis=0, dtype=np.int64)  # exact totals
    before = np.cumsum(counts_np, axis=0, dtype=np.int64) - counts_np
    needed = np.nonzero(
        np.any((counts_np > 0) & (before < c), axis=1)
    )[0]
    cand = np.full((p, c), n, np.int32)
    inflight: deque = deque()
    for j in needed:
        inflight.append(_extract_block(snapshot, jnp.int32(j), cfg=cfg))
        if len(inflight) >= _PIPELINE_DEPTH:
            cand = _merge_lowest_host(
                cand, np.asarray(inflight.popleft())
            )
    while inflight:
        cand = _merge_lowest_host(cand, np.asarray(inflight.popleft()))
    return jnp.asarray(cand), jnp.asarray(count)


def _parallel_build_mode() -> str:
    return os.environ.get("KOORD_PARALLEL_BUILD", "auto")


def _refresh_carry(snapshot, cand, count, node_idx, pod_idx, cfg):
    """One exact merge-refresh:

    * dirty NODE columns — evict the dirty nodes from every list (a
      dirty node invalidates only the lists containing it), subtract
      them from the counts, re-evaluate just their feasibility
      ([P, dB] — dB is the padded dirty bucket, never N), and
      sort-merge the still-feasible ones back;
    * dirty POD rows — rebuilt from scratch by the blocked sweep over
      a gathered sub-snapshot, scattered with ``mode="drop"`` exactly
      like the incremental rescore's row pass.

    Precondition: the residency being advanced was non-overflowed
    (count <= C everywhere), so every previously-feasible dirty node
    IS in its lists and the count arithmetic stays exact — which is
    what keeps overflow detection truthful across any delta stream.
    Pad slots in ``node_idx``/``pod_idx`` carry the out-of-range
    sentinels ``_pad_rows`` wrote; they gather a clipped row whose
    result is masked or dropped."""
    nodes, pods = snapshot.nodes, snapshot.pods
    n = nodes.allocatable.shape[0]
    p = pods.requests.shape[0]
    member = jnp.any(
        cand[:, :, None] == node_idx[None, None, :], axis=-1
    ) & (cand < n)
    count = count - jnp.sum(member, axis=-1, dtype=jnp.int64)
    cand = jnp.where(member, jnp.int32(n), cand)
    sub = dataclasses.replace(
        snapshot, nodes=_take_nodes(nodes, jnp.clip(node_idx, 0, n - 1))
    )
    feas_d = feasibility_mask(sub, cfg) & (node_idx < n)[None, :]
    count = count + jnp.sum(feas_d, axis=-1, dtype=jnp.int64)
    new_idx = jnp.where(
        feas_d, node_idx[None, :].astype(jnp.int32), jnp.int32(n)
    )
    cand = _merge_lowest(cand, new_idx)
    # dirty pod rows: full per-row rebuild (the row's old list says
    # nothing about its new requests), scatter-dropped at pad slots
    sub_pods = _take_pods(pods, jnp.clip(pod_idx, 0, p - 1))
    row_cand, row_count = _build_carry(
        dataclasses.replace(snapshot, pods=sub_pods), cfg
    )
    cand = cand.at[pod_idx, :].set(row_cand, mode="drop")
    count = count.at[pod_idx].set(row_count, mode="drop")
    return cand, count


@devprof.boundary("solver.candidates._refresh")
@partial(jax.jit, static_argnames=("cfg",))
def _refresh(snapshot, cand, count, node_idx, pod_idx, *, cfg):
    return _refresh_carry(snapshot, cand, count, node_idx, pod_idx, cfg)


@devprof.boundary("solver.candidates._refresh_sharded")
@partial(jax.jit, static_argnames=("cfg", "mesh"))
def _refresh_sharded(snapshot, cand, count, node_idx, pod_idx, *, cfg, mesh):
    from koordinator_tpu.parallel.mesh import (
        POD_AXIS,
        shard_map_compat,
        snapshot_pod_partition_specs,
    )

    cspec = P(POD_AXIS, None)

    def body(snap_l, cand_l, count_l, nidx, pidx):
        # node indices replicate (node tables are whole on every
        # device); pod indices rebase against the local shard like the
        # sharded rescore's dirty columns — foreign/pad rows rebase out
        # of range and drop
        p_local = snap_l.pods.requests.shape[0]
        start = lax.axis_index(POD_AXIS).astype(pidx.dtype) * p_local
        loc = pidx - start
        loc = jnp.where((loc >= 0) & (loc < p_local), loc, p_local)
        return _refresh_carry(snap_l, cand_l, count_l, nidx, loc, cfg)

    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(
            snapshot_pod_partition_specs(snapshot),
            cspec, P(POD_AXIS), P(), P(),
        ),
        out_specs=(cspec, P(POD_AXIS)),
    )(snapshot, cand, count, node_idx, pod_idx)


def _score_carry(snapshot, cand, cfg):
    """Score the gathered [P, C] cells through the UNCHANGED cellwise
    ``score_all`` body (fit + loadaware + the full term stack): vmap
    over pods of a [1]-pod x [C]-node sub-snapshot — bit-identical to
    the dense cells by the cellwise term contract.  Sentinel slots
    gather a clipped real row; their feasibility is forced off after
    (never rely on the clip: node n-1's row would alias into pads)."""
    nodes, pods = snapshot.nodes, snapshot.pods
    n = nodes.allocatable.shape[0]
    p = pods.requests.shape[0]

    def row(pi, cidx):
        sub = dataclasses.replace(
            snapshot,
            nodes=_take_nodes(nodes, jnp.clip(cidx, 0, n - 1)),
            pods=_take_pods(pods, pi[None]),
        )
        s, f = score_all(sub, cfg)
        return s[0], f[0]

    scores, feas = jax.vmap(row)(jnp.arange(p), cand)
    return scores, feas & (cand < n)


@devprof.boundary("solver.candidates._score")
@partial(jax.jit, static_argnames=("cfg",))
def _score(snapshot, cand, *, cfg):
    return _score_carry(snapshot, cand, cfg)


@devprof.boundary("solver.candidates._score_sharded")
@partial(jax.jit, static_argnames=("cfg", "mesh"))
def _score_sharded(snapshot, cand, *, cfg, mesh):
    from koordinator_tpu.parallel.mesh import (
        POD_AXIS,
        shard_map_compat,
        snapshot_pod_partition_specs,
    )

    cspec = P(POD_AXIS, None)
    return shard_map_compat(
        lambda snap, cd: _score_carry(snap, cd, cfg),
        mesh=mesh,
        in_specs=(snapshot_pod_partition_specs(snapshot), cspec),
        out_specs=(cspec, cspec),
    )(snapshot, cand)


def _check_sparse_cfg(cfg) -> None:
    if int(cfg.candidate_width) <= 0:
        raise ValueError(
            "sparse candidate scoring needs cfg.candidate_width > 0 "
            f"(got {cfg.candidate_width!r})"
        )


def _check_pod_mesh(snapshot, mesh) -> None:
    p = snapshot.pods.requests.shape[0]
    if p % mesh.size:
        raise ValueError(
            f"pod bucket {p} does not divide over {mesh.size} devices; "
            "resize the pod mesh to a power-of-two prefix"
        )


def build_candidates(snapshot, cfg, mesh=None, node_mesh=None):
    """Cold build: (cand i32[P, C] ascending index lists with sentinel
    N in pad slots, count i64[P] exact feasible totals).  ``mesh``: a
    1-D pod mesh (parallel/mesh.py ``pod_mesh``) runs the sweep
    pod-parallel with zero collectives.  Without a pod mesh, large
    geometries route through the pipelined build (ISSUE 20, see
    :func:`_build_pipelined` — byte-identical, host merge overlapped
    with device compute, counts pass block-sharded over ``node_mesh``
    when one is configured); ``KOORD_PARALLEL_BUILD`` (0/1/auto)
    overrides the routing.  Callers must
    :func:`check_candidate_overflow` the count readback before serving
    from the lists."""
    _check_sparse_cfg(cfg)
    if mesh is not None and mesh.size > 1:
        _check_pod_mesh(snapshot, mesh)
        return _build_sharded(snapshot, cfg=cfg, mesh=mesh)
    n = snapshot.nodes.allocatable.shape[0]
    blocks = n // _sweep_block(n, int(cfg.candidate_width))
    mode = _parallel_build_mode()
    pipelined = (
        blocks >= _PARALLEL_MIN_BLOCKS if mode not in ("0", "1")
        else mode == "1"
    )
    if pipelined:
        return _build_pipelined(snapshot, cfg, node_mesh=node_mesh)
    return _build(snapshot, cfg=cfg)


def refresh_candidates(snapshot, cand, count, node_rows, pod_rows,
                       cfg, mesh=None):
    """Advance (cand, count) past a warm delta: ``node_rows`` /
    ``pod_rows`` are the unpadded unique dirty row sets the commits
    accumulated (bridge/state.py ``CandidateResidency``).  Exact under
    the non-overflow precondition (:func:`_refresh_carry`); dirty
    buckets ride the incremental rescore's power-of-two pads, so a
    steady warm stream holds zero jit cache misses."""
    _check_sparse_cfg(cfg)
    n = snapshot.nodes.allocatable.shape[0]
    p = snapshot.pods.requests.shape[0]
    node_idx = jnp.asarray(_pad_rows(node_rows, n))
    pod_idx = jnp.asarray(_pad_rows(pod_rows, p))
    if mesh is not None and mesh.size > 1:
        _check_pod_mesh(snapshot, mesh)
        return _refresh_sharded(
            snapshot, cand, count, node_idx, pod_idx, cfg=cfg, mesh=mesh
        )
    return _refresh(snapshot, cand, count, node_idx, pod_idx, cfg=cfg)


def score_candidates(snapshot, cand, cfg, mesh=None):
    """(scores i64[P, C], feasible bool[P, C]) of the gathered cells —
    the sparse engine's whole scoring cost, O(P x C) instead of
    O(P x N).  Feasible bits at real slots equal the dense engine's at
    (p, cand[p, c]); sentinel slots are infeasible."""
    _check_sparse_cfg(cfg)
    if mesh is not None and mesh.size > 1:
        _check_pod_mesh(snapshot, mesh)
        return _score_sharded(snapshot, cand, cfg=cfg, mesh=mesh)
    return _score(snapshot, cand, cfg=cfg)


@devprof.boundary("solver.candidates.sparse_top_k")
@partial(jax.jit, static_argnames=("k", "hi"))
def sparse_top_k(scores, feasible, cand, *, k, hi):
    """Serving top-k over the [P, C] cells, mapped back to real node
    ids: (top_scores i64[P, k], top_node i32[P, k], ok bool[P, k]).

    ``masked_top_k`` runs unchanged on the trailing candidate axis
    (same f32 fast path, same ``hi`` bound); winners map through the
    index lists via ``take_along_axis``.  Because lists are ASCENDING
    by node index, ``lax.top_k``'s lower-slot tie-break IS the dense
    engine's lower-node-index tie-break after the map.  ``ok`` is the
    per-winner feasibility the reply assembly gates on (the dense path
    derives it by gathering the [P, N] feasible tensor — which the
    sparse engine never owns); non-ok slots report node 0, which the
    gate keeps out of every reply byte."""
    from koordinator_tpu.solver.topk import masked_top_k

    ts, tc = masked_top_k(scores, feasible, k=k, hi=hi)
    ok = jnp.take_along_axis(feasible, tc, axis=-1)
    ti = jnp.take_along_axis(cand, tc, axis=-1).astype(jnp.int32)
    return ts, jnp.where(ok, ti, jnp.int32(0)), ok


def candidate_membership_mask(cand, num_nodes: int) -> jnp.ndarray:
    """bool[P, N] membership mask of the candidate lists — the
    assign-side bridge (parallel/shard_assign.py ``candidates=``): the
    wave engines AND it into their ``extra_mask`` seam, so gang/quota
    resolution (the replicated wave top-M merge) sees only candidate
    cells.  Sentinel slots are out of range and drop; this tensor is
    dense [P, N] by design — the wave assign already materializes
    per-wave [W, N] blocks, and the mask exists to CONSTRAIN that
    engine, not to replace it."""
    p = cand.shape[0]
    mask = jnp.zeros((p, int(num_nodes)), bool)
    return mask.at[jnp.arange(p)[:, None], cand].set(True, mode="drop")
