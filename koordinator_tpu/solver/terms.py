"""Fused scoring-term registry (ISSUE 15).

The scorer reproduced only NodeResourcesFit/LoadAware/NUMA; PAPERS.md
names the workloads that make a batched TPU scorer worth having —
Gavel-style heterogeneity policies (2008.09213, per-(job class,
accelerator type) throughput matrices), Synergy-style CPU/mem
sensitivity profiles (2110.06073) and constraint-based bin packing
(2511.08373).  The repo's perf claim is "one dense pods x nodes launch,
no per-plugin loops", so new policies land as **fused tensor terms**
inside the existing ``score_all`` body — zero extra launches, zero
extra readbacks — never as sequential per-plugin passes the way the Go
reference runs its plugin chain (``bench.py --config plugins`` measures
the fused engine against exactly that per-term-sequential oracle).

The term contract (docs/KERNEL.md "Scoring terms"):

* **cellwise** — a term's score/mask contribution at cell (p, n) reads
  only pod row p, node row n, and replicated side tables (the
  throughput matrix).  This is the invariant that keeps the incremental
  engine exact: ``rescore_dirty``'s gather-compute-scatter re-derives
  the very same bits a full rescore would put in the dirty cells.
* **dirty-attributable** — every tensor a term reads must map a delta
  Sync onto score rows/columns (bridge/state.py ``_score_dirty_rows``:
  sensitivity deltas dirty pod rows, a throughput-matrix delta dirties
  the nodes of the touched accelerator type, accel/workload column
  flips diff per row).
* **statically bounded** — each term's contribution clamps to
  ``[0, weight * MAX_NODE_SCORE]`` on device, so
  :func:`terms_upper_bound` is a CONFIG property and the f32-exact
  serving top-k fast path (solver/topk.py) keeps running with terms on;
  a data tensor violating the clamp cannot mis-order the reply (the
  runtime in-bound cond takes the integer path).

The registry generalizes the ``extra_mask``/``extra_scores`` seam
(solver/greedy.py:240): ``apply_terms`` fuses contributions into
``score_all``'s one tensor program, and ``term_extras`` materializes
the same cellwise tensors once per Assign cycle so the sequential
engines — the scan, ``solver/wave.py`` and the Pallas kernels — consume
the fused total through the seam they already have.  Missing snapshot
data (a term enabled before its tensors synced) contributes nothing:
enabling a term must never fault a cycle, only inform it.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from koordinator_tpu.model.snapshot import MAX_NODE_SCORE
from koordinator_tpu.obs import devprof
from koordinator_tpu.ops.scoring import (
    most_requested_score,
    weighted_resource_score,
)


@dataclasses.dataclass(frozen=True)
class TermSpec:
    """One registered scoring term.

    ``enabled(cfg)``    — whether the CycleConfig turns the term on.
    ``score(snap, cfg)``— cellwise i64[P, N] score contribution, or
                          None (no data synced yet; the term is inert).
    ``mask(snap, cfg)`` — cellwise bool[P, N] feasibility mask, or None.
    ``upper_bound(cfg)``— static max of the score contribution; summed
                          into solver/topk.py ``score_upper_bound``.
    ``has_mask(cfg)``   — pure config predicate: whether ``mask`` would
                          return a tensor (so callers can size the jit
                          signature without tracing).
    """

    name: str
    enabled: Callable
    score: Callable
    mask: Callable
    upper_bound: Callable
    has_mask: Callable = staticmethod(lambda cfg: False)


def _clip_term(raw: jnp.ndarray, weight: int) -> jnp.ndarray:
    """The per-term clamp that makes the bound a config property."""
    return int(weight) * jnp.clip(
        raw.astype(jnp.int64), 0, MAX_NODE_SCORE
    )


# ---------------------------------------------------------------------------
# heterogeneity — Gavel-style throughput matrix (2008.09213)
# ---------------------------------------------------------------------------


def _het_score(snapshot, cfg):
    tput = getattr(snapshot, "throughput", None)
    if tput is None:
        return None
    wclass = snapshot.pods.workload_class
    accel = snapshot.nodes.accel_type
    C, A = tput.shape
    c = (
        jnp.clip(wclass.astype(jnp.int64), 0, C - 1)
        if wclass is not None
        else jnp.zeros(snapshot.pods.requests.shape[0], jnp.int64)
    )
    a = (
        jnp.clip(accel.astype(jnp.int64), 0, A - 1)
        if accel is not None
        else jnp.zeros(snapshot.nodes.allocatable.shape[0], jnp.int64)
    )
    raw = tput[c[:, None], a[None, :]]  # [P, N] gather
    return _clip_term(raw, cfg.heterogeneity.weight)


# ---------------------------------------------------------------------------
# sensitivity — Synergy-style CPU/mem profiles (2110.06073)
# ---------------------------------------------------------------------------


def _sens_score(snapshot, cfg):
    sens = snapshot.pods.sensitivity
    if sens is None:
        return None
    nodes = snapshot.nodes
    alloc = nodes.allocatable.astype(jnp.int64)
    usage = nodes.usage.astype(jnp.int64)
    safe_cap = jnp.where(alloc == 0, 1, alloc)
    # occupancy percent per (node, resource), clamped: a node reporting
    # usage past allocatable saturates at 100, an unallocatable resource
    # reads as empty (nothing to contend on)
    occ = jnp.clip(usage * MAX_NODE_SCORE // safe_cap, 0, MAX_NODE_SCORE)
    occ = jnp.where(alloc == 0, 0, occ)
    s = jnp.clip(sens.astype(jnp.int64), 0, MAX_NODE_SCORE)  # [P, R]
    s_sum = jnp.sum(s, axis=-1)  # [P]
    contention = (
        jnp.einsum("pr,nr->pn", s, occ) // jnp.maximum(s_sum, 1)[:, None]
    )
    # a pod with an all-zero profile is insensitive: contention 0, full
    # score — exactly the no-profile pod's treatment
    raw = MAX_NODE_SCORE - contention
    return _clip_term(raw, cfg.sensitivity.weight)


# ---------------------------------------------------------------------------
# packing — bin-packing objective + headroom mask (2511.08373)
# ---------------------------------------------------------------------------


def _pack_score(snapshot, cfg):
    nodes, pods = snapshot.nodes, snapshot.pods
    t = nodes.requested[None, :, :] + pods.requests[:, None, :]
    per_res = most_requested_score(t, nodes.allocatable[None, :, :])
    raw = weighted_resource_score(per_res, cfg.packing.weights_arr())
    return _clip_term(raw, cfg.packing.weight)


def _pack_masks(cfg) -> bool:
    """Whether the packing term contributes a mask — a pure CONFIG
    predicate (headroom is a frozen tuple), so callers can ask without
    tracing anything."""
    return any(int(v) > 0 for _, v in cfg.packing.headroom)


def _pack_mask(snapshot, cfg):
    if not _pack_masks(cfg):
        return None
    head = cfg.packing.headroom_arr()  # i64[R]; 0 = unconstrained
    nodes, pods = snapshot.nodes, snapshot.pods
    alloc = nodes.allocatable.astype(jnp.int64)
    post = (
        nodes.requested.astype(jnp.int64)[None, :, :]
        + pods.requests.astype(jnp.int64)[:, None, :]
    )
    limited = head[None, None, :] > 0
    ok = post * 100 <= head[None, None, :] * alloc[None, :, :]
    return jnp.all(jnp.where(limited, ok, True), axis=-1)


def _weight_bound(weight) -> int:
    return MAX_NODE_SCORE * int(weight)


TERMS: Tuple[TermSpec, ...] = (
    TermSpec(
        name="heterogeneity",
        enabled=lambda cfg: cfg.heterogeneity is not None,
        score=_het_score,
        mask=lambda snapshot, cfg: None,
        upper_bound=lambda cfg: _weight_bound(cfg.heterogeneity.weight),
    ),
    TermSpec(
        name="sensitivity",
        enabled=lambda cfg: cfg.sensitivity is not None,
        score=_sens_score,
        mask=lambda snapshot, cfg: None,
        upper_bound=lambda cfg: _weight_bound(cfg.sensitivity.weight),
    ),
    TermSpec(
        name="packing",
        enabled=lambda cfg: cfg.packing is not None,
        score=_pack_score,
        mask=_pack_mask,
        upper_bound=lambda cfg: _weight_bound(cfg.packing.weight),
        has_mask=_pack_masks,
    ),
)


def enabled_terms(cfg) -> Tuple[TermSpec, ...]:
    return tuple(t for t in TERMS if t.enabled(cfg))


def terms_upper_bound(cfg) -> int:
    """Static upper bound of the summed enabled-term contributions —
    the term-aware half of solver/topk.py ``score_upper_bound``."""
    return sum(t.upper_bound(cfg) for t in enabled_terms(cfg))


def apply_term_scores(snapshot, cfg, scores):
    """The SCORE half of the term stack: fold every enabled term's
    cellwise score contribution into ``scores``.  Factored out of
    :func:`apply_terms` (ISSUE 16) so the sparse candidate engine can
    run the mask half standalone (feasibility pre-mask) and the score
    half over gathered [P, C] cells; additions commute, so the split
    is bitwise identical to the fused loop."""
    for term in enabled_terms(cfg):
        s = term.score(snapshot, cfg)
        if s is not None:
            scores = scores + s
    return scores


def apply_term_masks(snapshot, cfg, feasible):
    """The MASK half of the term stack: AND every enabled term's
    cellwise feasibility mask into ``feasible`` — the term piece of the
    standalone feasibility pre-mask (solver/greedy.py
    ``feasibility_mask``, ISSUE 16).  ANDs commute, so running this
    apart from the score half changes no bits."""
    for term in enabled_terms(cfg):
        m = term.mask(snapshot, cfg)
        if m is not None:
            feasible = feasible & m
    return feasible


def apply_terms(snapshot, cfg, scores, feasible):
    """Fuse every enabled term's cellwise contribution into the
    (scores, feasible) pair INSIDE the one tensor program — called from
    ``score_all`` (solver/greedy.py), so score_cycle, the incremental
    column/row rescore and the sharded rescore all carry the terms with
    zero extra launches.  Shape-polymorphic over gathered sub-snapshots
    (the incremental engine scores [P, d] and [d_p, N] blocks through
    the same body).  Composed from the score/mask halves: the halves
    commute (adds with adds, ANDs with ANDs), so the sparse engine's
    standalone mask pass stays bit-identical to this fused loop."""
    return (
        apply_term_scores(snapshot, cfg, scores),
        apply_term_masks(snapshot, cfg, feasible),
    )


@devprof.boundary("solver.terms._term_extras_jit")
@partial(jax.jit, static_argnames=("cfg",))
def _term_extras_jit(snapshot, cfg):
    P = snapshot.pods.requests.shape[0]
    N = snapshot.nodes.allocatable.shape[0]
    scores = jnp.zeros((P, N), jnp.int64)
    feasible = jnp.ones((P, N), bool)
    return apply_terms(snapshot, cfg, scores, feasible)


def term_extras(snapshot, cfg):
    """(extra_scores, extra_mask) [P, N] tensors of the enabled terms —
    the fused total the sequential Assign engines consume through the
    existing ``extra_mask``/``extra_scores`` seam (greedy scan,
    solver/wave.py, the Pallas kernels).  Returns (None, None) with no
    terms enabled, so untermed configs pay nothing; otherwise ONE jit
    launch (async, no readback) whose cache keys only on (geometry,
    cfg).  The mask half is None when no enabled term masks (an
    all-True mask would widen the jit signature for nothing)."""
    terms = enabled_terms(cfg)
    if not terms:
        return None, None
    scores, feasible = _term_extras_jit(snapshot, cfg)
    has_mask = any(t.has_mask(cfg) for t in terms)
    return scores, (feasible if has_mask else None)


def term_names(cfg) -> Tuple[str, ...]:
    """Enabled term names (telemetry: koord_scorer_term_total{term})."""
    return tuple(t.name for t in enabled_terms(cfg))


def default_term_config(base=None, packing_headroom=None):
    """A CycleConfig with all three registry terms enabled — the shape
    the trace harness, the bench ``--config plugins`` child and the
    parity fuzz all drive.  ``base`` seeds every non-term field;
    ``packing_headroom`` (resource -> max utilization percent) turns
    the packing MASK on as well as its score."""
    import dataclasses as _dc

    from koordinator_tpu.config import (
        CycleConfig,
        HeterogeneityTermArgs,
        PackingTermArgs,
        SensitivityTermArgs,
    )

    base = base if base is not None else CycleConfig()
    return _dc.replace(
        base,
        heterogeneity=HeterogeneityTermArgs(),
        sensitivity=SensitivityTermArgs(),
        packing=PackingTermArgs(
            headroom=packing_headroom if packing_headroom else ()
        ),
    )
