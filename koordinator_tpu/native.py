"""ctypes bindings for the native runtime shims (native/koordnative.cpp).

The reference's native boundaries are cgo: libpfm4 perf groups
(reference ``pkg/koordlet/util/perf_group/perf_group_linux.go``) and NVML.
Here one C++ shared library carries the perf CPI group, a batched
small-file reader for the collectors, and the snapshot delta encoder; this
module builds it on demand (``make -C native``) and degrades gracefully —
every caller treats ``available() == False`` as "feature off", the same
way the reference gates perf collection behind a feature gate.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libkoordnative.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.koord_perf_open_cpi_group.restype = ctypes.c_int
        lib.koord_perf_open_cpi_group.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.koord_perf_read_cpi.restype = ctypes.c_int
        lib.koord_perf_read_cpi.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.koord_perf_close.argtypes = [ctypes.c_int]
        lib.koord_perf_open_single.restype = ctypes.c_int
        lib.koord_perf_open_single.argtypes = [
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_uint,
            ctypes.c_ulonglong,
            ctypes.c_int,
        ]
        lib.koord_perf_read_single.restype = ctypes.c_int
        lib.koord_perf_read_single.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.koord_read_files.restype = ctypes.c_int
        lib.koord_read_files.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int,
        ]
        lib.koord_delta_encode_i64.restype = ctypes.c_longlong
        lib.koord_delta_encode_i64.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_longlong,
        ]
        lib.koord_delta_apply_i64.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_longlong,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# perf CPI group
# ---------------------------------------------------------------------------


class PerfCPIGroup:
    """Grouped cycles+instructions counters (perf_group_linux.go analog).

    ``target`` is a pid, or a cgroup-dir fd when ``is_cgroup=True`` (the
    perf cgroup mode the reference uses per container).
    """

    def __init__(self, target: int, *, cpu: int = -1, is_cgroup: bool = False):
        lib = _load()
        if lib is None:
            raise OSError("native library unavailable")
        fd = lib.koord_perf_open_cpi_group(target, cpu, 1 if is_cgroup else 0)
        if fd < 0:
            raise OSError(-fd, os.strerror(-fd))
        self._fd = fd
        self._lib = lib

    def read(self) -> Tuple[int, int]:
        out = (ctypes.c_uint64 * 2)()
        rc = self._lib.koord_perf_read_cpi(self._fd, out)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return int(out[0]), int(out[1])

    def close(self) -> None:
        if self._fd >= 0:
            self._lib.koord_perf_close(self._fd)
            self._fd = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# raw perf_event_attr constants for PerfSingleReader (linux/perf_event.h)
PERF_TYPE_HARDWARE = 0
PERF_TYPE_SOFTWARE = 1
PERF_COUNT_HW_CPU_CYCLES = 0
PERF_COUNT_HW_INSTRUCTIONS = 1
PERF_COUNT_HW_CACHE_MISSES = 3
PERF_COUNT_SW_CPU_CLOCK = 0
PERF_COUNT_SW_TASK_CLOCK = 1
PERF_COUNT_SW_PAGE_FAULTS = 2
PERF_COUNT_SW_CONTEXT_SWITCHES = 3


class PerfSingleReader:
    """Non-grouped single-event perf reader (the reference's
    ``pkg/koordlet/util/perf/`` hodgesds/perf-utils path; the grouped CPI
    reader above covers ``perf_group``).  ``target`` is a pid, or a cgroup
    dir fd with ``is_cgroup=True``."""

    def __init__(
        self,
        target: int,
        event_type: int = PERF_TYPE_SOFTWARE,
        config: int = PERF_COUNT_SW_TASK_CLOCK,
        cpu: int = -1,
        is_cgroup: bool = False,
    ):
        lib = _load()
        if lib is None:
            raise OSError("native library unavailable")
        fd = lib.koord_perf_open_single(
            target, cpu, event_type, config, 1 if is_cgroup else 0
        )
        if fd < 0:
            raise OSError(-fd, os.strerror(-fd))
        self._fd = fd
        self._lib = lib

    def read(self) -> int:
        out = ctypes.c_uint64()
        rc = self._lib.koord_perf_read_single(
            self._fd, ctypes.byref(out)
        )
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return int(out.value)

    def close(self) -> None:
        if self._fd >= 0:
            self._lib.koord_perf_close(self._fd)
            self._fd = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_self_cpi() -> Optional[Tuple[int, int]]:
    """(cycles, instructions) for the current process, or None when perf
    is unavailable (kernel.perf_event_paranoid, containers, non-Linux)."""
    try:
        with PerfCPIGroup(0) as g:
            return g.read()
    except OSError:
        return None


# ---------------------------------------------------------------------------
# batched file reader
# ---------------------------------------------------------------------------


def read_files(paths: Sequence[str], *, max_per_file: int = 4096) -> List[Optional[str]]:
    """Read many small files in one native call; None per failed file.
    Pure-Python fallback when the library is absent."""
    lib = _load()
    if lib is None:
        out: List[Optional[str]] = []
        for p in paths:
            try:
                with open(p) as f:
                    out.append(f.read(max_per_file - 1))
            except OSError:
                out.append(None)
        return out
    blob = b"\0".join(p.encode() for p in paths) + b"\0"
    n = len(paths)
    buf = ctypes.create_string_buffer(n * max_per_file)
    sizes = (ctypes.c_longlong * n)()
    lib.koord_read_files(blob, len(blob), n, buf, sizes, max_per_file)
    out = []
    for i in range(n):
        if sizes[i] < 0:
            out.append(None)
        else:
            start = i * max_per_file
            out.append(buf.raw[start : start + sizes[i]].decode(errors="replace"))
    return out


# ---------------------------------------------------------------------------
# snapshot delta codec
# ---------------------------------------------------------------------------


def delta_encode(prev: np.ndarray, next_: np.ndarray, *, max_changes: Optional[int] = None):
    """(indices i64[m], values i64[m]) of changed elements, or None when the
    delta exceeds ``max_changes`` (fall back to full transfer).  Numpy
    fallback without the library."""
    prev = np.ascontiguousarray(prev.reshape(-1), dtype=np.int64)
    next_ = np.ascontiguousarray(next_.reshape(-1), dtype=np.int64)
    assert prev.shape == next_.shape
    cap = max_changes if max_changes is not None else prev.size
    lib = _load()
    if lib is None:
        idx = np.flatnonzero(prev != next_)
        if len(idx) > cap:
            return None
        return idx.astype(np.int64), next_[idx]
    idx = np.empty(cap, dtype=np.int64)
    val = np.empty(cap, dtype=np.int64)
    m = lib.koord_delta_encode_i64(
        prev.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        next_.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        prev.size,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        val.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cap,
    )
    if m < 0:
        return None
    return idx[:m].copy(), val[:m].copy()


def delta_apply(base: np.ndarray, idx: np.ndarray, val: np.ndarray) -> None:
    """In-place base[idx] = val (flat indexing)."""
    flat = base.reshape(-1)
    lib = _load()
    if lib is None:
        flat[idx] = val
        return
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    val = np.ascontiguousarray(val, dtype=np.int64)
    lib.koord_delta_apply_i64(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        val.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx),
    )
