"""Replication stream frame codec (ISSUE 8).

The leader daemon streams every committed Sync to its followers as the
**already-encoded** ``SyncRequest`` wire bytes (the delta economics of
``go/scorerclient/delta.go`` ride along for free: a warm frame is the
same few-hundred-byte sparse delta the client shipped).  This module
owns the frame layout — the one place the header fields, their emit
order and their widths are stated in Python; ``bridge/wirecheck.py``
carries an independent second implementation and
``go/scorerclient/replica.go`` the Go-side mirror, and koordlint's
``wire-contract`` rule diffs all three statically so a one-sided edit
fails lint, not a follower (the scorer.proto treatment, extended to
this stream).

Frame (all integers big-endian, matching the raw-UDS scorer framing)::

    magic        u32   0x4B52504C ("KRPL")
    version      u8    1
    kind         u8    1 = delta (payload applies onto gen-1),
                       2 = full  (payload replaces all resident state),
                       3 = hello (follower->leader resume offer: the
                           follower's chain position; the payload is a
                           capability string — empty for legacy
                           subscribers, ``z`` = "I accept zlib full
                           frames"),
                       4 = full_z (a kind=full frame whose payload is
                           level-1 zlib; only ever sent to a
                           subscriber that advertised ``z`` in its
                           hello — the wire stays byte-compatible with
                           pre-compression peers)
    epoch        8s    the leader's per-boot epoch (8 hex chars — the
                       <epoch> of "s<epoch>-<gen>" snapshot ids)
    generation   u64   generation AFTER applying the payload
    stamp_us     u64   leader commit wall clock, microseconds since the
                       unix epoch (feeds koord_scorer_replica_lag_ms)
    payload_len  u32   length of the SyncRequest bytes that follow
                       (0 is legal for a kind=full frame: "reset to the
                       empty pre-first-Sync state at this generation")

The ``s<epoch>-<gen>`` snapshot id doubles as the fencing token: a
follower applies a delta frame ONLY when it extends the exact chain it
is on (same epoch, generation + 1).  Anything else — a gap from a
dropped frame, a duplicate from a reordering transport, a fresh epoch
from a leader restart — is a detected discontinuity, and the follower's
documented response is the one-shot full resync (reconnect; the leader
opens every subscription with a kind=full frame).  A follower never
serves a torn snapshot: frames stage-then-commit through the same
atomic ``bridge/state.py`` seam client Syncs use.
"""

from __future__ import annotations

import dataclasses
import struct

# Header constants.  replica.go (Go) and wirecheck.py (independent
# Python mirror) restate these; koordlint wire-contract diffs them.
MAGIC = 0x4B52504C  # "KRPL"
VERSION = 1
KIND_DELTA = 1
KIND_FULL = 2
# subscription resume offer (ISSUE 11): sent FOLLOWER -> LEADER as the
# first frame of a new subscription — epoch/generation carry the
# follower's current chain position, payload is empty.  A leader whose
# journal covers that position answers with just the missing delta
# frames (no full-state resync); any other leader (or no hello at all,
# the pre-journal subscriber) gets the opening kind=full frame.
KIND_HELLO = 3
# compressed full frame (ISSUE 18): the same reset semantics as
# KIND_FULL, payload zlib-compressed at level 1 on the wire ONLY — the
# journal keeps raw KIND_FULL bytes, and a subscriber only ever sees
# kind 4 after offering the CAP_COMPRESS capability in its hello.
# Sparse-scale full resyncs are hundreds of MB of mostly-sentinel int64
# tensors; level-1 zlib trades a few ms of CPU for a ~10x smaller storm.
KIND_FULL_Z = 4

_KINDS = (KIND_DELTA, KIND_FULL, KIND_HELLO, KIND_FULL_Z)

# hello capability bytes (the hello payload is a flat ascii capability
# string; unknown bytes are ignored by both sides, so capabilities are
# forward- and backward-compatible: a legacy leader drains the payload
# unread, a legacy follower sends none)
CAP_COMPRESS = b"z"

# zlib level for KIND_FULL_Z payloads: level 1 is the latency-friendly
# point — the full frame rides the subscription-open path, where encode
# time is paid under the publisher lock
COMPRESS_LEVEL = 1


def compress_payload(payload: bytes) -> bytes:
    """The KIND_FULL_Z wire payload for a raw full-state payload."""
    import zlib

    return zlib.compress(payload, COMPRESS_LEVEL)


def decompress_payload(payload: bytes, max_bytes: int = 0) -> bytes:
    """Inverse of :func:`compress_payload`; raises :class:`FrameError`
    on corrupt input or a decompressed size past ``max_bytes`` (default
    :data:`MAX_PAYLOAD`) — a hostile tiny frame must not balloon into
    an unbounded allocation."""
    import zlib

    cap = max_bytes or MAX_PAYLOAD
    try:
        d = zlib.decompressobj()
        out = d.decompress(payload, cap)
        if d.unconsumed_tail:
            raise FrameError(
                f"compressed full frame inflates past the {cap}-byte cap"
            )
        out += d.flush()
        if len(out) > cap:
            raise FrameError(
                f"compressed full frame inflates past the {cap}-byte cap"
            )
        return out
    except zlib.error as exc:
        raise FrameError(f"corrupt compressed full frame: {exc}") from exc

# the one statement of the header layout: (field, byte width) in emit
# order — the wire-contract rule parses this table by AST and diffs it
# against replica.go's replicaFrameFields and wirecheck.py's
# REPLICA_FRAME_FIELDS, so the three codecs cannot drift apart silently
FRAME_FIELDS = (
    ("magic", 4),
    ("version", 1),
    ("kind", 1),
    ("epoch", 8),
    ("generation", 8),
    ("stamp_us", 8),
    ("payload_len", 4),
)

_HEADER = ">IBB8sQQI"
HEADER_LEN = struct.calcsize(_HEADER)
assert HEADER_LEN == sum(w for _, w in FRAME_FIELDS)

# mirrors the raw-UDS transport's frame cap (bridge/udsserver.py
# _MAX_FRAME): a full 10k x 2k SyncRequest is a few MB; anything past
# 64 MiB is a malformed or hostile frame, not a snapshot
MAX_PAYLOAD = 64 << 20


class FrameError(ValueError):
    """A malformed replication frame (bad magic/version/kind, oversized
    or truncated).  The follower's response is always the same: count
    it, drop the stream, full-resync — never apply a suspect frame."""


@dataclasses.dataclass(frozen=True)
class Frame:
    kind: int
    epoch: str
    generation: int
    stamp_us: int
    payload: bytes

    @property
    def snapshot_id(self) -> str:
        return f"s{self.epoch}-{self.generation}"


def encode_frame(
    kind: int, epoch: str, generation: int, stamp_us: int, payload: bytes
) -> bytes:
    """Serialize one frame.  ``epoch`` must be the 8-char per-boot hex
    nonce every servicer mints (bridge/server.py) — a fixed-width field
    keeps the header seekable without a second length prefix."""
    if kind not in _KINDS:
        raise FrameError(f"unknown frame kind {kind}")
    raw_epoch = epoch.encode("ascii")
    if len(raw_epoch) != 8:
        raise FrameError(
            f"epoch must be exactly 8 ascii chars, got {epoch!r}"
        )
    if generation < 0:
        raise FrameError(f"negative generation {generation}")
    if len(payload) > MAX_PAYLOAD:
        raise FrameError(
            f"payload of {len(payload)} bytes exceeds the "
            f"{MAX_PAYLOAD}-byte frame cap"
        )
    return struct.pack(
        _HEADER, MAGIC, VERSION, kind, raw_epoch,
        generation, stamp_us, len(payload),
    ) + payload


def decode_header(header: bytes):
    """Decode the fixed 34-byte header; returns ``(frame, payload_len)``
    where ``frame`` carries an empty payload the stream reader replaces
    after reading ``payload_len`` more bytes — see :func:`decode_frame`
    for whole-buffer decoding.  Raises :class:`FrameError` on any
    malformed field."""
    if len(header) != HEADER_LEN:
        raise FrameError(
            f"frame header is {len(header)} bytes, want {HEADER_LEN}"
        )
    magic, version, kind, raw_epoch, gen, stamp_us, plen = struct.unpack(
        _HEADER, header
    )
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic:#x} (want {MAGIC:#x})")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if kind not in _KINDS:
        raise FrameError(f"unknown frame kind {kind}")
    if plen > MAX_PAYLOAD:
        raise FrameError(
            f"frame payload of {plen} bytes exceeds the "
            f"{MAX_PAYLOAD}-byte cap"
        )
    try:
        epoch = raw_epoch.decode("ascii")
    except UnicodeDecodeError as exc:
        raise FrameError(f"non-ascii epoch field {raw_epoch!r}") from exc
    return Frame(kind=kind, epoch=epoch, generation=gen,
                 stamp_us=stamp_us, payload=b""), plen


def decode_frame(buf: bytes) -> Frame:
    """Decode one complete frame from ``buf`` (header + payload, exact
    length).  Raises :class:`FrameError` when truncated, oversized or
    malformed — a reordering/lossy transport can hand a follower any
    prefix, and every such prefix must be a detected discontinuity."""
    if len(buf) < HEADER_LEN:
        raise FrameError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_LEN}-byte header"
        )
    frame, plen = decode_header(buf[:HEADER_LEN])
    payload = buf[HEADER_LEN:]
    if len(payload) != plen:
        raise FrameError(
            f"frame payload truncated: header promises {plen} bytes, "
            f"got {len(payload)}"
        )
    return dataclasses.replace(frame, payload=payload)
