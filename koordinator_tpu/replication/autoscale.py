"""SLO-driven elastic replica autoscaling (ISSUE 18).

The relay tree (replication/leader.py ``publish_frame`` + the follower
relay role) makes read capacity CHEAP to add: a new follower splices
into any layer of the tree with one hello handshake, and interior
bandwidth multiplies with tree width instead of burning the root's.
This module is the control loop that decides WHEN: watch the read-side
signals a serving tier already exports — the windowed read-latency p99
(obs/slo.py estimator over the registry's histograms), replication lag,
and admission sheds — and hold a declared read SLO by spawning
followers into the tree under load and draining them back when the
storm passes.

Three pieces, separated so the decision logic is unit-testable with no
sockets, threads or clocks:

* :class:`AutoscalePolicy` — the declarative knobs: the SLO itself
  (``p99_high_ms``), the calm band (``p99_low_ratio``), lag/shed
  breach thresholds, and the anti-flap machinery (consecutive-tick
  hysteresis in both directions plus a post-action cooldown).
* :class:`RegistrySignals` — the production signal source: delta-window
  p99 over any histogram family (cumulative buckets snapshotted per
  tick, quantile-estimated on the difference — the
  :class:`~koordinator_tpu.obs.slo.SloWindow` trick, aggregated over a
  label subset), plus shed and lag deltas off the counters/gauges.
* :class:`ReplicaAutoscaler` — the loop: collect signals, run the
  hysteresis state machine, invoke the ``spawn``/``drain`` callbacks
  (the daemon layer owns HOW a replica starts — a process, a thread, a
  k8s scale-up; the harness hands in fakes), publish the
  ``koord_scorer_autoscale_*`` families, and keep a bounded decision
  log for /healthz and the bench artifact.

The decision rule, stated once: a tick is a BREACH when any watched
signal is over its threshold (p99 above the SLO with enough window
samples to trust it, lag past ``lag_high_ms``, or any shed in the
window); a tick is CALM only when every signal is comfortably inside
(p99 under ``p99_high_ms * p99_low_ratio`` or no read traffic at all,
zero sheds, lag under half the breach bound).  The band between breach
and calm is dead: both streaks reset, nothing moves — that dead band,
the consecutive-tick requirements and the cooldown are three
independent anti-flap stages, and the unit tests drive oscillating
signals through all of them asserting the replica count moves as a
step function, never a sawtooth.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from koordinator_tpu.obs.slo import aggregate_buckets, quantile_from_buckets

logger = logging.getLogger(__name__)

SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The declarative autoscaling contract.

    ``p99_high_ms`` IS the read SLO: the windowed read p99 the tier
    must hold.  ``p99_low_ratio`` defines the calm band's ceiling as a
    fraction of it — scaling down only when comfortably under the SLO
    keeps the up/down thresholds apart (classic hysteresis; equal
    thresholds flap on any noisy signal).  ``up_after``/``down_after``
    are consecutive-tick requirements (down is deliberately slower:
    adding capacity late costs SLO, removing it late costs only a
    replica's keep), and ``cooldown_ticks`` freezes decisions after
    every action so the tier's response has time to land in the
    signals before the next judgement."""

    min_replicas: int = 1
    max_replicas: int = 8
    p99_high_ms: float = 50.0
    p99_low_ratio: float = 0.5
    lag_high_ms: float = 1_000.0
    min_count: int = 20
    up_after: int = 2
    down_after: int = 5
    cooldown_ticks: int = 3

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"replica bounds [{self.min_replicas}, "
                f"{self.max_replicas}] are not a range"
            )
        if not (0.0 < self.p99_low_ratio <= 1.0):
            raise ValueError(
                f"p99_low_ratio {self.p99_low_ratio} must be in (0, 1]"
            )


@dataclasses.dataclass(frozen=True)
class AutoscaleSignals:
    """One tick's view of the tier.  ``read_p99_ms``/``read_count``
    are WINDOW values (since the previous tick), ``shed_delta`` sheds
    in the window, ``lag_ms`` the current replication lag gauge;
    any signal may be None/0 when its source has nothing to say."""

    read_p99_ms: Optional[float] = None
    read_count: int = 0
    shed_delta: int = 0
    lag_ms: Optional[float] = None
    replicas: Optional[int] = None


class RegistrySignals:
    """Signal source over a ``koordlet.metrics.MetricsRegistry``.

    ``p99_family``/``p99_labels`` name the read-latency histogram to
    window (the trace harness populates
    ``koord_scorer_trace_cycle_ms``; a daemon can point this at
    ``koord_scorer_cycle_latency_ms`` instead).  ``shed_families`` are
    counter (family, labels) pairs summed into the shed delta, and
    ``lag_gauge`` the replication-lag gauge to read directly.  Each
    ``collect()`` snapshots the cumulative counters/buckets, so the
    returned signals are per-window deltas — exactly what the
    hysteresis machine wants (cumulative counters never calm down)."""

    def __init__(
        self,
        registry,
        p99_family: str = "koord_scorer_trace_cycle_ms",
        p99_labels: Optional[Mapping[str, str]] = None,
        shed_families: Tuple[Tuple[str, Mapping[str, str]], ...] = (
            ("koord_scorer_shed_total", {"method": "score"}),
            ("koord_scorer_shed_total", {"method": "assign"}),
        ),
        lag_gauge: str = "koord_scorer_replica_lag_ms",
    ):
        self.registry = registry
        self.p99_family = p99_family
        self.p99_labels = dict(p99_labels or {})
        self.shed_families = tuple(
            (fam, dict(labels)) for fam, labels in shed_families
        )
        self.lag_gauge = lag_gauge
        self._prev_buckets: Tuple[int, ...] = ()
        self._prev_shed = 0.0

    def collect(self) -> AutoscaleSignals:
        bounds, cumulative, _count = aggregate_buckets(
            self.registry, self.p99_family, self.p99_labels
        )
        if self._prev_buckets and len(self._prev_buckets) == len(cumulative):
            delta = [c - p for c, p in zip(cumulative, self._prev_buckets)]
        else:
            delta = list(cumulative)
        self._prev_buckets = tuple(cumulative)
        p99 = quantile_from_buckets(bounds, delta, 0.99)
        count = delta[-1] if delta else 0
        shed = 0.0
        for fam, labels in self.shed_families:
            shed += self.registry.get(fam, labels) or 0.0
        shed_delta = max(0.0, shed - self._prev_shed)
        self._prev_shed = shed
        lag = self.registry.get(self.lag_gauge)
        return AutoscaleSignals(
            read_p99_ms=p99,
            read_count=int(count),
            shed_delta=int(shed_delta),
            lag_ms=lag,
        )


class ReplicaAutoscaler:
    """The elastic-tier control loop.

    ``spawn()``/``drain()`` are the daemon layer's capacity levers —
    called OUTSIDE the autoscaler's lock, expected to return quickly
    (kick off the replica start/stop, don't wait for it) and allowed
    to raise (a failed spawn logs, the decision stands and cooldown
    still applies, so a broken lever cannot turn into a spawn storm).
    ``signals`` is any callable returning :class:`AutoscaleSignals`
    (:class:`RegistrySignals` ``.collect`` in production, a lambda in
    tests).  ``replicas`` seeds the tracked target; when a tick's
    signals carry an authoritative ``replicas`` count it wins."""

    def __init__(
        self,
        policy: AutoscalePolicy,
        signals: Callable[[], AutoscaleSignals],
        spawn: Callable[[], object],
        drain: Callable[[], object],
        metrics=None,
        replicas: Optional[int] = None,
        interval_s: float = 1.0,
        max_events: int = 256,
    ):
        self.policy = policy
        self.signals = signals
        self.spawn = spawn
        self.drain = drain
        self.metrics = metrics
        self.interval_s = float(interval_s)
        self.replicas = (
            policy.min_replicas if replicas is None else int(replicas)
        )
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        # spawn -> first-served-read economics (ISSUE 20): each spawn
        # stamps a start; a SYNCHRONOUS lever (the in-process relay
        # tier returns once the replica serves) closes it on return,
        # an async lever closes it via notify_ready() when the replica
        # reports in.  The last closed interval is the stats() stat.
        self._spawn_t0: Optional[float] = None
        self.spawn_to_ready_ms: List[float] = []
        self.events: List[Dict[str, object]] = []
        self._max_events = max(1, int(max_events))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the decision state machine (pure against the signals) --
    def _classify(self, s: AutoscaleSignals) -> str:
        p = self.policy
        p99_known = (
            s.read_p99_ms is not None and s.read_count >= p.min_count
        )
        if (
            (p99_known and s.read_p99_ms > p.p99_high_ms)
            or (s.lag_ms is not None and s.lag_ms > p.lag_high_ms)
            or s.shed_delta > 0
        ):
            return "breach"
        p99_calm = (
            not p99_known  # idle tier: no read traffic to defend
            or s.read_p99_ms <= p.p99_high_ms * p.p99_low_ratio
        )
        lag_calm = s.lag_ms is None or s.lag_ms <= p.lag_high_ms / 2.0
        if p99_calm and lag_calm and s.shed_delta == 0:
            return "calm"
        return "band"  # the dead band: hold, reset both streaks

    def decide(self, s: AutoscaleSignals) -> str:
        """One tick of the hysteresis machine.  Returns the ACTION
        (:data:`SCALE_UP`/:data:`SCALE_DOWN`/:data:`HOLD`); the caller
        (``tick``) owns applying it.  Stateful across calls — streaks
        and cooldown live here — but free of I/O and clocks."""
        if s.replicas is not None:
            self.replicas = int(s.replicas)
        state = self._classify(s)
        if state == "breach":
            self._up_streak += 1
            self._down_streak = 0
        elif state == "calm":
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = 0
            self._down_streak = 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return HOLD
        p = self.policy
        if (
            self._up_streak >= p.up_after
            and self.replicas < p.max_replicas
        ):
            self._up_streak = 0
            self._down_streak = 0
            self._cooldown = p.cooldown_ticks
            return SCALE_UP
        if (
            self._down_streak >= p.down_after
            and self.replicas > p.min_replicas
        ):
            self._up_streak = 0
            self._down_streak = 0
            self._cooldown = p.cooldown_ticks
            return SCALE_DOWN
        return HOLD

    # -- the loop body --
    def tick(self) -> Dict[str, object]:
        """Collect -> decide -> act -> record.  Returns the decision
        record (also appended to the bounded ``events`` log)."""
        s = self.signals()
        action = self.decide(s)
        if action == SCALE_UP:
            self.replicas += 1
            self.scale_ups += 1
            t0 = time.perf_counter()
            self._spawn_t0 = t0
            try:
                self.spawn()
                # a synchronous lever just finished the whole start; an
                # async one re-stamps the real readiness via
                # notify_ready() (later wins — it replaces this sample)
                self._record_ready(t0)
            except Exception:  # a broken capacity lever must not kill the control loop; cooldown already gates the retry rate
                self._spawn_t0 = None
                logger.exception("autoscale spawn failed")
        elif action == SCALE_DOWN:
            self.replicas -= 1
            self.scale_downs += 1
            try:
                self.drain()
            except Exception:  # same contract as spawn
                logger.exception("autoscale drain failed")
        self.ticks += 1
        record: Dict[str, object] = {
            "tick": self.ticks,
            "action": action,
            "replicas": self.replicas,
            "read_p99_ms": s.read_p99_ms,
            "read_count": s.read_count,
            "shed_delta": s.shed_delta,
            "lag_ms": s.lag_ms,
        }
        if action != HOLD:
            self.events.append(record)
            del self.events[:-self._max_events]
        m = self.metrics
        if m is not None:
            try:
                if action != HOLD:
                    m.count_autoscale_event(action)
                m.set_autoscale_replicas(self.replicas)
            except Exception:  # koordlint: disable=broad-except(autoscale metrics are observability; they must never stop the control loop)
                pass
        return record

    def _record_ready(self, t0: float) -> None:
        ms = (time.perf_counter() - t0) * 1e3
        self.spawn_to_ready_ms.append(ms)
        del self.spawn_to_ready_ms[:-self._max_events]

    def notify_ready(self) -> None:
        """Async-lever readiness callback: the daemon layer calls this
        when the replica the last spawn started actually serves.  The
        measured interval REPLACES the lever-return sample the spawn
        recorded (for a kick-off-and-return lever, return time is not
        readiness)."""
        t0 = self._spawn_t0
        if t0 is None:
            return
        self._spawn_t0 = None
        ms = (time.perf_counter() - t0) * 1e3
        if self.spawn_to_ready_ms:
            self.spawn_to_ready_ms[-1] = ms
        else:
            self.spawn_to_ready_ms.append(ms)

    # -- optional daemon thread --
    def start(self) -> "ReplicaAutoscaler":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a transient signal-source failure (registry mid-mutation, healthz probe refused) must not end autoscaling forever
                logger.exception("autoscale tick failed")

    def stats(self) -> Dict[str, object]:
        return {
            "replicas": self.replicas,
            "min_replicas": self.policy.min_replicas,
            "max_replicas": self.policy.max_replicas,
            "p99_high_ms": self.policy.p99_high_ms,
            "ticks": self.ticks,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "cooldown": self._cooldown,
            "spawn_to_ready_ms": (
                round(self.spawn_to_ready_ms[-1], 3)
                if self.spawn_to_ready_ms else None
            ),
            "events": list(self.events[-16:]),
        }
