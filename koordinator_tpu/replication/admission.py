"""Admission control + load shedding for the bridge daemon (ISSUE 8).

Overload on the old daemon degraded as latency collapse: every Score
past the coalescer's throughput queued without bound, so p99 grew with
the backlog and EVERY caller — including the ones the daemon could have
served on time — missed its deadline.  The gate here sits IN FRONT of
the dispatch queue and converts overload into fast, bounded rejections
instead: once more than ``max_inflight`` read RPCs are admitted-but-
unfinished, new ones fail immediately with :class:`ResourceExhausted`
carrying a retry-after hint (one observed service period), which the
transports map to gRPC ``RESOURCE_EXHAUSTED`` / a tagged raw-UDS error
frame.  In-flight work is untouched — the gate never cancels, it only
refuses to deepen the queue.

The depth the gate counts is exactly the dispatch queue's upstream
population (admitted Score/Assign RPCs that have not finished), which
bounds the coalescer's gather queue plus everything in execution.  Sync
is deliberately NEVER shed: the paper's one-writer design means the
write path must stay live for the whole tier — followers replicate
from it — while read storms are the thing to shed.

``max_inflight=0`` (the default) disables the gate entirely; the
daemon flag is ``--max-inflight`` / ``KOORD_MAX_INFLIGHT``.  Sheds
count on the ``koord_scorer_shed_total{method}`` family.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class ResourceExhausted(Exception):
    """The admission gate refused a request: the dispatch queue is at
    its configured depth.  ``retry_after_ms`` is the server's hint —
    one observed service period, i.e. when a slot plausibly frees.
    Transports map this to gRPC RESOURCE_EXHAUSTED; the message itself
    carries the machine-parsable ``retry_after_ms=<n>`` token the Go
    client's ``IsResourceExhausted``/``RetryAfterMS`` helpers read."""

    def __init__(self, method: str, depth: int, limit: int,
                 retry_after_ms: float):
        self.method = method
        self.depth = depth
        self.limit = limit
        self.retry_after_ms = float(retry_after_ms)
        super().__init__(
            f"RESOURCE_EXHAUSTED: {method} shed at queue depth "
            f"{depth}/{limit}; retry_after_ms={self.retry_after_ms:.0f}"
        )


class AdmissionGate:
    """Queue-depth gate with a service-time EWMA for the retry hint.

    ``admit(method)`` returns a context manager; entering it either
    reserves a slot or raises :class:`ResourceExhausted` *immediately*
    (the bounded-deadline property: a shed response never waits on the
    device).  Exiting releases the slot and feeds the EWMA with the
    observed service time, so the retry-after hint tracks the actual
    per-request cost under the current load, not a config constant.

    Thread contract: everything under one small lock; no blocking calls
    inside it (the gate is on the RPC fast path of every Score)."""

    # hint floor/ceiling: a sub-ms hint makes clients busy-spin, a
    # multi-minute one (first request after an idle stretch measuring a
    # cold compile) parks them past any realistic drain
    _MIN_HINT_MS = 1.0
    _MAX_HINT_MS = 30_000.0

    def __init__(self, max_inflight: int = 0, alpha: float = 0.2,
                 clock=None):
        self.max_inflight = max(0, int(max_inflight))
        self.alpha = float(alpha)
        self._clock = clock or time.perf_counter
        self._lock = threading.Lock()
        self._inflight = 0
        self._ewma_ms: Optional[float] = None
        # lifetime stats (bench + /metrics feed)
        self.admitted = 0
        self.shed = 0

    @property
    def enabled(self) -> bool:
        return self.max_inflight > 0

    def depth(self) -> int:
        with self._lock:
            return self._inflight

    def retry_after_ms(self) -> float:
        """One observed service period, clamped (the hint a shed reply
        carries)."""
        with self._lock:
            return self._hint_locked()

    def _hint_locked(self) -> float:
        ewma = self._ewma_ms if self._ewma_ms is not None else 50.0
        return min(self._MAX_HINT_MS, max(self._MIN_HINT_MS, ewma))

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight": self._inflight,
                "max_inflight": self.max_inflight,
                "admitted": self.admitted,
                "shed": self.shed,
                "ewma_service_ms": self._ewma_ms,
            }

    def admit(self, method: str) -> "_Admission":
        return _Admission(self, method)

    # -- slot accounting (called by _Admission) --
    def _enter(self, method: str) -> float:
        with self._lock:
            if self.enabled and self._inflight >= self.max_inflight:
                self.shed += 1
                raise ResourceExhausted(
                    method, self._inflight, self.max_inflight,
                    self._hint_locked(),
                )
            self._inflight += 1
            self.admitted += 1
        return self._clock()

    def _exit(self, entered_at: float) -> None:
        served_ms = (self._clock() - entered_at) * 1000.0
        with self._lock:
            self._inflight -= 1
            if self._ewma_ms is None:
                self._ewma_ms = served_ms
            else:
                self._ewma_ms = (
                    self.alpha * served_ms
                    + (1.0 - self.alpha) * self._ewma_ms
                )


class _Admission:
    """One RPC's pass through the gate (context manager)."""

    __slots__ = ("_gate", "_method", "_entered_at")

    def __init__(self, gate: AdmissionGate, method: str):
        self._gate = gate
        self._method = method
        self._entered_at: Optional[float] = None

    def __enter__(self) -> "_Admission":
        self._entered_at = self._gate._enter(self._method)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._entered_at is not None:
            self._gate._exit(self._entered_at)
            self._entered_at = None
        return False
