"""Admission control, band-aware load shedding and the circuit breaker
for the bridge daemon (ISSUE 8; band ladder + breaker ISSUE 13).

Overload on the old daemon degraded as latency collapse: every Score
past the coalescer's throughput queued without bound, so p99 grew with
the backlog and EVERY caller — including the ones the daemon could have
served on time — missed its deadline.  The gate here sits IN FRONT of
the dispatch queue and converts overload into fast, bounded rejections
instead: once more than ``max_inflight`` read RPCs are admitted-but-
unfinished, new ones fail immediately with :class:`ResourceExhausted`
carrying a retry-after hint (one observed service period), which the
transports map to gRPC ``RESOURCE_EXHAUSTED`` / a tagged raw-UDS error
frame.  In-flight work is untouched — the gate never cancels, it only
refuses to deepen the queue.

ISSUE 13 makes the shedding BAND-AWARE: requests stamped with one of
the koord-prod|mid|batch|free priority bands (the bands the trace
generator already schedules; ``ScoreRequest.band`` on the wire) shed on
a LADDER instead of all at the same depth.  Each band owns a fraction
of ``max_inflight`` past which ITS new requests shed:

    koord-free   0.50   (sheds first: half the configured depth)
    koord-batch  0.65
    koord-mid    0.80
    koord-prod   1.00   (sheds last, at the full configured depth)
    <unbanded>   1.00   (legacy clients = prod treatment, so the
                         pre-band gate behavior is unchanged)

so under pressure the free/batch tiers absorb the sheds while prod
keeps its full admission depth — the Synergy-style multi-tenant
treatment (2110.06073) applied to the overload path.  Shed replies
carry BAND-SCALED retry-after hints (a shed free-band client backs off
4x the observed service period; prod 1x), pushing the recovered
capacity toward the bands that matter.  Sync is deliberately NEVER
shed or banded: the one-writer path the followers replicate from must
not degrade under a read storm.

The depth the gate counts is exactly the dispatch queue's upstream
population (admitted Score/Assign RPCs that have not finished), which
bounds the coalescer's gather queue plus everything in execution.

``max_inflight=0`` (the default) disables the gate entirely; the
daemon flag is ``--max-inflight`` / ``KOORD_MAX_INFLIGHT``.  Sheds
count on the ``koord_scorer_shed_total{method}`` and
``koord_scorer_shed_band_total{band}`` families.

:class:`CircuitBreaker` is the next rung of the degradation ladder
(ISSUE 13, docs/REPLICATION.md "Degradation ladder"): ``threshold``
consecutive DEVICE failures — a launch half raising, or the readback's
``device_get`` raising, where async dispatch actually surfaces a
failing program (the chaos harness's ``fail_next_launch`` /
``fail_next_readback`` idioms) — trip it OPEN, and while open the servicer
stops queueing work behind the dead device — Score degrades to the
bounded-staleness brownout cache (an explicit ``degraded`` reply
flag), Assign fails fast with :class:`BreakerOpen` + retry-after.
After ``cooldown_ms`` the breaker goes HALF-OPEN: exactly one launch
is admitted as a probe; success closes the breaker, failure re-opens
it for another cooldown.  Admission sheds happen BEFORE the dispatch
queue, so a shed storm can never feed the breaker — and the breaker's
failure feed additionally ignores request-level rejections
(stale snapshot, expired deadline), counting only real launch faults.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Mapping, Optional

from koordinator_tpu.obs.lockwitness import witness_lock

# the shed ladder: fraction of max_inflight each band may fill before
# ITS new requests shed.  Unknown/empty bands get prod treatment (shed
# last) so legacy clients keep the exact pre-band gate behavior.
# These are the DEFAULTS — tunable per deployment since ISSUE 14
# (ROADMAP 6(b) follow-on) via the ``--shed-fraction-<band>`` daemon
# flags / ``KOORD_SHED_FRACTION_{FREE,BATCH,MID,PROD}`` envs, validated
# by :func:`validate_shed_fractions` (each in (0, 1], monotone
# free <= batch <= mid <= prod — an inverted ladder would shed prod
# FIRST, the exact opposite of the contract).
BAND_SHED_FRACTION = {
    "koord-free": 0.50,
    "koord-batch": 0.65,
    "koord-mid": 0.80,
    "koord-prod": 1.00,
}

# band name <-> knob suffix for the flags/envs
_BAND_KNOBS = (
    ("koord-free", "FREE"),
    ("koord-batch", "BATCH"),
    ("koord-mid", "MID"),
    ("koord-prod", "PROD"),
)


def validate_shed_fractions(
    overrides: Optional[Mapping[str, float]],
) -> Dict[str, float]:
    """Merge ``overrides`` (band -> fraction; partial is fine) over the
    defaults and validate the result: every fraction in (0, 1], and
    monotone non-decreasing up the ladder (free <= batch <= mid <=
    prod) — the whole point of the ladder is that LOWER bands shed
    first, so an inverted configuration is an operator error, refused
    at startup rather than discovered in a prod-band shed storm.
    Returns the merged table; raises ValueError on a bad knob."""
    merged = dict(BAND_SHED_FRACTION)
    for band, value in (overrides or {}).items():
        if band not in merged:
            raise ValueError(
                f"unknown shed-fraction band {band!r} "
                f"(expected one of {sorted(merged)})"
            )
        value = float(value)
        if not 0.0 < value <= 1.0:
            raise ValueError(
                f"shed fraction for {band} must be in (0, 1], "
                f"got {value}"
            )
        merged[band] = value
    order = [band for band, _ in _BAND_KNOBS]
    for lo, hi in zip(order, order[1:]):
        if merged[lo] > merged[hi]:
            raise ValueError(
                "shed fractions must be monotone non-decreasing up the "
                f"ladder (free <= batch <= mid <= prod): {lo}="
                f"{merged[lo]} > {hi}={merged[hi]} would shed the "
                "higher band first"
            )
    return merged


def shed_fractions_from_env(env=None) -> Optional[Dict[str, float]]:
    """The ``KOORD_SHED_FRACTION_*`` overrides, or None when none is
    set (empty values mean unset — the KOORD_* convention).  Raises
    ValueError on an unparsable value: a typo'd fraction must fail the
    daemon at startup, never silently run the default ladder."""
    env = os.environ if env is None else env
    overrides: Dict[str, float] = {}
    for band, suffix in _BAND_KNOBS:
        raw = env.get(f"KOORD_SHED_FRACTION_{suffix}") or ""
        if raw:
            try:
                overrides[band] = float(raw)
            except ValueError:
                raise ValueError(
                    f"KOORD_SHED_FRACTION_{suffix}={raw!r} is not a "
                    "number"
                ) from None
    return overrides or None
# retry-after hint multiplier per band: shed low-priority clients back
# off harder, leaving the recovering capacity to the bands above them
BAND_HINT_SCALE = {
    "koord-free": 4.0,
    "koord-batch": 2.0,
    "koord-mid": 1.5,
    "koord-prod": 1.0,
}
_UNBANDED = "none"  # metric label for requests that carried no band


def band_label(band: Optional[str]) -> str:
    """Normalized metric/stats label for a request band (empty/None ->
    the explicit ``none`` so label values are never empty strings)."""
    return band if band else _UNBANDED


class ResourceExhausted(Exception):
    """The admission gate refused a request: the dispatch queue is at
    the refusing band's rung of the ladder.  ``retry_after_ms`` is the
    server's hint — the observed service period scaled by the band's
    back-off factor, i.e. when a slot plausibly frees for THIS band.
    Transports map this to gRPC RESOURCE_EXHAUSTED; the message itself
    carries the machine-parsable ``retry_after_ms=<n>`` token the
    clients' ``IsResourceExhausted``/``RetryAfterMS`` helpers read."""

    def __init__(self, method: str, depth: int, limit: int,
                 retry_after_ms: float, band: str = ""):
        self.method = method
        self.depth = depth
        self.limit = limit
        self.band = band
        self.retry_after_ms = float(retry_after_ms)
        at = f" ({band} band)" if band else ""
        super().__init__(
            f"RESOURCE_EXHAUSTED: {method} shed at queue depth "
            f"{depth}/{limit}{at}; "
            f"retry_after_ms={self.retry_after_ms:.0f}"
        )


class AdmissionGate:
    """Queue-depth gate with a per-band shed ladder and a service-time
    EWMA for the retry hint.

    ``admit(method, band)`` returns a context manager; entering it
    either reserves a slot or raises :class:`ResourceExhausted`
    *immediately* (the bounded-deadline property: a shed response never
    waits on the device).  Exiting releases the slot and feeds the EWMA
    with the observed service time, so the retry-after hint tracks the
    actual per-request cost under the current load, not a config
    constant.

    Thread contract: everything under one small lock; no blocking calls
    inside it (the gate is on the RPC fast path of every Score)."""

    # hint floor/ceiling: a sub-ms hint makes clients busy-spin, a
    # multi-minute one (first request after an idle stretch measuring a
    # cold compile) parks them past any realistic drain
    _MIN_HINT_MS = 1.0
    _MAX_HINT_MS = 30_000.0

    def __init__(self, max_inflight: int = 0, alpha: float = 0.2,
                 clock=None, shed_fractions=None):
        """``shed_fractions``: per-band ladder overrides (partial dict
        band -> fraction), merged over :data:`BAND_SHED_FRACTION` and
        validated (ISSUE 14 satellite); None reads the
        ``KOORD_SHED_FRACTION_*`` envs."""
        self.max_inflight = max(0, int(max_inflight))
        self.alpha = float(alpha)
        if shed_fractions is None:
            shed_fractions = shed_fractions_from_env()
        self.shed_fractions = validate_shed_fractions(shed_fractions)
        self._clock = clock or time.perf_counter
        self._lock = witness_lock("replication.admission.AdmissionGate._lock")
        self._inflight = 0
        self._ewma_ms: Optional[float] = None
        # lifetime stats (bench + /metrics feed)
        self.admitted = 0
        self.shed = 0
        self.shed_by_band: Dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return self.max_inflight > 0

    def depth(self) -> int:
        with self._lock:
            return self._inflight

    def band_limit(self, band: str) -> int:
        """The ladder rung: admitted-but-unfinished reads at or past
        which a NEW request of ``band`` sheds.  Unknown bands get prod
        treatment (the full depth) — never a surprise shed."""
        frac = self.shed_fractions.get(band, 1.0)
        return max(1, int(self.max_inflight * frac))

    def retry_after_ms(self, band: str = "") -> float:
        """The band-scaled observed service period, clamped (the hint a
        shed reply carries)."""
        with self._lock:
            return self._hint_locked(band)

    def _hint_locked(self, band: str = "") -> float:
        ewma = self._ewma_ms if self._ewma_ms is not None else 50.0
        ewma *= BAND_HINT_SCALE.get(band, 1.0)
        return min(self._MAX_HINT_MS, max(self._MIN_HINT_MS, ewma))

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight": self._inflight,
                "max_inflight": self.max_inflight,
                "admitted": self.admitted,
                "shed": self.shed,
                "shed_by_band": dict(self.shed_by_band),
                "ewma_service_ms": self._ewma_ms,
            }

    def admit(self, method: str, band: str = "") -> "_Admission":
        return _Admission(self, method, band)

    # -- slot accounting (called by _Admission) --
    def _enter(self, method: str, band: str = "") -> float:
        with self._lock:
            if self.enabled and self._inflight >= self.band_limit(band):
                self.shed += 1
                label = band_label(band)
                self.shed_by_band[label] = (
                    self.shed_by_band.get(label, 0) + 1
                )
                raise ResourceExhausted(
                    method, self._inflight, self.band_limit(band),
                    self._hint_locked(band), band=band,
                )
            self._inflight += 1
            self.admitted += 1
        return self._clock()

    def _exit(self, entered_at: float) -> None:
        served_ms = (self._clock() - entered_at) * 1000.0
        with self._lock:
            self._inflight -= 1
            if self._ewma_ms is None:
                self._ewma_ms = served_ms
            else:
                self._ewma_ms = (
                    self.alpha * served_ms
                    + (1.0 - self.alpha) * self._ewma_ms
                )


class _Admission:
    """One RPC's pass through the gate (context manager)."""

    __slots__ = ("_gate", "_method", "_band", "_entered_at")

    def __init__(self, gate: AdmissionGate, method: str, band: str = ""):
        self._gate = gate
        self._method = method
        self._band = band
        self._entered_at: Optional[float] = None

    def __enter__(self) -> "_Admission":
        self._entered_at = self._gate._enter(self._method, self._band)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._entered_at is not None:
            self._gate._exit(self._entered_at)
            self._entered_at = None
        return False


class BreakerOpen(Exception):
    """The circuit breaker refused a request outright: the device's
    launch path is failing and this RPC must not queue behind it (and,
    for Score, the brownout cache could not serve it within the
    staleness bound either).  ``retry_after_ms`` is the remaining
    cooldown before the next half-open probe — the earliest moment a
    retry could find the breaker willing to try the device again.
    Transports map this to gRPC UNAVAILABLE with the machine-parsable
    ``retry_after_ms=<n>`` token."""

    def __init__(self, method: str, retry_after_ms: float, detail: str = ""):
        self.method = method
        self.retry_after_ms = max(1.0, float(retry_after_ms))
        tail = f"; {detail}" if detail else ""
        super().__init__(
            f"BREAKER_OPEN: {method} refused while the device launch "
            f"path is failing{tail}; "
            f"retry_after_ms={self.retry_after_ms:.0f}"
        )


class CircuitBreaker:
    """Consecutive-launch-failure breaker with half-open probes.

    States: ``closed`` (all launches admitted), ``open`` (no launches;
    Score degrades to the brownout cache, Assign fails fast), and
    ``half-open`` (exactly ONE probe launch admitted; its outcome
    decides).  ``threshold=0`` disables the breaker entirely —
    ``allow_launch`` always grants.

    The failure feed is the dispatcher's launch outcome hook, filtered
    by the servicer: only real launch faults count.  Request-level
    rejections (stale snapshot, expired deadline) and admission sheds
    never reach this object — a shed storm cannot trip the breaker
    (regression-tested).

    Thread contract: every method takes the one internal lock; no
    blocking calls inside it (the breaker sits on the launch path)."""

    def __init__(self, threshold: int = 3, cooldown_ms: float = 250.0,
                 clock=time.monotonic, on_transition=None):
        self.threshold = max(0, int(threshold))
        self.cooldown_ms = max(1.0, float(cooldown_ms))
        self._clock = clock
        self._lock = witness_lock(
            "replication.admission.CircuitBreaker._lock")
        self._consecutive = 0
        self._state = "closed"
        self._opened_at: Optional[float] = None
        self._probe_out = False
        # observability seam (servicer wires the breaker-state gauge +
        # transition counter); called OUTSIDE the lock
        self.on_transition = on_transition
        # lifetime stats (bench + tests)
        self.trips = 0
        self.probes = 0

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._state == "open" and self._cooldown_left_locked() <= 0.0:
            return "half-open"
        return self._state

    def _cooldown_left_locked(self) -> float:
        if self._opened_at is None:
            return 0.0
        spent = (self._clock() - self._opened_at) * 1000.0
        return max(0.0, self.cooldown_ms - spent)

    def retry_after_ms(self) -> float:
        """Remaining cooldown (the hint a fast-fail reply carries); at
        least 1 ms so a shed client never busy-spins."""
        with self._lock:
            return max(1.0, self._cooldown_left_locked())

    def allow_launch(self) -> bool:
        """True when a launch may proceed: breaker closed, or this
        caller won the one half-open probe slot.  False = serve the
        degraded path instead (brownout / fast fail)."""
        if not self.enabled:
            return True
        transition = None
        with self._lock:
            state = self._state_locked()
            if state == "closed":
                return True
            if state == "half-open" and not self._probe_out:
                self._probe_out = True
                self._state = "half-open"
                self.probes += 1
                transition = "half-open"
            else:
                return False
        self._notify(transition)
        return True

    def record_failure(self) -> None:
        """One real launch fault (the servicer filters request-level
        rejections out before calling)."""
        if not self.enabled:
            return
        transition = None
        with self._lock:
            self._consecutive += 1
            was = self._state
            if self._state == "half-open":
                # the probe failed: re-open for a fresh cooldown
                self._state = "open"
                self._opened_at = self._clock()
                self._probe_out = False
                transition = "open"
            elif (
                was == "closed"
                and self._consecutive >= self.threshold
            ):
                self._state = "open"
                self._opened_at = self._clock()
                self.trips += 1
                transition = "open"
        self._notify(transition)

    def release_probe(self) -> None:
        """A half-open probe slot was granted but the batch performed
        no device launch after all (every entry stale/expired, or a
        memo served it): the device was not probed, so no verdict —
        the slot frees for the next caller instead of wedging the
        breaker half-open forever."""
        with self._lock:
            if self._state == "half-open":
                self._probe_out = False

    def record_success(self) -> None:
        if not self.enabled:
            return
        transition = None
        with self._lock:
            self._consecutive = 0
            if self._state != "closed":
                self._state = "closed"
                self._opened_at = None
                self._probe_out = False
                transition = "closed"
        self._notify(transition)

    def _notify(self, transition: Optional[str]) -> None:
        if transition is not None and self.on_transition is not None:
            try:
                self.on_transition(transition)
            except Exception:  # an observability hook must never fail the launch path; the transition itself already happened
                import logging

                logging.getLogger(__name__).exception(
                    "breaker transition hook failed"
                )

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state_locked(),
                "threshold": self.threshold,
                "cooldown_ms": self.cooldown_ms,
                "consecutive_failures": self._consecutive,
                "trips": self.trips,
                "probes": self.probes,
            }
