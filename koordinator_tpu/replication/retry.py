"""The ONE retry policy for the serving tier (ISSUE 11).

Every reconnect/failover loop in the tier — the follower's replication
redial, the Python client's Sync/read retries, the promotion probe —
retries through this module instead of hand-rolling ``time.sleep`` in
a loop.  Three properties a bare fixed-sleep loop lacks, each of which
has a production failure mode named after it:

* **jitter** — a leader restart wakes every follower and client at
  once; synchronized fixed sleeps re-arrive as a thundering herd at
  exactly the moment the new leader is coldest.  Every delay here is
  multiplied by ``uniform(1 - jitter, 1)``.
* **exponential growth with a cap** — a dead peer is polled at the
  base delay first (fast failover when the restart is fast) and at
  ``cap_ms`` forever after (a dead peer costs polls, not a spin).
* **a deadline budget** — retries stop when the budget is spent and
  the LAST error surfaces to the caller; an unbounded loop turns an
  outage into a hang nobody can distinguish from a deadlock.

koordlint's ``bare-retry`` rule statically rejects retry loops that
sleep a fixed constant outside this helper (analysis/bareretry.py).

Env knobs (the client and daemon both read them through
:func:`BackoffPolicy.from_env`): ``KOORD_RETRY_BASE_MS``,
``KOORD_RETRY_CAP_MS``, ``KOORD_RETRY_DEADLINE_MS``.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Callable, Iterator, Optional

DEFAULT_BASE_MS = 25.0
DEFAULT_CAP_MS = 2_000.0
DEFAULT_DEADLINE_MS = 15_000.0


def _env_float(name: str, default: float) -> float:
    # `or`: an empty env value means unset (the KOORD_* convention),
    # and a malformed one must degrade to the default, not crash a
    # daemon at boot
    try:
        return float(os.environ.get(name) or default)
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Jittered exponential backoff under a total deadline budget.

    ``base_ms`` doubles per attempt up to ``cap_ms``; every delay is
    jittered down by up to ``jitter`` (fraction).  ``deadline_ms`` is
    the TOTAL budget across all attempts — :meth:`delays` stops
    yielding once spending the next delay would cross it.
    ``deadline_ms=0`` means one attempt, no retries."""

    base_ms: float = DEFAULT_BASE_MS
    cap_ms: float = DEFAULT_CAP_MS
    deadline_ms: float = DEFAULT_DEADLINE_MS
    multiplier: float = 2.0
    jitter: float = 0.5

    @classmethod
    def from_env(cls, **overrides) -> "BackoffPolicy":
        kw = dict(
            base_ms=_env_float("KOORD_RETRY_BASE_MS", DEFAULT_BASE_MS),
            cap_ms=_env_float("KOORD_RETRY_CAP_MS", DEFAULT_CAP_MS),
            deadline_ms=_env_float(
                "KOORD_RETRY_DEADLINE_MS", DEFAULT_DEADLINE_MS
            ),
        )
        kw.update(overrides)
        return cls(**kw)

    def delay_ms(self, attempt: int, rng: Callable[[], float] = random.random) -> float:
        """The jittered delay before retry ``attempt`` (0-based)."""
        raw = min(
            float(self.cap_ms),
            float(self.base_ms) * (self.multiplier ** attempt),
        )
        span = max(0.0, min(1.0, float(self.jitter)))
        return raw * (1.0 - span * rng())

    def delays(
        self,
        clock: Callable[[], float] = time.monotonic,
        rng: Callable[[], float] = random.random,
    ) -> Iterator[float]:
        """Yield the delay to sleep before each RETRY, respecting the
        deadline budget: the first attempt is free (callers try once
        before consulting the iterator), and iteration ends when the
        next delay would land past the budget."""
        start = clock()
        attempt = 0
        while True:
            d_ms = self.delay_ms(attempt, rng)
            spent_ms = (clock() - start) * 1000.0
            if spent_ms + d_ms > self.deadline_ms:
                return
            attempt += 1
            yield d_ms


def call_with_retry(
    fn: Callable[[], object],
    policy: BackoffPolicy,
    retryable: Callable[[BaseException], bool],
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Call ``fn`` until it returns, a non-retryable error raises, or
    the policy's deadline budget is spent (the LAST error surfaces).
    ``on_retry(attempt, exc)`` observes each retry (metrics hooks)."""
    delays = policy.delays(clock=clock)
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as exc:
            if not retryable(exc):
                raise
            d_ms = next(delays, None)
            if d_ms is None:
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            attempt += 1
            sleep(d_ms / 1000.0)
