"""Replicated serving tier for the bridge daemon (ISSUE 8).

One **leader** daemon applies client Syncs to its device-resident
snapshot and streams the already-encoded delta frames to N **follower**
daemons; each follower maintains its own device-resident copy (the same
``bridge/state.py`` stage/commit + ``solver/resident.py`` scatter
machinery) and serves Score/Assign read traffic locally — the paper's
one-writer/many-readers split made horizontal.  The ``s<epoch>-<gen>``
snapshot id chain is the fencing token: a follower applies only frames
that extend its exact chain, and any discontinuity (gap, epoch bump,
failed validation, truncated frame) triggers the documented one-shot
full resync — never a torn snapshot.

Modules:

* ``codec``      — the frame layout (the one Python statement of the
  header fields; mirrored independently by bridge/wirecheck.py and
  go/scorerclient/replica.go, all three diffed by koordlint's
  wire-contract rule).
* ``admission``  — queue-depth admission control + load shedding
  (``--max-inflight`` / KOORD_MAX_INFLIGHT; RESOURCE_EXHAUSTED with a
  retry-after hint before the dispatch queue drowns).
* ``leader``     — ReplicationPublisher: per-follower bounded queues
  over a unix socket; the writer path never blocks on a reader.
* ``follower``   — ReplicaApplier (continuity core) +
  ReplicationSubscriber (reconnect = resync; sends the chain-position
  hello) + FollowerServicer (refuses client Syncs until promoted) +
  ``promote_replica`` (the raw-UDS admin call).
* ``journal``    — FrameJournal (ISSUE 11): the durable, CRC'd,
  compacting frame journal under ``--state-dir`` that makes the tier
  crash-tolerant — replay-on-boot resumes the same ``s<epoch>-<gen>``
  chain, and the publisher serves reconnecting followers just the
  missing delta frames out of it.  RelayFrameCache (ISSUE 18) is the
  in-memory twin a relay answers descendant hello/resume from.
* ``autoscale``  — SLO-driven elastic replica autoscaling (ISSUE 18):
  the hysteresis control loop that spawns/drains followers into the
  relay tree to hold a declared read p99 (imports ``obs.slo``; like
  leader/follower it is imported explicitly, not re-exported here).
* ``retry``      — the ONE jittered-exponential-backoff/deadline-budget
  policy every reconnect/failover loop retries through (koordlint's
  ``bare-retry`` rule rejects hand-rolled fixed-sleep retry loops).

``leader``/``follower`` import the bridge server and are therefore NOT
imported eagerly here (bridge/server.py imports ``admission`` — eager
re-export would cycle); import them explicitly.

docs/REPLICATION.md has the stream protocol, the fencing rules, the
shed policy and the journal/promotion failover walkthrough.
"""

from koordinator_tpu.replication.admission import (  # noqa: F401
    AdmissionGate,
    ResourceExhausted,
)
from koordinator_tpu.replication.codec import (  # noqa: F401
    Frame,
    FrameError,
    KIND_DELTA,
    KIND_FULL,
    KIND_FULL_Z,
    KIND_HELLO,
    decode_frame,
    encode_frame,
)
from koordinator_tpu.replication.journal import (  # noqa: F401
    FrameJournal,
    JournalError,
    RelayFrameCache,
)
from koordinator_tpu.replication.retry import (  # noqa: F401
    BackoffPolicy,
    call_with_retry,
)
