"""Follower side of the replicated serving tier (ISSUE 8).

A follower daemon maintains its own device-resident snapshot copy by
applying the leader's replication frames through the very same
``bridge/state.py`` stage/commit seam (delta scatters, warm residency,
donation barrier) a client Sync uses, and serves Score/Assign read
traffic locally.  Three pieces:

* :class:`ReplicaApplier` — the transport-independent continuity core.
  Every frame is judged against the ``s<epoch>-<gen>`` chain the
  follower is on; only a frame that EXTENDS it applies.  Anything else
  is a classified discontinuity: ``gap`` (dropped frame), ``epoch``
  (leader restart/failover), ``apply`` (payload failed validation —
  state untouched, the stage-then-commit atomicity), and duplicates
  from a reordering transport are dropped as ``stale``.  The fuzz in
  tests/test_replication.py drives this against a lossy/reordering
  channel with byte-parity asserted follower-vs-leader after every
  commit.
* :class:`ReplicationSubscriber` — the UDS transport: dial the
  leader's ``.repl`` socket, stream frames into the applier, and on
  ANY discontinuity (including a truncated or malformed frame) drop
  the connection and redial — the leader opens every subscription with
  a full-state frame, so reconnect IS the one-shot full resync.
* :class:`FollowerServicer` — a ScorerServicer that refuses client
  Syncs (the tier has ONE writer; Sync goes to the leader) while
  serving Score/Assign exactly like the leader, snapshot ids included.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from typing import Optional

import grpc

from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.obs.lockwitness import witness_lock
from koordinator_tpu.replication import codec
from koordinator_tpu.replication.retry import BackoffPolicy

logger = logging.getLogger(__name__)

APPLIED = "applied"
STALE = "stale"
RESYNC = "resync"


class NotLeader(Exception):
    """A client sent Sync to a follower replica.  Mapped to gRPC
    FAILED_PRECONDITION / a raw-UDS error frame; the fix is config
    (point Sync at the leader), so the message says where."""


class ReplicaApplier:
    """Continuity-checked frame application onto a follower servicer.

    ``offer(frame)`` returns :data:`APPLIED`, :data:`STALE` (duplicate
    or late redelivery — dropped) or :data:`RESYNC` (discontinuity
    detected; the caller must fetch a full frame, which ``offer``
    always accepts).  Not thread-safe by itself: one transport thread
    feeds one applier (client Score/Assign traffic runs concurrently —
    the servicer's own locks cover that side)."""

    def __init__(self, servicer, clock=time.time, hop: int = 1):
        """``hop`` is this replica's distance from the tree root
        (ISSUE 18: 1 = direct follower of the leader, 2 = behind one
        relay, ...) — it labels the per-hop lag gauge so a deep chain's
        lag amplification is visible per level, not just in aggregate."""
        self.servicer = servicer
        self._clock = clock
        self.hop = max(1, int(hop))
        self.applied = 0
        self.resyncs = 0
        self.last_lag_ms: Optional[float] = None
        servicer.telemetry.metrics.set_replica_role("follower")

    # -- current chain position --
    def position(self):
        """(epoch, generation) the follower is at.  Before the first
        full frame this is the follower's own boot epoch, which no
        leader frame can ever extend — exactly the "must resync first"
        state a fresh follower should be in."""
        from koordinator_tpu.bridge.client import parse_snapshot_id

        return parse_snapshot_id(self.servicer.snapshot_id())

    def offer(self, frame: "codec.Frame") -> str:
        metrics = self.servicer.telemetry.metrics
        if frame.kind == codec.KIND_FULL_Z:
            # negotiated wire compression (ISSUE 18): inflate back to
            # the canonical KIND_FULL before the continuity core sees
            # it — everything downstream (stage/commit, journal,
            # relay re-publication) handles raw bytes only
            import dataclasses

            try:
                frame = dataclasses.replace(
                    frame, kind=codec.KIND_FULL,
                    payload=codec.decompress_payload(frame.payload),
                )
            except codec.FrameError:
                # corrupt compressed payload: a detected discontinuity,
                # same contract as any malformed frame
                return self._resync("decode", metrics)
            metrics.count_replica_compress("decode")
        if frame.kind == codec.KIND_FULL:
            return self._apply(frame, metrics)
        epoch, gen = self.position()
        if frame.epoch != epoch:
            return self._resync("epoch", metrics)
        if frame.generation <= gen:
            # duplicate / late redelivery on the SAME chain: the state
            # already contains it; applying again would corrupt the
            # delta baselines — drop, don't resync
            metrics.count_replica_frame(STALE)
            return STALE
        if frame.generation != gen + 1:
            return self._resync("gap", metrics)
        return self._apply(frame, metrics)

    def _apply(self, frame, metrics) -> str:
        try:
            self.servicer.apply_replica_frame(frame)
        except Exception:  # a bad frame must demote to the documented full resync, never crash the follower; state is untouched by stage-then-commit
            logger.exception(
                "replica frame s%s-%d failed to apply; forcing full "
                "resync (resident state untouched)",
                frame.epoch, frame.generation,
            )
            return self._resync("apply", metrics)
        self.applied += 1
        lag_ms = max(0.0, self._clock() * 1e6 - frame.stamp_us) / 1000.0
        self.last_lag_ms = lag_ms
        metrics.count_replica_frame(APPLIED)
        metrics.set_replica_lag(lag_ms)
        metrics.set_replica_hop_lag(self.hop, lag_ms)
        return APPLIED

    def _resync(self, reason: str, metrics) -> str:
        self.resyncs += 1
        metrics.count_replica_frame(RESYNC)
        metrics.count_replica_resync(reason)
        return RESYNC


def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    """Exactly ``n`` bytes or None on EOF/reset (any partial read is a
    truncated frame — the caller treats it as a discontinuity)."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class ReplicationSubscriber:
    """Dial the leader's replication socket and pump frames into an
    applier; reconnect (= full resync) on any discontinuity.

    ``on_frame(result, frame)`` is an optional callback after every
    offer — the bench's follower worker uses it to publish catch-up
    status; tests use it to observe the stream."""

    def __init__(
        self,
        path: str,
        applier: ReplicaApplier,
        reconnect_delay_s: float = 0.05,
        on_frame=None,
        backoff: Optional[BackoffPolicy] = None,
        hello: bool = True,
        fallbacks=(),
        compress: bool = True,
        on_raw=None,
    ):
        """``backoff`` paces the redial loop (ISSUE 11): jittered
        exponential from ``reconnect_delay_s`` up to the policy cap —
        a dead leader is polled at the cap, a thundering herd of
        followers never re-arrives in phase.  The subscriber retries
        FOREVER (the deadline budget bounds individual client calls,
        not a daemon's lifelong subscription); a successful connect
        resets the ladder.

        ``hello`` sends the follower's chain position as the first
        frame of every subscription (codec.KIND_HELLO): a leader whose
        journal covers that position answers with only the missing
        delta frames — a journal warm-restart costs followers NO full
        resync.  Leaders ignore unexpected bytes conservatively (a
        hello to a pre-journal leader just reads as the subscription
        opening; the full frame still arrives).

        ``fallbacks`` (ISSUE 18, the relay tree) are ANCESTOR
        replication sockets in preference order behind the primary
        ``path`` (parent first, then grandparent, ... root): every dial
        attempt walks the whole ladder primary-first, so an interior
        relay's death re-parents this subscriber onto the nearest
        surviving ancestor — whose stream is the SAME chain, so the
        hello/resume handshake serves just the missing deltas (zero
        full resyncs) — and a healed parent is preferred again on the
        next redial.

        ``compress`` advertises the ``z`` hello capability: full frames
        may then arrive as level-1 zlib (KIND_FULL_Z), inflated before
        the continuity core sees them.

        ``on_raw(result, frame, raw_bytes)`` is the relay forwarding
        seam: called with the frame's exact wire bytes after every
        offer, so a relay can re-publish applied delta frames verbatim
        (near-zero-copy) on its own ``.repl`` socket."""
        self.path = path
        self.fallbacks = tuple(fallbacks)
        self.paths = (path,) + self.fallbacks
        self.compress = bool(compress)
        self.on_raw = on_raw
        self.applier = applier
        self.reconnect_delay_s = float(reconnect_delay_s)
        self.backoff = backoff or BackoffPolicy.from_env(
            base_ms=max(1.0, self.reconnect_delay_s * 1000.0)
        )
        self.hello = bool(hello)
        self.on_frame = on_frame
        self._stop = threading.Event()
        self._conn_lock = witness_lock(
            "replication.follower.ReplicationSubscriber._conn_lock")
        self._conn: Optional[socket.socket] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        # set when the LAST stream ended in a detected discontinuity
        # (RESYNC/decode): the next dial must skip the hello and take
        # the full-frame open — offering the same position to a
        # journal-holding leader would re-serve the very delta that
        # just failed to apply, forever (the pre-journal "reconnect IS
        # the full resync" guarantee, preserved exactly where it is
        # load-bearing)
        self._force_full = False
        self.connects = 0
        self.redials = 0
        # which ancestor currently feeds this subscriber (index into
        # ``paths``; 0 = the primary parent) and how many times a dial
        # landed on a non-primary ancestor (the interior-death path)
        self.active_path: Optional[str] = None
        self.ancestor_switches = 0

    def start(self) -> "ReplicationSubscriber":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # unblock a recv in flight: the pump thread would otherwise
        # sit in the blocking read until the leader sends again
        with self._conn_lock:
            conn = self._conn
        if conn is not None:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._thread.join(timeout=5)

    # -- internals --
    def _dial(self, metrics) -> Optional[socket.socket]:
        """One dial pass over the ancestor ladder, primary parent
        first.  Returns the connected socket (``active_path`` updated)
        or None when every ancestor refused — the caller backs off.  A
        connect that lands past index 0 is an ancestor switch: the
        parent is dead or unreachable and a surviving ancestor now
        feeds this subscriber (same chain, so resume still applies)."""
        for i, path in enumerate(self.paths):
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                conn.connect(path)
            except OSError:
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            if i > 0:
                self.ancestor_switches += 1
                try:
                    metrics.count_retry("failover")
                except Exception:  # koordlint: disable=broad-except(failover accounting must never abort a successful dial)
                    pass
                logger.warning(
                    "replication parent %s unreachable; re-parented "
                    "onto ancestor %s", self.path, path,
                )
            self.active_path = path
            return conn
        return None

    def _run(self) -> None:
        metrics = self.applier.servicer.telemetry.metrics
        attempt = 0
        while not self._stop.is_set():
            conn = None
            try:
                conn = self._dial(metrics)
                if conn is None:
                    raise OSError("no ancestor reachable")
                with self._conn_lock:
                    self._conn = conn
                self.connects += 1
                attempt = 0  # a live leader resets the backoff ladder
                if self.hello and not self._force_full:
                    epoch, gen = self.applier.position()
                    if len(epoch) != 8:
                        # legacy/malformed id: offer a position no
                        # journal matches -> ordinary full-frame open
                        epoch = "00000000"
                    caps = codec.CAP_COMPRESS if self.compress else b""
                    try:
                        conn.sendall(codec.encode_frame(
                            codec.KIND_HELLO, epoch, max(0, gen),
                            0, caps,
                        ))
                    except OSError:
                        # peer hung up mid-handshake: whatever it
                        # already sent is still buffered locally — the
                        # pump below must READ it (a truncated frame
                        # counts on the error family), not abandon it
                        pass
                self._pump(conn, metrics)
            except OSError:
                pass  # leader down/mid-restart: retry below
            finally:
                with self._conn_lock:
                    self._conn = None
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
            # every redial resyncs (a journal-holding leader serves the
            # missing deltas, anyone else a full frame); pace it on the
            # shared jittered ladder so a dead leader costs capped
            # polls, never a spin or a synchronized herd
            if self._stop.is_set():
                return
            self.redials += 1
            try:
                metrics.count_retry("subscribe")
            except Exception:  # koordlint: disable=broad-except(retry accounting must never kill the redial loop)
                pass
            self._stop.wait(self.backoff.delay_ms(attempt) / 1000.0)
            attempt += 1

    def _pump(self, conn: socket.socket, metrics) -> None:
        while not self._stop.is_set():
            header = _read_exact(conn, codec.HEADER_LEN)
            if header is None:
                return  # EOF between frames, or leader dropped us
            try:
                partial, plen = codec.decode_header(header)
                payload = b""
                if plen:
                    body = _read_exact(conn, plen)
                    if body is None:
                        # truncated mid-frame: a protocol violation,
                        # not a clean close — count it, then resync by
                        # reconnecting
                        metrics.count_replica_frame("error")
                        metrics.count_replica_resync("connect")
                        self._force_full = True
                        return
                    payload = body
                frame = codec.decode_frame(header + payload)
            except codec.FrameError as exc:
                logger.warning(
                    "malformed replication frame (%s); resyncing", exc
                )
                metrics.count_replica_frame("error")
                metrics.count_replica_resync("decode")
                self._force_full = True
                return
            result = self.applier.offer(frame)
            if result == APPLIED and frame.kind in (
                codec.KIND_FULL, codec.KIND_FULL_Z
            ):
                self._force_full = False  # healed: resume is safe again
            if self.on_raw is not None:
                # relay forwarding seam: the exact wire bytes, so a
                # relay re-publishes applied deltas verbatim with zero
                # re-encoding (delta frames are never compressed, so
                # the bytes are hop-invariant)
                try:
                    self.on_raw(result, frame, header + payload)
                except Exception:  # the relay's descendants resync on their own; a forwarding fault must not kill THIS stream
                    logger.exception("replication on_raw callback failed")
            if self.on_frame is not None:
                try:
                    self.on_frame(result, frame)
                except Exception:  # status callbacks are observability; they must not kill the stream
                    logger.exception("replication on_frame callback failed")
            if result == RESYNC:
                # reconnect -> the leader must reopen with a FULL
                # frame: a journal resume at our unchanged position
                # would re-serve the exact frame that just failed
                self._force_full = True
                return


class FollowerServicer(ScorerServicer):
    """A read-replica servicer: serves Score/Assign exactly like the
    leader (snapshot ids included — they ARE the leader's after the
    first applied frame) but refuses client Syncs: the tier has one
    writer, and a follower silently accepting a Sync would fork its
    chain off the leader's and poison every delta baseline.

    :meth:`promote` (ISSUE 11) flips this replica into the tier's
    writer: it BUMPS THE EPOCH (the old leader's chain must become
    unmistakably dead — a zombie leader's frames now fail the epoch
    fence everywhere) while keeping the generation, and starts
    accepting Syncs.  The daemon layer (scheduler/server.py) wires the
    surrounding moves: stop the subscription, open a journal, start a
    publisher on this daemon's own ``<uds>.repl``."""

    def __init__(self, *args, leader: str = "", **kwargs):
        super().__init__(*args, **kwargs)
        self._leader_hint = leader
        self._promoted = False

    @property
    def promoted(self) -> bool:
        return self._promoted

    def promote(self, epoch: Optional[str] = None) -> str:
        """Become the writer: new epoch, same generation, memos dead.
        Idempotent — a second promote returns the current id without
        bumping again.  Returns the new ``s<epoch>-<gen>`` id."""
        with self._sync_lock:
            with self._state_lock:
                if self._promoted:
                    return self.snapshot_id()
                self._promoted = True
                # the ONE epoch-bump implementation (memos die with
                # the old chain) — shared with the torn-tail rebase
                sid = self._rebase_epoch_locked(epoch)
        m = self.telemetry.metrics
        m.set_replica_role("leader")
        m.count_failover("promoted")
        logger.warning(
            "follower promoted to leader at %s (epoch bumped; clients "
            "full-resync once on the epoch fence, reads were never "
            "interrupted)", sid,
        )
        return sid

    def sync(self, req, ctx=None, wire_bytes=None):
        if self._promoted:
            return super().sync(req, ctx, wire_bytes=wire_bytes)
        msg = (
            "replica follower does not accept Sync: the tier has one "
            "writer"
            + (f" (sync against {self._leader_hint})"
               if self._leader_hint else "")
        )
        if ctx is not None:
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, msg)
        raise NotLeader(msg)


def promote_replica(raw_sock_path: str, timeout_s: float = 30.0) -> str:
    """Operator/admin seam: ask the follower daemon at ``<uds>.raw`` to
    promote itself (the raw-UDS admin method — SIGUSR2 is the signal
    twin).  Returns the promoted daemon's new snapshot id; raises
    :class:`RuntimeError` with the server's message on refusal."""
    from koordinator_tpu.bridge.udsserver import METHOD_PROMOTE

    conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    conn.settimeout(timeout_s)
    try:
        conn.connect(raw_sock_path)
        conn.sendall(struct.pack(">BI", METHOD_PROMOTE, 0))
        header = b""
        while len(header) < 5:
            chunk = conn.recv(5 - len(header))
            if not chunk:
                raise RuntimeError("promote: connection closed mid-reply")
            header += chunk
        status, length = struct.unpack(">BI", header)
        payload = b""
        while len(payload) < length:
            chunk = conn.recv(length - len(payload))
            if not chunk:
                raise RuntimeError("promote: connection closed mid-reply")
            payload += chunk
        if status != 0:
            raise RuntimeError(
                f"promote refused: {payload.decode(errors='replace')}"
            )
        return payload.decode()
    finally:
        try:
            conn.close()
        except OSError:
            pass
