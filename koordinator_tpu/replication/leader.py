"""Leader side of the replicated serving tier (ISSUE 8).

One daemon — the leader — applies client Syncs to its device-resident
snapshot and streams every committed frame to N follower daemons over a
unix socket, each follower maintaining its own device-resident copy and
serving Score/Assign read traffic locally.  The paper's design already
separates the one writer from many readers; this module is that split
made horizontal.

Protocol (replication/codec.py frames over a plain ``SOCK_STREAM``
unix socket, one-directional leader -> follower):

* every new subscription OPENS with a ``kind=full`` frame — the
  leader's full-state export at its current ``(epoch, generation)`` —
  so "resync" and "subscribe" are the same mechanism: a follower that
  detects any discontinuity simply drops the connection and redials;
* every committed Sync then streams as a ``kind=delta`` sequence frame
  (the client's already-encoded SyncRequest bytes — a warm delta frame
  replicates at its wire size, O(changed));
* a follower that cannot keep up is DROPPED, not waited for: each
  subscriber has a bounded frame queue drained by its own sender
  thread, and overflow closes the connection (the follower redials and
  full-resyncs).  The writer path never blocks on a reader — publish
  is enqueue-only.

Ordering: the servicer invokes ``replication_hook`` under its
``_sync_lock``, so frames fan out in strict generation order; new
subscriptions serialize against the fan-out under the publisher's own
lock and read the export under the servicer's ``_state_lock``, which
makes the opening full frame a committed-generation prefix of the
delta stream that follows (a delta the full frame already contains
arrives with ``generation <= current`` and is dropped as stale by the
follower — never applied twice).
"""

from __future__ import annotations

import collections
import logging
import os
import socket
import threading
import time

from koordinator_tpu.obs.lockwitness import witness_condition, witness_rlock
from koordinator_tpu.replication import codec

logger = logging.getLogger(__name__)

# frames a slow follower may have outstanding before it is dropped to
# a full resync; bounds leader-side memory at ~queue * frame size
DEFAULT_QUEUE_FRAMES = 64

# sender-path coalescing bound (ISSUE 18): consecutive queued frames
# are concatenated into ONE sendall per wakeup up to this many bytes —
# bounded by BYTES, not frame count, so a burst of sparse warm deltas
# (a few hundred bytes each) collapses hundreds of syscalls into one
# writev-sized write while a single huge full frame still goes alone
DEFAULT_BATCH_BYTES = 1 << 20

# a hello capability payload is a short ascii string; anything larger
# is drained and ignored (conservative: treated as capability-free)
_MAX_HELLO_CAPS = 64


def _parse_sid(snapshot_id: str):
    from koordinator_tpu.bridge.client import parse_snapshot_id

    return parse_snapshot_id(snapshot_id)


class _Subscriber:
    """One follower connection: bounded queue + sender thread.  The
    sender drains the queue in byte-bounded batches — frame boundaries
    are preserved by the stream framing itself, so concatenation is
    free — and reports each batch's occupancy through ``on_batch`` for
    the publisher's frames-per-wakeup stats."""

    def __init__(self, conn: socket.socket, max_frames: int, on_drop,
                 max_batch_bytes: int = DEFAULT_BATCH_BYTES,
                 on_batch=None):
        self.conn = conn
        self.max_frames = max_frames
        self.max_batch_bytes = max(1, int(max_batch_bytes))
        self._on_drop = on_drop
        self._on_batch = on_batch
        # negotiated in the hello handshake (publisher sets it before
        # any frame is enqueued): may this subscriber receive
        # KIND_FULL_Z compressed full frames?
        self.compress = False
        self._frames = collections.deque()
        self._cond = witness_condition("replication.leader._Subscriber._cond")
        self._dead = False
        # sender-thread-only counters; read racily by stats() (ints)
        self.sent_frames = 0
        self.sent_batches = 0
        self._thread = threading.Thread(target=self._drain, daemon=True)

    def start(self) -> "_Subscriber":
        self._thread.start()
        return self

    def enqueue(self, frame_bytes: bytes) -> None:
        """Non-blocking: the publish path must never wait on a reader.
        Overflow kills the subscription — the follower's reconnect
        gets a fresh full frame, which is strictly more information
        than the frames this queue would have held."""
        overflow = False
        with self._cond:
            if self._dead:
                return
            if len(self._frames) >= self.max_frames:
                overflow = True
            else:
                self._frames.append(frame_bytes)
                self._cond.notify_all()
        if overflow:
            logger.warning(
                "replication subscriber overflowed its %d-frame "
                "queue; dropping it to a full resync",
                self.max_frames,
            )
            self.close()

    def close(self) -> None:
        # the on_drop callback (publisher lock) runs with the condition
        # RELEASED: the sender thread takes cond -> publisher-lock and
        # the publish path publisher-lock -> cond, so calling out while
        # holding the condition would close a lock-order cycle
        if self._kill():
            self._on_drop(self)

    def _kill(self) -> bool:
        """Transition to dead exactly once; True for the transitioning
        caller (who then owns the on_drop notification)."""
        with self._cond:
            if self._dead:
                return False
            self._dead = True
            self._frames.clear()
            try:
                self.conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self.conn.close()
            except OSError:
                pass
            self._cond.notify_all()
            return True

    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._frames and not self._dead:
                    # backstop timeout only (unbounded-wait idiom):
                    # every enqueue/close notifies this condition
                    self._cond.wait(timeout=1.0)
                if self._dead:
                    return
                # byte-bounded coalescing (ISSUE 18): take every
                # consecutive queued frame that fits the batch bound
                # in ONE wakeup; the first frame always ships even
                # when it alone exceeds the bound
                batch = [self._frames.popleft()]
                size = len(batch[0])
                while self._frames and (
                    size + len(self._frames[0]) <= self.max_batch_bytes
                ):
                    nxt = self._frames.popleft()
                    size += len(nxt)
                    batch.append(nxt)
            data = batch[0] if len(batch) == 1 else b"".join(batch)
            try:
                self.conn.sendall(data)
            except OSError:
                self.close()
                return
            self.sent_frames += len(batch)
            self.sent_batches += 1
            if self._on_batch is not None:
                try:
                    self._on_batch(len(batch))
                except Exception:  # koordlint: disable=broad-except(batch-occupancy accounting must never kill the sender thread)
                    pass


class ReplicationPublisher:
    """Streams a leader servicer's committed Syncs to followers.

    ``attach`` + ``start`` on the leader daemon; the scheduler server
    binds it at ``<uds>.repl`` by default (scheduler/server.py)."""

    def __init__(
        self,
        servicer,
        path: str,
        queue_frames: int = DEFAULT_QUEUE_FRAMES,
        clock=time.time,
        journal=None,
        hello_timeout_s: float = 0.25,
        max_batch_bytes: int = DEFAULT_BATCH_BYTES,
        compress_full: bool = True,
    ):
        """``journal`` (ISSUE 11, a ``replication.journal.FrameJournal``
        — or any object with its ``frames_since`` shape, e.g. the
        relay-side ``replication.journal.RelayFrameCache``) lets a
        subscription RESUME instead of full-resyncing: a follower opens
        with a ``kind=hello`` frame naming its chain position, and when
        the journal's delta chain covers it the subscription is served
        just the missing frames — after a journal warm-restart,
        reconnecting followers observe no full resync.  Followers that
        send no hello within ``hello_timeout_s`` (pre-journal
        subscribers, plain taps) get the PR-8 behavior: a full opening
        frame.

        ``max_batch_bytes`` bounds the sender-path coalescing (ISSUE
        18): each subscriber's sender concatenates consecutive queued
        frames into one ``sendall`` up to this many bytes per wakeup.

        ``compress_full`` (ISSUE 18) serves the opening full frame as
        level-1 zlib (``KIND_FULL_Z``) to any subscriber whose hello
        advertised the ``z`` capability; journal bytes and delta frames
        stay uncompressed."""
        self.servicer = servicer
        self.path = path
        self.queue_frames = max(1, int(queue_frames))
        self.journal = journal
        self.hello_timeout_s = float(hello_timeout_s)
        self.max_batch_bytes = max(1, int(max_batch_bytes))
        self.compress_full = bool(compress_full)
        self._clock = clock
        # RLock: an enqueue overflow inside the fan-out (lock held)
        # drops the subscriber, and the drop re-enters to unregister
        self._lock = witness_rlock(
            "replication.leader.ReplicationPublisher._lock")
        self._subs = []
        self._stop = threading.Event()
        if os.path.exists(path):
            os.unlink(path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(16)
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        # lifetime stats (tests/bench)
        self.published = 0
        self.subscriptions = 0
        self.resumed_subscriptions = 0
        self.compressed_fulls = 0
        # send-batch totals of DROPPED subscribers, folded in by _drop
        # so stats() never loses a retired sender's work (live
        # subscribers are summed on demand)
        self._retired_frames = 0
        self._retired_batches = 0

    # -- lifecycle --
    def attach(self) -> "ReplicationPublisher":
        """Hook the servicer's Sync commit path.  Separate from start()
        so tests can attach without a socket."""
        self.servicer.replication_hook = self.on_sync_committed
        self.servicer.telemetry.metrics.set_replica_role("leader")
        return self

    def start(self) -> "ReplicationPublisher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.servicer.replication_hook is self.on_sync_committed:
            self.servicer.replication_hook = None
        try:
            self._sock.close()
        finally:
            with self._lock:
                subs = list(self._subs)
            for sub in subs:
                sub.close()
            if os.path.exists(self.path):
                try:
                    os.unlink(self.path)
                except OSError:
                    pass

    def follower_count(self) -> int:
        with self._lock:
            return len(self._subs)

    # -- the servicer hook (runs under the servicer's _sync_lock) --
    def on_sync_committed(self, req, snapshot_id: str,
                          wire_bytes=None) -> None:
        """``wire_bytes`` is the client's original frame when the
        transport kept it (the raw-UDS path) — streamed verbatim, the
        "already-encoded delta frames" economics; a None falls back to
        re-serializing the decoded message (gRPC), byte-identical."""
        epoch, gen = _parse_sid(snapshot_id)
        payload = (
            wire_bytes if wire_bytes is not None
            else req.SerializeToString()
        )
        frame = codec.encode_frame(
            codec.KIND_DELTA, epoch, gen,
            int(self._clock() * 1e6), payload,
        )
        self.publish_frame(frame)

    def publish_frame(self, frame_bytes: bytes) -> None:
        """Fan one already-encoded frame out to every subscriber — the
        relay seam (ISSUE 18): a relay follower hands the immutable
        delta bytes it just applied straight here, so re-publication is
        a near-zero-copy byte forward (no decode, no re-encode, same
        epoch fencing at every hop)."""
        with self._lock:
            self.published += 1
            for sub in list(self._subs):
                sub.enqueue(frame_bytes)

    # -- subscription plumbing --
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed by stop()
            try:
                self._register(conn)
            except Exception:  # one bad subscription must not kill the accept loop for every other follower
                logger.exception("replication subscription failed")
                try:
                    conn.close()
                except OSError:
                    pass

    def _read_hello(self, conn: socket.socket):
        """Peek for the subscriber's opening hello frame (bounded wait).
        Returns ``(frame, caps)`` — the decoded position frame plus its
        capability payload bytes — or ``(None, b"")``: no hello within
        the window, or anything unexpected, degrades to the PR-8
        full-frame open, never to a failed subscription.  The window
        is a WHOLE-handshake deadline, not per-recv: this runs on the
        one accept thread, and a peer dribbling bytes must not be able
        to stretch one handshake past ``hello_timeout_s`` total.  A
        payload past the small capability cap is drained and ignored
        (legacy behavior: the payload used to be spec'd empty)."""
        deadline = time.monotonic() + self.hello_timeout_s
        caps = b""
        try:
            buf = b""
            while len(buf) < codec.HEADER_LEN:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None, b""
                conn.settimeout(left)
                chunk = conn.recv(codec.HEADER_LEN - len(buf))
                if not chunk:
                    return None, b""
                buf += chunk
            frame, plen = codec.decode_header(buf)
            if frame.kind != codec.KIND_HELLO:
                return None, b""
            oversized = plen > _MAX_HELLO_CAPS
            while plen > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None, b""
                conn.settimeout(left)
                chunk = conn.recv(min(65536, plen))
                if not chunk:
                    return None, b""
                plen -= len(chunk)
                if not oversized:
                    caps += chunk
            return frame, (b"" if oversized else caps)
        except (socket.timeout, OSError, codec.FrameError):
            return None, b""
        finally:
            try:
                conn.settimeout(None)
            except OSError:
                pass

    def _register(self, conn: socket.socket) -> None:
        """Under the publisher lock: serve the subscription's opening
        state — the journal's missing-delta resume when the follower's
        hello position is covered (ISSUE 11), else the full-state
        export — then admit the subscriber, atomically against the
        fan-out, so no committed delta can slip between the opening
        frames and the subscription (the continuity argument in the
        module docstring; a frame journaled-but-not-yet-fanned-out can
        be enqueued twice, and the follower drops the second as
        stale)."""
        hello, caps = self._read_hello(conn)
        sub = _Subscriber(
            conn, self.queue_frames, self._drop,
            max_batch_bytes=self.max_batch_bytes,
            on_batch=self._observe_batch,
        )
        sub.compress = (
            self.compress_full and codec.CAP_COMPRESS in caps
        )
        resumed = compressed = False
        with self._lock:
            if hello is not None and self.journal is not None:
                frames = self.journal.frames_since(
                    hello.epoch, hello.generation
                )
                if frames is not None and len(frames) >= self.queue_frames:
                    # the resume frames must fit the subscriber's
                    # bounded queue (the drain thread starts after
                    # admission); a follower this far behind resyncs
                    # cheaper with one full frame anyway
                    frames = None
                if frames is not None:
                    for fb in frames:
                        sub.enqueue(fb)
                    resumed = True
                    self.resumed_subscriptions += 1
            if not resumed:
                epoch, gen, payload = (
                    self.servicer.export_replication_snapshot()
                )
                kind = codec.KIND_FULL
                if sub.compress and payload:
                    kind = codec.KIND_FULL_Z
                    payload = codec.compress_payload(payload)
                    self.compressed_fulls += 1
                    compressed = True
                full = codec.encode_frame(
                    kind, epoch, gen,
                    int(self._clock() * 1e6), payload,
                )
                sub.enqueue(full)
            self._subs.append(sub)
            self.subscriptions += 1
            n = len(self._subs)
        sub.start()
        metrics = self.servicer.telemetry.metrics
        metrics.set_replica_followers(n)
        if resumed:
            metrics.count_retry("resume")
        if compressed:
            metrics.count_replica_compress("encode")

    def _observe_batch(self, n_frames: int) -> None:
        """Sender-thread callback: one coalesced send of ``n_frames``
        frames (the frames-per-wakeup distribution)."""
        try:
            self.servicer.telemetry.metrics.observe_send_batch(n_frames)
        except Exception:  # koordlint: disable=broad-except(send-batch accounting is observability; it must never kill a sender)
            pass

    def stats(self) -> dict:
        """Lifetime fan-out stats, including the sender-path batching
        picture (ISSUE 18): ``frames_per_wakeup`` is the mean coalesced
        batch occupancy — 1.0 means the batching never fired (serial
        traffic), climbing under bursty fan-out load as syscalls are
        saved."""
        with self._lock:
            frames = self._retired_frames
            batches = self._retired_batches
            for sub in self._subs:
                frames += sub.sent_frames
                batches += sub.sent_batches
            return {
                "published": self.published,
                "subscriptions": self.subscriptions,
                "resumed_subscriptions": self.resumed_subscriptions,
                "followers": len(self._subs),
                "compressed_fulls": self.compressed_fulls,
                "sent_frames": frames,
                "sent_batches": batches,
                "frames_per_wakeup": (
                    frames / batches if batches else 0.0
                ),
                "max_batch_bytes": self.max_batch_bytes,
            }

    def _drop(self, sub: "_Subscriber") -> None:
        # from the sender thread (no lock) or re-entrantly from an
        # enqueue overflow during the fan-out (RLock)
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                return
            self._retired_frames += sub.sent_frames
            self._retired_batches += sub.sent_batches
            n = len(self._subs)
        try:
            self.servicer.telemetry.metrics.set_replica_followers(n)
        except Exception:  # koordlint: disable=broad-except(gauge update on a dying connection must not mask the drop itself)
            pass
