"""Durable frame journal: crash tolerance for the serving tier (ISSUE 11).

The replication stream already reduces every committed Sync to its
already-encoded wire bytes (replication/codec.py frames — a warm delta
is a few hundred bytes).  Those frames are the perfect durability unit,
so crash tolerance is an append, a replay and a truncate:

* the leader APPENDS every committed frame's encoded bytes to a
  length-prefixed, CRC'd journal file under ``--state-dir`` (the same
  bytes ``ReplicationPublisher`` fans out — encoded once, shared);
* every ``compact_every`` delta frames the journal COMPACTS: the full
  state (``export_sync_request``) is written as one ``kind=full``
  frame into a fresh file that atomically replaces the old one, so the
  journal's size tracks the cluster, not its history;
* on restart the daemon REPLAYS the journal through the existing
  stage/commit seam (``apply_replica_frame`` — the very path follower
  frames take) and resumes the SAME ``s<epoch>-<gen>`` chain, so
  reconnecting clients pass their delta-continuity check and
  reconnecting followers resume from their position (leader.py's hello
  handshake reads :meth:`FrameJournal.frames_since`) — no resync storm;
* a torn or corrupt tail (the crash landed mid-append, a disk flipped
  a bit) TRUNCATES to the last valid record and recovery proceeds from
  there — the daemon never serves a torn snapshot, because every frame
  it replays went through the same stage-then-commit atomicity a live
  frame does.

Record layout (all integers big-endian, like every framing here)::

    length   u32   byte length of the frame that follows
    crc32    u32   zlib.crc32 of the frame bytes
    frame    ...   one replication/codec.py frame (header + payload)

Validation on open walks records until the first invalid one (short
read, absurd length, CRC mismatch, frame decode failure) and truncates
there; tests/test_journal.py drives every negative shape.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.obs.lockwitness import witness_lock
from koordinator_tpu.replication import codec

logger = logging.getLogger(__name__)

_REC_HEADER = ">II"
_REC_HEADER_LEN = struct.calcsize(_REC_HEADER)
_MAX_RECORD = codec.HEADER_LEN + codec.MAX_PAYLOAD

DEFAULT_COMPACT_EVERY = 256


class JournalError(Exception):
    """The journal file cannot be used at all (unreadable directory,
    truncation failed).  A corrupt TAIL is not an error — it is the
    documented truncate-and-recover path."""


class FrameJournal:
    """Append/replay/compact over one journal file.

    Thread contract: ``append``/``compact`` run on the leader's Sync
    path (the servicer calls the hook under its ``_sync_lock``, so
    appends are strictly generation-ordered); ``frames_since`` runs on
    the publisher's subscription path concurrently — everything shared
    sits under one small lock, and resume reads use their own file
    handle so a subscription can never move the append offset.

    ``fsync=True`` makes every append durable against power loss, at a
    per-commit fsync cost; the default ``False`` flushes to the OS
    (durable against process crash — the SIGKILL the chaos harness
    throws — which is the failure mode this tier replicates against;
    machine-loss durability is what the follower tier itself is for).
    """

    def __init__(
        self,
        path: str,
        compact_every: int = DEFAULT_COMPACT_EVERY,
        fsync: bool = False,
        clock=time.time,
    ):
        self.path = path
        self.compact_every = max(1, int(compact_every))
        self.fsync = bool(fsync)
        self._clock = clock
        self._lock = witness_lock("replication.journal.FrameJournal._lock")
        self._fh = None
        self._metrics = None
        self._exporter = None
        # the contiguous resumable chain: the LAST full frame's
        # (epoch, generation) plus every delta extending it, mapped
        # gen -> (offset, record length) for leader.py's delta resume
        self._epoch: Optional[str] = None
        self._base_gen: Optional[int] = None
        self._last_gen: Optional[int] = None
        self._chain: Dict[int, Tuple[int, int]] = {}
        self._end = 0  # append offset == validated-prefix end
        self._deltas_since_compact = 0
        # lifetime stats (healthz + bench feed)
        self.appends = 0
        self.compactions = 0
        self.truncations = 0
        self.replayed_frames = 0
        self.replay_ms: Optional[float] = None
        self.last_append_us: Optional[float] = None
        self.last_compaction_us: Optional[int] = None
        self.last_truncate_reason: Optional[str] = None

    # -- wiring --
    def attach(self, servicer) -> "FrameJournal":
        """Hook the servicer's Sync commit path (`journal_hook`, called
        BEFORE the replication publisher's hook: durability first, then
        fan-out) and adopt its metrics/export seams."""
        servicer.journal_hook = self.on_sync_committed
        self._exporter = servicer.export_replication_snapshot
        telemetry = getattr(servicer, "telemetry", None)
        self._metrics = getattr(telemetry, "metrics", None)
        self._publish_gauges()
        return self

    def detach(self, servicer) -> None:
        if getattr(servicer, "journal_hook", None) is self.on_sync_committed:
            servicer.journal_hook = None

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -- the servicer hook (leader _sync_lock held) --
    def on_sync_committed(self, req, snapshot_id: str, wire_bytes=None) -> None:
        from koordinator_tpu.bridge.client import parse_snapshot_id

        epoch, gen = parse_snapshot_id(snapshot_id)
        payload = (
            wire_bytes if wire_bytes is not None else req.SerializeToString()
        )
        frame = codec.encode_frame(
            codec.KIND_DELTA, epoch, gen, int(self._clock() * 1e6), payload
        )
        self.append_frame(frame, codec.KIND_DELTA, epoch, gen)
        if self._deltas_since_compact >= self.compact_every:
            self._compact_from_exporter()

    # -- appends --
    def append_frame(self, frame: bytes, kind: int, epoch: str,
                     gen: int) -> None:
        t0 = time.perf_counter()
        rec = struct.pack(_REC_HEADER, len(frame), zlib.crc32(frame)) + frame
        with self._lock:
            fh = self._open_locked()
            fh.write(rec)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
            off = self._end
            self._end += len(rec)
            self._track_locked(kind, epoch, gen, off, len(rec))
            if kind == codec.KIND_DELTA:
                self._deltas_since_compact += 1
            self.appends += 1
            self.last_append_us = (time.perf_counter() - t0) * 1e6
        m = self._metrics
        if m is not None:
            m.count_journal("append")
            m.observe_journal_append_us(self.last_append_us)
        self._publish_gauges()

    def write_base(self, epoch: str, gen: int, payload: bytes,
                   stamp_us: Optional[int] = None) -> None:
        """Reset the journal to ONE full-state frame at (epoch, gen) —
        the compaction primitive, also used to seed a fresh journal and
        to open a promoted follower's own journal.  Atomic: the new
        file is written beside the old and ``os.replace``d over it, so
        a crash mid-compaction leaves the previous journal intact."""
        stamp = int(self._clock() * 1e6) if stamp_us is None else stamp_us
        frame = codec.encode_frame(
            codec.KIND_FULL, epoch, gen, stamp, payload
        )
        rec = struct.pack(_REC_HEADER, len(frame), zlib.crc32(frame)) + frame
        tmp = self.path + ".compact"
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            with open(tmp, "wb") as fh:
                fh.write(rec)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
            self._epoch = epoch
            self._base_gen = self._last_gen = gen
            self._chain = {}
            self._end = len(rec)
            self._deltas_since_compact = 0
            self.compactions += 1
            self.last_compaction_us = stamp
        m = self._metrics
        if m is not None:
            m.count_journal("compact")
            m.set_journal_compaction_stamp(stamp)
        self._publish_gauges()

    def _compact_from_exporter(self) -> None:
        if self._exporter is None:
            return
        try:
            epoch, gen, payload = self._exporter()
            self.write_base(epoch, gen, payload)
        except Exception:  # compaction is an optimization of journal SIZE; a failed compaction must cost disk, never the acked write it rides behind
            logger.exception("journal compaction failed; appends continue")

    def _track_locked(self, kind: int, epoch: str, gen: int, off: int,
                      rec_len: int) -> None:
        if kind == codec.KIND_FULL:
            self._epoch = epoch
            self._base_gen = self._last_gen = gen
            self._chain = {}
        elif (
            epoch == self._epoch
            and self._last_gen is not None
            and gen == self._last_gen + 1
        ):
            self._chain[gen] = (off, rec_len)
            self._last_gen = gen
        # anything else (paranoia: an out-of-chain append) stays in the
        # file but outside the resumable chain — replay still handles it

    def _open_locked(self):
        if self._fh is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "ab")
        return self._fh

    # -- introspection --
    def position(self) -> Tuple[Optional[str], Optional[int]]:
        with self._lock:
            return self._epoch, self._last_gen

    def size_bytes(self) -> int:
        with self._lock:
            return self._end

    def stats(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "epoch": self._epoch,
                "generation": self._last_gen,
                "bytes": self._end,
                "appends": self.appends,
                "compactions": self.compactions,
                "truncations": self.truncations,
                "replayed_frames": self.replayed_frames,
                "replay_ms": self.replay_ms,
                "last_append_us": self.last_append_us,
                "last_compaction_us": self.last_compaction_us,
                "last_truncate_reason": self.last_truncate_reason,
                "deltas_since_compact": self._deltas_since_compact,
                "compact_every": self.compact_every,
            }

    def _publish_gauges(self) -> None:
        m = self._metrics
        if m is None:
            return
        with self._lock:
            gen, size = self._last_gen, self._end
        if gen is not None:
            m.set_journal_position(gen)
        m.set_journal_bytes(size)

    # -- scan / recover / resume --
    def _scan(self) -> Tuple[List[Tuple[int, int, "codec.Frame"]], int,
                             Optional[str]]:
        """Validate the file front to back.  Returns
        ``(records, valid_end, bad_reason)`` where ``records`` is
        ``[(offset, record_len, frame), ...]`` for the valid prefix and
        ``bad_reason`` names the first invalid record (None = clean)."""
        records: List[Tuple[int, int, "codec.Frame"]] = []
        try:
            with open(self.path, "rb") as fh:
                data = fh.read()
        except FileNotFoundError:
            return records, 0, None
        off = 0
        while off < len(data):
            if off + _REC_HEADER_LEN > len(data):
                return records, off, "torn-header"
            length, crc = struct.unpack_from(_REC_HEADER, data, off)
            if length < codec.HEADER_LEN or length > _MAX_RECORD:
                return records, off, "bad-length"
            body_start = off + _REC_HEADER_LEN
            if body_start + length > len(data):
                return records, off, "torn-frame"
            frame_bytes = data[body_start:body_start + length]
            if zlib.crc32(frame_bytes) != crc:
                return records, off, "crc"
            try:
                frame = codec.decode_frame(frame_bytes)
            except codec.FrameError:
                return records, off, "decode"
            rec_len = _REC_HEADER_LEN + length
            records.append((off, rec_len, frame))
            off += rec_len
        return records, off, None

    def _truncate_locked(self, end: int, reason: str) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        try:
            with open(self.path, "r+b") as fh:
                fh.truncate(end)
        except FileNotFoundError:
            pass
        except OSError as exc:
            raise JournalError(
                f"cannot truncate journal {self.path} to its valid "
                f"{end}-byte prefix: {exc}"
            ) from exc
        self._end = end
        self.truncations += 1
        self.last_truncate_reason = reason
        logger.warning(
            "journal %s truncated to %d bytes (%s): recovery resumes "
            "from the last valid frame",
            self.path, end, reason,
        )
        if self._metrics is not None:
            self._metrics.count_journal("truncate")

    def recover(self, servicer) -> dict:
        """Replay the journal into ``servicer`` through the stage/commit
        seam and leave the journal open for appends at the end of the
        applied prefix.

        Continuity during replay mirrors the follower applier: a full
        frame resets and (re)bases the chain, a delta extending the
        chain applies, a delta at-or-behind the chain position is a
        STALE no-op kept in place (the compaction-snapshot-newer-than-
        tail shape), and a gap/epoch-jump/apply-failure ends the usable
        prefix — everything from that frame on is truncated away.  A
        missing or empty journal seeds itself with the servicer's
        current full state, so the file ALWAYS begins with a full
        frame."""
        t0 = time.perf_counter()
        records, valid_end, bad = self._scan()
        applied = stale = 0
        resumed_id = None
        stop_reason: Optional[str] = None
        stop_off: Optional[int] = None
        pos: Optional[Tuple[str, int]] = None
        kept: List[Tuple[int, int, int, str, int]] = []
        for off, rec_len, frame in records:
            if frame.kind == codec.KIND_FULL:
                try:
                    servicer.apply_replica_frame(
                        frame, origin="journal_replay"
                    )
                except Exception:  # a frame that fails validation ends the usable prefix — the documented truncate-and-recover path; state is untouched by stage-then-commit
                    logger.exception(
                        "journal full frame %s failed to apply; "
                        "truncating", frame.snapshot_id,
                    )
                    stop_reason, stop_off = "apply", off
                    break
                pos = (frame.epoch, frame.generation)
                applied += 1
            else:
                if pos is None:
                    # a delta with no full base in front of it: the
                    # chain it extends is not in this file
                    stop_reason, stop_off = "no-base", off
                    break
                epoch, gen = pos
                if frame.epoch != epoch or frame.generation > gen + 1:
                    stop_reason, stop_off = "gap", off
                    break
                if frame.generation <= gen:
                    stale += 1  # kept in place, not re-applied
                    kept.append(
                        (off, rec_len, frame.kind, frame.epoch,
                         frame.generation)
                    )
                    continue
                try:
                    # origin names the span a traced frame opens
                    # (ISSUE 14): a replay-on-boot joins the SAME trace
                    # as the leader commit it re-applies
                    servicer.apply_replica_frame(
                        frame, origin="journal_replay"
                    )
                except Exception:  # same truncate-and-recover contract as the full-frame apply above
                    logger.exception(
                        "journal delta frame %s failed to apply; "
                        "truncating", frame.snapshot_id,
                    )
                    stop_reason, stop_off = "apply", off
                    break
                pos = (frame.epoch, frame.generation)
                applied += 1
            kept.append(
                (off, rec_len, frame.kind, frame.epoch, frame.generation)
            )
        with self._lock:
            if stop_off is not None:
                self._truncate_locked(stop_off, stop_reason)
            elif bad is not None:
                self._truncate_locked(valid_end, bad)
            else:
                self._end = valid_end
            # rebuild the resumable chain from the kept prefix
            self._epoch = self._base_gen = self._last_gen = None
            self._chain = {}
            self._deltas_since_compact = 0
            for off, rec_len, kind, epoch, gen in kept:
                self._track_locked(kind, epoch, gen, off, rec_len)
                if kind == codec.KIND_DELTA:
                    self._deltas_since_compact += 1
        truncated = stop_reason if stop_reason is not None else bad
        self.replayed_frames = applied
        self.replay_ms = (time.perf_counter() - t0) * 1000.0
        if pos is None:
            # nothing usable (fresh journal, or unusable from byte 0):
            # seed with the servicer's CURRENT state so the file starts
            # with a full frame and the chain is live immediately
            epoch, gen, payload = servicer.export_replication_snapshot()
            self.write_base(epoch, gen, payload)
        elif truncated is not None:
            # the truncated tail frames may already have been PUBLISHED
            # before the crash: resuming the identical chain would
            # re-mint those generation numbers with different content —
            # a fork the epoch fence cannot see.  Rebase onto a fresh
            # epoch (clients/followers take the ordinary fenced
            # one-shot full resync) and compact the journal to match.
            rebase = getattr(servicer, "rebase_epoch", None)
            if rebase is not None:
                rebase()
            epoch, gen, payload = servicer.export_replication_snapshot()
            self.write_base(epoch, gen, payload)
        if applied:
            resumed_id = servicer.snapshot_id()
        m = self._metrics
        if m is not None and applied:
            m.count_journal("replay", applied)
        self._publish_gauges()
        return {
            "replayed_frames": applied,
            "stale_frames": stale,
            "replay_ms": self.replay_ms,
            "resumed_id": resumed_id,
            "truncated": truncated,
        }

    def frames_since(self, epoch: str, generation: int,
                     limit_bytes: int = 256 << 20) -> Optional[List[bytes]]:
        """The delta frames extending ``(epoch, generation)`` up to the
        journal's position, as encoded frame bytes — the leader's
        resume answer to a follower hello.  ``None`` means the journal
        cannot bridge that position (different epoch, position before
        the last compaction base, or ahead of the chain) and the caller
        must fall back to the full-frame subscription open."""
        with self._lock:
            if (
                self._epoch != epoch
                or self._base_gen is None
                or generation < self._base_gen
                or generation > (self._last_gen or -1)
            ):
                return None
            wanted = [
                self._chain[g]
                for g in range(generation + 1, self._last_gen + 1)
                if g in self._chain
            ]
            if len(wanted) != (self._last_gen - generation):
                return None  # chain hole (should not happen)
        out: List[bytes] = []
        total = 0
        want_gen = generation + 1
        try:
            with open(self.path, "rb") as fh:
                for off, rec_len in wanted:
                    fh.seek(off + _REC_HEADER_LEN)
                    frame = fh.read(rec_len - _REC_HEADER_LEN)
                    if len(frame) != rec_len - _REC_HEADER_LEN:
                        return None
                    # re-validate AFTER the read: a concurrent
                    # compaction os.replace()s the file, so an offset
                    # computed against the old file can resolve into
                    # the new one's bytes — a frame that does not
                    # decode to exactly the chain entry the index
                    # promised must never reach a subscriber
                    try:
                        decoded = codec.decode_frame(frame)
                    except codec.FrameError:
                        return None
                    if (
                        decoded.kind != codec.KIND_DELTA
                        or decoded.epoch != epoch
                        or decoded.generation != want_gen
                    ):
                        return None
                    want_gen += 1
                    total += len(frame)
                    if total > limit_bytes:
                        return None
                    out.append(frame)
        except OSError:
            return None
        return out


class RelayFrameCache:
    """In-memory ``frames_since`` for a relay (ISSUE 18).

    A relay re-publishes its parent's delta stream on its own ``.repl``
    socket, and its DESCENDANTS resume through the same hello handshake
    a leader serves from its :class:`FrameJournal`.  A relay has no
    journal (durability lives at the root; a relay is a fan-out
    amplifier), so this cache keeps the recent raw delta frame bytes —
    the exact bytes forwarded, still byte-identical to the root's — in
    a bounded in-memory window and answers :meth:`frames_since` with
    :class:`FrameJournal`-identical semantics: ``None`` whenever the
    window cannot bridge the offered position (the caller falls back to
    the full-frame subscription open, served from the relay's own
    state).

    ``note_full(epoch, gen)`` rebases the window on every full frame
    the relay APPLIES (a full resets the chain exactly as a compaction
    base does); ``add_delta`` extends it and evicts from the front once
    ``max_bytes`` is exceeded — an evicted position simply resumes via
    the full-frame open.  Thread contract: the relay's one subscriber
    pump thread writes, the relay publisher's subscription threads read
    ``frames_since`` concurrently — one small lock covers both."""

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = max(1, int(max_bytes))
        self._lock = witness_lock(
            "replication.journal.RelayFrameCache._lock")
        self._epoch: Optional[str] = None
        self._base_gen: Optional[int] = None
        self._last_gen: Optional[int] = None
        self._frames: Dict[int, bytes] = {}
        self._bytes = 0
        self.evictions = 0

    def note_full(self, epoch: str, generation: int) -> None:
        """A full frame applied at (epoch, generation): everything
        cached belongs to a superseded prefix — rebase the window."""
        with self._lock:
            self._epoch = epoch
            self._base_gen = self._last_gen = int(generation)
            self._frames = {}
            self._bytes = 0

    def add_delta(self, epoch: str, generation: int,
                  raw_frame: bytes) -> None:
        """One APPLIED delta's exact wire bytes.  A frame that does not
        extend the cached chain rebases the window onto it (the relay's
        own applier already continuity-checked it — the cache only
        mirrors positions the relay actually holds)."""
        gen = int(generation)
        with self._lock:
            if (
                epoch != self._epoch
                or self._last_gen is None
                or gen != self._last_gen + 1
            ):
                self._epoch = epoch
                self._base_gen = gen - 1
                self._frames = {}
                self._bytes = 0
            self._frames[gen] = raw_frame
            self._bytes += len(raw_frame)
            self._last_gen = gen
            while self._bytes > self.max_bytes and self._frames:
                first = min(self._frames)
                self._bytes -= len(self._frames.pop(first))
                self._base_gen = first
                self.evictions += 1

    def position(self) -> Tuple[Optional[str], Optional[int]]:
        with self._lock:
            return self._epoch, self._last_gen

    def frames_since(self, epoch: str, generation: int,
                     limit_bytes: int = 256 << 20) -> Optional[List[bytes]]:
        """:meth:`FrameJournal.frames_since` over the in-memory window;
        the signature matches so leader.py's hello/resume path takes
        either interchangeably."""
        with self._lock:
            if (
                self._epoch != epoch
                or self._base_gen is None
                or generation < self._base_gen
                or generation > (self._last_gen or -1)
            ):
                return None
            out: List[bytes] = []
            total = 0
            for g in range(generation + 1, self._last_gen + 1):
                frame = self._frames.get(g)
                if frame is None:
                    return None  # window hole (should not happen)
                total += len(frame)
                if total > limit_bytes:
                    return None
                out.append(frame)
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "base_generation": self._base_gen,
                "generation": self._last_gen,
                "frames": len(self._frames),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "evictions": self.evictions,
            }
