"""ElasticQuota runtime fair division.

Host-side exact mirror of the reference's RuntimeQuotaCalculator
(``pkg/scheduler/plugins/elasticquota/core/runtime_quota_calculator.go``):

* ``redistribution`` (:109-141): each group's runtime starts at
  ``min(max(min, guarantee), request)`` — groups requesting more than their
  (auto-scaled) min are capped at min and share the leftover by
  ``sharedWeight``; groups under min keep ``request`` if they lend unused
  quota (``allowLentResource``), else their full min.
* ``iterationForRedistribution`` (:143-155): leftover is split
  proportionally to sharedWeight, iterating until no group is left short or
  nothing remains (surplus handed back by satisfied groups re-enters).

The division runs per resource dimension over a flat list of sibling groups
(one quotaTree per resource, as in the reference).  The result feeds the
device-side ``QuotaTable.runtime`` caps used as admission masks; the
stateful tree itself stays host-side, exactly like the reference keeps it in
the GroupQuotaManager (``core/group_quota_manager.go:35``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Mapping, Sequence

from koordinator_tpu.model import resources as res


@dataclasses.dataclass
class QuotaGroup:
    """One ElasticQuota group (a child of a single parent in the tree)."""

    name: str
    min: List[int]  # dense resource vector
    max: List[int]
    request: List[int]  # current demand (sum of member pod requests)
    used: List[int]
    shared_weight: int = 1
    guarantee: List[int] = dataclasses.field(
        default_factory=lambda: [0] * res.NUM_RESOURCES
    )
    allow_lent_resource: bool = True
    # resource dims the quota spec declares (indices into RESOURCE_AXIS);
    # admission applies only to these.  A declared dim with runtime 0
    # admits nothing: the reference's RefreshRuntime emits declared dims
    # with an explicit 0 that quotav1.LessThanOrEqual then compares
    # against (undeclared dims are simply absent and fall open).
    declared: List[int] = dataclasses.field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "QuotaGroup":
        def vec(key):
            return res.resource_vector(d.get(key, {}) or {})

        declared = sorted(
            {
                res.RESOURCE_INDEX[name]
                for key in ("min", "max")
                for name in (d.get(key, {}) or {})
                if name in res.RESOURCE_INDEX
            }
        )
        return cls(
            name=d["name"],
            min=vec("min"),
            max=vec("max"),
            request=vec("request"),
            used=vec("used"),
            shared_weight=int(d.get("shared_weight", 1)),
            guarantee=vec("guarantee"),
            allow_lent_resource=bool(d.get("allow_lent_resource", True)),
            declared=declared,
        )


def _redistribute_one_resource(
    groups: Sequence[QuotaGroup], r: int, total: int
) -> List[int]:
    """runtime_quota_calculator.go:109-141, one resource dimension."""
    runtime = [0] * len(groups)
    to_partition = total
    total_shared_weight = 0
    need_adjust: List[int] = []
    for i, g in enumerate(groups):
        gmin = max(g.min[r], g.guarantee[r])
        request = min(g.request[r], g.max[r])  # request never exceeds max
        if request > gmin:
            need_adjust.append(i)
            total_shared_weight += g.shared_weight
            runtime[i] = gmin
        else:
            runtime[i] = request if g.allow_lent_resource else gmin
        to_partition -= runtime[i]

    # iterationForRedistribution (:143-155)
    while to_partition > 0 and total_shared_weight > 0 and need_adjust:
        still_short: List[int] = []
        next_weight = 0
        surplus = 0
        for i in need_adjust:
            g = groups[i]
            delta = int(
                math.floor(g.shared_weight * to_partition / total_shared_weight + 0.5)
            )
            runtime[i] += delta
            request = min(g.request[r], g.max[r])
            if runtime[i] < request:
                still_short.append(i)
                next_weight += g.shared_weight
            else:
                surplus += runtime[i] - request
                runtime[i] = request
        to_partition = surplus
        total_shared_weight = next_weight
        need_adjust = still_short

    # runtime never exceeds max
    for i, g in enumerate(groups):
        runtime[i] = min(runtime[i], g.max[r])
    return runtime


def refresh_runtime(
    groups: Sequence[QuotaGroup], total_resource: Sequence[int]
) -> List[List[int]]:
    """Compute each sibling group's runtimeQuota vector for the given total.

    ``total_resource`` is the parent's distributable quantity per resource
    (cluster total for root-level trees).
    """
    runtimes = [[0] * res.NUM_RESOURCES for _ in groups]
    for r in range(res.NUM_RESOURCES):
        if total_resource[r] == 0 and not any(g.request[r] for g in groups):
            continue
        col = _redistribute_one_resource(groups, r, int(total_resource[r]))
        for i, v in enumerate(col):
            runtimes[i][r] = v
    return runtimes


def build_quota_table_inputs(
    quota_dicts: Sequence[Mapping],
    pod_requests: Sequence[Sequence[int]],
    pod_quota_ids: Sequence[int],
    total_resource: Sequence[int],
) -> List[Dict]:
    """Aggregate per-group demand, run fair division, emit encode_snapshot
    quota dicts with dense ``runtime``/``used`` vectors.
    """
    groups = [QuotaGroup.from_dict(d) for d in quota_dicts]
    for req, qid in zip(pod_requests, pod_quota_ids):
        if 0 <= qid < len(groups):
            for r in range(res.NUM_RESOURCES):
                groups[qid].request[r] += req[r]
    runtimes = refresh_runtime(groups, total_resource)
    out = []
    for g, rt in zip(groups, runtimes):
        # Emit every *declared* dimension, including runtime 0: the
        # reference's RefreshRuntime keeps declared dims with explicit
        # zeros, so admission rejects on them; only undeclared dims are
        # absent from the runtime list and fall open.
        limited = set(g.declared) | {r for r in range(res.NUM_RESOURCES) if rt[r]}
        out.append(
            {
                "name": g.name,
                # values are in axis units already; render them as
                # round-trippable quantities ("...Mi"/"...m") so
                # encode_snapshot's parse_quantity doesn't re-divide
                # byte-denominated lanes by MiB
                "runtime": {
                    res.RESOURCE_AXIS[r]: res.format_quantity(
                        rt[r], res.RESOURCE_AXIS[r]
                    )
                    for r in sorted(limited)
                },
                "limited": [res.RESOURCE_AXIS[r] for r in sorted(limited)],
                "used": {
                    res.RESOURCE_AXIS[r]: res.format_quantity(
                        g.used[r], res.RESOURCE_AXIS[r]
                    )
                    for r in range(res.NUM_RESOURCES)
                    if g.used[r]
                },
            }
        )
    return out
