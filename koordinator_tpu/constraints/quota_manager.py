"""Hierarchical ElasticQuota management: quota tree, min scaling, multi-tree.

Host-side control plane mirroring the reference's GroupQuotaManager
(``pkg/scheduler/plugins/elasticquota/core/group_quota_manager.go:35``):
the stateful tree lives here; each scheduling cycle flattens the current
leaf runtimes into the device-side ``QuotaTable`` admission masks
(constraints/quota.py ``build_quota_table_inputs``).

Semantics mirrored (citations into /root/reference):

* request/used aggregation up the tree with limit-request clamping and the
  no-lend min floor (``group_quota_manager.go:184 recursiveUpdateGroup
  TreeWithDeltaRequest``, ``quota_info.go:193 getLimitRequestNoLock``) —
  implemented as a bottom-up recompute, which converges to the same fixed
  point as the reference's delta propagation;
* cluster total excludes the system/default groups' used
  (``group_quota_manager.go:120 updateClusterTotalResourceNoLock``);
* per-level runtime refresh walking root->leaf, feeding each level's
  runtime as the next level's distributable total
  (``group_quota_manager.go:264 RefreshRuntimeNoLock``), with the sibling
  fair division from constraints/quota.py (``runtime_quota_calculator.go``);
* min-quota scaling when the children's min sum exceeds the (shrunken)
  total (``core/scale_minquota_when_over_root_res.go``);
* multi quota tree: one independent manager per tree id plus the default
  manager (``plugin.go ListGroupQuotaManagersForQuotaTree``, feature gate
  MultiQuotaTree in ``pkg/features/features.go``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from koordinator_tpu.constraints.quota import QuotaGroup, refresh_runtime
from koordinator_tpu.model import resources as res

R = res.NUM_RESOURCES

# reference apis/extension/elastic_quota.go:28-32
ROOT_QUOTA = "koordinator-root-quota"
SYSTEM_QUOTA = "koordinator-system-quota"
DEFAULT_QUOTA = "koordinator-default-quota"


def _zeros() -> List[int]:
    return [0] * R


def _add(a: Sequence[int], b: Sequence[int]) -> List[int]:
    return [x + y for x, y in zip(a, b)]


def _sub_nonneg(a: Sequence[int], b: Sequence[int]) -> List[int]:
    return [max(0, x - y) for x, y in zip(a, b)]


def _min_vec(a: Sequence[int], b: Sequence[int]) -> List[int]:
    return [min(x, y) for x, y in zip(a, b)]


class ScaleMinQuota:
    """core/scale_minquota_when_over_root_res.go:36 ScaleMinQuotaManager.

    Tracks, per parent, the min-quota sums of scaling-enabled and
    scaling-disabled children; when the distributable total drops below the
    combined min sum, enabled children's mins shrink proportionally while
    disabled children keep theirs (:99 getScaledMinQuota).
    """

    def __init__(self):
        self.enable_sums: Dict[str, List[int]] = {}
        self.disable_sums: Dict[str, List[int]] = {}
        self.original_min: Dict[str, List[int]] = {}
        self.enabled: Dict[str, bool] = {}
        # the parent each sub's min is currently registered under — a
        # re-parented quota must be subtracted from its OLD parent's sums,
        # not the new one's (:58 keys sums by the prior parent)
        self.parent_of: Dict[str, str] = {}

    def _unregister(self, sub: str) -> None:
        old_parent = self.parent_of.get(sub)
        if old_parent is None:
            return
        target = self.enable_sums if self.enabled[sub] else self.disable_sums
        target[old_parent] = _sub_nonneg(
            target[old_parent], self.original_min[sub]
        )

    def update(
        self, parent: str, sub: str, min_quota: Sequence[int], enable: bool
    ) -> None:
        """:58 update — move the child's min between the two parent sums."""
        self.enable_sums.setdefault(parent, _zeros())
        self.disable_sums.setdefault(parent, _zeros())
        self._unregister(sub)
        target = self.enable_sums if enable else self.disable_sums
        target[parent] = _add(target[parent], list(min_quota))
        self.original_min[sub] = list(min_quota)
        self.enabled[sub] = enable
        self.parent_of[sub] = parent

    def remove(self, sub: str) -> None:
        """Drop a deleted quota's contribution (delete path of :58)."""
        self._unregister(sub)
        self.original_min.pop(sub, None)
        self.enabled.pop(sub, None)
        self.parent_of.pop(sub, None)

    def get_scaled_min(
        self, new_total: Optional[Sequence[int]], parent: str, sub: str
    ) -> Tuple[bool, Optional[List[int]]]:
        """:99 getScaledMinQuota."""
        if new_total is None or sub not in self.original_min:
            return False, None
        if parent not in self.disable_sums or parent not in self.enable_sums:
            return False, None
        if not self.enabled[sub]:
            return False, None
        enable_sum = self.enable_sums[parent]
        disable_sum = self.disable_sums[parent]
        need_scale = [
            r
            for r in range(R)
            if new_total[r] < enable_sum[r] + disable_sum[r]
        ]
        original = self.original_min[sub]
        if not need_scale:
            return True, list(original)
        new_min = list(original)
        for r in need_scale:
            avail = new_total[r] - disable_sum[r]
            if avail <= 0:
                new_min[r] = 0
            elif enable_sum[r] > 0:
                # Go truncates: int64(float64(avail) * orig / enableSum)
                new_min[r] = int(avail * original[r] / enable_sum[r])
            else:
                new_min[r] = 0
        return True, new_min


@dataclasses.dataclass
class QuotaNode:
    """core/quota_info.go QuotaInfo analog (dense vectors, host-side)."""

    name: str
    parent: str = ROOT_QUOTA
    is_parent: bool = False
    allow_lent_resource: bool = True
    enable_min_quota_scale: bool = False
    shared_weight: int = 1
    min: List[int] = dataclasses.field(default_factory=_zeros)
    max: List[int] = dataclasses.field(default_factory=lambda: [1 << 60] * R)
    auto_scale_min: List[int] = dataclasses.field(default_factory=_zeros)
    guarantee: List[int] = dataclasses.field(default_factory=_zeros)
    # aggregates
    request: List[int] = dataclasses.field(default_factory=_zeros)
    child_request: List[int] = dataclasses.field(default_factory=_zeros)
    used: List[int] = dataclasses.field(default_factory=_zeros)
    non_preemptible_used: List[int] = dataclasses.field(default_factory=_zeros)
    runtime: List[int] = dataclasses.field(default_factory=_zeros)
    declared: List[int] = dataclasses.field(default_factory=list)
    # leaf pod cache: name -> pod mapping (with "requests", "priority",
    # "non_preemptible", "start_time"); assigned tracked separately like
    # quota_info.go:393 UpdatePodIsAssigned
    pods: Dict[str, Mapping] = dataclasses.field(default_factory=dict)
    assigned: Dict[str, bool] = dataclasses.field(default_factory=dict)

    def limit_request(self) -> List[int]:
        """quota_info.go:193 — request clamped to max."""
        return _min_vec(self.request, self.max)

    @classmethod
    def from_dict(cls, d: Mapping) -> "QuotaNode":
        def vec(key, default=None):
            v = d.get(key)
            if v is None:
                return default() if default else None
            return res.resource_vector(v)

        declared = sorted(
            {
                res.RESOURCE_INDEX[name]
                for key in ("min", "max")
                for name in (d.get(key) or {})
                if name in res.RESOURCE_INDEX
            }
        )
        node = cls(
            name=d["name"],
            parent=d.get("parent") or ROOT_QUOTA,
            is_parent=bool(d.get("is_parent", False)),
            allow_lent_resource=bool(d.get("allow_lent_resource", True)),
            enable_min_quota_scale=bool(d.get("enable_min_quota_scale", False)),
            shared_weight=int(d.get("shared_weight", 1)),
            declared=declared,
        )
        m = vec("min")
        if m is not None:
            node.min = m
            node.auto_scale_min = list(m)
        if d.get("max") is not None:
            # dims the max spec does not declare stay UNLIMITED (the
            # reference masks runtime to declared max dims, quota_info.go:334
            # — a dense zero would instead clamp undeclared dims shut)
            for idx, v in res.encode_resource_list(d["max"]).items():
                node.max[idx] = v
        g = vec("guarantee")
        if g is not None:
            node.guarantee = g
        return node


class GroupQuotaManager:
    """One quota tree (group_quota_manager.go:35)."""

    def __init__(self, tree_id: str = "", scale_min_enabled: bool = True):
        self.tree_id = tree_id
        self.scale_min_enabled = scale_min_enabled
        self.cluster_total: List[int] = _zeros()
        self.nodes: Dict[str, QuotaNode] = {}
        self.scale_min = ScaleMinQuota()
        self._children: Dict[str, List[str]] = {}

    # -- topology ----------------------------------------------------------
    def update_quota(self, quota: Mapping, is_delete: bool = False) -> None:
        name = quota["name"]
        if is_delete:
            if self.nodes.pop(name, None) is not None:
                self.scale_min.remove(name)
        else:
            node = QuotaNode.from_dict(quota)
            old = self.nodes.get(name)
            if old is not None:
                node.pods, node.assigned = old.pods, old.assigned
            self.nodes[name] = node
            self.scale_min.update(
                node.parent, name, node.min, node.enable_min_quota_scale
            )
        self._rebuild()

    def _rebuild(self) -> None:
        """buildSubParGroupTopoNoLock (:425): recompute the child lists and
        re-aggregate all request/used bottom-up."""
        self._children = {}
        for node in self.nodes.values():
            self._children.setdefault(node.parent, []).append(node.name)
        self._recompute_aggregates()

    def children_of(self, name: str) -> List[QuotaNode]:
        return [self.nodes[c] for c in sorted(self._children.get(name, ()))]

    def _depth_order(self) -> List[QuotaNode]:
        """Nodes deepest-first (leaves before parents)."""

        def depth(n: QuotaNode) -> int:
            d = 0
            seen = set()
            while n.parent != ROOT_QUOTA and n.parent in self.nodes:
                if n.parent in seen:
                    break  # defensive: cycles are validation errors
                seen.add(n.parent)
                n = self.nodes[n.parent]
                d += 1
            return d

        return sorted(self.nodes.values(), key=depth, reverse=True)

    def _recompute_aggregates(self) -> None:
        """Fixed point of recursiveUpdateGroupTreeWithDeltaRequest (:184)
        and updateGroupDeltaUsedNoLock (:227), recomputed bottom-up."""
        for node in self._depth_order():
            kids = self.children_of(node.name)
            if kids:
                child_request = _zeros()
                used = _zeros()
                npu = _zeros()
                for k in kids:
                    child_request = _add(child_request, k.limit_request())
                    used = _add(used, k.used)
                    npu = _add(npu, k.non_preemptible_used)
                node.child_request = child_request
                node.used = used
                node.non_preemptible_used = npu
                request = list(child_request)
            else:
                reqs = [res.resource_vector(p.get("requests") or {}) for p in node.pods.values()]
                request = _zeros()
                for v in reqs:
                    request = _add(request, v)
                node.child_request = list(request)
                used = _zeros()
                npu = _zeros()
                for pname, p in node.pods.items():
                    if node.assigned.get(pname):
                        v = res.resource_vector(p.get("requests") or {})
                        used = _add(used, v)
                        if p.get("non_preemptible"):
                            npu = _add(npu, v)
                node.used = used
                node.non_preemptible_used = npu
            if not node.allow_lent_resource:
                # no-lend groups always request at least their min (:196)
                request = [max(a, b) for a, b in zip(request, node.min)]
            node.request = request

    # -- pods --------------------------------------------------------------
    def on_pod_add(self, quota_name: str, pod: Mapping, assigned: bool = False):
        node = self._leaf(quota_name)
        node.pods[pod["name"]] = pod
        if assigned:
            node.assigned[pod["name"]] = True
        self._recompute_aggregates()

    def on_pod_delete(self, quota_name: str, pod_name: str) -> None:
        node = self._leaf(quota_name)
        node.pods.pop(pod_name, None)
        node.assigned.pop(pod_name, None)
        self._recompute_aggregates()

    def update_pod_assigned(self, quota_name: str, pod_name: str, assigned: bool):
        node = self._leaf(quota_name)
        if pod_name not in node.pods:
            raise KeyError(f"pod {pod_name} not cached in quota {quota_name}")
        node.assigned[pod_name] = assigned
        self._recompute_aggregates()

    def migrate_pod(self, pod_name: str, out: str, in_: str) -> None:
        """group_quota_manager.go:684 MigratePod."""
        src = self._leaf(out)
        pod = src.pods.get(pod_name)
        if pod is None:
            return
        assigned = src.assigned.get(pod_name, False)
        src.pods.pop(pod_name)
        src.assigned.pop(pod_name, None)
        dst = self._leaf(in_)
        dst.pods[pod_name] = pod
        if assigned:
            dst.assigned[pod_name] = True
        self._recompute_aggregates()

    def _leaf(self, quota_name: str) -> QuotaNode:
        node = self.nodes.get(quota_name)
        if node is None:
            node = self.nodes.get(DEFAULT_QUOTA)
            if node is None:
                node = QuotaNode(name=DEFAULT_QUOTA)
                self.nodes[DEFAULT_QUOTA] = node
                self._rebuild()
        return node

    # -- totals / runtime --------------------------------------------------
    def set_cluster_total(self, total: Sequence[int]) -> None:
        self.cluster_total = list(total)

    def total_except_system_default_used(self) -> List[int]:
        """group_quota_manager.go:120: total minus system+default used."""
        sys_used = _zeros()
        for special in (SYSTEM_QUOTA, DEFAULT_QUOTA):
            node = self.nodes.get(special)
            if node is not None:
                sys_used = _add(sys_used, node.used)
        return [t - u for t, u in zip(self.cluster_total, sys_used)]

    def _chain(self, name: str) -> List[QuotaNode]:
        """cur -> ... -> top-level (children of root), leaf first (:334)."""
        chain = []
        cur = self.nodes[name]
        while True:
            chain.append(cur)
            if cur.parent == ROOT_QUOTA or cur.parent not in self.nodes:
                return chain
            cur = self.nodes[cur.parent]

    def refresh_runtime(self, name: str) -> Optional[List[int]]:
        """group_quota_manager.go:264 RefreshRuntimeNoLock."""
        node = self.nodes.get(name)
        if node is None:
            return None
        if name == ROOT_QUOTA:
            return self.total_except_system_default_used()
        if name in (SYSTEM_QUOTA, DEFAULT_QUOTA):
            return list(node.max)
        chain = self._chain(name)
        total = self.total_except_system_default_used()
        for cur in reversed(chain):  # top level down to the leaf
            if self.scale_min_enabled:
                need, scaled = self.scale_min.get_scaled_min(
                    total, cur.parent, cur.name
                )
                if need and scaled is not None:
                    cur.auto_scale_min = scaled
            siblings = self.children_of(cur.parent)
            groups = [
                QuotaGroup(
                    name=s.name,
                    min=list(s.auto_scale_min),
                    max=list(s.max),
                    request=s.limit_request(),
                    used=list(s.used),
                    shared_weight=s.shared_weight,
                    guarantee=list(s.guarantee),
                    allow_lent_resource=s.allow_lent_resource,
                )
                for s in siblings
            ]
            runtimes = refresh_runtime(groups, total)
            for s, rt in zip(siblings, runtimes):
                s.runtime = rt
            total = next(
                rt for s, rt in zip(siblings, runtimes) if s.name == cur.name
            )
        # masked runtime: only dims the quota declares a max for
        # (quota_info.go:334); undeclared dims fall open host-side too
        return list(self.nodes[name].runtime)

    def leaf_quota_table(
        self, leaf_names: Sequence[str]
    ) -> List[Dict]:
        """Flatten current leaf runtimes into encode_snapshot quota dicts —
        the tree's cycle-facing output (device admission masks)."""
        out = []
        for name in leaf_names:
            node = self.nodes.get(name)
            if node is None:
                continue
            rt = self.refresh_runtime(name)
            limited = set(node.declared) | {r for r in range(R) if rt[r]}
            out.append(
                {
                    "name": name,
                    "runtime": {
                        res.RESOURCE_AXIS[r]: res.format_quantity(
                            rt[r], res.RESOURCE_AXIS[r]
                        )
                        for r in sorted(limited)
                    },
                    "limited": [res.RESOURCE_AXIS[r] for r in sorted(limited)],
                    "used": {
                        res.RESOURCE_AXIS[r]: res.format_quantity(
                            node.used[r], res.RESOURCE_AXIS[r]
                        )
                        for r in range(R)
                        if node.used[r]
                    },
                }
            )
        return out


class MultiTreeQuotaManager:
    """Default manager plus one independent manager per quota tree id
    (plugin.go ListGroupQuotaManagersForQuotaTree; MultiQuotaTree feature,
    reference pkg/features/features.go)."""

    def __init__(self, scale_min_enabled: bool = True):
        self.scale_min_enabled = scale_min_enabled
        self.default = GroupQuotaManager("", scale_min_enabled)
        self.trees: Dict[str, GroupQuotaManager] = {}

    def manager_for(self, tree_id: str = "") -> GroupQuotaManager:
        if not tree_id:
            return self.default
        if tree_id not in self.trees:
            self.trees[tree_id] = GroupQuotaManager(
                tree_id, self.scale_min_enabled
            )
        return self.trees[tree_id]

    def update_quota(self, quota: Mapping, is_delete: bool = False) -> None:
        self.manager_for(quota.get("tree", "")).update_quota(quota, is_delete)

    def managers(self) -> List[GroupQuotaManager]:
        return [self.default, *self.trees.values()]

    def all_quota_names(self) -> Dict[str, GroupQuotaManager]:
        out: Dict[str, GroupQuotaManager] = {}
        for mgr in self.managers():
            for name in mgr.nodes:
                if name in (ROOT_QUOTA, SYSTEM_QUOTA, DEFAULT_QUOTA):
                    continue
                out[name] = mgr
        return out
