from koordinator_tpu.constraints.quota import (  # noqa: F401
    QuotaGroup,
    refresh_runtime,
    build_quota_table_inputs,
)
from koordinator_tpu.constraints.gang import gang_satisfaction  # noqa: F401
from koordinator_tpu.constraints.quota_manager import (  # noqa: F401
    DEFAULT_QUOTA,
    GroupQuotaManager,
    MultiTreeQuotaManager,
    QuotaNode,
    ROOT_QUOTA,
    SYSTEM_QUOTA,
    ScaleMinQuota,
)
from koordinator_tpu.constraints.gang_manager import (  # noqa: F401
    GANG_MODE_NONSTRICT,
    GANG_MODE_STRICT,
    Gang,
    PERMIT_SUCCESS,
    PERMIT_WAIT,
    PodGroupController,
    PodGroupManager,
)
from koordinator_tpu.constraints.quota_enforce import (  # noqa: F401
    NodeVictims,
    QuotaOverUsedGroupMonitor,
    QuotaOverUsedRevokeController,
    can_preempt,
    pick_preemption_node,
    select_victims_on_node,
)
