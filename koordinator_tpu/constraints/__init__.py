from koordinator_tpu.constraints.quota import (  # noqa: F401
    QuotaGroup,
    refresh_runtime,
    build_quota_table_inputs,
)
from koordinator_tpu.constraints.gang import gang_satisfaction  # noqa: F401
