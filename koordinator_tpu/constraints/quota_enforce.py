"""ElasticQuota enforcement: overuse revocation and quota preemption.

Round 1 only *admitted* pods against runtime caps; this module adds the
reclaim half (citations into /root/reference):

* ``QuotaOverUsedRevokeController`` — watches every quota across all trees;
  when a group's used exceeds its runtime continuously for longer than the
  configured delay, evicts the smallest set of its lowest-priority pods
  that brings used back under runtime
  (``pkg/scheduler/plugins/elasticquota/quota_overuse_revoke.go``).
* ``select_victims_on_node`` / ``pick_preemption_node`` — the PostFilter
  preemption path: a pod rejected by quota admission may preempt
  lower-priority pods of the SAME quota group
  (``pkg/scheduler/plugins/elasticquota/preempt.go:283 canPreempt``,
  ``:111 SelectVictimsOnNode``).

Pods are plain mappings ({"name", "priority", "requests", "start_time",
"non_preemptible"}); node feasibility is exact integer fit over the dense
resource axis, so the victim sets match what the reference computes from
NodeInfo.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.constraints.quota_manager import (
    DEFAULT_QUOTA,
    MultiTreeQuotaManager,
)
from koordinator_tpu.model import resources as res

R = res.NUM_RESOURCES


def _req(pod: Mapping) -> List[int]:
    return res.resource_vector(pod.get("requests") or {})


def more_important_pod(a: Mapping, b: Mapping) -> bool:
    """k8s scheduler util.MoreImportantPod: higher priority wins; ties go
    to the earlier-started pod (used by both revoke and preemption)."""
    pa, pb = int(a.get("priority") or 0), int(b.get("priority") or 0)
    if pa != pb:
        return pa > pb
    return float(a.get("start_time") or 0) < float(b.get("start_time") or 0)


def _importance_key(pod: Mapping):
    # sort key equivalent of more_important_pod, ascending = least first
    return (int(pod.get("priority") or 0), -float(pod.get("start_time") or 0))


def _less_equal(used: Sequence[int], runtime: Sequence[int], dims=None) -> bool:
    if dims is not None:
        return all(used[r] <= runtime[r] for r in dims)
    return all(u <= r for u, r in zip(used, runtime))


def _constraining_dims(declared: Sequence[int], runtime: Sequence[int]):
    """The dims the over-use check compares: declared ones plus any with a
    nonzero runtime — an undeclared dim whose cluster total is zero must not
    constrain, or the revoke target is unreachable and every preemptible pod
    gets evicted (the same mask monitor() uses for its over check)."""
    return sorted(set(declared) | {r for r in range(R) if runtime[r]})


# ---------------------------------------------------------------------------
# Overuse revocation (quota_overuse_revoke.go)
# ---------------------------------------------------------------------------


class QuotaOverUsedGroupMonitor:
    """quota_overuse_revoke.go:45 — per-quota debounce + victim selection."""

    def __init__(
        self,
        quota_name: str,
        manager,
        trigger_evict_duration: float,
        now: float = 0.0,
    ):
        self.quota_name = quota_name
        self.manager = manager
        self.trigger_evict_duration = trigger_evict_duration
        self.last_under_used_time = now

    def monitor(self, now: float) -> bool:
        """:61 — True once used > runtime continuously past the delay."""
        node = self.manager.nodes.get(self.quota_name)
        if node is None:
            return False
        runtime = self.manager.refresh_runtime(self.quota_name)
        # only declared dims constrain (undeclared fall open, matching the
        # masked runtime the reference compares against)
        over = any(
            node.used[r] > runtime[r] for r in node.declared
        ) or any(
            node.used[r] > runtime[r]
            for r in range(R)
            if runtime[r] and r not in node.declared
        )
        if not over:
            self.last_under_used_time = now
            return False
        if now - self.last_under_used_time > self.trigger_evict_duration:
            self.last_under_used_time = now
            return True
        return False

    def get_to_revoke_pod_list(self) -> List[Mapping]:
        """:92 getToRevokePodList — exact reference algorithm: strip
        lowest-priority pods until used <= runtime, then try to assign back
        from highest priority down."""
        node = self.manager.nodes.get(self.quota_name)
        if node is None:
            return []
        runtime = self.manager.refresh_runtime(self.quota_name)
        dims = _constraining_dims(node.declared, runtime)
        used = list(node.used)
        # assigned pods, low priority first (:105 sorts by !MoreImportantPod)
        pods = sorted(
            (p for n, p in node.pods.items() if node.assigned.get(n)),
            key=_importance_key,
        )
        try_revoke: List[Mapping] = []
        for pod in pods:
            if _less_equal(used, runtime, dims):
                break
            if pod.get("non_preemptible"):
                continue  # :114 IsPodNonPreemptible
            used = [u - v for u, v in zip(used, _req(pod))]
            try_revoke.append(pod)
        if not _less_equal(used, runtime, dims):
            return try_revoke  # :123 still over -> evict all tried
        # :131 assign back high -> low while it still fits
        revoke: List[Mapping] = []
        for pod in reversed(try_revoke):
            preq = _req(pod)
            used = [u + v for u, v in zip(used, preq)]
            if not _less_equal(used, runtime, dims):
                used = [u - v for u, v in zip(used, preq)]
                revoke.append(pod)
        return revoke


class QuotaOverUsedRevokeController:
    """quota_overuse_revoke.go:149 — all-quota monitor across trees."""

    def __init__(
        self,
        multi_manager: MultiTreeQuotaManager,
        trigger_evict_duration: float = 300.0,
        monitor_all: bool = True,
    ):
        self.multi = multi_manager
        self.trigger_evict_duration = trigger_evict_duration
        self.monitor_all = monitor_all
        self.monitors: Dict[str, QuotaOverUsedGroupMonitor] = {}

    def sync_quota(self, now: float) -> None:
        """:210 — add monitors for new quotas, drop removed ones."""
        alive = self.multi.all_quota_names()
        for name, mgr in alive.items():
            if name not in self.monitors:
                self.monitors[name] = QuotaOverUsedGroupMonitor(
                    name, mgr, self.trigger_evict_duration, now
                )
        for name in list(self.monitors):
            if name not in alive:
                del self.monitors[name]

    def monitor_all_quotas(self, now: float) -> List[Mapping]:
        """:197 monitorAll — one tick: returns the pods to revoke."""
        if not self.monitor_all:
            return []
        self.sync_quota(now)
        to_revoke: List[Mapping] = []
        for monitor in self.monitors.values():
            if monitor.monitor(now):
                to_revoke.extend(monitor.get_to_revoke_pod_list())
        return to_revoke


# ---------------------------------------------------------------------------
# Preemption (preempt.go)
# ---------------------------------------------------------------------------


def can_preempt(pod: Mapping, victim: Mapping) -> bool:
    """preempt.go:283 — same quota group, strictly higher priority, and the
    victim is preemptible."""
    if victim.get("non_preemptible"):
        return False
    return int(pod.get("priority") or 0) > int(
        victim.get("priority") or 0
    ) and (pod.get("quota") or DEFAULT_QUOTA) == (
        victim.get("quota") or DEFAULT_QUOTA
    )


@dataclasses.dataclass
class NodeVictims:
    node: str
    victims: List[Mapping]
    num_violating: int = 0


def _fits(
    requested: Sequence[int], allocatable: Sequence[int], req: Sequence[int]
) -> bool:
    return all(
        q + r <= a if r > 0 else True
        for q, a, r in zip(requested, allocatable, req)
    )


def select_victims_on_node(
    pod: Mapping,
    node_name: str,
    node_allocatable: Sequence[int],
    node_pods: Sequence[Mapping],
    quota_used: Sequence[int],
    quota_runtime: Sequence[int],
    pdb_violators: Optional[set] = None,
) -> Optional[NodeVictims]:
    """preempt.go:111 SelectVictimsOnNode.

    ``node_pods`` are the pods currently placed on the node (each carrying
    "requests"); ``quota_used``/``quota_runtime`` are the preemptor's
    group's vectors.  Returns None when preemption on this node cannot make
    the pod schedulable.
    """
    preq = _req(pod)
    potential = [p for p in node_pods if can_preempt(pod, p)]
    if not potential:
        return None  # :150 no victims -> UnschedulableAndUnresolvable

    # remove all potential victims, check the pod then fits (:137-163)
    requested = _zeros_like(node_allocatable)
    for p in node_pods:
        requested = [a + b for a, b in zip(requested, _req(p))]
    removed_req = _zeros_like(node_allocatable)
    removed_quota = _zeros_like(node_allocatable)
    for p in potential:
        removed_req = [a + b for a, b in zip(removed_req, _req(p))]
        removed_quota = [a + b for a, b in zip(removed_quota, _req(p))]
    base_requested = [a - b for a, b in zip(requested, removed_req)]
    if not _fits(base_requested, node_allocatable, preq):
        return None
    base_quota_used = [u - v for u, v in zip(quota_used, removed_quota)]
    if not _less_equal([u + v for u, v in zip(base_quota_used, preq)], quota_runtime):
        return None

    # reprieve most-important first (:166-213); PDB violators first so as
    # many of them as possible survive
    ordered = sorted(potential, key=_importance_key, reverse=True)
    violators = [p for p in ordered if pdb_violators and p["name"] in pdb_violators]
    others = [p for p in ordered if not (pdb_violators and p["name"] in pdb_violators)]
    victims: List[Mapping] = []
    num_violating = 0
    cur_requested = list(base_requested)
    cur_quota_used = list(base_quota_used)

    def reprieve(p: Mapping) -> bool:
        nonlocal cur_requested, cur_quota_used
        trial_requested = [a + b for a, b in zip(cur_requested, _req(p))]
        trial_quota = [a + b for a, b in zip(cur_quota_used, _req(p))]
        fits = _fits(trial_requested, node_allocatable, preq) and _less_equal(
            [u + v for u, v in zip(trial_quota, preq)], quota_runtime
        )
        if fits:
            cur_requested = trial_requested
            cur_quota_used = trial_quota
        else:
            victims.append(p)
        return fits

    for p in violators:
        if not reprieve(p):
            num_violating += 1
    for p in others:
        reprieve(p)
    return NodeVictims(node=node_name, victims=victims, num_violating=num_violating)


def _zeros_like(v: Sequence[int]) -> List[int]:
    return [0] * len(v)


def run_quota_preemption(
    pod: Mapping,
    node_allocatable: Mapping[str, Sequence[int]],
    node_pods: Mapping[str, Sequence[Mapping]],
    quota_used: Sequence[int],
    quota_runtime: Sequence[int],
    pdb_violators: Optional[set] = None,
) -> Optional[NodeVictims]:
    """The PostFilter dry run (preempt.go via upstream defaultpreemption):
    evaluate SelectVictimsOnNode on every candidate node and pick the best
    (:43 GetOffsetAndNumCandidates evaluates ALL nodes)."""
    candidates = []
    for name, alloc in node_allocatable.items():
        nv = select_victims_on_node(
            pod,
            name,
            alloc,
            node_pods.get(name, ()),
            quota_used,
            quota_runtime,
            pdb_violators=pdb_violators,
        )
        if nv is not None and nv.victims:
            candidates.append(nv)
    return pick_preemption_node(candidates)


def pick_preemption_node(candidates: Sequence[NodeVictims]) -> Optional[NodeVictims]:
    """Upstream dry-run node choice (defaultpreemption pickOneNodeForPreemption,
    delegated to by preempt.go): fewest PDB violations, then lowest highest
    victim priority, then lowest priority sum, then fewest victims, then
    stable by node name."""
    if not candidates:
        return None

    def key(c: NodeVictims):
        prios = [int(v.get("priority") or 0) for v in c.victims]
        return (
            c.num_violating,
            max(prios) if prios else 0,
            sum(prios),
            len(c.victims),
            c.node,
        )

    return min(candidates, key=key)
