"""Coscheduling gang (PodGroup) helpers.

The reference gates gangs at PreFilter (member count below minMember never
enters the cycle — ``coscheduling/core/core.go:241-246``) and at Permit
(assumed members counted against minMember; short gangs Wait —
``core.go:308-338``).  In the batched cycle the PreFilter gate is a
host-side check at encode time; the Permit gate is the post-scan
all-or-nothing reduction below (also used standalone for tests).
"""

from __future__ import annotations

import jax.numpy as jnp


def gang_satisfaction(
    assignment: jnp.ndarray,  # i32[P] node or -1
    pod_valid: jnp.ndarray,  # bool[P]
    gang_id: jnp.ndarray,  # i32[P], -1 = no gang
    min_member: jnp.ndarray,  # i32[G]
):
    """Returns (gang_satisfied bool[G], pod_gang_ok bool[P]).

    A pod with no gang is always ok; a gang is satisfied when its number of
    assigned members reaches minMember (Permit-stage check, core.go:308).
    """
    G = min_member.shape[0]
    assigned = (assignment >= 0) & pod_valid
    slot = jnp.where(gang_id >= 0, gang_id, G)
    counts = jnp.zeros((G + 1,), jnp.int32).at[slot].add(assigned.astype(jnp.int32))
    satisfied = counts[:G] >= min_member
    pod_ok = jnp.where(gang_id >= 0, satisfied[jnp.maximum(gang_id, 0)], True)
    return satisfied, pod_ok
