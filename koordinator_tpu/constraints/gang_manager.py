"""Coscheduling control flow: gang cache, Permit wait/timeout, gang-group
reject, and the PodGroup lifecycle controller.

Round 1 had only the within-cycle all-or-nothing reduction
(constraints/gang.py); this module adds the CROSS-cycle state machine the
reference runs around it (citations into /root/reference):

* gang cache + schedule-cycle bookkeeping
  (``pkg/scheduler/plugins/coscheduling/core/gang.go``: ScheduleCycle
  :71-78, isGangValidForPermit :485, addAssumedPod/addBoundPod);
* PreFilter gating (``core/core.go PreFilter``: init check, minNum check,
  strict-mode schedule-cycle checks);
* Permit: the whole gang GROUP must have enough assumed members or the
  pod Waits with the gang's wait timeout (``core/core.go:307 Permit``);
* Unreserve / PostFilter rejection: a strict gang's failure rejects every
  waiting pod of the whole gang group and invalidates their schedule
  cycles (``core/core.go:359 rejectGangGroupById``);
* wait timeout: waiting pods past their deadline trigger the same group
  rejection (the reference delegates the timer to the framework's
  WaitingPod; here ``check_timeouts`` is the explicit clock tick);
* PodGroup phase controller (``coscheduling/controller/podgroup.go:200
  syncHandler``): Pending -> PreScheduling -> Scheduling -> Scheduled ->
  Running -> Finished/Failed.

Pods are referenced by name; timestamps are plain floats injected by the
caller (tests tick them explicitly).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

# gang modes (apis/extension/constants.go)
GANG_MODE_STRICT = "Strict"
GANG_MODE_NONSTRICT = "NonStrict"
# match policies (gang.go:493-499)
MATCH_ONLY_WAITING = "only-waiting"
MATCH_WAITING_AND_RUNNING = "waiting-and-running"
MATCH_ONCE_SATISFIED = "once-satisfied"

# PodGroup phases (scheduler-plugins v1alpha1)
PHASE_PENDING = "Pending"
PHASE_PRESCHEDULING = "PreScheduling"
PHASE_SCHEDULING = "Scheduling"
PHASE_SCHEDULED = "Scheduled"
PHASE_RUNNING = "Running"
PHASE_FAILED = "Failed"
PHASE_FINISHED = "Finished"

# Permit statuses (core/core.go Status)
PERMIT_NOT_SPECIFIED = "PodGroupNotSpecified"
PERMIT_NOT_FOUND = "PodGroupNotFound"
PERMIT_WAIT = "Wait"
PERMIT_SUCCESS = "Success"

DEFAULT_WAIT_TIME = 600.0  # args defaultTimeout analog (seconds)


@dataclasses.dataclass
class Gang:
    """core/gang.go:40 Gang."""

    name: str
    min_member: int = 0
    total_num: int = 0
    mode: str = GANG_MODE_STRICT
    match_policy: str = MATCH_ONCE_SATISFIED
    wait_time: float = DEFAULT_WAIT_TIME
    gang_group: List[str] = dataclasses.field(default_factory=list)
    has_init: bool = False
    # members
    children: Set[str] = dataclasses.field(default_factory=set)
    waiting_for_bind: Set[str] = dataclasses.field(default_factory=set)
    bound: Set[str] = dataclasses.field(default_factory=set)
    waiting_since: Dict[str, float] = dataclasses.field(default_factory=dict)
    once_resource_satisfied: bool = False
    # schedule-cycle machinery (gang.go:71-78)
    schedule_cycle: int = 1
    schedule_cycle_valid: bool = True
    child_schedule_cycle: Dict[str, int] = dataclasses.field(default_factory=dict)

    def group(self) -> List[str]:
        return self.gang_group or [self.name]

    # -- membership --------------------------------------------------------
    def add_assumed_pod(self, pod: str, now: float) -> None:
        self.waiting_for_bind.add(pod)
        self.waiting_since[pod] = now
        self._refresh_once_satisfied()

    def del_assumed_pod(self, pod: str) -> None:
        self.waiting_for_bind.discard(pod)
        self.waiting_since.pop(pod, None)

    def add_bound_pod(self, pod: str) -> None:
        self.del_assumed_pod(pod)
        self.bound.add(pod)
        self._refresh_once_satisfied()

    def _refresh_once_satisfied(self) -> None:
        if len(self.waiting_for_bind) + len(self.bound) >= self.min_member:
            self.once_resource_satisfied = True

    # -- permit ------------------------------------------------------------
    def is_valid_for_permit(self) -> bool:
        """gang.go:485 isGangValidForPermit."""
        if not self.has_init:
            return False
        if self.match_policy == MATCH_ONLY_WAITING:
            return len(self.waiting_for_bind) >= self.min_member
        if self.match_policy == MATCH_WAITING_AND_RUNNING:
            return len(self.waiting_for_bind) + len(self.bound) >= self.min_member
        return (
            len(self.waiting_for_bind) >= self.min_member
            or self.once_resource_satisfied
        )

    # -- schedule cycle ----------------------------------------------------
    def try_set_schedule_cycle_valid(self) -> None:
        """gang.go trySetScheduleCycleValid: when every child has reached
        the current cycle, open the next one."""
        if all(
            self.child_schedule_cycle.get(c, 0) >= self.schedule_cycle
            for c in self.children
        ) and self.children:
            self.schedule_cycle += 1
            self.schedule_cycle_valid = True


class PodGroupManager:
    """core/core.go:84 PodGroupManager (host-side)."""

    def __init__(self, default_wait_time: float = DEFAULT_WAIT_TIME):
        self.default_wait_time = default_wait_time
        self.gangs: Dict[str, Gang] = {}
        self.rejected_messages: Dict[str, str] = {}

    # -- cache maintenance (gang_cache.go / PodGroup events) ---------------
    def on_pod_group_add(self, pg: Mapping) -> Gang:
        name = pg["name"]
        gang = self.gangs.get(name) or Gang(name=name)
        gang.min_member = int(pg.get("min_member", 0))
        gang.total_num = max(int(pg.get("total_num", 0)), gang.min_member)
        gang.mode = pg.get("mode", GANG_MODE_STRICT)
        gang.match_policy = pg.get("match_policy", MATCH_ONCE_SATISFIED)
        gang.wait_time = float(pg.get("wait_time", self.default_wait_time))
        gang.gang_group = list(pg.get("gang_group", []))
        gang.has_init = True
        self.gangs[name] = gang
        return gang

    def on_pod_add(self, gang_name: str, pod: str) -> Gang:
        gang = self.gangs.get(gang_name)
        if gang is None:
            gang = Gang(name=gang_name)
            self.gangs[gang_name] = gang
        gang.children.add(pod)
        return gang

    def on_pod_delete(self, gang_name: str, pod: str) -> None:
        gang = self.gangs.get(gang_name)
        if gang is None:
            return
        gang.children.discard(pod)
        gang.del_assumed_pod(pod)
        gang.bound.discard(pod)
        gang.child_schedule_cycle.pop(pod, None)

    # -- scheduling phases -------------------------------------------------
    def pre_filter(self, gang_name: Optional[str], pod: str) -> Optional[str]:
        """core/core.go PreFilter; returns a rejection message or None."""
        if not gang_name:
            return None
        gang = self.gangs.get(gang_name)
        if gang is None:
            return f"can't find gang {gang_name}"
        if not gang.has_init:
            return f"gang {gang_name} has not init"
        if (
            gang.match_policy == MATCH_ONCE_SATISFIED
            and gang.once_resource_satisfied
        ):
            return None
        if len(gang.children) < gang.min_member:
            return (
                f"gang {gang_name} child pod not collect enough "
                f"({len(gang.children)}/{gang.min_member})"
            )
        gang.try_set_schedule_cycle_valid()
        cycle = gang.schedule_cycle
        try:
            if gang.mode == GANG_MODE_STRICT:
                if not gang.schedule_cycle_valid:
                    return f"gang {gang_name} scheduleCycle not valid"
                if gang.child_schedule_cycle.get(pod, 0) >= cycle:
                    return f"pod {pod} schedule cycle too large"
            return None
        finally:
            gang.child_schedule_cycle[pod] = cycle

    def permit(
        self, gang_name: Optional[str], pod: str, now: float
    ) -> Tuple[float, str]:
        """core/core.go:307 Permit: (wait_timeout_seconds, status)."""
        if not gang_name:
            return 0.0, PERMIT_NOT_SPECIFIED
        gang = self.gangs.get(gang_name)
        if gang is None:
            return 0.0, PERMIT_NOT_FOUND
        gang.add_assumed_pod(pod, now)
        for member in gang.group():
            g = self.gangs.get(member)
            if g is None or not g.is_valid_for_permit():
                return gang.wait_time, PERMIT_WAIT
        return 0.0, PERMIT_SUCCESS

    def unreserve(self, gang_name: Optional[str], pod: str) -> List[str]:
        """core/core.go:341 Unreserve: release the pod; in strict mode the
        whole gang group is rejected.  Returns released pod names."""
        if not gang_name:
            return []
        gang = self.gangs.get(gang_name)
        if gang is None:
            return []
        gang.del_assumed_pod(pod)
        if not (
            gang.match_policy == MATCH_ONCE_SATISFIED
            and gang.once_resource_satisfied
        ) and gang.mode == GANG_MODE_STRICT:
            return self.reject_gang_group(
                gang.name, f"gang {gang.name} rejected: pod {pod} unreserved"
            )
        return []

    def post_filter_reject(self, gang_name: str, pod: str) -> List[str]:
        """core/core.go PostFilter: a strict gang member that came out of
        the cycle unschedulable rejects the whole group."""
        gang = self.gangs.get(gang_name)
        if gang is None:
            return []
        if (
            gang.match_policy == MATCH_ONCE_SATISFIED
            and gang.once_resource_satisfied
        ):
            return []
        if gang.mode != GANG_MODE_STRICT:
            return []
        return self.reject_gang_group(
            gang_name, f"gang {gang_name} rejected: pod {pod} unschedulable"
        )

    def reject_gang_group(self, gang_name: str, message: str) -> List[str]:
        """core/core.go:359 rejectGangGroupById: reject every waiting pod
        of every gang in the group, invalidate their schedule cycles.
        Returns the released (previously waiting) pod names."""
        gang = self.gangs.get(gang_name)
        if gang is None:
            return []
        released: List[str] = []
        for member in gang.group():
            g = self.gangs.get(member)
            if g is None:
                continue
            released.extend(sorted(g.waiting_for_bind))
            g.waiting_for_bind.clear()
            g.waiting_since.clear()
            g.schedule_cycle_valid = False
            self.rejected_messages[member] = message
        return released

    def check_timeouts(self, now: float) -> List[str]:
        """Reject gang groups whose waiting pods exceeded the gang's wait
        timeout (the framework's WaitingPod timer in the reference; Permit
        returns the timeout at core.go:332).  Returns released pods."""
        released: List[str] = []
        for gang in list(self.gangs.values()):
            expired = [
                p
                for p, since in gang.waiting_since.items()
                if now - since > gang.wait_time
            ]
            if expired:
                released.extend(
                    self.reject_gang_group(
                        gang.name,
                        f"gang {gang.name} rejected: Permit wait timeout",
                    )
                )
        return released

    def post_bind(self, gang_name: str, pod: str) -> None:
        gang = self.gangs.get(gang_name)
        if gang is not None:
            gang.add_bound_pod(pod)

    # -- cycle integration -------------------------------------------------
    def apply_cycle_result(
        self,
        pod_gangs: Sequence[Optional[str]],
        pod_names: Sequence[str],
        assignment: Sequence[int],
        status: Sequence[int],
        now: float,
    ) -> Dict[str, List[str]]:
        """Feed one batched cycle's outcome through Permit/PostFilter:
        WAIT_GANG pods become assumed+waiting, ASSIGNED gang pods bind,
        and strict gangs with unschedulable members reject their group.
        Returns {"bound": [...], "waiting": [...], "released": [...]}.
        """
        from koordinator_tpu.solver.greedy import (
            STATUS_ASSIGNED,
            STATUS_UNSCHEDULABLE,
            STATUS_WAIT_GANG,
        )

        bound: List[str] = []
        waiting: List[str] = []
        released: List[str] = []

        def bind_whole_group(gname: str) -> None:
            # the whole gang group goes binding (core.go:306 "let the
            # whole gangGroup go binding"): every waiting pod across the
            # group is allowed through together
            nonlocal waiting
            for member in self.gangs[gname].group():
                g = self.gangs.get(member)
                if g is None:
                    continue
                for waiter in sorted(g.waiting_for_bind):
                    self.post_bind(member, waiter)
                    bound.append(waiter)
            waiting = [w for w in waiting if w not in bound]

        # Permit pass first (assumed adds), then rejections
        for name, gname, a, s in zip(pod_names, pod_gangs, assignment, status):
            if not gname:
                if a >= 0:
                    bound.append(name)
                continue
            if s == STATUS_WAIT_GANG or (s == STATUS_ASSIGNED and a >= 0):
                _, st = self.permit(gname, name, now)
                if st == PERMIT_SUCCESS:
                    bind_whole_group(gname)
                else:
                    waiting.append(name)
        for name, gname, a, s in zip(pod_names, pod_gangs, assignment, status):
            if gname and s == STATUS_UNSCHEDULABLE:
                released.extend(self.post_filter_reject(gname, name))
        # a pod the rejection released is no longer waiting (or bound)
        waiting = [w for w in waiting if w not in released]
        bound = [b for b in bound if b not in released]
        return {"bound": bound, "waiting": waiting, "released": released}


class PodGroupController:
    """controller/podgroup.go:200 syncHandler — PodGroup phase machine.

    ``pod_phases``: {pod_name: "Pending"|"Running"|"Succeeded"|"Failed"}.
    """

    def __init__(self, manager: PodGroupManager):
        self.manager = manager
        self.phases: Dict[str, str] = {}

    def sync(self, gang_name: str, pod_phases: Mapping[str, str]) -> str:
        gang = self.manager.gangs.get(gang_name)
        if gang is None:
            self.phases.pop(gang_name, None)
            return ""
        phase = self.phases.get(gang_name, "")
        pods = sorted(gang.children)
        scheduled = len(gang.bound)

        if phase == "":
            phase = PHASE_PENDING
        if phase == PHASE_PENDING:
            if len(pods) >= gang.min_member > 0:
                phase = PHASE_PRESCHEDULING
        if phase not in ("", PHASE_PENDING):
            running = sum(1 for p in pods if pod_phases.get(p) == "Running")
            succeeded = sum(1 for p in pods if pod_phases.get(p) == "Succeeded")
            failed = sum(1 for p in pods if pod_phases.get(p) == "Failed")
            if not pods:
                phase = PHASE_PENDING
            else:
                if phase == PHASE_PRESCHEDULING and scheduled > 0:
                    phase = PHASE_SCHEDULING
                if scheduled >= gang.min_member and phase in (
                    PHASE_PRESCHEDULING,
                    PHASE_SCHEDULING,
                ):
                    phase = PHASE_SCHEDULED
                if succeeded + running >= gang.min_member and phase == PHASE_SCHEDULED:
                    phase = PHASE_RUNNING
                if failed and failed + running + succeeded >= gang.min_member:
                    phase = PHASE_FAILED
                if succeeded >= gang.min_member:
                    phase = PHASE_FINISHED
        self.phases[gang_name] = phase
        return phase
