"""LoadAwareScheduling scorer and filter as batched tensors.

Reference: ``pkg/scheduler/plugins/loadaware/load_aware.go``:

* Score (:269-335): estimatedUsed = estimator(pod) + estimated(assigned pods
  not yet in metrics) + measured node usage, then
  ``loadAwareSchedulingScorer`` (:378) = weighted leastRequestedScore.
  Nodes without a fresh NodeMetric score 0 (:282-289).
* Filter (:173-224): usage percentage >= threshold -> unschedulable;
  ``usage = round(used/total*100)`` in float64, reproduced here with exact
  integer arithmetic.

The assign-cache term (:298) is carried as ``node_estimated`` state by the
solver; in one-shot scoring it is an input tensor.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.ops.scoring import least_requested_score, weighted_resource_score


def loadaware_scores(
    pod_estimated: jnp.ndarray,  # i64[P, R] estimator output per pod
    node_usage: jnp.ndarray,  # i64[N, R] measured usage (NodeMetric)
    node_estimated: jnp.ndarray,  # i64[N, R] assign-cache estimated usage
    node_allocatable: jnp.ndarray,  # i64[N, R]
    weights: jnp.ndarray,  # i64[R]
    metric_fresh: jnp.ndarray,  # bool[N]
) -> jnp.ndarray:
    """LoadAware Score for all (pod, node) pairs -> i64[P, N]."""
    estimated_used = (
        node_usage[None, :, :] + node_estimated[None, :, :] + pod_estimated[:, None, :]
    )
    scores = least_requested_score(estimated_used, node_allocatable[None, :, :])
    score = weighted_resource_score(scores, weights)
    return jnp.where(metric_fresh[None, :], score, 0)


def usage_percent(used: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """round(used/total*100) half-away-from-zero, exact integers.

    Go (:214): int64(math.Round(float64(used)/float64(total)*100)).
    For non-negative ints floor((200*used + total) / (2*total)) is identical.
    """
    used = used.astype(jnp.int64)
    total = total.astype(jnp.int64)
    safe_total = jnp.where(total == 0, 1, total)
    pct = (200 * used + safe_total) // (2 * safe_total)
    return jnp.where(total == 0, 0, pct)


def loadaware_filter_mask(
    node_usage: jnp.ndarray,  # i64[N, R]
    node_allocatable: jnp.ndarray,  # i64[N, R]
    thresholds: jnp.ndarray,  # i64[R] usage thresholds percent (0 = unchecked)
    metric_fresh: jnp.ndarray,  # bool[N]
) -> jnp.ndarray:
    """Filter mask bool[N]; True = node passes the utilization thresholds.

    Per reference :185-222: a resource with threshold 0 or zero allocatable
    is skipped; usage% >= threshold rejects the node.  Nodes without a fresh
    metric pass (Filter skips them, :138-147).
    """
    pct = usage_percent(node_usage, node_allocatable)
    checked = (thresholds[None, :] > 0) & (node_allocatable > 0)
    exceeded = jnp.any(checked & (pct >= thresholds[None, :]), axis=-1)
    return ~exceeded | ~metric_fresh
