"""LoadAwareScheduling scorer and filter as batched tensors.

Reference: ``pkg/scheduler/plugins/loadaware/load_aware.go``:

* Score (:269-335): estimatedUsed = estimator(pod) + estimated(assigned pods
  not yet in metrics) + measured node usage, then
  ``loadAwareSchedulingScorer`` (:378) = weighted leastRequestedScore.
  Nodes without a fresh NodeMetric score 0 (:282-289).
* Filter (:173-224): usage percentage >= threshold -> unschedulable;
  ``usage = round(used/total*100)`` in float64, reproduced here with exact
  integer arithmetic.

The assign-cache term (:298) is carried as ``node_estimated`` state by the
solver; in one-shot scoring it is an input tensor.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.model.snapshot import PERCENTILES
from koordinator_tpu.ops.scoring import least_requested_score, weighted_resource_score


def loadaware_scores(
    pod_estimated: jnp.ndarray,  # i64[P, R] estimator output per pod
    node_usage: jnp.ndarray,  # i64[N, R] measured usage (NodeMetric)
    node_estimated: jnp.ndarray,  # i64[N, R] assign-cache estimated usage
    node_allocatable: jnp.ndarray,  # i64[N, R]
    weights: jnp.ndarray,  # i64[R]
    metric_fresh: jnp.ndarray,  # bool[N]
) -> jnp.ndarray:
    """LoadAware Score for all (pod, node) pairs -> i64[P, N]."""
    estimated_used = (
        node_usage[None, :, :] + node_estimated[None, :, :] + pod_estimated[:, None, :]
    )
    scores = least_requested_score(estimated_used, node_allocatable[None, :, :])
    score = weighted_resource_score(scores, weights)
    return jnp.where(metric_fresh[None, :], score, 0)


def usage_percent(used: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """round(used/total*100) half-away-from-zero, exact integers.

    Go (:214): int64(math.Round(float64(used)/float64(total)*100)).
    For non-negative ints floor((200*used + total) / (2*total)) is identical.
    """
    used = used.astype(jnp.int64)
    total = total.astype(jnp.int64)
    safe_total = jnp.where(total == 0, 1, total)
    pct = (200 * used + safe_total) // (2 * safe_total)
    return jnp.where(total == 0, 0, pct)


def loadaware_filter_mask(
    node_usage: jnp.ndarray,  # i64[N, R]
    node_allocatable: jnp.ndarray,  # i64[N, R]
    thresholds: jnp.ndarray,  # i64[R] usage thresholds percent (0 = unchecked)
    metric_fresh: jnp.ndarray,  # bool[N]
) -> jnp.ndarray:
    """Filter mask bool[N]; True = node passes the utilization thresholds.

    Per reference :185-222: a resource with threshold 0 or zero allocatable
    is skipped; usage% >= threshold rejects the node.  Nodes without a fresh
    metric pass (Filter skips them, :138-147).
    """
    pct = usage_percent(node_usage, node_allocatable)
    checked = (thresholds[None, :] > 0) & (node_allocatable > 0)
    exceeded = jnp.any(checked & (pct >= thresholds[None, :]), axis=-1)
    return ~exceeded | ~metric_fresh


# one implementation of the threshold check: loadaware_filter_mask above
_threshold_mask = loadaware_filter_mask


def loadaware_node_masks(nodes, cfg):
    """Per-node Filter masks -> (mask_default bool[N], mask_prod bool[N]).

    Reference ``load_aware.go:150-226``:

    * with an aggregated profile, non-prod pods filter against the selected
      usage percentile and the profile's thresholds; nodes that reported no
      aggregates pass (``getTargetAggregatedUsage`` nil -> continue)
    * PriorityProd pods with ProdUsageThresholds configured filter against
      the node's prod-pods usage sum INSTEAD of whole-node usage
    * expired/missing NodeMetric always passes (Filter skips those nodes)
    """
    thr = cfg.loadaware_thresholds_arr()
    agg = cfg.loadaware.aggregated
    if (
        agg is not None
        and dict(agg.usage_thresholds)
        and agg.usage_aggregation_type
        and nodes.agg_usage is not None
    ):
        a = PERCENTILES.index(agg.usage_aggregation_type)
        mask_default = _threshold_mask(
            nodes.agg_usage[:, a], nodes.allocatable, thr, nodes.metric_fresh
        )
        if nodes.agg_fresh is not None:
            # a (node, percentile) cell with no data passes the filter
            # (getTargetAggregatedUsage nil -> continue)
            mask_default = mask_default | ~nodes.agg_fresh[:, a]
    else:
        mask_default = _threshold_mask(
            nodes.usage, nodes.allocatable, thr, nodes.metric_fresh
        )
    if dict(cfg.loadaware.prod_usage_thresholds):
        # the prod branch is selected by CONFIG + pod class alone
        # (load_aware.go:151); a node with no prod-pods metrics passes
        # (filterProdUsage:227 returns nil on empty PodsMetric), which
        # zeros reproduce exactly
        pu = (
            nodes.prod_usage
            if nodes.prod_usage is not None
            else jnp.zeros_like(nodes.usage)
        )
        mask_prod = _threshold_mask(
            pu,
            nodes.allocatable,
            cfg.prod_thresholds_arr(),
            nodes.metric_fresh,
        )
    else:
        mask_prod = mask_default
    return mask_default, mask_prod


def select_score_usage(nodes, cfg):
    """Score-phase usage tensors -> (usage_nonprod i64[N, R], usage_prod or
    None).

    Reference ``load_aware.go:291-327``: non-prod pods score against the
    score-aggregation percentile when configured (plain NodeUsage for nodes
    without aggregates), PriorityProd pods score against the prod-pods
    usage sum when ScoreAccordingProdUsage is set.
    """
    agg = cfg.loadaware.aggregated
    usage = nodes.usage
    if (
        agg is not None
        and agg.score_aggregation_type
        and nodes.agg_usage is not None
    ):
        a = PERCENTILES.index(agg.score_aggregation_type)
        sel = nodes.agg_usage[:, a]
        if nodes.agg_fresh is not None:
            # missing percentile -> plain NodeUsage for that node
            sel = jnp.where(nodes.agg_fresh[:, a, None], sel, usage)
        usage = sel
    prod = None
    if cfg.loadaware.score_according_prod_usage and nodes.prod_usage is not None:
        prod = nodes.prod_usage
    return usage, prod
