from koordinator_tpu.ops.scoring import (  # noqa: F401
    least_requested_score,
    most_requested_score,
    weighted_resource_score,
    least_allocated_scores,
    most_allocated_scores,
)
from koordinator_tpu.ops.fit import fit_mask, nonzero_requests  # noqa: F401
from koordinator_tpu.ops.loadaware import (  # noqa: F401
    loadaware_scores,
    loadaware_filter_mask,
    usage_percent,
)
