"""Filter-phase feasibility masks (NodeResourcesFit semantics).

The reference runs Filter per (pod, node) in parallel goroutines
(``frameworkext/framework_extender.go:192``); here feasibility is one
boolean ``pods x nodes`` tensor produced by a broadcast compare.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.model import resources as res

# Upstream kube-scheduler non-zero request defaults
# (k8s.io/kubernetes/pkg/scheduler/util: DefaultMilliCPURequest=100,
# DefaultMemoryRequest=200*1024*1024 bytes = 200 on the MiB-unit axis),
# applied by NodeResourcesFit scoring.
NONZERO_MILLI_CPU = 100
NONZERO_MEMORY = 200

_CPU_IDX = res.RESOURCE_INDEX[res.CPU]
_MEM_IDX = res.RESOURCE_INDEX[res.MEMORY]


def nonzero_requests(pod_requests: jnp.ndarray) -> jnp.ndarray:
    """Apply upstream GetNonzeroRequests defaults to cpu/memory slots."""
    defaults = jnp.zeros((res.NUM_RESOURCES,), jnp.int64)
    defaults = defaults.at[_CPU_IDX].set(NONZERO_MILLI_CPU)
    defaults = defaults.at[_MEM_IDX].set(NONZERO_MEMORY)
    return jnp.where(pod_requests == 0, defaults[None, :], pod_requests)


def fit_mask(
    pod_requests: jnp.ndarray,  # i64[P, R]
    node_requested: jnp.ndarray,  # i64[N, R]
    node_allocatable: jnp.ndarray,  # i64[N, R]
    node_valid: jnp.ndarray,  # bool[N]
    pod_valid: jnp.ndarray,  # bool[P]
) -> jnp.ndarray:
    """Feasibility mask bool[P, N]: pod fits node's remaining allocatable.

    A resource constrains only when the pod requests it (upstream Fit checks
    only the pod's requested resources; zero-request resources never fail).
    """
    need = pod_requests[:, None, :] > 0
    fits_r = node_requested[None, :, :] + pod_requests[:, None, :] <= node_allocatable[None, :, :]
    ok = jnp.all(jnp.where(need, fits_r, True), axis=-1)
    return ok & node_valid[None, :] & pod_valid[:, None]
