"""NodeNUMAResource scoring/fit as batched zone tensors.

The reference's Score runs a full NUMA allocation per (pod, node) on the
host (reference ``pkg/scheduler/plugins/nodenumaresource/scoring.go:86``,
``resource_manager.go:142 Allocate``) — the single most expensive scorer in
the cycle (SURVEY §3.1).  The TPU-first redesign replaces that with dense
zone tensors: per-zone fit and least/most-allocated scores are one broadcast
over ``[P, N, Z, R]`` (fused by XLA into a single HBM pass), and the exact
sequential cpuset accumulator runs host-side only once, for the node the
solver actually picks (``koordinator_tpu.scheduler.cpu_accumulator``).

Amplified-CPU scoring (``scoring.go:95 scoreWithAmplifiedCPUs``) keeps exact
integer parity: amplification ratios are fixed-point x10000 and the ceil is
integer ceil-div, matching ``apis/extension``'s Amplify.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.topology import DEFAULT_AMPLIFICATION_DENOMINATOR
from koordinator_tpu.ops.scoring import (
    least_requested_score,
    most_requested_score,
    weighted_resource_score,
)

# NUMATopologyPolicy codes on the node axis (encode_* helpers put the
# apis/extension numa_aware.go policy names into these ints).
POLICY_NONE = 0
POLICY_BEST_EFFORT = 1
POLICY_RESTRICTED = 2
POLICY_SINGLE_NUMA_NODE = 3

_CPU_IDX = res.RESOURCE_INDEX[res.CPU]


def zone_fit_mask(
    pod_requests: jnp.ndarray,  # i64[P, R]
    zone_alloc: jnp.ndarray,  # i64[N, Z, R]
    zone_requested: jnp.ndarray,  # i64[N, Z, R]
    zone_valid: jnp.ndarray,  # bool[N, Z]
) -> jnp.ndarray:
    """bool[P, N, Z]: pod fits entirely inside one zone.

    Mirrors the single-NUMA-node admission check the reference's hint
    providers express (``plugin.go GetPodTopologyHints`` +
    ``resource_manager.go``): free = allocatable - requested per zone.
    """
    free = zone_alloc - zone_requested  # [N, Z, R]
    fits = jnp.all(
        pod_requests[:, None, None, :] <= free[None, :, :, :], axis=-1
    )  # [P, N, Z]
    return fits & zone_valid[None, :, :]


def numa_admit_mask(
    pod_requests: jnp.ndarray,  # i64[P, R]
    zone_alloc: jnp.ndarray,  # i64[N, Z, R]
    zone_requested: jnp.ndarray,  # i64[N, Z, R]
    zone_valid: jnp.ndarray,  # bool[N, Z]
    node_policy: jnp.ndarray,  # i32[N] POLICY_* codes
) -> jnp.ndarray:
    """bool[P, N]: NUMA admission per (pod, node) by node topology policy.

    * single-numa-node: some single zone holds the whole request
      (policy_single_numa_node.go admits only preferred = single-node hints).
    * restricted: the request fits within the union of zones (resources are
      summable across zones for the summable request kinds the tensors
      carry); a node whose total zoned free space can't hold the pod is
      rejected (policy_restricted.go admits only preferred merges).
    * best-effort / none: always admitted (policy_best_effort.go,
      policy_none.go) — zone pressure then only shapes the score.
    """
    single = jnp.any(
        zone_fit_mask(pod_requests, zone_alloc, zone_requested, zone_valid), axis=-1
    )  # [P, N]
    free = jnp.where(zone_valid[:, :, None], zone_alloc - zone_requested, 0)
    union_free = free.sum(axis=1)  # [N, R]
    has_zones = jnp.any(zone_valid, axis=-1)  # [N]
    union_fit = jnp.all(
        pod_requests[:, None, :] <= union_free[None, :, :], axis=-1
    )  # [P, N]

    policy = node_policy[None, :]
    admitted = jnp.where(
        policy == POLICY_SINGLE_NUMA_NODE,
        single,
        jnp.where(policy == POLICY_RESTRICTED, union_fit, True),
    )
    # nodes that report no zones skip NUMA admission entirely (the reference
    # skips nodes without NodeResourceTopology, plugin.go skipTheNode)
    return admitted | ~has_zones[None, :]


def numa_zone_scores(
    pod_requests: jnp.ndarray,  # i64[P, R]
    zone_alloc: jnp.ndarray,  # i64[N, Z, R]
    zone_requested: jnp.ndarray,  # i64[N, Z, R]
    zone_valid: jnp.ndarray,  # bool[N, Z]
    weights: jnp.ndarray,  # i64[R]
    *,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """i64[P, N]: the score of the zone the allocator would pick.

    The reference scores the post-Allocate zone occupancy with
    least/most-allocated (``scoring.go calculateAllocatableAndRequested`` +
    ``resourceAllocationScorer``).  Batched form: score every (pod, node,
    zone) placement, mask to fitting zones, and take the zone the NUMA
    allocate strategy would choose — the highest-scoring fitting zone (for
    MostAllocated the most-packed zone scores highest; for LeastAllocated
    the emptiest does), which is exactly the allocator's preference order.
    Nodes with no fitting zone fall back to the best invalid-fit zone score
    of 0 (the reference returns score 0 when Allocate fails, scoring.go:86).
    """
    req_after = zone_requested[None, :, :, :] + pod_requests[:, None, None, :]
    if most_allocated:
        per_res = most_requested_score(req_after, zone_alloc[None, :, :, :])
    else:
        per_res = least_requested_score(req_after, zone_alloc[None, :, :, :])
    per_zone = weighted_resource_score(per_res, weights)  # i64[P, N, Z]

    fits = zone_fit_mask(pod_requests, zone_alloc, zone_requested, zone_valid)
    masked = jnp.where(fits, per_zone, -1)
    best = masked.max(axis=-1)  # [P, N]
    return jnp.maximum(best, 0)


def amplify_milli(value: jnp.ndarray, ratio_x10000: jnp.ndarray) -> jnp.ndarray:
    """Integer ceil(value * ratio), ratio fixed-point x10000
    (reference apis/extension Amplify; topology.py amplify, vectorized)."""
    num = value.astype(jnp.int64) * ratio_x10000.astype(jnp.int64)
    amplified = -(-num // DEFAULT_AMPLIFICATION_DENOMINATOR)
    return jnp.where(
        ratio_x10000 <= DEFAULT_AMPLIFICATION_DENOMINATOR, value, amplified
    )


def amplified_cpu_scores(
    pod_requests: jnp.ndarray,  # i64[P, R]
    node_requested: jnp.ndarray,  # i64[N, R]
    node_allocatable: jnp.ndarray,  # i64[N, R] (amplified allocatable)
    cpuset_allocated_milli: jnp.ndarray,  # i64[N] milli-cpus held by cpuset pods
    cpu_amplification: jnp.ndarray,  # i32[N] ratio x10000
    weights: jnp.ndarray,  # i64[R]
    *,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """i64[P, N]: least/most-allocated score with amplified cpuset usage.

    Parity with ``scoring.go:95 scoreWithAmplifiedCPUs``: on nodes with a
    CPU amplification ratio, the milli-CPUs held by cpuset-bound pods are
    re-counted at the amplified rate before scoring:
    ``requested.cpu += amplify(allocated) - allocated``.
    """
    adjusted_cpu = (
        node_requested[:, _CPU_IDX]
        - cpuset_allocated_milli
        + amplify_milli(cpuset_allocated_milli, cpu_amplification)
    )
    node_requested = node_requested.at[:, _CPU_IDX].set(adjusted_cpu)
    requested = node_requested[None, :, :] + pod_requests[:, None, :]
    if most_allocated:
        per_res = most_requested_score(requested, node_allocatable[None, :, :])
    else:
        per_res = least_requested_score(requested, node_allocatable[None, :, :])
    return weighted_resource_score(per_res, weights)
