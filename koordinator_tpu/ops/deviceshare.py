"""DeviceShare fit / scoring as batched tensors + exact minor allocation.

Reference: ``pkg/scheduler/plugins/deviceshare``:

* Requests are shares-of-100 per card; a request whose gpu-memory-ratio is a
  multiple of 100 spans ``ratio/100`` whole cards at ``request/wanted`` per
  card (``device_cache.go:367 calcDeviceWanted``).
* gpu-memory and gpu-memory-ratio fill each other from the card's total
  memory (``utils.go:211 fillGPUTotalMem``) — node-dependent, so the
  normalized request is a ``[P, N, C]`` tensor here.
* Filter: a node fits if, per requested device type, at least ``wanted``
  minors have ``free >= perCard`` (``device_cache.go:329-352``).
* Score: least/most-allocated over summed minor resources
  (``scoring.go:179 scoreNode``).
* The per-minor choice on the selected node replays the reference's exact
  ordering host-side (``allocate_minors``; ``device_resources.go:161,177``).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model.device import (
    DEVICE_FPGA,
    DEVICE_GPU,
    DEVICE_RDMA,
    DEVICE_RESOURCE_INDEX,
    DEVICE_TYPE_CODE_TO_NAME,
    DEVICE_TYPE_NAMES,
    DEVICE_TYPE_RESOURCES,
    DeviceBatch,
    NUM_DEVICE_RESOURCES,
)
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE
from koordinator_tpu.ops.scoring import (
    least_requested_score,
    most_requested_score,
    weighted_resource_score,
)

_CORE = DEVICE_RESOURCE_INDEX[res.GPU_CORE]
_MEM = DEVICE_RESOURCE_INDEX[res.GPU_MEMORY]
_RATIO = DEVICE_RESOURCE_INDEX[res.GPU_MEMORY_RATIO]


def pod_device_requests(pod_requests: jnp.ndarray) -> jnp.ndarray:
    """i64[P, C]: project snapshot resource rows onto the device axis."""
    idx = jnp.asarray(
        [res.RESOURCE_INDEX[n] for n in
         (res.GPU_CORE, res.GPU_MEMORY, res.GPU_MEMORY_RATIO, res.RDMA, res.FPGA)],
        dtype=jnp.int32,
    )
    return pod_requests[:, idx]


def gpu_card_total_memory(devices: DeviceBatch) -> jnp.ndarray:
    """i64[N]: per-node GPU card memory (all cards on a node are the same
    model — utils.go:225)."""
    is_gpu = (devices.dev_type == DEVICE_GPU) & devices.valid
    mem = jnp.where(is_gpu, devices.total[:, :, _MEM], 0)
    return mem.max(axis=1)


def normalize_gpu_requests(
    dev_requests: jnp.ndarray,  # i64[P, C]
    card_mem: jnp.ndarray,  # i64[N]
) -> jnp.ndarray:
    """i64[P, N, C]: fill gpu-memory <-> gpu-memory-ratio per node
    (fillGPUTotalMem): a memory-only request derives its ratio from the
    node's card memory and vice versa."""
    P = dev_requests.shape[0]
    N = card_mem.shape[0]
    out = jnp.broadcast_to(dev_requests[:, None, :], (P, N, dev_requests.shape[1]))
    mem_req = dev_requests[:, _MEM][:, None]  # [P, 1]
    ratio_req = dev_requests[:, _RATIO][:, None]
    safe_card = jnp.maximum(card_mem, 1)[None, :]  # [1, N]
    derived_ratio = mem_req * 100 // safe_card
    derived_mem = ratio_req * card_mem[None, :] // 100
    new_ratio = jnp.where(mem_req > 0, derived_ratio, ratio_req)
    new_mem = jnp.where(mem_req > 0, mem_req, derived_mem)
    out = out.at[:, :, _RATIO].set(new_ratio)
    out = out.at[:, :, _MEM].set(new_mem)
    return out


# device resource dims that belong to the GPU type (the card-spanning
# division applies to these ONLY: an RDMA/FPGA quantity must not be
# divided by the number of GPU cards a co-requesting pod wants)
_GPU_DIMS = jnp.asarray(
    [DEVICE_RESOURCE_INDEX[n] for n in DEVICE_TYPE_RESOURCES[DEVICE_GPU]],
    dtype=jnp.int32,
)


def split_per_card(norm_requests: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(perCard i64[P, N, C], wanted i64[P, N]) — calcDeviceWanted: a ratio
    that is a positive multiple of 100 spans ratio/100 cards.  Division
    applies to the GPU dims only; other device types keep their full
    per-minor quantity (they allocate one minor per request)."""
    ratio = norm_requests[..., _RATIO]
    multi = (ratio >= 100) & (ratio % 100 == 0)
    wanted = jnp.where(multi, ratio // 100, 1)
    is_gpu_dim = jnp.zeros((norm_requests.shape[-1],), bool).at[_GPU_DIMS].set(True)
    divided = norm_requests // jnp.maximum(wanted, 1)[..., None]
    per_card = jnp.where(is_gpu_dim, divided, norm_requests)
    return per_card, wanted


def device_fit_mask(
    pod_requests: jnp.ndarray,  # i64[P, R] (snapshot axis)
    devices: DeviceBatch,
) -> jnp.ndarray:
    """bool[P, N]: every requested device type has >= wanted satisfying minors."""
    dev_req = pod_device_requests(pod_requests)  # [P, C]
    card_mem = gpu_card_total_memory(devices)  # [N]
    norm = normalize_gpu_requests(dev_req, card_mem)  # [P, N, C]
    per_card, wanted = split_per_card(norm)

    ok = jnp.ones((dev_req.shape[0], devices.total.shape[0]), bool)
    for type_code, type_resources in DEVICE_TYPE_RESOURCES.items():
        dims = jnp.asarray(
            [DEVICE_RESOURCE_INDEX[n] for n in type_resources], dtype=jnp.int32
        )
        req_t = norm[:, :, dims]  # [P, N, Ct]
        requested_type = jnp.any(dev_req[:, dims] > 0, axis=-1)  # [P]
        minors_of_type = (devices.dev_type == type_code) & devices.valid  # [N, D]
        free_t = devices.free[:, :, dims]  # [N, D, Ct]
        per_card_t = per_card[:, :, dims]  # [P, N, Ct]
        satisfied = jnp.all(
            per_card_t[:, :, None, :] <= free_t[None, :, :, :], axis=-1
        )  # [P, N, D]
        satisfied &= minors_of_type[None, :, :]
        count = satisfied.sum(axis=-1)  # [P, N]
        # card-spanning applies to the GPU type; other types want one
        # satisfying minor for their (undivided) request
        type_wanted = wanted if type_code == DEVICE_GPU else 1
        type_ok = count >= type_wanted
        ok &= jnp.where(requested_type[:, None], type_ok, True)
        # requesting a type the node doesn't have at all fails
        has_type = jnp.any(minors_of_type, axis=-1)  # [N]
        ok &= jnp.where(
            requested_type[:, None], has_type[None, :] | type_ok, True
        )
    return ok


def deviceshare_scores(
    pod_requests: jnp.ndarray,  # i64[P, R]
    devices: DeviceBatch,
    weights: Optional[jnp.ndarray] = None,  # i64[C]
    *,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """i64[P, N]: scoreNode (scoring.go:179) — least/most allocated over
    per-type summed minor resources; types the pod doesn't request
    contribute weight 0 (the reference masks podRequest per type)."""
    dev_req = pod_device_requests(pod_requests)  # [P, C]
    card_mem = gpu_card_total_memory(devices)
    norm = normalize_gpu_requests(dev_req, card_mem)  # [P, N, C]

    total = jnp.where(devices.valid[:, :, None], devices.total, 0).sum(axis=1)
    free = jnp.where(devices.valid[:, :, None], devices.free, 0).sum(axis=1)
    used = total - free  # [N, C]
    requested = used[None, :, :] + norm  # [P, N, C]
    if most_allocated:
        per_res = most_requested_score(requested, total[None, :, :])
    else:
        per_res = least_requested_score(requested, total[None, :, :])
    if weights is None:
        weights = jnp.ones((NUM_DEVICE_RESOURCES,), jnp.int64)
    # weight only the dims the pod requests (scoreNode skips total==0 dims;
    # requested-dim masking keeps non-requested types out of the mean)
    w = weights[None, None, :] * (norm > 0)
    wsum = jnp.maximum(w.sum(axis=-1), 1)
    return (per_res * w).sum(axis=-1) // wsum


def allocate_minors(
    minors: Sequence[Mapping],
    per_card: Mapping[str, int],
    wanted: int,
    *,
    preferred: Optional[Set[int]] = None,
    required: Optional[Set[int]] = None,
    most_allocated: bool = False,
    preferred_numa: Optional[Set[int]] = None,
) -> List[int]:
    """Host-side exact minor selection on the chosen node.

    ``minors``: ``[{"minor": int, "total": {dim: qty}, "free": {dim: qty},
    "topology": {"numaNode": int}}]``.
    Ordering parity with scoreDevices + sortDeviceResourcesByMinor
    (device_resources.go:161,177): preferred minors first, then score
    descending (scoreDevice), then — when ``preferred_numa`` is given —
    NUMA-affine minors before others (the joint allocator's cross-type
    alignment tiebreak), then minor ascending; the first ``wanted``
    satisfying minors win.  Raises ValueError when the node can't satisfy.
    """
    preferred = preferred or set()
    required = required or set()
    preferred_numa = preferred_numa or set()

    ranked = sorted(
        minors,
        key=lambda m: (
            m["minor"] not in preferred,
            -_score_device(m, per_card, most_allocated),
            bool(preferred_numa) and _numa_of(m) not in preferred_numa,
            m["minor"],
        ),
    )
    out: List[int] = []
    for m in ranked:
        if required and m["minor"] not in required:
            continue
        if _satisfies(m, per_card):
            out.append(m["minor"])
            if len(out) == wanted:
                return out
    raise ValueError(f"node cannot satisfy {wanted} device minors")


def _free_of(m: Mapping) -> Dict[str, int]:
    # an unallocated healthy device is fully free (deviceFree == total)
    src = m.get("free")
    if src is None:
        src = m.get("total") or {}
    return {dim: res.parse_quantity(v, dim) for dim, v in src.items()}


def _numa_of(m: Mapping) -> int:
    return int((m.get("topology") or {}).get("numaNode", 0))


def _satisfies(m: Mapping, per_card: Mapping[str, int]) -> bool:
    free = _free_of(m)
    return all(free.get(d, 0) >= q for d, q in per_card.items())


def _score_device(
    m: Mapping, per_card: Mapping[str, int], most_allocated: bool
) -> int:
    """scoreDevice (device_resources.go:161): least/most allocated over
    the minor's dims as if per_card were placed — the ONE copy both the
    per-minor and the partition-group orderings use."""
    s = 0
    n = 0
    free = _free_of(m)
    for dim, total in (m.get("total") or {}).items():
        total = res.parse_quantity(total, dim)
        if total == 0:
            continue
        f = free.get(dim, 0)
        req = total - f + int(per_card.get(dim, 0)) if total >= f else total
        if most_allocated:
            val = max(0, MAX_NODE_SCORE * req // total) if req <= total else 0
        else:
            val = (total - req) * MAX_NODE_SCORE // total if req <= total else 0
        s += val
        n += 1
    return s // n if n else 0


def allocate_partitioned(
    minors: Sequence[Mapping],
    per_card: Mapping[str, int],
    wanted: int,
    partitions: Mapping[int, Sequence[Sequence[int]]],
    *,
    preferred: Optional[Set[int]] = None,
    required: Optional[Set[int]] = None,
    most_allocated: bool = False,
) -> List[int]:
    """Partition-table-constrained multi-card selection.

    ``partitions`` maps allocation size -> the minor groups that may be
    co-allocated at that size (the GPU partition-table semantics of newer
    koordinator ``apis/extension`` — e.g. NVLink rings on an 8-GPU host:
    ``{4: [[0,1,2,3], [4,5,6,7]], 8: [[0..7]]}``).  The chosen set must
    be exactly one listed group whose every minor satisfies ``per_card``
    (and covers ``required`` when given); among feasible groups the one
    containing preferred minors wins, then the emptiest (least-allocated)
    or fullest (most-allocated) by summed minor score, then the lowest
    first minor.  Sizes without a table entry fall back to the free
    per-minor ordering (``allocate_minors``).
    """
    groups = partitions.get(wanted) if partitions else None
    if not groups:
        return allocate_minors(
            minors,
            per_card,
            wanted,
            preferred=preferred,
            required=required,
            most_allocated=most_allocated,
        )
    preferred = preferred or set()
    required = required or set()
    by_minor = {m["minor"]: m for m in minors}

    feasible = []
    for group in groups:
        if len(group) != wanted:
            continue
        members = [by_minor.get(g) for g in group]
        if any(m is None for m in members):
            continue
        if required and not required.issubset(set(group)):
            continue
        if not all(_satisfies(m, per_card) for m in members):
            continue
        feasible.append((group, members))
    if not feasible:
        raise ValueError(
            f"no partition group of size {wanted} can satisfy the request"
        )
    best = min(
        feasible,
        key=lambda gm: (
            not any(g in preferred for g in gm[0]),
            -sum(_score_device(m, per_card, most_allocated) for m in gm[1]),
            min(gm[0]),
        ),
    )
    return sorted(best[0])


# device-type allocation order of the joint allocator (tryAllocateDevice
# iterates DeviceResourceNames; a fixed order keeps results deterministic)
_JOINT_TYPE_ORDER = (DEVICE_GPU, DEVICE_RDMA, DEVICE_FPGA)
_TYPE_NAMES = {DEVICE_GPU: "gpu", DEVICE_RDMA: "rdma", DEVICE_FPGA: "fpga"}


def allocate_joint(
    minors: Sequence[Mapping],
    per_card_by_type: Mapping[int, Mapping[str, int]],
    wanted_by_type: Mapping[int, int],
    *,
    partitions: Optional[Mapping[int, Sequence[Sequence[int]]]] = None,
    preferred: Optional[Mapping[int, Set[int]]] = None,
    required: Optional[Mapping[int, Set[int]]] = None,
    most_allocated: bool = False,
) -> Dict[int, List[int]]:
    """Joint allocation across device types on one node (reference
    ``device_cache.go:272 tryAllocateDevice`` loops the requested types;
    ``allocator.go:91`` drives it from the plugin).

    Types allocate in a fixed order (GPU first); after the first type
    lands, its minors' NUMA nodes become the NUMA-affinity preference for
    every later type, so a GPU+RDMA pod gets an RDMA NIC on the same NUMA
    node as its GPUs whenever one satisfies the request (the alignment
    newer koordinator drives through device topology hints).  GPU
    allocations honor the node's partition table when one exists.

    ``minors`` carry a ``"type"`` name; returns {type_code: [minor, ...]}.
    Raises ValueError when any requested type cannot be satisfied
    (all-or-nothing, like the reference's tryAllocateDevice).
    """
    preferred = preferred or {}
    required = required or {}
    out: Dict[int, List[int]] = {}
    numa_hint: Set[int] = set()
    by_type: Dict[int, List[Mapping]] = {}
    for m in minors:
        code = DEVICE_TYPE_NAMES.get(str(m.get("type", "gpu")).lower(), DEVICE_GPU)
        by_type.setdefault(code, []).append(m)
    for code in _JOINT_TYPE_ORDER:
        per_card = per_card_by_type.get(code)
        if not per_card:
            continue
        wanted = int(wanted_by_type.get(code, 1))
        pool = by_type.get(code, [])
        if not pool:
            raise ValueError(f"node has no {_TYPE_NAMES[code]} devices")
        if code == DEVICE_GPU and partitions:
            chosen = allocate_partitioned(
                pool,
                per_card,
                wanted,
                partitions,
                preferred=preferred.get(code),
                required=required.get(code),
                most_allocated=most_allocated,
            )
        else:
            chosen = allocate_minors(
                pool,
                per_card,
                wanted,
                preferred=preferred.get(code),
                required=required.get(code),
                most_allocated=most_allocated,
                preferred_numa=numa_hint or None,
            )
        out[code] = chosen
        for m in pool:
            if m["minor"] in chosen:
                numa_hint.add(_numa_of(m))
    return out


def minor_dicts_from_batch(
    devices: DeviceBatch, node_idx: int
) -> List[Dict]:
    """Reconstruct host-side minor dicts for one node from the dense
    DeviceBatch — the Reserve path's input when the caller supplies only
    the tensor extras (device id from ``devices.minor``, falling back to
    the dense index; topology carried by ``devices.numa``)."""
    total = np.asarray(devices.total[node_idx])
    free = np.asarray(devices.free[node_idx])
    dtyp = np.asarray(devices.dev_type[node_idx])
    valid = np.asarray(devices.valid[node_idx])
    numa = (
        np.asarray(devices.numa[node_idx])
        if devices.numa is not None
        else np.zeros_like(dtyp)
    )
    minors_t = (
        np.asarray(devices.minor[node_idx])
        if devices.minor is not None
        else np.arange(total.shape[0], dtype=np.int32)
    )
    out: List[Dict] = []
    for d in range(total.shape[0]):
        if not valid[d]:
            continue
        dims = DEVICE_TYPE_RESOURCES[int(dtyp[d])]
        # tensor cells are axis units (MiB/milli); the minor-dict contract
        # carries parse_quantity-round-trippable forms
        out.append(
            {
                "minor": int(minors_t[d]),
                "type": DEVICE_TYPE_CODE_TO_NAME[int(dtyp[d])],
                "total": {
                    n: res.format_quantity(
                        int(total[d, DEVICE_RESOURCE_INDEX[n]]), n
                    )
                    for n in dims
                },
                "free": {
                    n: res.format_quantity(
                        int(free[d, DEVICE_RESOURCE_INDEX[n]]), n
                    )
                    for n in dims
                },
                "topology": {"numaNode": int(numa[d])},
            }
        )
    return out


def partition_fit_mask(
    pod_requests: jnp.ndarray,  # i64[P, R] (snapshot axis)
    devices: DeviceBatch,
    partitions_by_node: Mapping[int, Mapping[int, Sequence[Sequence[int]]]],
    *,
    per_card: Optional[np.ndarray] = None,  # precomputed [P, N, C]
    wanted: Optional[np.ndarray] = None,  # precomputed [P, N]
) -> np.ndarray:
    """bool[P, N] host-side refinement of ``device_fit_mask``: on nodes
    with a GPU partition table, a multi-card request only fits when some
    listed group of the wanted size has every member free enough — the
    count-based tensor fit can overcount minors that no single partition
    group contains.  Callers that already ran the normalization pipeline
    (plugins.DeviceSharePlugin.filter_mask) pass ``per_card``/``wanted``
    to avoid recomputing it."""
    dev_req = np.asarray(pod_device_requests(pod_requests))  # [P, C]
    if per_card is None or wanted is None:
        card_mem = gpu_card_total_memory(devices)
        norm = normalize_gpu_requests(jnp.asarray(dev_req), card_mem)
        per_card_t, wanted_t = split_per_card(norm)
        per_card = np.asarray(per_card_t)
        wanted = np.asarray(wanted_t)
    free = np.asarray(devices.free)
    is_gpu = np.asarray((devices.dev_type == DEVICE_GPU) & devices.valid)
    gpu_dims = [DEVICE_RESOURCE_INDEX[n] for n in DEVICE_TYPE_RESOURCES[DEVICE_GPU]]
    # partition-table groups carry CR minor ids (the id space
    # allocate_partitioned matches against m["minor"]), which differ from
    # dense slot indices on multi-type nodes; map minor -> slot per node,
    # restricted to GPU minors so an RDMA NIC sharing a minor number with
    # a GPU cannot shadow it.
    minors_t = (
        np.asarray(devices.minor)
        if devices.minor is not None
        else np.broadcast_to(
            np.arange(free.shape[1], dtype=np.int64), is_gpu.shape
        )
    )

    P, N = wanted.shape
    ok = np.ones((P, N), bool)
    gpu_requested = dev_req[:, gpu_dims].max(axis=1) > 0  # [P]
    for n, tables in (partitions_by_node or {}).items():
        if n >= N or not tables:
            continue
        minor_to_slot = {
            int(minors_t[n, d]): d
            for d in range(free.shape[1])
            if is_gpu[n, d]
        }
        for p in range(P):
            if not gpu_requested[p]:
                continue
            w = int(wanted[p, n])
            groups = tables.get(w)
            if not groups:
                continue  # no table for this size: tensor fit stands
            need = per_card[p, n][gpu_dims]
            fit = False
            for group in groups:
                if len(group) != w:
                    continue
                slots = [minor_to_slot.get(g) for g in group]
                if all(
                    d is not None and (free[n, d][gpu_dims] >= need).all()
                    for d in slots
                ):
                    fit = True
                    break
            ok[p, n] = fit
    return ok
