"""DeviceShare fit / scoring as batched tensors + exact minor allocation.

Reference: ``pkg/scheduler/plugins/deviceshare``:

* Requests are shares-of-100 per card; a request whose gpu-memory-ratio is a
  multiple of 100 spans ``ratio/100`` whole cards at ``request/wanted`` per
  card (``device_cache.go:367 calcDeviceWanted``).
* gpu-memory and gpu-memory-ratio fill each other from the card's total
  memory (``utils.go:211 fillGPUTotalMem``) — node-dependent, so the
  normalized request is a ``[P, N, C]`` tensor here.
* Filter: a node fits if, per requested device type, at least ``wanted``
  minors have ``free >= perCard`` (``device_cache.go:329-352``).
* Score: least/most-allocated over summed minor resources
  (``scoring.go:179 scoreNode``).
* The per-minor choice on the selected node replays the reference's exact
  ordering host-side (``allocate_minors``; ``device_resources.go:161,177``).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model.device import (
    DEVICE_GPU,
    DEVICE_RESOURCE_INDEX,
    DEVICE_TYPE_RESOURCES,
    DeviceBatch,
    NUM_DEVICE_RESOURCES,
)
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE
from koordinator_tpu.ops.scoring import (
    least_requested_score,
    most_requested_score,
    weighted_resource_score,
)

_CORE = DEVICE_RESOURCE_INDEX[res.GPU_CORE]
_MEM = DEVICE_RESOURCE_INDEX[res.GPU_MEMORY]
_RATIO = DEVICE_RESOURCE_INDEX[res.GPU_MEMORY_RATIO]


def pod_device_requests(pod_requests: jnp.ndarray) -> jnp.ndarray:
    """i64[P, C]: project snapshot resource rows onto the device axis."""
    idx = jnp.asarray(
        [res.RESOURCE_INDEX[n] for n in
         (res.GPU_CORE, res.GPU_MEMORY, res.GPU_MEMORY_RATIO, res.RDMA, res.FPGA)],
        dtype=jnp.int32,
    )
    return pod_requests[:, idx]


def gpu_card_total_memory(devices: DeviceBatch) -> jnp.ndarray:
    """i64[N]: per-node GPU card memory (all cards on a node are the same
    model — utils.go:225)."""
    is_gpu = (devices.dev_type == DEVICE_GPU) & devices.valid
    mem = jnp.where(is_gpu, devices.total[:, :, _MEM], 0)
    return mem.max(axis=1)


def normalize_gpu_requests(
    dev_requests: jnp.ndarray,  # i64[P, C]
    card_mem: jnp.ndarray,  # i64[N]
) -> jnp.ndarray:
    """i64[P, N, C]: fill gpu-memory <-> gpu-memory-ratio per node
    (fillGPUTotalMem): a memory-only request derives its ratio from the
    node's card memory and vice versa."""
    P = dev_requests.shape[0]
    N = card_mem.shape[0]
    out = jnp.broadcast_to(dev_requests[:, None, :], (P, N, dev_requests.shape[1]))
    mem_req = dev_requests[:, _MEM][:, None]  # [P, 1]
    ratio_req = dev_requests[:, _RATIO][:, None]
    safe_card = jnp.maximum(card_mem, 1)[None, :]  # [1, N]
    derived_ratio = mem_req * 100 // safe_card
    derived_mem = ratio_req * card_mem[None, :] // 100
    new_ratio = jnp.where(mem_req > 0, derived_ratio, ratio_req)
    new_mem = jnp.where(mem_req > 0, mem_req, derived_mem)
    out = out.at[:, :, _RATIO].set(new_ratio)
    out = out.at[:, :, _MEM].set(new_mem)
    return out


def split_per_card(norm_requests: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(perCard i64[P, N, C], wanted i64[P, N]) — calcDeviceWanted: a ratio
    that is a positive multiple of 100 spans ratio/100 cards."""
    ratio = norm_requests[..., _RATIO]
    multi = (ratio >= 100) & (ratio % 100 == 0)
    wanted = jnp.where(multi, ratio // 100, 1)
    per_card = norm_requests // jnp.maximum(wanted, 1)[..., None]
    return per_card, wanted


def device_fit_mask(
    pod_requests: jnp.ndarray,  # i64[P, R] (snapshot axis)
    devices: DeviceBatch,
) -> jnp.ndarray:
    """bool[P, N]: every requested device type has >= wanted satisfying minors."""
    dev_req = pod_device_requests(pod_requests)  # [P, C]
    card_mem = gpu_card_total_memory(devices)  # [N]
    norm = normalize_gpu_requests(dev_req, card_mem)  # [P, N, C]
    per_card, wanted = split_per_card(norm)

    ok = jnp.ones((dev_req.shape[0], devices.total.shape[0]), bool)
    for type_code, type_resources in DEVICE_TYPE_RESOURCES.items():
        dims = jnp.asarray(
            [DEVICE_RESOURCE_INDEX[n] for n in type_resources], dtype=jnp.int32
        )
        req_t = norm[:, :, dims]  # [P, N, Ct]
        requested_type = jnp.any(dev_req[:, dims] > 0, axis=-1)  # [P]
        minors_of_type = (devices.dev_type == type_code) & devices.valid  # [N, D]
        free_t = devices.free[:, :, dims]  # [N, D, Ct]
        per_card_t = per_card[:, :, dims]  # [P, N, Ct]
        satisfied = jnp.all(
            per_card_t[:, :, None, :] <= free_t[None, :, :, :], axis=-1
        )  # [P, N, D]
        satisfied &= minors_of_type[None, :, :]
        count = satisfied.sum(axis=-1)  # [P, N]
        type_ok = count >= wanted
        ok &= jnp.where(requested_type[:, None], type_ok, True)
        # requesting a type the node doesn't have at all fails
        has_type = jnp.any(minors_of_type, axis=-1)  # [N]
        ok &= jnp.where(
            requested_type[:, None], has_type[None, :] | type_ok, True
        )
    return ok


def deviceshare_scores(
    pod_requests: jnp.ndarray,  # i64[P, R]
    devices: DeviceBatch,
    weights: Optional[jnp.ndarray] = None,  # i64[C]
    *,
    most_allocated: bool = False,
) -> jnp.ndarray:
    """i64[P, N]: scoreNode (scoring.go:179) — least/most allocated over
    per-type summed minor resources; types the pod doesn't request
    contribute weight 0 (the reference masks podRequest per type)."""
    dev_req = pod_device_requests(pod_requests)  # [P, C]
    card_mem = gpu_card_total_memory(devices)
    norm = normalize_gpu_requests(dev_req, card_mem)  # [P, N, C]

    total = jnp.where(devices.valid[:, :, None], devices.total, 0).sum(axis=1)
    free = jnp.where(devices.valid[:, :, None], devices.free, 0).sum(axis=1)
    used = total - free  # [N, C]
    requested = used[None, :, :] + norm  # [P, N, C]
    if most_allocated:
        per_res = most_requested_score(requested, total[None, :, :])
    else:
        per_res = least_requested_score(requested, total[None, :, :])
    if weights is None:
        weights = jnp.ones((NUM_DEVICE_RESOURCES,), jnp.int64)
    # weight only the dims the pod requests (scoreNode skips total==0 dims;
    # requested-dim masking keeps non-requested types out of the mean)
    w = weights[None, None, :] * (norm > 0)
    wsum = jnp.maximum(w.sum(axis=-1), 1)
    return (per_res * w).sum(axis=-1) // wsum


def allocate_minors(
    minors: Sequence[Mapping],
    per_card: Mapping[str, int],
    wanted: int,
    *,
    preferred: Optional[Set[int]] = None,
    required: Optional[Set[int]] = None,
    most_allocated: bool = False,
) -> List[int]:
    """Host-side exact minor selection on the chosen node.

    ``minors``: ``[{"minor": int, "total": {dim: qty}, "free": {dim: qty}}]``.
    Ordering parity with scoreDevices + sortDeviceResourcesByMinor
    (device_resources.go:161,177): preferred minors first, then score
    descending (scoreDevice), then minor ascending; the first ``wanted``
    satisfying minors win.  Raises ValueError when the node can't satisfy.
    """
    preferred = preferred or set()
    required = required or set()

    def q(dim, value) -> int:
        return res.parse_quantity(value, dim)

    def free_of(m) -> Dict[str, int]:
        # an unallocated healthy device is fully free (deviceFree == total)
        src = m.get("free")
        if src is None:
            src = m.get("total") or {}
        return {dim: q(dim, v) for dim, v in src.items()}

    def score(m) -> int:
        s = 0
        n = 0
        free = free_of(m)
        for dim, total in (m.get("total") or {}).items():
            total = q(dim, total)
            if total == 0:
                continue
            f = free.get(dim, 0)
            req = total - f + int(per_card.get(dim, 0)) if total >= f else total
            if most_allocated:
                val = max(0, MAX_NODE_SCORE * req // total) if req <= total else 0
            else:
                val = (total - req) * MAX_NODE_SCORE // total if req <= total else 0
            s += val
            n += 1
        return s // n if n else 0

    ranked = sorted(
        minors,
        key=lambda m: (
            m["minor"] not in preferred,
            -score(m),
            m["minor"],
        ),
    )
    out: List[int] = []
    for m in ranked:
        if required and m["minor"] not in required:
            continue
        free = free_of(m)
        if all(free.get(d, 0) >= q_ for d, q_ in per_card.items()):
            out.append(m["minor"])
            if len(out) == wanted:
                return out
    raise ValueError(f"node cannot satisfy {wanted} device minors")
