"""Batched resource-allocation scorers.

Each function is shape-polymorphic over leading batch dims and uses exact
int64 integer arithmetic, bit-identical with the reference's Go scorers:

* ``least_requested_score`` — reference
  ``pkg/scheduler/plugins/nodenumaresource/least_allocated.go:49-58`` (same
  math as ``loadaware/load_aware.go:388`` and upstream NodeResourcesFit).
* ``most_requested_score`` — reference
  ``pkg/scheduler/plugins/nodenumaresource/most_allocated.go:46-63``.
* ``weighted_resource_score`` — the ``sum(score*weight)/weightSum`` reduction
  shared by every scorer (e.g. ``least_allocated.go:31-44``).

The per-pod/per-node Go loops become one broadcast over a dense
``pods x nodes x resources`` tensor; XLA fuses the broadcast, the integer
division and the weighted reduction into a single pass over HBM.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.model.snapshot import MAX_NODE_SCORE


def least_requested_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """((capacity-requested)*MaxNodeScore)/capacity; 0 if cap==0 or req>cap."""
    requested = requested.astype(jnp.int64)
    capacity = capacity.astype(jnp.int64)
    safe_cap = jnp.where(capacity == 0, 1, capacity)
    score = ((capacity - requested) * MAX_NODE_SCORE) // safe_cap
    return jnp.where((capacity == 0) | (requested > capacity), 0, score)


def most_requested_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """(min(requested,capacity)*MaxNodeScore)/capacity; 0 if cap==0."""
    requested = requested.astype(jnp.int64)
    capacity = capacity.astype(jnp.int64)
    safe_cap = jnp.where(capacity == 0, 1, capacity)
    clamped = jnp.minimum(requested, capacity)
    score = (clamped * MAX_NODE_SCORE) // safe_cap
    return jnp.where(capacity == 0, 0, score)


def weighted_resource_score(
    per_resource_score: jnp.ndarray, weights: jnp.ndarray
) -> jnp.ndarray:
    """sum_r(score_r * weight_r) / sum_r(weight_r) with integer division.

    ``per_resource_score``: i64[..., R]; ``weights``: i64[R] (0 = unscored).
    """
    weights = weights.astype(jnp.int64)
    weight_sum = jnp.sum(weights)
    total = jnp.sum(per_resource_score * weights, axis=-1)
    return jnp.where(weight_sum == 0, 0, total // jnp.maximum(weight_sum, 1))


def least_allocated_scores(
    pod_requests: jnp.ndarray,  # i64[P, R]
    node_requested: jnp.ndarray,  # i64[N, R]
    node_allocatable: jnp.ndarray,  # i64[N, R]
    weights: jnp.ndarray,  # i64[R]
) -> jnp.ndarray:
    """NodeResourcesFit/LeastAllocated over all (pod, node) pairs -> i64[P, N].

    Upstream semantics: for each weighted resource, score the node as if the
    pod were placed (requested + podRequest vs allocatable).
    """
    total = node_requested[None, :, :] + pod_requests[:, None, :]
    scores = least_requested_score(total, node_allocatable[None, :, :])
    return weighted_resource_score(scores, weights)


def most_allocated_scores(
    pod_requests: jnp.ndarray,
    node_requested: jnp.ndarray,
    node_allocatable: jnp.ndarray,
    weights: jnp.ndarray,
) -> jnp.ndarray:
    """MostAllocated (bin-packing) variant -> i64[P, N]."""
    total = node_requested[None, :, :] + pod_requests[:, None, :]
    scores = most_requested_score(total, node_allocatable[None, :, :])
    return weighted_resource_score(scores, weights)
