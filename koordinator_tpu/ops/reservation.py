"""Reservation restore / fit / scoring as batched tensors.

Reference: ``pkg/scheduler/plugins/reservation``:

* BeforePreFilter (``transformer.go:39``): for each node, matched
  reservations' unallocated remainder is returned to the node's free space
  for the scheduling pod — here a per-pod segment-sum over the node axis.
* Filter: Aligned/Restricted policies constrain the pod to the matched
  reservation's remaining resources (``plugin.go filterWithReservations``).
* PreScore/Score (``scoring.go:42,105,177``): nodes with a matching
  reservation score by MostAllocated over the reservation's declared
  resources; the node carrying the smallest nonzero reservation-order
  label is the preferred node and scores max.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.model.reservation import (
    ALLOCATE_POLICY_ALIGNED,
    ALLOCATE_POLICY_RESTRICTED,
    ReservationTable,
)
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE

_LONG_MAX = jnp.int64(2**62)


def _remaining_by_node(rsv: ReservationTable, num_nodes: int) -> jnp.ndarray:
    """i64[V, N, R] -> segment view helper is avoided; scatter-add each
    reservation's remainder onto its node row: i64[N, R]."""
    safe_idx = jnp.where(rsv.valid, rsv.node_index, 0)
    contrib = jnp.where(rsv.valid[:, None], rsv.remaining, 0)
    return (
        jnp.zeros((num_nodes, contrib.shape[-1]), contrib.dtype)
        .at[safe_idx]
        .add(jnp.where(rsv.valid[:, None], contrib, 0))
    )


def restored_node_free(
    node_allocatable: jnp.ndarray,  # i64[N, R]
    node_requested: jnp.ndarray,  # i64[N, R]
    rsv: ReservationTable,
) -> jnp.ndarray:
    """i64[P, N, R]: per-pod free space after restoring matched reservations.

    The reserve pseudo-pod holds the reservation's full allocatable in
    ``node_requested``; a matching pod sees the unallocated remainder of its
    matched reservations returned (transformer.go restore semantics).
    """
    base_free = (node_allocatable - node_requested)[None, :, :]  # [1, N, R]
    num_nodes = node_allocatable.shape[0]
    # per-pod restore: sum of remaining over matched reservations per node
    safe_idx = jnp.where(rsv.valid, rsv.node_index, 0)
    onehot = (
        (safe_idx[:, None] == jnp.arange(num_nodes)[None, :]) & rsv.valid[:, None]
    )  # [V, N]
    m = rsv.matched.astype(jnp.int64)  # [P, V]
    # [P, V] @ ([V, N] * [V, R] -> via einsum): restore[p, n, r]
    restore = jnp.einsum("pv,vn,vr->pnr", m, onehot.astype(jnp.int64), rsv.remaining)
    return base_free + restore


def reservation_fit_mask(
    pod_requests: jnp.ndarray,  # i64[P, R]
    rsv: ReservationTable,
) -> jnp.ndarray:
    """bool[P, V]: pod can allocate from the reservation under its policy.

    Restricted/Aligned: for every declared dim, request fits inside the
    reservation's remainder (plugin.go filterWithReservations).  Default:
    always true (the pod may spill to node free space).
    """
    fits_declared = jnp.all(
        ~rsv.declared[None, :, :]
        | (pod_requests[:, None, :] <= rsv.remaining[None, :, :]),
        axis=-1,
    )  # [P, V]
    constrained = (rsv.allocate_policy == ALLOCATE_POLICY_ALIGNED) | (
        rsv.allocate_policy == ALLOCATE_POLICY_RESTRICTED
    )
    ok = jnp.where(constrained[None, :], fits_declared, True)
    return ok & rsv.matched & rsv.valid[None, :] & ~rsv.unschedulable[None, :]


def reservation_scores(
    pod_requests: jnp.ndarray,  # i64[P, R]
    rsv: ReservationTable,
) -> jnp.ndarray:
    """i64[P, V]: scoreReservation (scoring.go:177) — MostAllocated over the
    reservation's declared dims with all weights 1:
    ``sum over declared r of MaxNodeScore * min-guarded (request+allocated)
    / allocatable`` divided by the number of declared dims.
    """
    requested = pod_requests[:, None, :] + rsv.allocated[None, :, :]
    cap = rsv.allocatable[None, :, :]
    safe_cap = jnp.where(cap == 0, 1, cap)
    per_res = jnp.where(
        rsv.declared[None, :, :] & (requested <= cap),
        MAX_NODE_SCORE * requested // safe_cap,
        0,
    )
    w = jnp.maximum(rsv.declared.sum(axis=-1), 1)[None, :]  # [1, V]
    scores = per_res.sum(axis=-1) // w
    return jnp.where(rsv.valid[None, :], scores, 0)


def reservation_affinity_mask(
    rsv: ReservationTable,
    num_nodes: int,
) -> Optional[jnp.ndarray]:
    """bool[P, N] Filter for required reservation affinity (reference
    ``plugin.go:238``: "node(s) no reservations match reservation
    affinity").  A pod flagged ``affinity_required`` may only land on
    nodes holding a matched, schedulable reservation; other pods pass
    everywhere.  None when no pod requires affinity (no mask cost)."""
    # trace-safe: only the None (field absent) case skips; an all-False
    # column just yields an all-True mask inside the fused program
    if rsv.affinity_required is None:
        return None
    usable = rsv.matched & rsv.valid[None, :] & ~rsv.unschedulable[None, :]
    safe_idx = jnp.where(rsv.valid, rsv.node_index, 0)
    onehot = (
        (safe_idx[None, :] == jnp.arange(num_nodes)[:, None])
        & rsv.valid[None, :]
    )  # [N, V]
    has_match = jnp.einsum(
        "pv,nv->pn", usable.astype(jnp.int32), onehot.astype(jnp.int32)
    ) > 0
    return has_match | ~rsv.affinity_required[:, None]


def nominate_reservations(
    pod_requests: jnp.ndarray,  # i64[P, R]
    rsv: ReservationTable,
    num_nodes: int,
):
    """Per (pod, node) nomination + node score, one device program.

    Returns ``(node_scores i64[P, N], nominated i32[P, N])`` where
    ``nominated`` is the reservation index the pod would allocate on that
    node (-1 = none).  Mirrors PreScore+Score (scoring.go:42,105): among
    fitting matched reservations on a node the highest scoreReservation
    wins; the node holding the globally smallest nonzero order label
    scores ``mostPreferredScore`` (max score here, the reference uses a
    large constant then normalizes).
    """
    fit = reservation_fit_mask(pod_requests, rsv)  # [P, V]
    scores = reservation_scores(pod_requests, rsv)  # [P, V]
    num_v = rsv.capacity

    safe_idx = jnp.where(rsv.valid, rsv.node_index, 0)
    onehot = (
        (safe_idx[None, :] == jnp.arange(num_nodes)[:, None]) & rsv.valid[None, :]
    )  # [N, V]

    masked = jnp.where(fit[:, None, :] & onehot[None, :, :], scores[:, None, :], -1)
    node_scores = masked.max(axis=-1)  # [P, N]
    nominated = jnp.where(
        node_scores >= 0, masked.argmax(axis=-1).astype(jnp.int32), -1
    )
    node_scores = jnp.maximum(node_scores, 0)

    # preferred node: reservation with the smallest nonzero order among the
    # pod's fitting matches (scoring.go:92-101)
    order = jnp.where(
        (rsv.order != 0) & fit, rsv.order[None, :], _LONG_MAX
    )  # [P, V]
    best_order = order.min(axis=-1)  # [P]
    best_v = order.argmin(axis=-1)  # [P]
    has_order = best_order < _LONG_MAX
    preferred_node = jnp.where(has_order, rsv.node_index[best_v], -1)  # [P]
    node_ids = jnp.arange(num_nodes)[None, :]
    node_scores = jnp.where(
        node_ids == preferred_node[:, None], MAX_NODE_SCORE, node_scores
    )
    return node_scores, nominated
