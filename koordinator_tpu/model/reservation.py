"""Reservation data model: reservations as dense tables + owner matching.

The reference models a Reservation as a pseudo-pod occupying its reserved
resources on a node, restored into NodeInfo per scheduling pod by the
transformer (reference ``pkg/scheduler/plugins/reservation/transformer.go:39
BeforePreFilter``).  Here a cycle carries one ``ReservationTable`` with a
host-precomputed ``matched[P, V]`` owner matrix, and the restore becomes a
segment-sum over the node axis inside the jitted cycle
(``koordinator_tpu.ops.reservation``).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res

# Allocate policies (reference apis/scheduling/v1alpha1/reservation_types.go
# ReservationAllocatePolicy)
ALLOCATE_POLICY_DEFAULT = 0
ALLOCATE_POLICY_ALIGNED = 1
ALLOCATE_POLICY_RESTRICTED = 2


@dataclasses.dataclass
class ReservationTable:
    """Dense reservation state, shapes [V] / [V, R] (+ matched [P, V]).

    ``remaining = allocatable - allocated`` is what a matching pod may take;
    ``declared`` marks the nonzero allocatable dims (the reference scores
    and restricts only over ``quotav1.RemoveZeros(allocatable)``,
    scoring.go:186).
    """

    node_index: jnp.ndarray  # i32[V] node the reservation is bound to, -1 unbound
    allocatable: jnp.ndarray  # i64[V, R]
    allocated: jnp.ndarray  # i64[V, R] already taken by owner pods
    declared: jnp.ndarray  # bool[V, R]
    allocate_policy: jnp.ndarray  # i32[V] ALLOCATE_POLICY_*
    order: jnp.ndarray  # i64[V] LabelReservationOrder, 0 = unset
    unschedulable: jnp.ndarray  # bool[V]
    valid: jnp.ndarray  # bool[V]
    matched: jnp.ndarray  # bool[P, V] owner match per pending pod
    names: Tuple[str, ...] = ()

    @property
    def capacity(self) -> int:
        return self.allocatable.shape[0]

    @property
    def remaining(self) -> jnp.ndarray:
        return self.allocatable - self.allocated


jax.tree_util.register_dataclass(
    ReservationTable,
    data_fields=[
        "node_index",
        "allocatable",
        "allocated",
        "declared",
        "allocate_policy",
        "order",
        "unschedulable",
        "valid",
        "matched",
    ],
    meta_fields=["names"],
)


def match_owners(pod: Mapping, owners: Sequence[Mapping]) -> bool:
    """reference ``pkg/util/reservation`` MatchReservationOwners: a pod may
    allocate a reservation if any owner entry matches — by exact object
    reference (namespace/name), controller reference, or label selector.
    """
    for owner in owners or ():
        obj = owner.get("object")
        if obj is not None:
            if obj.get("name") == pod.get("name") and obj.get(
                "namespace", "default"
            ) == pod.get("namespace", "default"):
                return True
            continue
        controller = owner.get("controller")
        if controller is not None:
            ref = pod.get("owner_ref") or {}
            if controller.get("name") == ref.get("name") and controller.get(
                "namespace", pod.get("namespace", "default")
            ) == pod.get("namespace", "default"):
                return True
            continue
        selector = owner.get("label_selector")
        if selector is not None:
            labels = pod.get("labels", {})
            if all(labels.get(k) == v for k, v in selector.items()):
                return True
    return False


_POLICY_NAMES = {
    "Default": ALLOCATE_POLICY_DEFAULT,
    "Aligned": ALLOCATE_POLICY_ALIGNED,
    "Restricted": ALLOCATE_POLICY_RESTRICTED,
}


def encode_reservations(
    reservations: Sequence[Mapping],
    pods: Sequence[Mapping],
    node_names: Sequence[str],
    *,
    pod_bucket: Optional[int] = None,
    reservation_bucket: Optional[int] = None,
) -> ReservationTable:
    """Encode reservation dicts + pending pods into a ReservationTable.

    Reservation dict: ``{"name", "node": node-name, "allocatable": {...},
    "allocated": {...}, "owners": [...], "allocate_policy":
    "Default"|"Aligned"|"Restricted", "order": int, "allocate_once": bool,
    "assigned_pods": int, "unschedulable": bool}``.

    AllocateOnce reservations that already have assigned pods are dropped
    from the table entirely (the reference skips them during restore,
    transformer.go:95).
    """
    from koordinator_tpu.model.snapshot import pad_bucket

    active = [
        r
        for r in reservations
        if not (r.get("allocate_once") and r.get("assigned_pods", 0) > 0)
    ]
    v_bucket = reservation_bucket or pad_bucket(max(len(active), 1))
    p_bucket = pod_bucket or pad_bucket(max(len(pods), 1))
    R = res.NUM_RESOURCES
    node_idx = {n: i for i, n in enumerate(node_names)}

    node_index = np.full((v_bucket,), -1, np.int32)
    alloc = np.zeros((v_bucket, R), np.int64)
    allocated = np.zeros((v_bucket, R), np.int64)
    declared = np.zeros((v_bucket, R), bool)
    policy = np.zeros((v_bucket,), np.int32)
    order = np.zeros((v_bucket,), np.int64)
    unsched = np.zeros((v_bucket,), bool)
    valid = np.zeros((v_bucket,), bool)
    matched = np.zeros((p_bucket, v_bucket), bool)

    for i, r in enumerate(active):
        node_index[i] = node_idx.get(r.get("node"), -1)
        alloc[i] = res.resource_vector(r.get("allocatable", {}))
        allocated[i] = res.resource_vector(r.get("allocated", {}))
        declared[i] = alloc[i] != 0
        policy[i] = _POLICY_NAMES.get(r.get("allocate_policy", "Default"), 0)
        order[i] = int(r.get("order", 0))
        unsched[i] = bool(r.get("unschedulable"))
        valid[i] = node_index[i] >= 0
        for p, pod in enumerate(pods):
            matched[p, i] = valid[i] and match_owners(pod, r.get("owners", ()))

    return ReservationTable(
        node_index=jnp.asarray(node_index),
        allocatable=jnp.asarray(alloc),
        allocated=jnp.asarray(allocated),
        declared=jnp.asarray(declared),
        allocate_policy=jnp.asarray(policy),
        order=jnp.asarray(order),
        unschedulable=jnp.asarray(unsched),
        valid=jnp.asarray(valid),
        matched=jnp.asarray(matched),
        names=tuple(r.get("name", f"rsv-{i}") for i, r in enumerate(active)),
    )
