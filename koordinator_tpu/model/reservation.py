"""Reservation data model: reservations as dense tables + owner matching.

The reference models a Reservation as a pseudo-pod occupying its reserved
resources on a node, restored into NodeInfo per scheduling pod by the
transformer (reference ``pkg/scheduler/plugins/reservation/transformer.go:39
BeforePreFilter``).  Here a cycle carries one ``ReservationTable`` with a
host-precomputed ``matched[P, V]`` owner matrix, and the restore becomes a
segment-sum over the node axis inside the jitted cycle
(``koordinator_tpu.ops.reservation``).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res

# Allocate policies (reference apis/scheduling/v1alpha1/reservation_types.go
# ReservationAllocatePolicy)
ALLOCATE_POLICY_DEFAULT = 0
ALLOCATE_POLICY_ALIGNED = 1
ALLOCATE_POLICY_RESTRICTED = 2


@dataclasses.dataclass
class ReservationTable:
    """Dense reservation state, shapes [V] / [V, R] (+ matched [P, V]).

    ``remaining = allocatable - allocated`` is what a matching pod may take;
    ``declared`` marks the nonzero allocatable dims (the reference scores
    and restricts only over ``quotav1.RemoveZeros(allocatable)``,
    scoring.go:186).
    """

    node_index: jnp.ndarray  # i32[V] node the reservation is bound to, -1 unbound
    allocatable: jnp.ndarray  # i64[V, R]
    allocated: jnp.ndarray  # i64[V, R] already taken by owner pods
    declared: jnp.ndarray  # bool[V, R]
    allocate_policy: jnp.ndarray  # i32[V] ALLOCATE_POLICY_*
    order: jnp.ndarray  # i64[V] LabelReservationOrder, 0 = unset
    unschedulable: jnp.ndarray  # bool[V]
    valid: jnp.ndarray  # bool[V]
    matched: jnp.ndarray  # bool[P, V] owner match per pending pod
    # pods carrying a REQUIRED reservation affinity
    # (AnnotationReservationAffinity): such a pod may only land on nodes
    # holding a matched reservation (reference plugin.go:238 Filter
    # "node(s) no reservations match reservation affinity")
    affinity_required: Optional[jnp.ndarray] = None  # bool[P]
    names: Tuple[str, ...] = ()
    # CR UIDs, parallel to names ("" when unknown): the
    # reservation-allocated annotation carries both (reference
    # SetReservationAllocated, apis/extension/reservation.go:86-97)
    uids: Tuple[str, ...] = ()

    @property
    def capacity(self) -> int:
        return self.allocatable.shape[0]

    @property
    def remaining(self) -> jnp.ndarray:
        return self.allocatable - self.allocated


jax.tree_util.register_dataclass(
    ReservationTable,
    data_fields=[
        "node_index",
        "allocatable",
        "allocated",
        "declared",
        "allocate_policy",
        "order",
        "unschedulable",
        "valid",
        "matched",
        "affinity_required",
    ],
    # names/uids are static metadata for the embedded extras path only
    # (host-side reply/report assembly); the bridge hot path never ships
    # a ReservationTable, and a changed reservation set already retraces
    # through the [V]-shape change itself
    meta_fields=["names", "uids"],  # koordlint: disable=retrace-hazard(embedded extras path; shape change dominates)
)


def match_owners(pod: Mapping, owners: Sequence[Mapping]) -> bool:
    """reference ``pkg/util/reservation`` MatchReservationOwners: a pod may
    allocate a reservation if any owner entry matches — by exact object
    reference (namespace/name), controller reference, or label selector.
    """
    for owner in owners or ():
        obj = owner.get("object")
        if obj is not None:
            if obj.get("name") == pod.get("name") and obj.get(
                "namespace", "default"
            ) == pod.get("namespace", "default"):
                return True
            continue
        controller = owner.get("controller")
        if controller is not None:
            ref = pod.get("owner_ref") or {}
            if controller.get("name") == ref.get("name") and controller.get(
                "namespace", pod.get("namespace", "default")
            ) == pod.get("namespace", "default"):
                return True
            continue
        selector = owner.get("label_selector")
        if selector is not None:
            labels = pod.get("labels", {})
            if all(labels.get(k) == v for k, v in selector.items()):
                return True
    return False


_POLICY_NAMES = {
    "Default": ALLOCATE_POLICY_DEFAULT,
    "Aligned": ALLOCATE_POLICY_ALIGNED,
    "Restricted": ALLOCATE_POLICY_RESTRICTED,
}

# reference apis/extension/reservation.go:40
RESERVATION_AFFINITY_ANNOTATION = (
    "scheduling.koordinator.sh/reservation-affinity"
)


#: sentinel for a present-but-unparseable affinity annotation: the pod
#: REQUIRES reservation affinity but can match nothing — it schedules
#: nowhere through reservations, mirroring the reference's per-pod
#: rejection (GetReservationAffinity error -> PreFilter Unschedulable)
#: without aborting the whole table encode.
INVALID_AFFINITY = object()


def required_reservation_affinity(pod: Mapping):
    """Parse the pod's ReservationAffinity annotation (the reference's
    exact key and JSON shape, apis/extension/reservation.go:48-68):
    ``{"reservationSelector": {k: v}, "requiredDuringScheduling...":
    {"reservationSelectorTerms": [{"matchExpressions": [...]}]}}``.
    Returns the parsed dict, None when the pod has no affinity, or
    ``INVALID_AFFINITY`` when the annotation is present but malformed
    (one bad pod must not abort encoding every other pod's table)."""
    import json

    raw = (pod.get("annotations") or {}).get(RESERVATION_AFFINITY_ANNOTATION)
    if not raw:
        return None
    if isinstance(raw, Mapping):
        return raw
    try:
        parsed = json.loads(raw)
    except ValueError:
        return INVALID_AFFINITY
    return parsed if isinstance(parsed, Mapping) else INVALID_AFFINITY


def _match_expressions(labels: Mapping, exprs: Sequence[Mapping]) -> bool:
    """corev1.NodeSelectorTerm matchExpressions over reservation labels
    (terms reuse the node-selector operators; GetReservationAffinity
    validates the same set)."""
    for e in exprs or ():
        key = e.get("key")
        op = e.get("operator")
        values = e.get("values") or []
        have = key in labels
        val = labels.get(key)
        if op == "In":
            if not (have and val in values):
                return False
        elif op == "NotIn":
            if have and val in values:
                return False
        elif op == "Exists":
            if not have:
                return False
        elif op == "DoesNotExist":
            if have:
                return False
        else:
            return False  # unknown operator: fail closed, like validation
    return True


def matches_reservation_affinity(
    affinity: Mapping, reservation_labels: Mapping
) -> bool:
    """reference pkg/util/reservation GetRequiredReservationAffinity +
    ReservationAffinity.Match: the flat ``reservationSelector`` map must
    all match; selector TERMS are ORed."""
    selector = affinity.get("reservationSelector")
    if selector:
        if not all(reservation_labels.get(k) == v for k, v in selector.items()):
            return False
    required = affinity.get(
        "requiredDuringSchedulingIgnoredDuringExecution"
    )
    terms = (required or {}).get("reservationSelectorTerms")
    if terms:
        return any(
            _match_expressions(reservation_labels, t.get("matchExpressions"))
            for t in terms
        )
    return True


def encode_reservations(
    reservations: Sequence[Mapping],
    pods: Sequence[Mapping],
    node_names: Sequence[str],
    *,
    pod_bucket: Optional[int] = None,
    reservation_bucket: Optional[int] = None,
) -> ReservationTable:
    """Encode reservation dicts + pending pods into a ReservationTable.

    Reservation dict: ``{"name", "node": node-name, "allocatable": {...},
    "allocated": {...}, "owners": [...], "labels": {...},
    "allocate_policy": "Default"|"Aligned"|"Restricted", "order": int,
    "allocate_once": bool, "assigned_pods": int, "unschedulable": bool}``.

    AllocateOnce reservations that already have assigned pods are dropped
    from the table entirely (the reference skips them during restore,
    transformer.go:95).

    A pod carrying the ReservationAffinity annotation (the reference's
    exact key ``scheduling.koordinator.sh/reservation-affinity``) matches
    only reservations whose LABELS satisfy its selector, and is flagged
    in ``affinity_required`` — the ReservationPlugin's filter then admits
    it only onto nodes holding a matched reservation (plugin.go:238).
    """
    from koordinator_tpu.model.snapshot import pad_bucket

    active = [
        r
        for r in reservations
        if not (r.get("allocate_once") and r.get("assigned_pods", 0) > 0)
    ]
    v_bucket = reservation_bucket or pad_bucket(max(len(active), 1))
    p_bucket = pod_bucket or pad_bucket(max(len(pods), 1))
    R = res.NUM_RESOURCES
    node_idx = {n: i for i, n in enumerate(node_names)}

    node_index = np.full((v_bucket,), -1, np.int32)
    alloc = np.zeros((v_bucket, R), np.int64)
    allocated = np.zeros((v_bucket, R), np.int64)
    declared = np.zeros((v_bucket, R), bool)
    policy = np.zeros((v_bucket,), np.int32)
    order = np.zeros((v_bucket,), np.int64)
    unsched = np.zeros((v_bucket,), bool)
    valid = np.zeros((v_bucket,), bool)
    matched = np.zeros((p_bucket, v_bucket), bool)

    affinity_required = np.zeros((p_bucket,), bool)
    pod_affinity = [required_reservation_affinity(pod) for pod in pods]
    for p, aff in enumerate(pod_affinity):
        affinity_required[p] = aff is not None

    for i, r in enumerate(active):
        node_index[i] = node_idx.get(r.get("node"), -1)
        alloc[i] = res.resource_vector(r.get("allocatable", {}))
        allocated[i] = res.resource_vector(r.get("allocated", {}))
        declared[i] = alloc[i] != 0
        policy[i] = _POLICY_NAMES.get(r.get("allocate_policy", "Default"), 0)
        order[i] = int(r.get("order", 0))
        unsched[i] = bool(r.get("unschedulable"))
        valid[i] = node_index[i] >= 0
        rlabels = r.get("labels", {})
        for p, pod in enumerate(pods):
            ok = valid[i] and match_owners(pod, r.get("owners", ()))
            if ok and pod_affinity[p] is not None:
                # malformed affinity: required with zero matches (the
                # pod alone becomes unschedulable via reservations)
                ok = pod_affinity[p] is not INVALID_AFFINITY and (
                    matches_reservation_affinity(pod_affinity[p], rlabels)
                )
            matched[p, i] = ok

    return ReservationTable(
        node_index=jnp.asarray(node_index),
        allocatable=jnp.asarray(alloc),
        allocated=jnp.asarray(allocated),
        declared=jnp.asarray(declared),
        allocate_policy=jnp.asarray(policy),
        order=jnp.asarray(order),
        unschedulable=jnp.asarray(unsched),
        valid=jnp.asarray(valid),
        matched=jnp.asarray(matched),
        affinity_required=jnp.asarray(affinity_required),
        names=tuple(r.get("name", f"rsv-{i}") for i, r in enumerate(active)),
        uids=tuple(str(r.get("uid", "")) for r in active),
    )
