"""Resource registry and Kubernetes quantity parsing.

The dense snapshot tensors have a fixed, ordered resource axis.  This module
defines that ordering and converts Kubernetes-style quantity strings
("500m", "8Gi", "2") into the integer units each resource is accounted in.

Units follow the reference's accounting (reference
``pkg/scheduler/plugins/loadaware/load_aware.go`` ``getResourceValue``:
CPU in milli-cores via ``MilliValue()``, everything else in base units via
``Value()``; batch-cpu is already milli — ``apis/extension/resource.go:26``).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Mapping

# Canonical resource axis for all snapshot tensors.  Order is part of the
# on-device ABI: encoders, kernels and the bridge all index by it.
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
BATCH_CPU = "kubernetes.io/batch-cpu"
BATCH_MEMORY = "kubernetes.io/batch-memory"
MID_CPU = "kubernetes.io/mid-cpu"
MID_MEMORY = "kubernetes.io/mid-memory"
GPU_CORE = "koordinator.sh/gpu-core"
GPU_MEMORY_RATIO = "koordinator.sh/gpu-memory-ratio"
GPU_MEMORY = "koordinator.sh/gpu-memory"
RDMA = "koordinator.sh/rdma"
FPGA = "koordinator.sh/fpga"

RESOURCE_AXIS = (
    CPU,
    MEMORY,
    EPHEMERAL_STORAGE,
    PODS,
    BATCH_CPU,
    BATCH_MEMORY,
    MID_CPU,
    MID_MEMORY,
    GPU_CORE,
    GPU_MEMORY_RATIO,
    GPU_MEMORY,
    RDMA,
    FPGA,
)
NUM_RESOURCES = len(RESOURCE_AXIS)
RESOURCE_INDEX: Dict[str, int] = {name: i for i, name in enumerate(RESOURCE_AXIS)}

# Resources accounted in milli-units (the reference calls MilliValue() for
# native cpu; batch-cpu / mid-cpu quantities are already expressed in milli).
_MILLI_RESOURCES = frozenset({CPU})

# Byte-denominated resources are accounted in MiB on the dense axis (the
# reference accounts them in bytes via Quantity.Value()).  MiB units keep
# every scoring intermediate — (capacity - requested) * MaxNodeScore — inside
# int32 for capacities up to 2^31/100 MiB (~20 TiB per node), which lets the
# Pallas cycle kernel run exact integer score math on the VPU without int64
# emulation.  Inputs remain k8s byte quantities; only the axis unit changes.
MIB_RESOURCES = frozenset(
    {MEMORY, EPHEMERAL_STORAGE, BATCH_MEMORY, MID_MEMORY, GPU_MEMORY}
)
MIB = 1024 * 1024

_BINARY_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIX = {
    "n": 10**-9,
    "u": 10**-6,
    "m": 10**-3,
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+)([A-Za-z]*)$")


def _base_units(value, resource: str) -> float:
    """Quantity -> float base units (bytes for memory, cores for cpu)."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    text = str(value).strip()
    m = _QUANTITY_RE.match(text)
    if m is None:
        raise ValueError(f"unparseable quantity {value!r} for {resource}")
    digits, suffix = m.groups()
    if suffix in _BINARY_SUFFIX:
        return float(digits) * _BINARY_SUFFIX[suffix]
    if suffix in _DECIMAL_SUFFIX:
        return float(digits) * _DECIMAL_SUFFIX[suffix]
    raise ValueError(f"unknown quantity suffix {suffix!r} in {value!r}")


def _ceil(base: float) -> int:
    # Quantity.Value() rounds up to the nearest integer.
    iv = int(base)
    return iv if iv == base or base < 0 else iv + 1


def parse_quantity(value, resource: str) -> int:
    """Parse a quantity into the integer unit used on the resource axis.

    ``cpu`` is returned in milli-cores (``"1.5" -> 1500``, ``"500m" -> 500``);
    byte-denominated resources (memory, ephemeral-storage, batch/mid memory,
    gpu-memory) in MiB rounded up (``"1Gi" -> 1024``, ``"512Mi" -> 512``);
    all other resources in base units rounded up like apimachinery's
    ``Quantity.Value()`` (``"100m" -> 1`` for non-cpu, matching ceil
    semantics).
    """
    base = _base_units(value, resource)
    if resource in _MILLI_RESOURCES:
        return round(base * 1000)
    if resource in MIB_RESOURCES:
        base = base / MIB
    return _ceil(base)


def parse_quantity_bytes(value, resource: str) -> int:
    """Parse a byte-denominated quantity into BYTES (not axis MiB units).

    For node-local actuation (cgroup memory limits) where the kernel needs
    bytes.  Accepts the same forms as parse_quantity; raw numbers are bytes.
    """
    if resource not in MIB_RESOURCES:
        raise ValueError(f"{resource} is not byte-denominated")
    return _ceil(_base_units(value, resource))


def format_quantity(axis_value: int, resource: str):
    """Render an axis-unit integer as a quantity that parse_quantity will
    round-trip exactly (MiB resources need the "Mi" suffix; cpu axis units
    are milli, rendered with "m").  Producers that write system-computed
    resources back into pod/node objects must use this."""
    if resource in MIB_RESOURCES:
        return f"{int(axis_value)}Mi"
    if resource in _MILLI_RESOURCES:
        return f"{int(axis_value)}m"
    return int(axis_value)


def encode_resource_list(resources: Mapping[str, object]) -> Dict[int, int]:
    """Map a {resource-name: quantity} dict onto {axis-index: int units}.

    Unknown resource names are ignored (the dense axis is fixed; exotic
    scalar resources ride the bridge as opaque key/values instead).
    """
    out: Dict[int, int] = {}
    for name, q in resources.items():
        idx = RESOURCE_INDEX.get(name)
        if idx is not None:
            out[idx] = parse_quantity(q, name)
    return out


def resource_vector(resources: Mapping[str, object]) -> list:
    """Encode into a dense length-NUM_RESOURCES python int list."""
    vec = [0] * NUM_RESOURCES
    for idx, v in encode_resource_list(resources).items():
        vec[idx] = v
    return vec


def weights_vector(weights: Mapping[str, int]) -> list:
    """Encode a resource->weight map onto the dense axis (0 = unscored)."""
    vec = [0] * NUM_RESOURCES
    for name, w in weights.items():
        idx = RESOURCE_INDEX.get(name)
        if idx is not None:
            vec[idx] = int(w)
    return vec


def names(indices: Iterable[int]) -> list:
    return [RESOURCE_AXIS[i] for i in indices]
