from koordinator_tpu.model.resources import (  # noqa: F401
    RESOURCE_AXIS,
    RESOURCE_INDEX,
    NUM_RESOURCES,
    parse_quantity,
    resource_vector,
    weights_vector,
)
from koordinator_tpu.model.snapshot import (  # noqa: F401
    ClusterSnapshot,
    NodeBatch,
    PodBatch,
    GangTable,
    QuotaTable,
    PriorityClass,
    QoSClass,
    encode_snapshot,
    pad_bucket,
)
