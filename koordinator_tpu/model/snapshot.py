"""Fixed-shape cluster snapshot: the contract everything compiles against.

A scheduling cycle's world state — nodes (allocatable / requested / measured
usage), pending pods (requests / estimated usage / QoS / priority / gang /
quota membership) — is encoded as dense, padded int64 arrays so that one
``jax.jit``-compiled program scores and assigns every pending pod against
every candidate node at once.  This mirrors the semantics of the reference's
data model (reference ``apis/extension/qos.go:22``, ``priority.go:29``,
``resource.go:26``) without its per-object Go representation.

Pod/node counts vary per cycle; arrays are padded to shape *buckets*
(powers of two by default) so XLA compiles one program per bucket instead of
one per cycle (reference analog: the Go scheduler has no compile step; for
XLA this padding is what keeps the hot path recompile-free).

Estimator semantics (``estimated`` field) follow the reference's
defaultEstimator exactly (reference
``pkg/scheduler/plugins/loadaware/estimator/default_estimator.go:81-127``):
``max(request, limit)`` scaled by per-resource factors, with 250m CPU /
200MiB defaults for unset requests, translated to batch-/mid- resources by
priority class (reference ``apis/extension/resource.go:53``).
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res

MAX_NODE_SCORE = 100  # k8s framework.MaxNodeScore

DEFAULT_MILLI_CPU_REQUEST = 250  # default_estimator.go:36
DEFAULT_MEMORY_REQUEST = 200  # default_estimator.go:38: 200Mi, on the MiB axis

# v1beta2/defaults.go:35-48
DEFAULT_RESOURCE_WEIGHTS = {res.CPU: 1, res.MEMORY: 1}
DEFAULT_USAGE_THRESHOLDS = {res.CPU: 65, res.MEMORY: 95}
DEFAULT_ESTIMATED_SCALING_FACTORS = {res.CPU: 85, res.MEMORY: 70}
DEFAULT_NODE_METRIC_EXPIRATION_SECONDS = 180

# NodeMetric aggregated-usage percentiles carried by NodeBatch.agg_usage
# (reference slov1alpha1 AggregationType; the statesinformer aggregates
# these four windows, ``impl/states_nodemetric.go:324``)
PERCENTILES = ("p50", "p90", "p95", "p99")


class PriorityClass(enum.IntEnum):
    """Koordinator priority bands (reference apis/extension/priority.go:29)."""

    PROD = 0
    MID = 1
    BATCH = 2
    FREE = 3
    NONE = 4

    @classmethod
    def from_name(cls, name: Optional[str]) -> "PriorityClass":
        return {
            "koord-prod": cls.PROD,
            "koord-mid": cls.MID,
            "koord-batch": cls.BATCH,
            "koord-free": cls.FREE,
        }.get(name or "", cls.NONE)

    @classmethod
    def from_priority_value(cls, priority: Optional[int]) -> "PriorityClass":
        # reference apis/extension/priority.go:84-101
        if priority is None:
            return cls.NONE
        if 9000 <= priority <= 9999:
            return cls.PROD
        if 7000 <= priority <= 7999:
            return cls.MID
        if 5000 <= priority <= 5999:
            return cls.BATCH
        if 3000 <= priority <= 3999:
            return cls.FREE
        return cls.NONE


class QoSClass(enum.IntEnum):
    """Koordinator QoS classes (reference apis/extension/qos.go:22-28)."""

    LSE = 0
    LSR = 1
    LS = 2
    BE = 3
    SYSTEM = 4
    NONE = 5

    @classmethod
    def from_name(cls, name: Optional[str]) -> "QoSClass":
        return {
            "LSE": cls.LSE,
            "LSR": cls.LSR,
            "LS": cls.LS,
            "BE": cls.BE,
            "SYSTEM": cls.SYSTEM,
        }.get(name or "", cls.NONE)


# PriorityClass -> {native resource index -> translated resource index},
# reference apis/extension/resource.go:40-49.
_RESOURCE_TRANSLATION = {
    PriorityClass.BATCH: {
        res.RESOURCE_INDEX[res.CPU]: res.RESOURCE_INDEX[res.BATCH_CPU],
        res.RESOURCE_INDEX[res.MEMORY]: res.RESOURCE_INDEX[res.BATCH_MEMORY],
    },
    PriorityClass.MID: {
        res.RESOURCE_INDEX[res.CPU]: res.RESOURCE_INDEX[res.MID_CPU],
        res.RESOURCE_INDEX[res.MEMORY]: res.RESOURCE_INDEX[res.MID_MEMORY],
    },
}


def translate_resource_index(priority_class: PriorityClass, idx: int) -> int:
    """reference apis/extension/resource.go:53 TranslateResourceNameByPriorityClass."""
    if priority_class in (PriorityClass.PROD, PriorityClass.NONE):
        return idx
    return _RESOURCE_TRANSLATION.get(priority_class, {}).get(idx, idx)


def pad_bucket(n: int, minimum: int = 8) -> int:
    """Smallest power-of-two bucket >= n (>= minimum)."""
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass
class NodeBatch:
    """Dense node-side state, shapes [N] / [N, R]."""

    allocatable: jnp.ndarray  # i64[N, R] node allocatable (estimator-adjusted)
    requested: jnp.ndarray  # i64[N, R] sum of scheduled pod requests (Fit accounting)
    usage: jnp.ndarray  # i64[N, R] measured usage from NodeMetric
    metric_fresh: jnp.ndarray  # bool[N] NodeMetric exists and is not expired
    valid: jnp.ndarray  # bool[N] padding mask
    # LoadAware aggregated/prod extensions (reference
    # ``plugins/loadaware/load_aware.go:150-226,291-311``; None = the node
    # source reported no such data and the plain tensors apply):
    # aggregated usage percentiles, axis order config.PERCENTILES
    agg_usage: "jnp.ndarray | None" = None  # i64[N, A, R]
    # which (node, percentile) cells carry real data — a node may report
    # only some percentiles; missing ones fall back like the reference's
    # nil getTargetAggregatedUsage (filter passes, score uses plain usage)
    agg_fresh: "jnp.ndarray | None" = None  # bool[N, A]
    prod_usage: "jnp.ndarray | None" = None  # i64[N, R] sum of prod pods' usage
    # accelerator type per node (ISSUE 15 heterogeneity term): index
    # into the throughput matrix's accelerator axis; None = never
    # synced (the term treats every node as type 0)
    accel_type: "jnp.ndarray | None" = None  # i32[N]
    names: Tuple[str, ...] = ()

    @property
    def capacity(self) -> int:
        return self.allocatable.shape[0]


@dataclasses.dataclass
class PodBatch:
    """Dense pending-pod state, shapes [P] / [P, R]."""

    requests: jnp.ndarray  # i64[P, R] raw requests (Fit accounting)
    estimated: jnp.ndarray  # i64[P, R] LoadAware estimator output
    priority_class: jnp.ndarray  # i32[P] PriorityClass enum
    qos: jnp.ndarray  # i32[P] QoSClass enum
    priority: jnp.ndarray  # i32[P] raw pod priority value (queue order)
    gang_id: jnp.ndarray  # i32[P] index into GangTable, -1 = no gang
    quota_id: jnp.ndarray  # i32[P] index into QuotaTable, -1 = no quota
    valid: jnp.ndarray  # bool[P] padding mask
    # fused-term pod columns (ISSUE 15): workload class indexes the
    # throughput matrix's class axis (heterogeneity); sensitivity is the
    # Synergy-style per-resource profile in [0, 100].  None = never
    # synced — the terms are inert for the missing half.
    workload_class: "jnp.ndarray | None" = None  # i32[P]
    sensitivity: "jnp.ndarray | None" = None  # i64[P, R]
    names: Tuple[str, ...] = ()

    @property
    def capacity(self) -> int:
        return self.requests.shape[0]


@dataclasses.dataclass
class GangTable:
    """Coscheduling PodGroups (reference plugins/coscheduling/core/core.go:220).

    ``min_member`` is the gang's minMember; a gang admits only if at least
    that many members can be placed in the same cycle (all-or-nothing mask).
    """

    min_member: jnp.ndarray  # i32[G]
    valid: jnp.ndarray  # bool[G]
    names: Tuple[str, ...] = ()


@dataclasses.dataclass
class QuotaTable:
    """Flattened ElasticQuota groups after host-side runtime fair division.

    ``runtime`` is each group's runtimeQuota per resource, computed by
    ``koordinator_tpu.constraints.quota`` with the same redistribution rule
    as the reference (``elasticquota/core/runtime_quota_calculator.go:126``);
    ``used`` is current usage.  The device-side mask admits a pod onto any
    node only while its quota group stays within runtime.
    """

    runtime: jnp.ndarray  # i64[Q, R]
    used: jnp.ndarray  # i64[Q, R]
    limited: jnp.ndarray  # bool[Q, R] quota declares this dimension
    valid: jnp.ndarray  # bool[Q]
    names: Tuple[str, ...] = ()


@dataclasses.dataclass
class ClusterSnapshot:
    nodes: NodeBatch
    pods: PodBatch
    gangs: GangTable
    quotas: QuotaTable
    # per-(workload class, accelerator type) throughput matrix
    # (ISSUE 15 heterogeneity term, Gavel 2008.09213): [C, A] i64 with
    # values normalized to [0, MAX_NODE_SCORE]; replicated over the
    # cluster mesh.  None = the term has no data and contributes nothing.
    throughput: "jnp.ndarray | None" = None

    @property
    def num_nodes(self) -> int:
        return int(np.asarray(self.nodes.valid).sum())

    @property
    def num_pods(self) -> int:
        return int(np.asarray(self.pods.valid).sum())


# Snapshot containers cross the jit boundary: register as pytrees with the
# host-side name tuples as static aux data.
for _cls, _data in (
    (
        NodeBatch,
        [
            "allocatable",
            "requested",
            "usage",
            "metric_fresh",
            "valid",
            "agg_usage",
            "agg_fresh",
            "prod_usage",
            "accel_type",
        ],
    ),
    (
        PodBatch,
        [
            "requests",
            "estimated",
            "priority_class",
            "qos",
            "priority",
            "gang_id",
            "quota_id",
            "valid",
            "workload_class",
            "sensitivity",
        ],
    ),
    (GangTable, ["min_member", "valid"]),
    (QuotaTable, ["runtime", "used", "limited", "valid"]),
):
    # names are static metadata ON PURPOSE for the embedded API (reply
    # assembly reads them host-side); the hot bridge path strips them to
    # () before any jit sees the snapshot (bridge/state.py builds every
    # resident table with names=()), so the jit cache never keys on them
    jax.tree_util.register_dataclass(  # koordlint: disable=retrace-hazard(names stripped on the resident path; embedded API only)
        _cls, data_fields=_data, meta_fields=["names"]
    )
jax.tree_util.register_dataclass(
    ClusterSnapshot,
    data_fields=["nodes", "pods", "gangs", "quotas", "throughput"],
    meta_fields=[],
)


# ---------------------------------------------------------------------------
# Host-side estimator (exact integer parity with default_estimator.go)
# ---------------------------------------------------------------------------


def _estimated_used_by_resource(
    request: int, limit: int, default_value: int, scaling_factor: int
) -> int:
    """default_estimator.go:81-127 estimatedUsedByResource, one resource."""
    if limit > request:
        scaling_factor = 100
        quantity = limit
    else:
        quantity = request
    if quantity == 0:
        return default_value
    # Go: int64(math.Round(float64(q) * float64(factor) / 100)); math.Round
    # rounds half away from zero (quantities are non-negative here), unlike
    # Python's banker's round().
    estimated = int(math.floor(quantity * scaling_factor / 100 + 0.5))
    if limit > 0 and estimated > limit:
        estimated = limit
    return estimated


def estimate_pod(
    requests_vec: Sequence[int],
    limits_vec: Sequence[int],
    priority_class: PriorityClass,
    resource_weights: Mapping[str, int] = DEFAULT_RESOURCE_WEIGHTS,
    scaling_factors: Mapping[str, int] = DEFAULT_ESTIMATED_SCALING_FACTORS,
) -> List[int]:
    """defaultEstimator.EstimatePod (default_estimator.go:58-73), dense form.

    Returns estimated used in the *weighted* (native) resource slots; the
    lookup reads the priority-translated slot of requests/limits.
    """
    out = [0] * res.NUM_RESOURCES
    for name, _w in resource_weights.items():
        idx = res.RESOURCE_INDEX[name]
        real_idx = translate_resource_index(priority_class, idx)
        if res.RESOURCE_AXIS[real_idx] in (res.CPU, res.BATCH_CPU):
            default_value = DEFAULT_MILLI_CPU_REQUEST
        elif res.RESOURCE_AXIS[real_idx] in (res.MEMORY, res.BATCH_MEMORY):
            default_value = DEFAULT_MEMORY_REQUEST
        else:
            default_value = 0
        out[idx] = _estimated_used_by_resource(
            requests_vec[real_idx],
            limits_vec[real_idx],
            default_value,
            # A weighted resource with no scaling-factor entry estimates 0
            # (Go map zero-value in estimatedPodUsed, default_estimator.go:67).
            int(scaling_factors.get(name, 0)),
        )
    return out


# ---------------------------------------------------------------------------
# Encoders
# ---------------------------------------------------------------------------


def encode_snapshot(
    nodes: Sequence[Mapping],
    pods: Sequence[Mapping],
    gangs: Sequence[Mapping] = (),
    quotas: Sequence[Mapping] = (),
    *,
    resource_weights: Mapping[str, int] = DEFAULT_RESOURCE_WEIGHTS,
    scaling_factors: Mapping[str, int] = DEFAULT_ESTIMATED_SCALING_FACTORS,
    node_bucket: Optional[int] = None,
    pod_bucket: Optional[int] = None,
    throughput: Optional[Sequence[Sequence[int]]] = None,
) -> ClusterSnapshot:
    """Encode plain-dict cluster state into a padded ClusterSnapshot.

    Node dict: ``{"name", "allocatable": {res: qty}, "requested": {...},
    "usage": {...}, "metric_fresh": bool}``.
    Pod dict: ``{"name", "requests": {...}, "limits": {...},
    "priority_class": "koord-prod"|..., "priority": int, "qos": "LS"|...,
    "gang": gang-name|None, "quota": quota-name|None}``.
    Gang dict: ``{"name", "min_member": int}``.
    Quota dict: ``{"name", "runtime": {...}, "used": {...}}`` (runtime from
    ``constraints.quota.refresh_runtime``).

    Fused-term data (ISSUE 15; all optional — the resulting leaves stay
    None when no input mentions them, so existing callers' snapshot
    structure is unchanged): node ``"accel_type"`` (int), pod
    ``"workload_class"`` (int) and ``"sensitivity"`` ({res: 0..100}),
    and the ``throughput`` [C, A] matrix keyword.
    """
    n_bucket = node_bucket or pad_bucket(len(nodes))
    p_bucket = pod_bucket or pad_bucket(len(pods))
    g_bucket = pad_bucket(max(len(gangs), 1))
    q_bucket = pad_bucket(max(len(quotas), 1))
    R = res.NUM_RESOURCES

    gang_index = {g["name"]: i for i, g in enumerate(gangs)}
    quota_index = {q["name"]: i for i, q in enumerate(quotas)}

    node_alloc = np.zeros((n_bucket, R), np.int64)
    node_req = np.zeros((n_bucket, R), np.int64)
    node_usage = np.zeros((n_bucket, R), np.int64)
    node_fresh = np.zeros((n_bucket,), bool)
    node_valid = np.zeros((n_bucket,), bool)
    n_pct = len(PERCENTILES)
    node_agg = np.zeros((n_bucket, n_pct, R), np.int64)
    node_agg_fresh = np.zeros((n_bucket, n_pct), bool)
    node_prod = np.zeros((n_bucket, R), np.int64)
    node_accel = np.zeros((n_bucket,), np.int32)
    any_accel = any("accel_type" in nd for nd in nodes)
    for i, nd in enumerate(nodes):
        node_alloc[i] = res.resource_vector(nd.get("allocatable", {}))
        node_req[i] = res.resource_vector(nd.get("requested", {}))
        node_usage[i] = res.resource_vector(nd.get("usage", {}))
        node_fresh[i] = bool(nd.get("metric_fresh", True))
        node_valid[i] = True
        # aggregated percentile usage: {"p50": {res: qty}, ...} — nodes
        # whose koordlet reported AggregatedNodeUsages
        agg = nd.get("agg_usage")
        if agg:
            for a, pct in enumerate(PERCENTILES):
                if pct in agg:
                    node_agg[i, a] = res.resource_vector(agg[pct])
                    node_agg_fresh[i, a] = True
        if nd.get("prod_usage") is not None:
            node_prod[i] = res.resource_vector(nd["prod_usage"])
        if nd.get("accel_type") is not None:
            node_accel[i] = int(nd["accel_type"])

    pod_req = np.zeros((p_bucket, R), np.int64)
    pod_est = np.zeros((p_bucket, R), np.int64)
    pod_prio_class = np.full((p_bucket,), int(PriorityClass.NONE), np.int32)
    pod_qos = np.full((p_bucket,), int(QoSClass.NONE), np.int32)
    pod_prio = np.zeros((p_bucket,), np.int32)
    pod_gang = np.full((p_bucket,), -1, np.int32)
    pod_quota = np.full((p_bucket,), -1, np.int32)
    pod_valid = np.zeros((p_bucket,), bool)
    pod_wclass = np.zeros((p_bucket,), np.int32)
    pod_sens = np.zeros((p_bucket, R), np.int64)
    any_wclass = any("workload_class" in pd for pd in pods)
    any_sens = any("sensitivity" in pd for pd in pods)
    for i, pd in enumerate(pods):
        req_vec = res.resource_vector(pd.get("requests", {}))
        lim_vec = res.resource_vector(pd.get("limits", {}))
        pc = pd.get("priority_class")
        if pc is not None:
            prio_class = PriorityClass.from_name(pc)
        else:
            prio_class = PriorityClass.from_priority_value(pd.get("priority"))
        pod_req[i] = req_vec
        pod_est[i] = estimate_pod(
            req_vec, lim_vec, prio_class, resource_weights, scaling_factors
        )
        pod_prio_class[i] = int(prio_class)
        pod_qos[i] = int(QoSClass.from_name(pd.get("qos")))
        pod_prio[i] = int(pd.get("priority") or 0)
        # Unknown gang/quota names (object not yet synced into the snapshot)
        # degrade to "no gang"/"no quota" rather than crashing the encode.
        if pd.get("gang") is not None:
            pod_gang[i] = gang_index.get(pd["gang"], -1)
        if pd.get("quota") is not None:
            pod_quota[i] = quota_index.get(pd["quota"], -1)
        if pd.get("workload_class") is not None:
            pod_wclass[i] = int(pd["workload_class"])
        if pd.get("sensitivity") is not None:
            pod_sens[i] = res.resource_vector(pd["sensitivity"])
        pod_valid[i] = True

    gang_min = np.zeros((g_bucket,), np.int32)
    gang_valid = np.zeros((g_bucket,), bool)
    for i, g in enumerate(gangs):
        gang_min[i] = int(g.get("min_member", 0))
        gang_valid[i] = True

    quota_runtime = np.zeros((q_bucket, R), np.int64)
    quota_used = np.zeros((q_bucket, R), np.int64)
    quota_limited = np.zeros((q_bucket, R), bool)
    quota_valid = np.zeros((q_bucket,), bool)
    for i, q in enumerate(quotas):
        quota_runtime[i] = res.resource_vector(q.get("runtime", {}))
        quota_used[i] = res.resource_vector(q.get("used", {}))
        # A quota constrains only the dimensions it declares (the reference
        # checks used+request against runtime only for the quota's declared
        # resource dimensions, elasticquota plugin PreFilter).  "limited"
        # lists the declared dims explicitly so a zero-runtime dimension
        # still rejects (the reference keeps declared dims in the runtime
        # list with explicit zeros; only undeclared dims fall open).
        for name in q.get("limited", q.get("runtime", {})):
            idx = res.RESOURCE_INDEX.get(name)
            if idx is not None:
                quota_limited[i, idx] = True
        quota_valid[i] = True

    return ClusterSnapshot(
        nodes=NodeBatch(
            allocatable=jnp.asarray(node_alloc),
            requested=jnp.asarray(node_req),
            usage=jnp.asarray(node_usage),
            metric_fresh=jnp.asarray(node_fresh),
            valid=jnp.asarray(node_valid),
            agg_usage=jnp.asarray(node_agg),
            agg_fresh=jnp.asarray(node_agg_fresh),
            prod_usage=jnp.asarray(node_prod),
            accel_type=jnp.asarray(node_accel) if any_accel else None,
            names=tuple(nd.get("name", f"node-{i}") for i, nd in enumerate(nodes)),
        ),
        pods=PodBatch(
            requests=jnp.asarray(pod_req),
            estimated=jnp.asarray(pod_est),
            priority_class=jnp.asarray(pod_prio_class),
            qos=jnp.asarray(pod_qos),
            priority=jnp.asarray(pod_prio),
            gang_id=jnp.asarray(pod_gang),
            quota_id=jnp.asarray(pod_quota),
            valid=jnp.asarray(pod_valid),
            workload_class=jnp.asarray(pod_wclass) if any_wclass else None,
            sensitivity=jnp.asarray(pod_sens) if any_sens else None,
            names=tuple(pd.get("name", f"pod-{i}") for i, pd in enumerate(pods)),
        ),
        gangs=GangTable(
            min_member=jnp.asarray(gang_min),
            valid=jnp.asarray(gang_valid),
            names=tuple(g["name"] for g in gangs),
        ),
        quotas=QuotaTable(
            runtime=jnp.asarray(quota_runtime),
            used=jnp.asarray(quota_used),
            limited=jnp.asarray(quota_limited),
            valid=jnp.asarray(quota_valid),
            names=tuple(q["name"] for q in quotas),
        ),
        throughput=(
            jnp.asarray(np.asarray(throughput, np.int64))
            if throughput is not None
            else None
        ),
    )
