"""CPU / NUMA topology model.

Host side: ``CPUTopology`` describes one node's logical-CPU layout
(cpu -> core -> NUMA node -> socket), the contract for the cpuset
accumulator (reference ``pkg/scheduler/plugins/nodenumaresource/cpu_topology.go``,
populated from the NodeResourceTopology CR by ``topology_options.go``).

Device side: ``ZoneBatch`` encodes every node's NUMA-zone resources as one
dense ``[N, Z, R]`` tensor so zone-level fit and scoring run batched on TPU
(reference keeps per-node ``NUMANodeResource`` lists,
``topology_options.go TopologyOptions.NUMANodeResources``; here the zone
axis is padded like every other snapshot axis).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res

DEFAULT_AMPLIFICATION_DENOMINATOR = 10_000


def amplify(value: int, ratio_x10000: int) -> int:
    """reference apis/extension/node.go Amplify: ceil(value * ratio).

    Ratios are carried as fixed-point x10000 ints (the reference uses a
    float64 Ratio; fixed point keeps the tensor math integral).
    """
    if ratio_x10000 <= DEFAULT_AMPLIFICATION_DENOMINATOR:
        return value
    num = value * ratio_x10000
    return -(-num // DEFAULT_AMPLIFICATION_DENOMINATOR)  # ceil div


@dataclasses.dataclass(frozen=True)
class CPUInfo:
    """One logical CPU (reference cpu_topology.go CPUInfo)."""

    cpu: int
    core: int
    node: int  # NUMA node id
    socket: int


@dataclasses.dataclass
class CPUTopology:
    """Logical-CPU layout of one node (reference cpu_topology.go CPUTopology).

    ``details`` maps cpu id -> CPUInfo.  Derived counts mirror
    ``CPUsPerCore/CPUsPerNode/CPUsPerSocket`` (cpu_topology.go:51-73).
    """

    details: Dict[int, CPUInfo]

    @classmethod
    def build(
        cls,
        sockets: int,
        nodes_per_socket: int,
        cores_per_node: int,
        threads_per_core: int = 2,
    ) -> "CPUTopology":
        """Synthesize a regular topology (test/e2e helper; the production
        path decodes the NodeResourceTopology CR annotation).

        CPU ids are contiguous per core (siblings adjacent), the layout the
        reference synthesizes in its tests
        (cpu_accumulator_test.go:30 buildCPUTopologyForTest).
        """
        details: Dict[int, CPUInfo] = {}
        cpu = 0
        core = 0
        node = 0
        for s in range(sockets):
            for _n in range(nodes_per_socket):
                for _c in range(cores_per_node):
                    for _t in range(threads_per_core):
                        details[cpu] = CPUInfo(cpu=cpu, core=core, node=node, socket=s)
                        cpu += 1
                    core += 1
                node += 1
        return cls(details=details)

    @property
    def num_cpus(self) -> int:
        return len(self.details)

    @property
    def num_cores(self) -> int:
        return len({i.core for i in self.details.values()})

    @property
    def num_nodes(self) -> int:
        return len({i.node for i in self.details.values()})

    @property
    def num_sockets(self) -> int:
        return len({i.socket for i in self.details.values()})

    def is_valid(self) -> bool:
        return self.num_cpus > 0 and self.num_cores > 0

    def cpus_per_core(self) -> int:
        return self.num_cpus // max(self.num_cores, 1)

    def cpus_per_node(self) -> int:
        return self.num_cpus // max(self.num_nodes, 1)

    def cpus_per_socket(self) -> int:
        return self.num_cpus // max(self.num_sockets, 1)

    def cpus_in_node(self, node: int) -> List[int]:
        return sorted(i.cpu for i in self.details.values() if i.node == node)

    def cpus_in_socket(self, socket: int) -> List[int]:
        return sorted(i.cpu for i in self.details.values() if i.socket == socket)

    def cpus_in_core(self, core: int) -> List[int]:
        return sorted(i.cpu for i in self.details.values() if i.core == core)


@dataclasses.dataclass
class ZoneBatch:
    """Dense per-node NUMA-zone resources, shapes [N, Z, R] / [N, Z].

    ``allocatable``/``requested`` follow the same resource axis as the
    snapshot; ``valid`` masks real zones (nodes report differing zone
    counts; Z is the padded max).  ``cpu_amplification`` is the node-level
    CPU amplification ratio x10000 (reference
    ``apis/extension/node.go NodeResourceAmplificationRatio``).
    """

    allocatable: jnp.ndarray  # i64[N, Z, R]
    requested: jnp.ndarray  # i64[N, Z, R]
    valid: jnp.ndarray  # bool[N, Z]
    cpu_amplification: jnp.ndarray  # i32[N] ratio x10000 (10000 = 1.0)

    @property
    def num_zones(self) -> int:
        return self.allocatable.shape[1]


jax.tree_util.register_dataclass(
    ZoneBatch,
    data_fields=["allocatable", "requested", "valid", "cpu_amplification"],
    meta_fields=[],
)


def encode_zones(
    nodes: Sequence[Mapping],
    *,
    node_bucket: Optional[int] = None,
    zone_bucket: Optional[int] = None,
) -> ZoneBatch:
    """Encode per-node zone dicts into a ZoneBatch.

    Node dict: ``{"zones": [{"allocatable": {res: qty}, "requested": {...}},
    ...], "cpu_amplification": float}`` — nodes without zones get zero
    zones (they fall back to node-level accounting in the kernels).
    """
    from koordinator_tpu.model.snapshot import pad_bucket

    n_bucket = node_bucket or pad_bucket(len(nodes))
    max_zones = max((len(nd.get("zones", ())) for nd in nodes), default=0)
    z_bucket = zone_bucket or max(1, max_zones)
    R = res.NUM_RESOURCES

    alloc = np.zeros((n_bucket, z_bucket, R), np.int64)
    reqd = np.zeros((n_bucket, z_bucket, R), np.int64)
    valid = np.zeros((n_bucket, z_bucket), bool)
    ampl = np.full((n_bucket,), DEFAULT_AMPLIFICATION_DENOMINATOR, np.int32)
    for i, nd in enumerate(nodes):
        for z, zone in enumerate(nd.get("zones", ())):
            alloc[i, z] = res.resource_vector(zone.get("allocatable", {}))
            reqd[i, z] = res.resource_vector(zone.get("requested", {}))
            valid[i, z] = True
        ratio = nd.get("cpu_amplification")
        if ratio:
            ampl[i] = int(round(float(ratio) * DEFAULT_AMPLIFICATION_DENOMINATOR))
    return ZoneBatch(
        allocatable=jnp.asarray(alloc),
        requested=jnp.asarray(reqd),
        valid=jnp.asarray(valid),
        cpu_amplification=jnp.asarray(ampl),
    )
