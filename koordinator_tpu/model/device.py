"""Device (GPU / RDMA / FPGA / TPU) data model for DeviceShare.

The reference keeps a per-node device cache keyed by device type and minor
(reference ``pkg/scheduler/plugins/deviceshare/device_cache.go:44
nodeDevice``: ``deviceTotal/deviceFree/deviceUsed[type][minor]``).  Here
every node's device minors are one dense ``[N, D, C]`` tensor (D = padded
minors per node across all types, C = device resource dims), typed by a
``[N, D]`` device-type code, so device fit counting runs batched on TPU.

On a TPU cluster the GPU type code doubles for TPU chips — device
enumeration comes from the platform (koordlet's device collector) but the
allocation math is identical shares-of-100 accounting.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res

# Device type codes ([N, D] tensor values; reference
# apis/scheduling/v1alpha1/device_types.go DeviceType)
DEVICE_GPU = 0
DEVICE_RDMA = 1
DEVICE_FPGA = 2
DEVICE_TYPE_NAMES = {"gpu": DEVICE_GPU, "rdma": DEVICE_RDMA, "fpga": DEVICE_FPGA}
DEVICE_TYPE_CODE_TO_NAME = {v: k for k, v in DEVICE_TYPE_NAMES.items()}

# Device resource dims (the C axis).  Order is part of the device ABI.
DEVICE_RESOURCE_AXIS = (
    res.GPU_CORE,
    res.GPU_MEMORY,
    res.GPU_MEMORY_RATIO,
    res.RDMA,
    res.FPGA,
)
NUM_DEVICE_RESOURCES = len(DEVICE_RESOURCE_AXIS)
DEVICE_RESOURCE_INDEX = {n: i for i, n in enumerate(DEVICE_RESOURCE_AXIS)}

# Which device resources each type supports (reference
# pkg/scheduler/plugins/deviceshare/utils.go DeviceResourceNames)
DEVICE_TYPE_RESOURCES = {
    DEVICE_GPU: (res.GPU_CORE, res.GPU_MEMORY, res.GPU_MEMORY_RATIO),
    DEVICE_RDMA: (res.RDMA,),
    DEVICE_FPGA: (res.FPGA,),
}


def device_resource_vector(rl: Mapping[str, object]) -> np.ndarray:
    full = res.resource_vector(rl or {})
    return np.array(
        [full[res.RESOURCE_INDEX[n]] for n in DEVICE_RESOURCE_AXIS], dtype=np.int64
    )


@dataclasses.dataclass
class DeviceBatch:
    """Dense per-node device minors, shapes [N, D, C] / [N, D].

    ``numa`` carries each minor's NUMA node id from the Device CR's
    topology block (reference
    ``apis/scheduling/v1alpha1/device_types.go DeviceTopology.NodeID``) —
    the joint allocator's NUMA-affinity tiebreak reads it.
    """

    total: jnp.ndarray  # i64[N, D, C]
    free: jnp.ndarray  # i64[N, D, C]
    dev_type: jnp.ndarray  # i32[N, D] DEVICE_* code
    valid: jnp.ndarray  # bool[N, D] healthy minor exists
    numa: Optional[jnp.ndarray] = None  # i32[N, D] NUMA node id
    # per-TYPE CR minor (reference device_types.go: each type numbers its
    # own minors, so slot index != device id on multi-type nodes)
    minor: Optional[jnp.ndarray] = None  # i32[N, D]

    @property
    def minors(self) -> int:
        return self.total.shape[1]


jax.tree_util.register_dataclass(
    DeviceBatch,
    data_fields=["total", "free", "dev_type", "valid", "numa", "minor"],
    meta_fields=[],
)


def encode_devices(
    nodes: Sequence[Mapping],
    *,
    node_bucket: Optional[int] = None,
    minor_bucket: Optional[int] = None,
) -> DeviceBatch:
    """Encode per-node device dicts into a DeviceBatch.

    Node dict: ``{"devices": [{"type": "gpu", "minor": 0,
    "total": {res: qty}, "free": {...}, "health": bool}, ...]}``.
    ``free`` defaults to ``total`` (an unallocated healthy device).

    Devices occupy dense slots in list order; the CR minor (which is
    per-TYPE in the reference, so raw minors collide across types) rides
    the ``minor`` tensor so the Reserve path reports real device ids.
    An unhealthy device keeps its slot with ``valid=False`` — dropping
    it from the list would renumber nothing (ids are carried, not
    positional) but would lose the health visibility.
    """
    from koordinator_tpu.model.snapshot import pad_bucket

    n_bucket = node_bucket or pad_bucket(len(nodes))
    max_minors = max((len(nd.get("devices", ())) for nd in nodes), default=0)
    d_bucket = minor_bucket or max(1, max_minors)
    C = NUM_DEVICE_RESOURCES

    total = np.zeros((n_bucket, d_bucket, C), np.int64)
    free = np.zeros((n_bucket, d_bucket, C), np.int64)
    dtype = np.zeros((n_bucket, d_bucket), np.int32)
    valid = np.zeros((n_bucket, d_bucket), bool)
    numa = np.zeros((n_bucket, d_bucket), np.int32)
    minor = np.zeros((n_bucket, d_bucket), np.int32)
    for i, nd in enumerate(nodes):
        for j, dev in enumerate(nd.get("devices", ())):
            total[i, j] = device_resource_vector(dev.get("total", {}))
            free[i, j] = device_resource_vector(
                dev.get("free", dev.get("total", {}))
            )
            dtype[i, j] = DEVICE_TYPE_NAMES.get(
                str(dev.get("type", "gpu")).lower(), 0
            )
            valid[i, j] = bool(dev.get("health", True))
            topo = dev.get("topology") or {}
            numa[i, j] = int(topo.get("numaNode", 0))
            minor[i, j] = int(dev.get("minor", j))
    return DeviceBatch(
        total=jnp.asarray(total),
        free=jnp.asarray(free),
        dev_type=jnp.asarray(dtype),
        valid=jnp.asarray(valid),
        numa=jnp.asarray(numa),
        minor=jnp.asarray(minor),
    )
