"""Resident snapshot state for the scorer sidecar.

The host->device transfer is the boundary to engineer (SURVEY §5/§7), in
two layers:

* **host mirrors** — the server keeps numpy mirrors of every snapshot
  tensor; a warm Sync ships only sparse (index, value) deltas
  (native/koordnative.cpp codec) against them.  The mirrors are the
  source of truth: validation, i32-bounds checks and cold rebuilds all
  read them.
* **device residency** (the warm-cycle fast path) — the committed
  ``ClusterSnapshot``'s ``jax.Array`` tensors stay alive across Syncs.
  A delta frame is applied ON DEVICE as a jitted scatter
  (solver/resident.py, donating the dead pre-delta buffer); a full
  tensor of unchanged geometry re-uploads just that tensor; and derived
  columns (padded priority/gang/quota vectors, freshness masks) are
  rebuilt only when their wire columns actually changed.  Assign/Score
  then run straight off the resident arrays — a warm cycle pays
  O(changed), skipping the host re-encode and the full host->device
  re-upload entirely.

Any geometry change (table size, pad bucket, a tensor appearing or
disappearing) drops device residency and the next snapshot() is a cold
rebuild from the mirrors.  The two paths are bit-exact by construction
(the warm path edits the same padded cells the cold encode would write);
tests/test_resident_warm.py fuzzes random delta sequences against cold
re-encodes on both the scan and interpret-mode Pallas paths.

The resident snapshot carries NO name tuples: names are static pytree
metadata, so routing them through the jitted cycle would retrace it
whenever a pod name changes (every warm cycle on the Go seam).  Names
stay host-side on this object; replies are index-based.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from koordinator_tpu import native
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import (
    ClusterSnapshot,
    GangTable,
    NodeBatch,
    PodBatch,
    QuotaTable,
    pad_bucket,
)

R = res.NUM_RESOURCES

logger = logging.getLogger(__name__)

# "no precomputed plan" sentinel for commit_donates/commit_sync —
# distinct from None, which is a real plan value meaning "go cold"
_PLAN_UNSET = object()


def decode_tensor(
    t: "pb2.Tensor", base: Optional[np.ndarray]
) -> Tuple[Optional[np.ndarray], str, Optional[np.ndarray], Optional[np.ndarray]]:
    """Decode a proto Tensor against the resident ``base`` mirror.

    Returns ``(mirror, kind, idx, val)`` where kind is "none" (message
    carries nothing; tensor unchanged), "full" (full payload) or "delta"
    (sparse update; idx/val are the validated flat indices and values so
    the device path can scatter them without re-diffing).
    """
    if t.data:
        arr = np.frombuffer(t.data, dtype="<i8").copy()
        return arr.reshape(tuple(t.shape)), "full", None, None
    if t.delta_idx:
        if base is None:
            raise ValueError("delta sync without a resident tensor")
        # the delta must target the RESIDENT shape: a client with a stale
        # differently-shaped mirror emits indices that may all land
        # inside the resident cell count yet write the wrong cells —
        # shape equality rejects every mismatch, not just the
        # out-of-range subset
        if t.shape and tuple(t.shape) != base.shape:
            raise ValueError(
                f"delta shape {tuple(t.shape)} != resident {base.shape}"
            )
        idx = np.frombuffer(t.delta_idx, dtype="<i8")
        val = np.frombuffer(t.delta_val, dtype="<i8")
        if len(idx) != len(val):
            raise ValueError(
                f"delta index/value length mismatch: {len(idx)} vs {len(val)}"
            )
        # duplicate indices are rejected, not tolerated: the host path
        # (native.delta_apply) is sequential last-wins but the device
        # scatter's duplicate semantics are implementation-defined, so a
        # frame with repeats could silently split the mirror from the
        # resident tensors — and no honest delta encoder emits them
        if len(idx) != len(np.unique(idx)):
            raise ValueError("delta carries duplicate indices")
        # bounds-check BEFORE the native path: delta_apply writes through
        # raw pointers, so an out-of-range index from a hostile frame
        # would corrupt server memory instead of raising
        if len(idx) and (int(idx.min()) < 0 or int(idx.max()) >= base.size):
            raise ValueError(
                f"delta index out of range for resident tensor of "
                f"{base.size} cells"
            )
        out = base.copy()
        native.delta_apply(out, idx, val)
        return out, "delta", idx, val
    return None, "none", None, None


def tensor_to_numpy(
    t: "pb2.Tensor", base: Optional[np.ndarray]
) -> Optional[np.ndarray]:
    """Decode a proto Tensor: full payload, or sparse delta onto ``base``.

    Returns the new mirror array, or None when the message carries nothing
    (tensor unchanged since the last sync).
    """
    arr, kind, _, _ = decode_tensor(t, base)
    return arr if kind != "none" else None


def numpy_to_tensor(
    arr: np.ndarray, prev: Optional[np.ndarray] = None, max_delta_ratio: float = 0.25
) -> "pb2.Tensor":
    """Encode full, or as a sparse delta when <= max_delta_ratio changed."""
    t = pb2.Tensor()
    arr = np.ascontiguousarray(arr, dtype=np.int64)
    t.shape.extend(arr.shape)
    if prev is not None and prev.shape == arr.shape:
        enc = native.delta_encode(
            prev, arr, max_changes=max(1, int(arr.size * max_delta_ratio))
        )
        if enc is not None:
            idx, val = enc
            t.delta_idx = idx.astype("<i8").tobytes()
            t.delta_val = val.astype("<i8").tobytes()
            return t
    t.data = arr.astype("<i8").tobytes()
    return t


def _pad_rows_to(a, rows):
    """Pad (or keep) leading axis to ``rows`` regardless of current length
    (mirrors the length-agnostic padded()/_pad2 helpers — a stale mirror
    after a node-count change must never produce a wrong-shaped tensor)."""
    a = np.asarray(a)
    if a.shape[0] >= rows:
        return a[:rows]
    pad = [(0, rows - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad)


def _pc_column(explicit, priority, P, pb):
    from koordinator_tpu.model.snapshot import PriorityClass

    col = np.full(pb, int(PriorityClass.NONE), np.int32)
    if explicit is not None:
        col[: len(explicit)] = explicit[:pb]
    else:
        for i in range(P):
            col[i] = int(PriorityClass.from_priority_value(int(priority[i])))
    return col


def _present(a: Optional[np.ndarray]) -> bool:
    return a is not None and a.size > 0


# wire tensors that ride the sparse-delta path, keyed by mirror attribute
_DELTA_TENSORS = (
    "node_alloc",
    "node_requested",
    "node_usage",
    "node_agg",
    "node_agg_fresh",
    "node_prod",
    "pod_requests",
    "pod_estimated",
    "quota_runtime",
    "quota_used",
    "quota_limited",
    # fused-term tensors (ISSUE 15): the Synergy sensitivity profile and
    # the Gavel throughput matrix delta-sync like every snapshot tensor
    "pod_sensitivity",
    "term_throughput",
)

# score-relevant tensors (ISSUE 9): which resident mirrors feed the
# stateless score_cycle math — a delta to one of these dirties the
# touched ROWS of the resident [P, N] score tensors.  Quota tensors are
# deliberately absent: score_cycle reads no quota state (quota admission
# lives in the sequential Assign step only), so a quota-only Sync leaves
# the resident score tensors exactly valid — zero columns to rescore.
_SCORE_NODE_TENSORS = ("node_alloc", "node_requested", "node_usage",
                       "node_agg", "node_agg_fresh", "node_prod")
_SCORE_POD_TENSORS = ("pod_requests", "pod_estimated", "pod_sensitivity")
# the throughput matrix is neither node- nor pod-major: a delta to cell
# (c, a) invalidates the score COLUMNS of every node whose (clipped)
# accelerator type is ``a`` — _score_dirty_rows attributes it through
# the accel mirror, so a one-type matrix update rescores only that
# type's node columns (O(dirty), the ISSUE-15 acceptance)


class ScoreResidency:
    """The [P, N] score/feasible tensors as first-class device-resident
    leaves (ISSUE 9), plus the dirty row/column sets accumulated since
    the launch that certified them.

    ``scores``/``feasible`` are the padded tensors the last Score launch
    produced (node-axis-sharded ``P(None, "nodes")`` when mesh-resident);
    every warm commit unions the rows it invalidated into
    ``dirty_nodes``/``dirty_pods`` instead of discarding the tensors —
    the generation advances, the derived result advances with it.  The
    next Score recomputes only the dirty columns/rows
    (solver/incremental.py ``rescore_dirty``) and the sets clear.

    ``cfg`` is the CycleConfig the tensors were scored under: a config
    change means a different scoring program, so the servicer drops the
    residency rather than advance tensors it cannot certify.
    """

    __slots__ = ("cfg", "scores", "feasible", "dirty_nodes", "dirty_pods")

    def __init__(self, cfg, scores, feasible):
        self.cfg = cfg
        self.scores = scores
        self.feasible = feasible
        self.dirty_nodes: set = set()
        self.dirty_pods: set = set()


class CandidateResidency:
    """The sparse engine's device-resident [P, C] candidate-index map
    (ISSUE 16, solver/candidates.py) plus the exact per-pod feasible
    counts and the dirt accumulated since the launch that built them.

    The same commit seam that advances :class:`ScoreResidency` advances
    this: a warm commit unions its invalidated rows into
    ``dirty_nodes``/``dirty_pods`` (a dirty node invalidates only the
    candidate lists containing it — the next Score's lazy
    merge-refresh evicts and re-merges just those entries), and an
    attribution-losing commit (full re-upload) or a geometry move
    drops the residency for a cold rebuild.  The dirty sets are a
    conservative superset of what feasibility actually read: a
    score-only delta (e.g. sensitivity) forces a harmless re-merge,
    never a wrong list.

    ``merges`` counts exact merge-refreshes since the last full build —
    the staleness bound (``cfg.candidate_max_stale``) forces a full
    rebuild (refresh reason "stale") once the chain grows past it.
    ``count`` is the EXACT per-pod feasible total, maintained through
    every merge; the serving path refuses (``CandidateOverflow``)
    whenever it exceeds C rather than serve a truncated list.
    """

    __slots__ = ("cfg", "idx", "count", "dirty_nodes", "dirty_pods",
                 "merges")

    def __init__(self, cfg, idx, count, merges: int = 0):
        self.cfg = cfg
        self.idx = idx
        self.count = count
        self.dirty_nodes: set = set()
        self.dirty_pods: set = set()
        self.merges = int(merges)


# companions reset to defaults when a full tensor changes the node table
# size (ADVICE r5: a stale differently-shaped column must not linger to
# fail later at snapshot build).  node_requested/node_usage are included:
# a resize frame may legally omit them, and an old-shaped mirror would
# otherwise be padded/truncated onto the NEW nodes' rows at snapshot
# build — silently wrong data, or a broadcast error under a smaller
# explicit bucket
_NODE_COMPANIONS = ("node_fresh", "node_names", "node_agg", "node_agg_fresh",
                    "node_prod", "node_requested", "node_usage", "node_accel")
# NOTE: gang_min is deliberately NOT a pod companion — the gang table's
# shape is per-gang, not per-pod (like the quota tables), so resetting it
# on a pod resize would wipe gang gating while the new pod table's
# gang_id column still references the gangs
_POD_COMPANIONS = ("pod_priority", "pod_priority_class", "pod_gang",
                   "pod_quota", "pod_names", "pod_estimated",
                   "pod_workload", "pod_sensitivity")
_COMPANION_DEFAULTS = {"node_names": (), "pod_names": ()}


class ResidentState:
    """Numpy mirrors + the device-resident ClusterSnapshot built from them.

    ``mesh``: a cluster mesh (parallel/mesh.py ``cluster_mesh``) makes
    the resident snapshot MESH-SHARDED (ISSUE 7): node tensors split
    along the mesh's node axis (each device holds one shard of the
    cluster — the combined HBM is the capacity), pod rows and the
    gang/quota tables replicate, and every leaf carries the
    ``NamedSharding`` that ``parallel.mesh.snapshot_shardings``
    prescribes — the per-field builders here apply the same policy
    through ``node_sharding``/``replicated_sharding``, and
    tests/test_mesh_resident.py asserts the two stay in lockstep
    leaf-for-leaf (a field classified differently in the two places is
    a test failure, not silent mis-sharding).  Warm
    delta Syncs scatter SHARD-LOCALLY (solver/resident.py
    ``_scatter_flat_sharded``): a delta for node *j* lands on the one
    device owning *j*'s rows, no all-gather, no full re-upload — the
    same O(changed) warm path, now over N chips.  A node bucket that
    does not divide over the mesh falls back to single-chip placement
    for that geometry (logged once); buckets are powers of two, so any
    power-of-two device prefix always divides.
    """

    def __init__(self, mesh=None):
        self.mesh = mesh
        self._mesh_skip_warned: set = set()
        self.node_alloc: Optional[np.ndarray] = None
        self.node_requested: Optional[np.ndarray] = None
        self.node_usage: Optional[np.ndarray] = None
        self.node_fresh: Optional[np.ndarray] = None
        self.node_agg: Optional[np.ndarray] = None  # [N, A, R]
        self.node_agg_fresh: Optional[np.ndarray] = None
        self.node_prod: Optional[np.ndarray] = None
        self.node_names: tuple = ()
        self.pod_requests: Optional[np.ndarray] = None
        self.pod_estimated: Optional[np.ndarray] = None
        self.pod_priority: Optional[np.ndarray] = None
        self.pod_priority_class: Optional[np.ndarray] = None
        self.pod_gang: Optional[np.ndarray] = None
        self.pod_quota: Optional[np.ndarray] = None
        self.pod_names: tuple = ()
        self.gang_min: Optional[np.ndarray] = None
        self.quota_runtime: Optional[np.ndarray] = None
        self.quota_used: Optional[np.ndarray] = None
        self.quota_limited: Optional[np.ndarray] = None
        # fused-term mirrors (ISSUE 15): accel/workload columns plus the
        # sensitivity and throughput tensors; None = never synced (the
        # terms are inert for the missing data)
        self.node_accel: Optional[np.ndarray] = None
        self.pod_workload: Optional[np.ndarray] = None
        self.pod_sensitivity: Optional[np.ndarray] = None
        self.term_throughput: Optional[np.ndarray] = None
        self.node_bucket = 0
        self.pod_bucket = 0
        self._snapshot: Optional[ClusterSnapshot] = None
        # resident [P, N] score/feasible tensors + accumulated dirt
        # (ISSUE 9); populated by the servicer's Score launches via
        # store_score_result, advanced by warm commits, dropped cold
        self._score_res: Optional[ScoreResidency] = None
        # resident [P, C] sparse candidate lists + exact feasible
        # counts (ISSUE 16); populated by sparse Score launches via
        # store_candidates, advanced by warm commits, dropped cold
        self._cand_res: Optional[CandidateResidency] = None
        self._i32_ok: Optional[bool] = None
        # observability: how the last Sync landed on the device
        # ("cold" = residency dropped, rebuild at next snapshot();
        #  "warm" = resident tensors updated in place)
        self.last_sync_path = "cold"

    def apply_sync(self, reqmsg: "pb2.SyncRequest", spans=None) -> dict:
        """Decode EVERYTHING first, commit only if every tensor decoded:
        a rejected frame (bad delta shape/index, missing first-sync
        tensors) must leave the resident state untouched — a torn
        half-applied sync would hand every OTHER client a corrupted
        delta baseline behind an unbumped generation.

        ``spans``: an optional ``obs.spans.SpanRecorder``; the host-side
        decode ("sync_decode") and the on-device warm update
        ("delta_scatter") are recorded as stages of the upcoming cycle.
        Returns a summary dict for the scorer metric families:
        ``{"path": "warm"|"cold", "delta_tensors": n, "full_tensors": n}``.

        One-shot convenience over the two-phase ``stage_sync`` /
        ``commit_sync`` seam the coalescing pipeline uses (ISSUE 5): the
        server runs the protobuf->numpy decode OUTSIDE its device
        critical section — decode of Sync k+1 overlaps the on-device
        delta scatter of cycle k — and commits under its state lock.
        """
        return self.commit_sync(
            self.stage_sync(reqmsg, spans=spans), spans=spans
        )

    def stage_sync(self, reqmsg: "pb2.SyncRequest", spans=None):
        """Phase 1 — pure decode/validate.  Mutates NOTHING; every
        validation error (bad delta shape/index, duplicate indices,
        missing first-sync tensors, pre-resize companions) raises here,
        so a frame that passes staging always commits.  The caller must
        hold whatever serializes Syncs (the servicer's ``_sync_lock``):
        deltas are validated against the current mirrors, so another
        Sync committing mid-decode would invalidate the staging."""
        from koordinator_tpu.obs.spans import maybe_span

        with maybe_span(spans, "sync_decode"):
            return self._decode_sync(reqmsg)

    def plan_commit(self, staged_tinfo):
        """Compute the device-update plan for a staged frame against
        the current (pre-commit) mirrors and residency.  Pure planning:
        mutates nothing.  The plan depends on whether a snapshot is
        resident, and residency can flip at any *launch* (a Score's
        launch section lazily cold-rebuilds via ``snapshot()``) — so a
        plan that will gate or feed a commit must be computed with the
        dispatch launch lock held (``run_exclusive`` evaluates its
        ``drain`` callable exactly there) and handed to ``commit_sync``
        via its ``plan=`` parameter rather than recomputed."""
        staged, tinfo = staged_tinfo
        return self._warm_plan(staged, tinfo)

    def commit_donates(self, staged_tinfo, plan=_PLAN_UNSET) -> bool:
        """Whether committing this staged frame will DONATE resident
        device buffers (a warm plan with at least one delta scatter —
        solver/resident.py apply_flat_delta donates the dead pre-delta
        buffer).  The pipelined dispatcher (ISSUE 6) uses this to pick
        the commit barrier: a donating commit must drain in-flight
        launches (``run_exclusive(fn, drain=True)``) because deleting a
        donated buffer would invalidate python references a launched-
        but-unread batch still holds, while a cold or full-upload
        commit only needs launch ordering — in-flight batches keep
        their own snapshot references alive, so the pipeline keeps
        flowing.  Pass ``plan=`` from :meth:`plan_commit` to decide on
        the plan the commit will actually run (and to avoid planning
        twice); call between ``stage_sync`` and ``commit_sync`` under
        the same Sync serialization."""
        if plan is _PLAN_UNSET:
            plan = self.plan_commit(staged_tinfo)
        if plan is None:
            return False
        tensor_updates, _ = plan
        return any(u[0] == "delta" for u in tensor_updates.values())

    def commit_sync(self, staged_tinfo, spans=None, plan=_PLAN_UNSET) -> dict:
        """Phase 2 — atomic commit of a staged frame + the device-side
        warm update.  The delta scatter donates the pre-delta resident
        buffers, so the caller must hold the device-dispatch lock
        (bridge/coalesce.py run_exclusive, drained when
        ``commit_donates`` says so) to keep the donation from
        invalidating arrays a coalesced Score batch captured but has
        not read back yet.  ``plan=`` accepts the
        :meth:`plan_commit` result the drain decision was made on, so
        the barrier and the commit provably act on the same plan (and
        the full-tensor ``np.array_equal`` sweep runs once per Sync,
        not twice)."""
        from koordinator_tpu.obs.spans import maybe_span

        staged, tinfo = staged_tinfo
        if plan is _PLAN_UNSET:
            # device-update plan against the PRE-commit mirrors
            plan = self._warm_plan(staged, tinfo)
        # dirty score rows/columns this commit invalidates (ISSUE 9) —
        # computed against the PRE-commit mirrors, like the plan
        score_dirty = (
            self._score_dirty_rows(staged, plan) if plan is not None else None
        )
        # atomic commit point: nothing above mutated self
        for key, value in staged.items():
            setattr(self, key, value)
        if plan is None:
            self._snapshot = None  # cold: rebuilt lazily at snapshot()
            self._score_res = None  # geometry moved: nothing to advance
            self._cand_res = None
            self.last_sync_path = "cold"
        else:
            try:
                with maybe_span(spans, "delta_scatter"):
                    self._snapshot = self._apply_warm(plan)
                self.last_sync_path = "warm"
                self._note_score_dirty(score_dirty)
                self._note_candidate_dirty(score_dirty)
            except Exception:
                # a torn device update may have donated buffers out of the
                # old snapshot: drop residency, the mirrors stay truthful
                # and the next snapshot() cold-rebuilds from them
                logger.exception(
                    "warm device update failed; falling back to cold rebuild"
                )
                self._snapshot = None
                self._score_res = None
                self._cand_res = None
                self.last_sync_path = "cold"
        self._i32_ok = None
        kinds = [kind for kind, _, _ in tinfo.values()]
        return {
            "path": self.last_sync_path,
            "delta_tensors": kinds.count("delta"),
            "full_tensors": kinds.count("full"),
        }

    def export_sync_request(self) -> Optional["pb2.SyncRequest"]:
        """Full-state ``SyncRequest`` rebuilt from the host mirrors —
        the replication tier's one-shot full-resync payload (ISSUE 8):
        a follower that applies this onto a FRESH ResidentState ends
        with mirrors byte-identical to this one's (the same wire
        decode both sides; tests/test_replication.py asserts the
        round trip leaf-for-leaf).  Explicit buckets ride along so the
        follower pads — and compiles — the very same geometry.  Returns
        None before the first Sync (nothing to replicate yet; the
        follower resets to the empty pre-first-Sync state instead)."""
        if self.node_alloc is None or self.pod_requests is None:
            return None
        req = pb2.SyncRequest(
            node_bucket=self.node_bucket, pod_bucket=self.pod_bucket
        )
        for target, arr in (
            (req.nodes.allocatable, self.node_alloc),
            (req.nodes.requested, self.node_requested),
            (req.nodes.usage, self.node_usage),
            (req.nodes.agg_usage, self.node_agg),
            (req.nodes.agg_fresh, self.node_agg_fresh),
            (req.nodes.prod_usage, self.node_prod),
            (req.pods.requests, self.pod_requests),
            (req.pods.estimated, self.pod_estimated),
            (req.pods.sensitivity, self.pod_sensitivity),
            (req.quotas.runtime, self.quota_runtime),
            (req.quotas.used, self.quota_used),
            (req.quotas.limited, self.quota_limited),
            (req.terms.throughput, self.term_throughput),
        ):
            if _present(arr):
                # prev=None: always the full payload, never a delta —
                # the receiver has no baseline by definition
                target.CopyFrom(numpy_to_tensor(np.asarray(arr, np.int64)))
        if self.node_names:
            req.nodes.names.extend(self.node_names)
        if self.node_fresh is not None and len(self.node_fresh):
            req.nodes.metric_fresh.extend(bool(b) for b in self.node_fresh)
        if self.pod_names:
            req.pods.names.extend(self.pod_names)
        for target, arr in (
            (req.pods.priority, self.pod_priority),
            (req.pods.priority_class, self.pod_priority_class),
            (req.pods.gang_id, self.pod_gang),
            (req.pods.quota_id, self.pod_quota),
            (req.gangs.min_member, self.gang_min),
            (req.nodes.accel_type, self.node_accel),
            (req.pods.workload_class, self.pod_workload),
        ):
            if arr is not None and len(arr):
                target.extend(int(v) for v in arr)
        return req

    def _decode_sync(self, reqmsg: "pb2.SyncRequest"):
        """The pure decode/validate half of apply_sync: returns the
        staged mirror values and per-tensor wire info without mutating
        any resident state."""
        n = reqmsg.nodes
        p = reqmsg.pods
        wire = {
            "node_alloc": n.allocatable,
            "node_requested": n.requested,
            "node_usage": n.usage,
            "node_agg": n.agg_usage,
            "node_agg_fresh": n.agg_fresh,
            "node_prod": n.prod_usage,
            "pod_requests": p.requests,
            "pod_estimated": p.estimated,
            "quota_runtime": reqmsg.quotas.runtime,
            "quota_used": reqmsg.quotas.used,
            "quota_limited": reqmsg.quotas.limited,
            "pod_sensitivity": p.sensitivity,
            "term_throughput": reqmsg.terms.throughput,
        }
        staged: Dict[str, object] = {}
        tinfo: Dict[str, tuple] = {}
        for key, tensor in wire.items():
            current = getattr(self, key)
            arr, kind, idx, val = decode_tensor(tensor, current)
            staged[key] = current if kind == "none" else arr
            tinfo[key] = (kind, idx, val)
        if staged["node_alloc"] is None or staged["pod_requests"] is None:
            raise ValueError("first Sync must carry full node and pod tensors")
        if n.metric_fresh:
            staged["node_fresh"] = np.asarray(list(n.metric_fresh), dtype=bool)
        if n.names:
            staged["node_names"] = tuple(n.names)
        if p.priority:
            staged["pod_priority"] = np.asarray(list(p.priority), dtype=np.int64)
        if p.priority_class:
            staged["pod_priority_class"] = np.asarray(
                list(p.priority_class), dtype=np.int32
            )
        if p.gang_id:
            staged["pod_gang"] = np.asarray(list(p.gang_id), dtype=np.int32)
        if p.quota_id:
            staged["pod_quota"] = np.asarray(list(p.quota_id), dtype=np.int32)
        if p.names:
            staged["pod_names"] = tuple(p.names)
        if reqmsg.gangs.min_member:
            staged["gang_min"] = np.asarray(
                list(reqmsg.gangs.min_member), np.int32
            )
        if n.accel_type:
            staged["node_accel"] = np.asarray(list(n.accel_type), np.int32)
        if p.workload_class:
            staged["pod_workload"] = np.asarray(
                list(p.workload_class), np.int32
            )
        # explicit wire buckets win; otherwise a warm frame that omits
        # them INHERITS the resident bucket (sticky-grow) instead of
        # recomputing pad_bucket and silently reshaping — and recompiling
        # — the resident snapshot mid-stream
        def bucket(wire_value, current, rows):
            if wire_value:
                return int(wire_value)
            if current and current >= rows:
                return current
            return pad_bucket(rows)

        staged["node_bucket"] = bucket(
            reqmsg.node_bucket, self.node_bucket,
            staged["node_alloc"].shape[0],
        )
        staged["pod_bucket"] = bucket(
            reqmsg.pod_bucket, self.pod_bucket,
            staged["pod_requests"].shape[0],
        )
        self._reset_companions(staged, tinfo)
        return staged, tinfo

    # -- companion resets (ADVICE r5) --
    def _reset_companions(self, staged: Dict[str, object], tinfo) -> None:
        """When a full tensor changes a table's row count, omitted
        companion columns reset to defaults of the new shape instead of
        lingering at the stale shape to fail later at snapshot build.
        (None means "use the default of the current shape" everywhere in
        this class: all-fresh, zero priority, no gang/quota membership,
        estimated = requests.)"""
        def rows(a):
            return -1 if a is None else a.shape[0]

        def reset(companions, new_rows):
            for key in companions:
                if key in _DELTA_TENSORS:
                    # carried-over tensor mirror (nothing in this frame):
                    # its rows no longer match the new table
                    if tinfo[key][0] == "none":
                        staged[key] = None
                    elif rows(staged[key]) != new_rows:
                        # a delta (validated against the PRE-resize
                        # shape) or an old-shaped full tensor riding the
                        # same frame as the resize: committing it would
                        # silently pad stale rows onto the new table
                        raise ValueError(
                            f"{key} targets the pre-resize table "
                            f"({rows(staged[key])} rows != {new_rows})"
                        )
                elif key not in staged:
                    staged[key] = _COMPANION_DEFAULTS.get(key)

        if rows(staged["node_alloc"]) != rows(self.node_alloc):
            reset(_NODE_COMPANIONS, rows(staged["node_alloc"]))
        if rows(staged["pod_requests"]) != rows(self.pod_requests):
            reset(_POD_COMPANIONS, rows(staged["pod_requests"]))

    # -- warm-path planning / application --
    def _warm_plan(self, staged, tinfo):
        """Decide how this Sync lands on the resident device snapshot.

        Returns None when residency must drop (no resident snapshot, or
        any geometry change: table rows, pad buckets, a tensor or table
        appearing/disappearing).  Otherwise returns
        ``(tensor_updates, derived)`` where tensor_updates maps mirror
        keys to ("delta", idx, val) / ("full",) and derived is the set of
        scalar-derived device columns to rebuild.  Runs BEFORE the mirror
        commit so it can compare staged against current values."""
        if self._snapshot is None:
            return None
        if (
            staged["node_bucket"] != self.node_bucket
            or staged["pod_bucket"] != self.pod_bucket
        ):
            return None

        def shape(a):
            return None if a is None else a.shape

        # geometry must be identical for every resident tensor, and
        # presence flips (None <-> array, empty <-> non-empty) change the
        # snapshot structure -> cold
        for key in _DELTA_TENSORS:
            old, new = getattr(self, key), staged[key]
            if _present(old) != _present(new):
                return None
            if _present(old) and shape(old) != shape(new):
                return None
        old_gang = self.gang_min if self.gang_min is not None else ()
        new_gang = staged.get("gang_min", self.gang_min)
        new_gang = new_gang if new_gang is not None else ()
        if len(old_gang) != len(new_gang):
            return None
        # a freshness column of the wrong length would fail the cold
        # build too; surface it there instead of a device-shape error
        new_fresh = staged.get("node_fresh", self.node_fresh)
        if new_fresh is not None and len(new_fresh) != staged["node_alloc"].shape[0]:
            return None

        tensor_updates = {}
        for key in _DELTA_TENSORS:
            kind, idx, val = tinfo[key]
            if kind == "delta":
                tensor_updates[key] = ("delta", idx, val)
            elif kind == "full":
                if not np.array_equal(staged[key], getattr(self, key)):
                    tensor_updates[key] = ("full",)
        # estimated falls back to requests while never synced: a requests
        # update must land on the estimated device tensor too
        if staged["pod_estimated"] is None and "pod_requests" in tensor_updates:
            tensor_updates["pod_estimated_from_requests"] = tensor_updates[
                "pod_requests"
            ]

        # first appearance of a term COLUMN (ISSUE 15): the resident
        # snapshot gains a leaf (None -> array), which changes the
        # pytree structure every downstream jit keys on — one cold
        # rebuild, exactly like a tensor appearing in _DELTA_TENSORS
        for key in ("node_accel", "pod_workload"):
            if staged.get(key) is not None and getattr(self, key) is None:
                return None

        derived = set()
        for key in ("node_fresh", "pod_priority", "pod_priority_class",
                    "pod_gang", "pod_quota", "gang_min",
                    "node_accel", "pod_workload"):
            if key not in staged:
                continue
            old = getattr(self, key)
            if old is None or not np.array_equal(
                np.asarray(staged[key]), np.asarray(old)
            ):
                derived.add(key)
        if "pod_priority" in derived and staged.get(
            "pod_priority_class", self.pod_priority_class
        ) is None:
            # priority_class is derived from priority bands when the wire
            # never sent explicit classes
            derived.add("pod_priority_class")
        return tensor_updates, derived

    def _apply_warm(self, plan) -> ClusterSnapshot:
        """Apply a warm plan to the resident snapshot (mirrors are already
        committed).  Delta tensors scatter on device (donating the dead
        buffer); full tensors re-upload just themselves; derived columns
        rebuild through the same builders the cold path uses."""
        from koordinator_tpu.solver.resident import apply_flat_delta

        tensor_updates, derived = plan
        snap = self._snapshot
        nodes, pods, quotas = snap.nodes, snap.pods, snap.quotas
        mesh = self.active_mesh()

        def updated(dev_arr, key, update):
            if update[0] == "delta":
                # node tensors scatter SHARD-LOCALLY on the mesh: only
                # the device owning the touched rows writes (pod/quota
                # tensors replicate, so their scatter runs everywhere —
                # identical values, still donated in place)
                return apply_flat_delta(
                    dev_arr, update[1], update[2],
                    mesh=mesh if key.startswith("node_") else None,
                )
            return None  # full: rebuilt below from the committed mirror

        node_patch = {}
        for key, field in (
            ("node_alloc", "allocatable"),
            ("node_requested", "requested"),
            ("node_usage", "usage"),
        ):
            if key in tensor_updates:
                new = updated(getattr(nodes, field), key, tensor_updates[key])
                node_patch[field] = (
                    new if new is not None
                    else self._dev_padded2(key, self.node_bucket)
                )
        for key, field, builder in (
            ("node_agg", "agg_usage", self._dev_agg_usage),
            ("node_agg_fresh", "agg_fresh", self._dev_agg_fresh),
            ("node_prod", "prod_usage", self._dev_prod_usage),
        ):
            if key in tensor_updates:
                new = updated(getattr(nodes, field), key, tensor_updates[key])
                node_patch[field] = new if new is not None else builder()
        if "node_fresh" in derived:
            node_patch["metric_fresh"] = self._dev_metric_fresh()
        if "node_accel" in derived:
            node_patch["accel_type"] = self._dev_accel_type()

        pod_patch = {}
        if "pod_requests" in tensor_updates:
            new = updated(pods.requests, "pod_requests",
                          tensor_updates["pod_requests"])
            pod_patch["requests"] = (
                new if new is not None
                else self._dev_padded2("pod_requests", self.pod_bucket)
            )
        est_update = tensor_updates.get(
            "pod_estimated", tensor_updates.get("pod_estimated_from_requests")
        )
        if est_update is not None:
            new = updated(pods.estimated, "pod_estimated", est_update)
            pod_patch["estimated"] = (
                new if new is not None else self._dev_estimated()
            )
        if "pod_sensitivity" in tensor_updates:
            new = updated(pods.sensitivity, "pod_sensitivity",
                          tensor_updates["pod_sensitivity"])
            pod_patch["sensitivity"] = (
                new if new is not None else self._dev_sensitivity()
            )
        if "pod_priority" in derived:
            pod_patch["priority"] = self._dev_priority()
        if "pod_priority_class" in derived:
            pod_patch["priority_class"] = self._dev_priority_class()
        if "pod_gang" in derived:
            pod_patch["gang_id"] = self._dev_gang_id()
        if "pod_quota" in derived:
            pod_patch["quota_id"] = self._dev_quota_id()
        if "pod_workload" in derived:
            pod_patch["workload_class"] = self._dev_workload_class()

        quota_patch = {}
        for key, field in (
            ("quota_runtime", "runtime"),
            ("quota_used", "used"),
            ("quota_limited", "limited"),
        ):
            if key in tensor_updates:
                new = updated(getattr(quotas, field), key, tensor_updates[key])
                if new is None:
                    arr = getattr(self, key)
                    new = self._place_rep(
                        arr.astype(bool) if field == "limited" else arr
                    )
                quota_patch[field] = new

        throughput = snap.throughput
        if "term_throughput" in tensor_updates:
            # replicated side table: the scatter runs on every device
            # with identical values, like the pod/quota tensors
            new = updated(snap.throughput, "term_throughput",
                          tensor_updates["term_throughput"])
            throughput = new if new is not None else self._dev_throughput()

        if node_patch:
            nodes = dataclasses.replace(nodes, **node_patch)
        if pod_patch:
            pods = dataclasses.replace(pods, **pod_patch)
        if quota_patch:
            quotas = dataclasses.replace(quotas, **quota_patch)
        gangs = self._dev_gangs() if "gang_min" in derived else snap.gangs
        return ClusterSnapshot(
            nodes=nodes, pods=pods, gangs=gangs, quotas=quotas,
            throughput=throughput,
        )

    # -- resident score tensors (ISSUE 9) --
    def score_residency(self) -> Optional[ScoreResidency]:
        """The resident [P, N] score/feasible tensors with their
        accumulated dirt, or None (never scored, or residency dropped).
        Callers serialize through the dispatch launch lock: commits
        mutate the dirt under it (run_exclusive) and Score launches
        read/advance under it."""
        return self._score_res

    def drop_score_residency(self) -> None:
        self._score_res = None

    def store_score_result(self, cfg, scores, feasible) -> None:
        """Adopt the tensors a Score launch just certified: the
        residency's dirt clears (the launch incorporated it) and the
        tensors land in the canonical placement — node-axis-sharded
        over the cluster mesh when mesh-resident
        (parallel/mesh.py ``score_sharding``), so the next incremental
        rescore partitions without any resharding program.  device_put
        with an already-matching sharding is a no-op, which is exactly
        the incremental path's case (the shard_map preserves specs)."""
        mesh = self.active_mesh()
        if mesh is not None:
            from koordinator_tpu.parallel.mesh import score_sharding

            spec = score_sharding(mesh)
            scores = jax.device_put(scores, spec)
            feasible = jax.device_put(feasible, spec)
        self._score_res = ScoreResidency(cfg, scores, feasible)

    def _note_score_dirty(self, score_dirty) -> None:
        """Advance the score residency past a warm commit: union the
        invalidated rows (None = attribution lost, e.g. a full-tensor
        re-upload — the residency drops and the next Score full-
        rescores)."""
        res = self._score_res
        if res is None:
            return
        if score_dirty is None:
            self._score_res = None
            return
        dirty_nodes, dirty_pods = score_dirty
        res.dirty_nodes |= dirty_nodes
        res.dirty_pods |= dirty_pods

    # -- resident sparse candidate lists (ISSUE 16) --
    def candidate_residency(self) -> Optional[CandidateResidency]:
        """The resident [P, C] candidate-index map with its exact
        per-pod feasible counts and accumulated dirt, or None (never
        built, or dropped).  Same serialization contract as
        :meth:`score_residency`: commits mutate the dirt under the
        dispatch launch lock and sparse Score launches read/advance
        under it."""
        return self._cand_res

    def drop_candidate_residency(self) -> None:
        self._cand_res = None

    def store_candidates(self, cfg, idx, count, merges: int = 0) -> None:
        """Adopt the candidate lists a sparse Score launch just built
        or refreshed: the dirt clears (the launch incorporated it) and
        ``merges`` records how deep the merge-refresh chain has grown
        since the last full build (0 after a cold/stale rebuild).
        Stored unsharded: the serving path runs the GSPMD-compatible
        unsharded functions regardless of node-mesh residency — the
        pod-mesh shard_map variants are exercised through
        solver/candidates.py's explicit ``mesh=`` parameter."""
        self._cand_res = CandidateResidency(cfg, idx, count, merges=merges)

    def _note_candidate_dirty(self, score_dirty) -> None:
        """Advance the candidate residency past a warm commit with the
        SAME row attribution the score residency uses — a conservative
        superset for feasibility (which reads fewer tensors than
        scoring), so the extra merge-refreshes are harmless and the
        lists stay exact.  None = attribution lost: drop, the next
        sparse Score cold-rebuilds."""
        res = self._cand_res
        if res is None:
            return
        if score_dirty is None:
            self._cand_res = None
            return
        dirty_nodes, dirty_pods = score_dirty
        res.dirty_nodes |= dirty_nodes
        res.dirty_pods |= dirty_pods

    def _score_dirty_rows(self, staged, plan):
        """(dirty node rows, dirty pod rows) a warm plan invalidates in
        the resident score tensors, or None when row attribution is
        lost (a full tensor rode the frame).  Runs BEFORE the mirror
        commit — derived-column comparisons need the old values.

        Row attribution per update kind:

        * a sparse delta's flat indices divide by the mirror's trailing
          row size — the same index space the device scatter targets;
        * quota tensors contribute nothing (``_SCORE_NODE_TENSORS``
          note: score_cycle never reads quota state);
        * derived freshness (``node_fresh``) diffs old-vs-new per node
          (None means the all-fresh default, the ``_dev_metric_fresh``
          rule);
        * priority/priority-class changes dirty the pods whose
          EFFECTIVE class moved — the one column score_cycle reads
          (``_pc_column``, the same derivation the device builder
          uses); raw priority feeds scoring only through it.
        """
        tensor_updates, derived = plan
        dirty_nodes: set = set()
        dirty_pods: set = set()
        for key, update in tensor_updates.items():
            if key == "pod_estimated_from_requests":
                continue  # rides pod_requests' indices, counted there
            if key == "term_throughput":
                # a change to matrix cell (c, a) invalidates the score
                # columns of every node whose CLIPPED accel type is a
                # (the gather clips, so out-of-range types alias the
                # edge rows) — matched against the post-commit accel
                # column, since that is what the next gather reads; an
                # accel flip in the SAME frame dirties its own rows
                # through the derived diff below.  Unlike the row-major
                # snapshot tensors, a FULL re-upload stays attributable:
                # the matrix is tiny ([C, A]) and warm-plan geometry is
                # unchanged, so the exact changed-cell set is one cheap
                # mirror diff (the delta ratio gate routinely ships
                # small matrices full — dropping residency for that
                # would make every trace-realistic throughput event a
                # full rescore).
                tput = np.asarray(self.term_throughput, np.int64)
                if update[0] == "delta":
                    changed = np.asarray(update[1], np.int64)
                else:
                    changed = np.flatnonzero(
                        tput.reshape(-1)
                        != np.asarray(staged[key], np.int64).reshape(-1)
                    )
                A = int(tput.shape[-1]) if tput.ndim > 1 else 1
                touched = set((changed % A).tolist())
                N = self.node_alloc.shape[0]
                accel_new = staged.get("node_accel", self.node_accel)
                accel = (
                    np.asarray(accel_new, np.int64)
                    if accel_new is not None
                    else np.zeros(N, np.int64)
                )
                accel = np.clip(accel[:N], 0, A - 1)
                for a in touched:
                    dirty_nodes.update(np.flatnonzero(accel == a).tolist())
                continue
            if key not in _SCORE_NODE_TENSORS and key not in _SCORE_POD_TENSORS:
                continue
            if update[0] != "delta":
                return None  # full re-upload: which rows moved is unknown
            base = np.asarray(getattr(self, key))
            trailing = int(np.prod(base.shape[1:])) if base.ndim > 1 else 1
            rows = dirty_nodes if key in _SCORE_NODE_TENSORS else dirty_pods
            rows.update(
                (np.asarray(update[1], np.int64) // trailing).tolist()
            )
        # gate on the plan's derived set: _warm_plan already diffed the
        # scalar columns, so an unchanged list riding the frame costs
        # nothing here (the effective-class derivation below is an O(P)
        # Python loop — it must not run on every priority-carrying Sync
        # while the launch lock holds back Score/Assign)
        new_fresh = staged.get("node_fresh")
        if "node_fresh" in derived and new_fresh is not None:
            new_fresh = np.asarray(new_fresh, bool)
            old_fresh = (
                np.asarray(self.node_fresh, bool)
                if self.node_fresh is not None
                else np.ones(len(new_fresh), bool)
            )
            if len(old_fresh) == len(new_fresh):
                dirty_nodes.update(
                    np.flatnonzero(old_fresh != new_fresh).tolist()
                )
            else:
                return None  # length moved without a resize: stay safe
        if "pod_priority" in derived or "pod_priority_class" in derived:
            P = self.pod_requests.shape[0]

            def eff_class(explicit, priority):
                prio = (
                    np.asarray(priority)
                    if priority is not None
                    else np.zeros(P, np.int64)
                )
                return _pc_column(explicit, prio, P, P)

            old_cls = eff_class(self.pod_priority_class, self.pod_priority)
            new_cls = eff_class(
                staged.get("pod_priority_class", self.pod_priority_class),
                staged.get("pod_priority", self.pod_priority),
            )
            dirty_pods.update(np.flatnonzero(old_cls != new_cls).tolist())
        # term columns (ISSUE 15): an accel-type flip moves that node's
        # heterogeneity gather (dirty column), a workload-class flip
        # moves that pod's row.  First appearance went cold in
        # _warm_plan, so old is always an array here; length moved
        # without a resize = stay safe, like the freshness rule.
        for key, rows in (("node_accel", dirty_nodes),
                          ("pod_workload", dirty_pods)):
            if key not in derived:
                continue
            new_col = staged.get(key)
            old_col = getattr(self, key)
            if new_col is None or old_col is None:
                return None
            new_col = np.asarray(new_col, np.int64)
            old_col = np.asarray(old_col, np.int64)
            if len(new_col) != len(old_col):
                return None
            rows.update(np.flatnonzero(old_col != new_col).tolist())
        return dirty_nodes, dirty_pods

    def i32_fits(self) -> bool:
        """Whether the resident tensors fit the Pallas kernel's i32
        arithmetic — computed from the host-side numpy mirrors so the
        per-cycle device round-trip in solver.pallas_inputs_fit_i32 is
        skipped on the Assign hot path."""
        if self._i32_ok is None:
            from koordinator_tpu.solver import check_i32_bounds

            zeros = np.zeros(1, np.int64)

            def amax(a):
                return int(np.abs(a).max()) if a is not None and a.size else 0

            est = (
                self.pod_estimated
                if self.pod_estimated is not None
                else self.pod_requests
            )
            scored = max(
                amax(self.node_alloc),
                amax(self.node_requested),
                amax(self.node_usage),
                amax(self.pod_requests),
                amax(est),
            )
            quota = max(amax(self.quota_runtime), amax(self.quota_used))
            est_sum = int(
                np.abs(est if est is not None else zeros).sum(axis=0).max()
            )
            req_sum = int(np.abs(self.pod_requests).sum(axis=0).max())
            self._i32_ok = check_i32_bounds((scored, quota, est_sum, req_sum))
        return self._i32_ok

    def _pad2(self, a: np.ndarray, rows: int) -> np.ndarray:
        out = np.zeros((rows, a.shape[1]), np.int64)
        out[: a.shape[0]] = a
        return out

    # -- mesh placement (ISSUE 7) --
    def active_mesh(self):
        """The cluster mesh for the CURRENT node bucket, or None (no
        mesh configured, or the bucket does not divide over it — then
        the snapshot stays single-chip for this geometry, logged once).
        Buckets are powers of two, so power-of-two device prefixes
        always divide."""
        if self.mesh is None:
            return None
        nb = self.node_bucket
        if nb and nb % self.mesh.size == 0:
            return self.mesh
        if nb and nb not in self._mesh_skip_warned:
            self._mesh_skip_warned.add(nb)
            logger.warning(
                "node bucket %d does not divide over the %d-device "
                "cluster mesh; resident snapshot stays single-chip for "
                "this geometry",
                nb, self.mesh.size,
            )
        return None

    def _place_node(self, a):
        """Place a node-major tensor: sharded along the cluster mesh's
        node axis when mesh-resident, plain device array otherwise."""
        m = self.active_mesh()
        if m is None:
            return jnp.asarray(a)
        from koordinator_tpu.parallel.mesh import node_sharding

        return jax.device_put(a, node_sharding(m, np.ndim(a)))

    def _place_rep(self, a):
        """Place a pod/gang/quota tensor: replicated over the cluster
        mesh when mesh-resident (the wave certifier and the quota
        admission recheck read them on every shard)."""
        m = self.active_mesh()
        if m is None:
            return jnp.asarray(a)
        from koordinator_tpu.parallel.mesh import replicated_sharding

        return jax.device_put(a, replicated_sharding(m))

    # -- per-field device builders (shared by cold rebuild + warm patch;
    #    one implementation keeps the two paths bit-exact) --
    def _dev_padded2(self, key: str, rows: int) -> jnp.ndarray:
        place = self._place_node if key.startswith("node_") else self._place_rep
        return place(
            self._pad2(np.asarray(getattr(self, key), np.int64), rows)
        )

    def _dev_metric_fresh(self) -> jnp.ndarray:
        N = self.node_alloc.shape[0]
        fresh = np.zeros(self.node_bucket, bool)
        fresh[:N] = (
            self.node_fresh if self.node_fresh is not None else np.ones(N, bool)
        )
        return self._place_node(fresh)

    def _dev_agg_usage(self):
        if not _present(self.node_agg):
            return None
        return self._place_node(_pad_rows_to(self.node_agg, self.node_bucket))

    def _dev_agg_fresh(self):
        if not _present(self.node_agg_fresh):
            return None
        return self._place_node(
            _pad_rows_to(self.node_agg_fresh, self.node_bucket).astype(bool)
        )

    def _dev_prod_usage(self):
        if not _present(self.node_prod):
            return None
        return self._place_node(
            _pad_rows_to(np.asarray(self.node_prod, np.int64), self.node_bucket)
        )

    def _dev_accel_type(self) -> jnp.ndarray:
        """Node accel-type column padded to the bucket (pad rows type 0
        — padded nodes are masked by ``valid`` everywhere, and the term
        gather clips, so the pad value is inert)."""
        N = self.node_alloc.shape[0]
        col = np.zeros(self.node_bucket, np.int32)
        if self.node_accel is not None:
            src = np.asarray(self.node_accel, np.int32)
            col[: min(N, len(src))] = src[:N]
        return self._place_node(col)

    def _dev_workload_class(self) -> jnp.ndarray:
        P = self.pod_requests.shape[0]
        col = np.zeros(self.pod_bucket, np.int32)
        if self.pod_workload is not None:
            src = np.asarray(self.pod_workload, np.int32)
            col[: min(P, len(src))] = src[:P]
        return self._place_rep(col)

    def _dev_sensitivity(self) -> jnp.ndarray:
        return self._place_rep(
            self._pad2(
                np.asarray(self.pod_sensitivity, np.int64), self.pod_bucket
            )
        )

    def _dev_throughput(self) -> jnp.ndarray:
        """The [C, A] throughput matrix: replicated, never padded (its
        geometry is per-(class, accel), not per-row)."""
        return self._place_rep(np.asarray(self.term_throughput, np.int64))

    def _dev_estimated(self) -> jnp.ndarray:
        est = (
            self.pod_estimated
            if self.pod_estimated is not None
            else self.pod_requests
        )
        return self._place_rep(self._pad2(np.asarray(est, np.int64), self.pod_bucket))

    def _dev_priority(self) -> jnp.ndarray:
        P = self.pod_requests.shape[0]
        prio = (
            self.pod_priority
            if self.pod_priority is not None
            else np.zeros(P, np.int64)
        )
        pprio = np.zeros(self.pod_bucket, np.int64)
        pprio[:P] = prio
        return self._place_rep(pprio)

    def _dev_priority_class(self) -> jnp.ndarray:
        P = self.pod_requests.shape[0]
        prio = (
            self.pod_priority
            if self.pod_priority is not None
            else np.zeros(P, np.int64)
        )
        # explicit classes from the wire, else derived from the priority
        # value bands (apis/extension/priority.go:84); padding is NONE —
        # zeros would mean PROD and wrongly put padded pods on the prod
        # filter/score path
        return self._place_rep(
            _pc_column(self.pod_priority_class, prio, P, self.pod_bucket)
        )

    def _dev_gang_id(self) -> jnp.ndarray:
        P = self.pod_requests.shape[0]
        gang = (
            self.pod_gang if self.pod_gang is not None else np.full(P, -1, np.int32)
        )
        pgang = np.full(self.pod_bucket, -1, np.int32)
        pgang[:P] = gang
        return self._place_rep(pgang)

    def _dev_quota_id(self) -> jnp.ndarray:
        P = self.pod_requests.shape[0]
        quota = (
            self.pod_quota if self.pod_quota is not None else np.full(P, -1, np.int32)
        )
        pquota = np.full(self.pod_bucket, -1, np.int32)
        pquota[:P] = quota
        return self._place_rep(pquota)

    def _dev_gangs(self) -> GangTable:
        gmin = self.gang_min if self.gang_min is not None else np.zeros(0, np.int32)
        G = max(1, len(gmin))
        gvalid = np.zeros(G, bool)
        gvalid[: len(gmin)] = True
        gm = np.zeros(G, np.int32)
        gm[: len(gmin)] = gmin
        return GangTable(
            min_member=self._place_rep(gm),
            valid=self._place_rep(gvalid),
            names=(),
        )

    def snapshot(self) -> ClusterSnapshot:
        if self._snapshot is not None:
            return self._snapshot
        N = self.node_alloc.shape[0]
        P = self.pod_requests.shape[0]
        nb, pb = self.node_bucket, self.pod_bucket
        nvalid = np.zeros(nb, bool)
        nvalid[:N] = True
        pvalid = np.zeros(pb, bool)
        pvalid[:P] = True
        if _present(self.quota_runtime):
            Q = self.quota_runtime.shape[0]
            qrt, quse = self.quota_runtime, self.quota_used
            qlim = self.quota_limited.astype(bool)
            qvalid = np.ones(Q, bool)
        else:
            qrt = np.zeros((1, R), np.int64)
            quse = np.zeros((1, R), np.int64)
            qlim = np.zeros((1, R), bool)
            qvalid = np.zeros(1, bool)

        self._snapshot = ClusterSnapshot(
            nodes=NodeBatch(
                allocatable=self._dev_padded2("node_alloc", nb),
                requested=(
                    self._dev_padded2("node_requested", nb)
                    if self.node_requested is not None
                    else self._place_node(np.zeros((nb, R), np.int64))
                ),
                usage=(
                    self._dev_padded2("node_usage", nb)
                    if self.node_usage is not None
                    else self._place_node(np.zeros((nb, R), np.int64))
                ),
                metric_fresh=self._dev_metric_fresh(),
                valid=self._place_node(nvalid),
                agg_usage=self._dev_agg_usage(),
                agg_fresh=self._dev_agg_fresh(),
                prod_usage=self._dev_prod_usage(),
                accel_type=(
                    self._dev_accel_type()
                    if self.node_accel is not None
                    else None
                ),
                names=(),
            ),
            pods=PodBatch(
                requests=self._dev_padded2("pod_requests", pb),
                estimated=self._dev_estimated(),
                priority_class=self._dev_priority_class(),
                qos=self._place_rep(np.zeros(pb, np.int32)),
                priority=self._dev_priority(),
                gang_id=self._dev_gang_id(),
                quota_id=self._dev_quota_id(),
                valid=self._place_rep(pvalid),
                workload_class=(
                    self._dev_workload_class()
                    if self.pod_workload is not None
                    else None
                ),
                sensitivity=(
                    self._dev_sensitivity()
                    if _present(self.pod_sensitivity)
                    else None
                ),
                names=(),
            ),
            gangs=self._dev_gangs(),
            quotas=QuotaTable(
                runtime=self._place_rep(qrt),
                used=self._place_rep(quse),
                limited=self._place_rep(qlim),
                valid=self._place_rep(qvalid),
                names=(),
            ),
            throughput=(
                self._dev_throughput()
                if _present(self.term_throughput)
                else None
            ),
        )
        return self._snapshot
