"""Resident snapshot state for the scorer sidecar.

The host->device transfer is the boundary to engineer (SURVEY §5/§7): the
server keeps numpy mirrors of every snapshot tensor; a warm Sync ships
only sparse (index, value) deltas (native/koordnative.cpp codec) against
them, and only the tensors that changed are re-uploaded to the device.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from koordinator_tpu import native
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import (
    ClusterSnapshot,
    GangTable,
    NodeBatch,
    PodBatch,
    QuotaTable,
    pad_bucket,
)

R = res.NUM_RESOURCES


def tensor_to_numpy(
    t: "pb2.Tensor", base: Optional[np.ndarray]
) -> Optional[np.ndarray]:
    """Decode a proto Tensor: full payload, or sparse delta onto ``base``.

    Returns the new mirror array, or None when the message carries nothing
    (tensor unchanged since the last sync).
    """
    if t.data:
        arr = np.frombuffer(t.data, dtype="<i8").copy()
        return arr.reshape(tuple(t.shape))
    if t.delta_idx:
        if base is None:
            raise ValueError("delta sync without a resident tensor")
        # the delta must target the RESIDENT shape: a client with a stale
        # differently-shaped mirror emits indices that may all land
        # inside the resident cell count yet write the wrong cells —
        # shape equality rejects every mismatch, not just the
        # out-of-range subset
        if t.shape and tuple(t.shape) != base.shape:
            raise ValueError(
                f"delta shape {tuple(t.shape)} != resident {base.shape}"
            )
        idx = np.frombuffer(t.delta_idx, dtype="<i8")
        val = np.frombuffer(t.delta_val, dtype="<i8")
        if len(idx) != len(val):
            raise ValueError(
                f"delta index/value length mismatch: {len(idx)} vs {len(val)}"
            )
        # bounds-check BEFORE the native path: delta_apply writes through
        # raw pointers, so an out-of-range index from a hostile frame
        # would corrupt server memory instead of raising
        if len(idx) and (int(idx.min()) < 0 or int(idx.max()) >= base.size):
            raise ValueError(
                f"delta index out of range for resident tensor of "
                f"{base.size} cells"
            )
        out = base.copy()
        native.delta_apply(out, idx, val)
        return out
    return None


def numpy_to_tensor(
    arr: np.ndarray, prev: Optional[np.ndarray] = None, max_delta_ratio: float = 0.25
) -> "pb2.Tensor":
    """Encode full, or as a sparse delta when <= max_delta_ratio changed."""
    t = pb2.Tensor()
    arr = np.ascontiguousarray(arr, dtype=np.int64)
    t.shape.extend(arr.shape)
    if prev is not None and prev.shape == arr.shape:
        enc = native.delta_encode(
            prev, arr, max_changes=max(1, int(arr.size * max_delta_ratio))
        )
        if enc is not None:
            idx, val = enc
            t.delta_idx = idx.astype("<i8").tobytes()
            t.delta_val = val.astype("<i8").tobytes()
            return t
    t.data = arr.astype("<i8").tobytes()
    return t


def _pad_rows_to(a, rows):
    """Pad (or keep) leading axis to ``rows`` regardless of current length
    (mirrors the length-agnostic padded()/_pad2 helpers — a stale mirror
    after a node-count change must never produce a wrong-shaped tensor)."""
    a = np.asarray(a)
    if a.shape[0] >= rows:
        return a[:rows]
    pad = [(0, rows - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad)


def _pc_column(explicit, priority, P, pb):
    from koordinator_tpu.model.snapshot import PriorityClass

    col = np.full(pb, int(PriorityClass.NONE), np.int32)
    if explicit is not None:
        col[: len(explicit)] = explicit[:pb]
    else:
        for i in range(P):
            col[i] = int(PriorityClass.from_priority_value(int(priority[i])))
    return col


class ResidentState:
    """Numpy mirrors + the device ClusterSnapshot built from them."""

    def __init__(self):
        self.node_alloc: Optional[np.ndarray] = None
        self.node_requested: Optional[np.ndarray] = None
        self.node_usage: Optional[np.ndarray] = None
        self.node_fresh: Optional[np.ndarray] = None
        self.node_agg: Optional[np.ndarray] = None  # [N, A, R]
        self.node_agg_fresh: Optional[np.ndarray] = None
        self.node_prod: Optional[np.ndarray] = None
        self.node_names: tuple = ()
        self.pod_requests: Optional[np.ndarray] = None
        self.pod_estimated: Optional[np.ndarray] = None
        self.pod_priority: Optional[np.ndarray] = None
        self.pod_priority_class: Optional[np.ndarray] = None
        self.pod_gang: Optional[np.ndarray] = None
        self.pod_quota: Optional[np.ndarray] = None
        self.pod_names: tuple = ()
        self.gang_min: Optional[np.ndarray] = None
        self.quota_runtime: Optional[np.ndarray] = None
        self.quota_used: Optional[np.ndarray] = None
        self.quota_limited: Optional[np.ndarray] = None
        self.node_bucket = 0
        self.pod_bucket = 0
        self._snapshot: Optional[ClusterSnapshot] = None
        self._i32_ok: Optional[bool] = None

    def apply_sync(self, reqmsg: "pb2.SyncRequest") -> None:
        """Decode EVERYTHING first, commit only if every tensor decoded:
        a rejected frame (bad delta shape/index, missing first-sync
        tensors) must leave the resident state untouched — a torn
        half-applied sync would hand every OTHER client a corrupted
        delta baseline behind an unbumped generation."""
        n = reqmsg.nodes
        p = reqmsg.pods

        def upd(current, tensor):
            new = tensor_to_numpy(tensor, current)
            return current if new is None else new

        staged = {
            "node_alloc": upd(self.node_alloc, n.allocatable),
            "node_requested": upd(self.node_requested, n.requested),
            "node_usage": upd(self.node_usage, n.usage),
            "node_agg": upd(self.node_agg, n.agg_usage),
            "node_agg_fresh": upd(self.node_agg_fresh, n.agg_fresh),
            "node_prod": upd(self.node_prod, n.prod_usage),
            "pod_requests": upd(self.pod_requests, p.requests),
            "pod_estimated": upd(self.pod_estimated, p.estimated),
            "quota_runtime": upd(self.quota_runtime, reqmsg.quotas.runtime),
            "quota_used": upd(self.quota_used, reqmsg.quotas.used),
            "quota_limited": upd(self.quota_limited, reqmsg.quotas.limited),
        }
        if staged["node_alloc"] is None or staged["pod_requests"] is None:
            raise ValueError("first Sync must carry full node and pod tensors")
        if n.metric_fresh:
            staged["node_fresh"] = np.asarray(list(n.metric_fresh), dtype=bool)
        if n.names:
            staged["node_names"] = tuple(n.names)
        if p.priority:
            staged["pod_priority"] = np.asarray(list(p.priority), dtype=np.int64)
        if p.priority_class:
            staged["pod_priority_class"] = np.asarray(
                list(p.priority_class), dtype=np.int32
            )
        if p.gang_id:
            staged["pod_gang"] = np.asarray(list(p.gang_id), dtype=np.int32)
        if p.quota_id:
            staged["pod_quota"] = np.asarray(list(p.quota_id), dtype=np.int32)
        if p.names:
            staged["pod_names"] = tuple(p.names)
        if reqmsg.gangs.min_member:
            staged["gang_min"] = np.asarray(
                list(reqmsg.gangs.min_member), np.int32
            )
        staged["node_bucket"] = int(reqmsg.node_bucket) or pad_bucket(
            staged["node_alloc"].shape[0]
        )
        staged["pod_bucket"] = int(reqmsg.pod_bucket) or pad_bucket(
            staged["pod_requests"].shape[0]
        )
        # atomic commit point: nothing above mutated self
        for key, value in staged.items():
            setattr(self, key, value)
        self._snapshot = None  # rebuilt lazily
        self._i32_ok = None

    def i32_fits(self) -> bool:
        """Whether the resident tensors fit the Pallas kernel's i32
        arithmetic — computed from the host-side numpy mirrors so the
        per-cycle device round-trip in solver.pallas_inputs_fit_i32 is
        skipped on the Assign hot path."""
        if self._i32_ok is None:
            from koordinator_tpu.solver import check_i32_bounds

            zeros = np.zeros(1, np.int64)

            def amax(a):
                return int(np.abs(a).max()) if a is not None and a.size else 0

            est = (
                self.pod_estimated
                if self.pod_estimated is not None
                else self.pod_requests
            )
            scored = max(
                amax(self.node_alloc),
                amax(self.node_requested),
                amax(self.node_usage),
                amax(self.pod_requests),
                amax(est),
            )
            quota = max(amax(self.quota_runtime), amax(self.quota_used))
            est_sum = int(
                np.abs(est if est is not None else zeros).sum(axis=0).max()
            )
            req_sum = int(np.abs(self.pod_requests).sum(axis=0).max())
            self._i32_ok = check_i32_bounds((scored, quota, est_sum, req_sum))
        return self._i32_ok

    def _pad2(self, a: np.ndarray, rows: int) -> np.ndarray:
        out = np.zeros((rows, a.shape[1]), np.int64)
        out[: a.shape[0]] = a
        return out

    def snapshot(self) -> ClusterSnapshot:
        if self._snapshot is not None:
            return self._snapshot
        N = self.node_alloc.shape[0]
        P = self.pod_requests.shape[0]
        nb, pb = self.node_bucket, self.pod_bucket
        nvalid = np.zeros(nb, bool)
        nvalid[:N] = True
        pvalid = np.zeros(pb, bool)
        pvalid[:P] = True
        fresh = np.zeros(nb, bool)
        fresh[:N] = (
            self.node_fresh if self.node_fresh is not None else np.ones(N, bool)
        )
        est = (
            self.pod_estimated
            if self.pod_estimated is not None
            else self.pod_requests
        )
        prio = (
            self.pod_priority
            if self.pod_priority is not None
            else np.zeros(P, np.int64)
        )
        gang = (
            self.pod_gang if self.pod_gang is not None else np.full(P, -1, np.int32)
        )
        quota = (
            self.pod_quota if self.pod_quota is not None else np.full(P, -1, np.int32)
        )
        gmin = self.gang_min if self.gang_min is not None else np.zeros(0, np.int32)
        G = max(1, len(gmin))
        gvalid = np.zeros(G, bool)
        gvalid[: len(gmin)] = True
        gm = np.zeros(G, np.int32)
        gm[: len(gmin)] = gmin
        if self.quota_runtime is not None and self.quota_runtime.size:
            Q = self.quota_runtime.shape[0]
            qrt, quse = self.quota_runtime, self.quota_used
            qlim = self.quota_limited.astype(bool)
            qvalid = np.ones(Q, bool)
        else:
            Q = 1
            qrt = np.zeros((1, R), np.int64)
            quse = np.zeros((1, R), np.int64)
            qlim = np.zeros((1, R), bool)
            qvalid = np.zeros(1, bool)

        def padded(a, rows):
            return jnp.asarray(self._pad2(np.asarray(a, np.int64), rows))

        pprio = np.zeros(pb, np.int64)
        pprio[:P] = prio
        pgang = np.full(pb, -1, np.int32)
        pgang[:P] = gang
        pquota = np.full(pb, -1, np.int32)
        pquota[:P] = quota
        self._snapshot = ClusterSnapshot(
            nodes=NodeBatch(
                allocatable=padded(self.node_alloc, nb),
                requested=padded(
                    self.node_requested
                    if self.node_requested is not None
                    else np.zeros_like(self.node_alloc),
                    nb,
                ),
                usage=padded(
                    self.node_usage
                    if self.node_usage is not None
                    else np.zeros_like(self.node_alloc),
                    nb,
                ),
                metric_fresh=jnp.asarray(fresh),
                valid=jnp.asarray(nvalid),
                agg_usage=(
                    jnp.asarray(_pad_rows_to(self.node_agg, nb))
                    if self.node_agg is not None and self.node_agg.size
                    else None
                ),
                agg_fresh=(
                    jnp.asarray(
                        _pad_rows_to(self.node_agg_fresh, nb).astype(bool)
                    )
                    if self.node_agg_fresh is not None
                    and self.node_agg_fresh.size
                    else None
                ),
                prod_usage=(
                    jnp.asarray(
                        _pad_rows_to(
                            np.asarray(self.node_prod, np.int64), nb
                        )
                    )
                    if self.node_prod is not None and self.node_prod.size
                    else None
                ),
                names=self.node_names,
            ),
            pods=PodBatch(
                requests=padded(self.pod_requests, pb),
                estimated=padded(est, pb),
                # explicit classes from the wire, else derived from the
                # priority value bands (apis/extension/priority.go:84);
                # padding is NONE — zeros would mean PROD and wrongly put
                # padded pods on the prod filter/score path
                priority_class=jnp.asarray(_pc_column(
                    self.pod_priority_class, prio, P, pb
                )),
                qos=jnp.zeros(pb, jnp.int32),
                priority=jnp.asarray(pprio),
                gang_id=jnp.asarray(pgang),
                quota_id=jnp.asarray(pquota),
                valid=jnp.asarray(pvalid),
                names=self.pod_names,
            ),
            gangs=GangTable(
                min_member=jnp.asarray(gm), valid=jnp.asarray(gvalid), names=()
            ),
            quotas=QuotaTable(
                runtime=jnp.asarray(qrt),
                used=jnp.asarray(quse),
                limited=jnp.asarray(qlim),
                valid=jnp.asarray(qvalid),
                names=(),
            ),
        )
        return self._snapshot
