"""Executable spec of ``go/plugin/batchedtpuscorer.go``.

The image has no Go toolchain, so the Go plugin cannot compile or run
here.  This module re-states its PreScore protocol — vector building,
delta-vs-full sync decision, generation-continuity check with full
re-sync, mirror promotion/invalidation, flat-score row extraction —
step for step in Python, using the independent wire codec
(``bridge/wirecheck.py``), and drives it against the REAL raw-UDS server
in ``tests/test_plugin_seam.py``.  Any behavior change in the Go file
must land here too; the tests are the executable contract the Go code
is reviewed against (the release gate in go/README.md additionally
requires ``go test ./...`` where a toolchain exists).

Go references (line-level mirrors):
  * nodeInfoVectors       -> node_vectors
  * DeltaTensor           -> delta_tensor (go/scorerclient/delta.go)
  * buildSync             -> build_sync
  * Scorer.PreScore       -> GoPluginSim.pre_score (including the
    delta-failure full-retry and the epoch+generation continuity check)
  * parseSnapshotID       -> parse_snapshot_id
  * scorerclient.Generation -> generation
  * NodeMetricCache.SetQuantities (the NodeMetric informer parse)
                          -> usage_vector_from_node_metric
"""

from __future__ import annotations

import socket
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.bridge import wirecheck

NUM_AXES = 13
AXIS_CPU = 0
AXIS_MEMORY = 1
DEFAULT_MAX_DELTA_RATIO = 0.25

METHOD_SYNC = 1
METHOD_SCORE = 2
METHOD_ASSIGN = 3


def parse_snapshot_id(snapshot_id: str) -> Tuple[str, int]:
    """scorerclient.ParseSnapshotID: "s<epoch>-<generation>" -> (epoch,
    generation); legacy "s<generation>" -> ("", generation); malformed
    generations parse as -1 (never satisfies a continuity check).  The
    epoch is the sidecar's per-boot nonce: delta continuity requires the
    SAME epoch — after a restart the generation counter resets and bare
    ``gen == mirror.gen+1`` can coincidentally pass (ADVICE r5)."""
    body = snapshot_id.removeprefix("s")
    epoch, sep, gen = body.rpartition("-")
    if not sep:
        epoch, gen = "", body
    try:
        return epoch, int(gen)
    except ValueError:
        return epoch, -1


def generation(snapshot_id: str) -> int:
    """scorerclient.Generation: the generation half of the snapshot id."""
    return parse_snapshot_id(snapshot_id)[1]


def usage_vector_from_node_metric(payload: Dict) -> Optional[List[int]]:
    """NodeMetricCache.SetQuantities' parse path, in Python: convert the
    koordlet NodeMetricReporter payload
    (statesinformer.py: ``{"nodeMetric": {"nodeUsage": {"cpu": "1500m",
    "memory": "<bytes>"}}}``) into the dense usage vector the shim syncs
    (cpu milli at axis 0, memory MiB at axis 1).  None when the payload
    carries no node usage (the cache keeps its previous sample).
    Quantities go through the one parser (model/resources.parse_quantity)
    so every Kubernetes serialization form ("2Gi", "1500000000n") lands
    in the exact axis units."""
    from koordinator_tpu.model import resources as res

    usage = ((payload or {}).get("nodeMetric") or {}).get("nodeUsage")
    if not usage:
        return None
    vec = [0] * NUM_AXES
    vec[AXIS_CPU] = int(res.parse_quantity(usage.get("cpu", 0), res.CPU))
    vec[AXIS_MEMORY] = int(
        res.parse_quantity(usage.get("memory", 0), res.MEMORY)
    )
    return vec


def delta_tensor(
    shape: Sequence[int],
    prev: Optional[Sequence[int]],
    next_: Sequence[int],
    max_ratio: float = DEFAULT_MAX_DELTA_RATIO,
) -> Dict:
    """go/scorerclient/delta.go DeltaTensor, exactly: full Data when prev
    is absent/mismatched or more than max(1, int(size*ratio)) cells
    changed; sparse flat (idx, val) otherwise (empty = unchanged)."""
    next_ = list(next_)
    t = {"shape": list(shape)}
    if prev is None or len(prev) != len(next_):
        t["data"] = np.asarray(next_, "<i8").tobytes()
        return t
    max_changes = max(1, int(len(next_) * max_ratio))
    idx = [i for i, (a, b) in enumerate(zip(prev, next_)) if a != b]
    if len(idx) > max_changes:
        t["data"] = np.asarray(next_, "<i8").tobytes()
        return t
    t["delta_idx"] = np.asarray(idx, "<i8").tobytes()
    t["delta_val"] = np.asarray([next_[i] for i in idx], "<i8").tobytes()
    return t


def node_vectors(
    nodes: Sequence[Tuple[str, Sequence[int], Sequence[int]]],
    metrics: Optional[Dict[str, Sequence[int]]],
):
    """nodeInfoVectors: (names, alloc, requested, usage, fresh) with
    usage from the metrics provider when a fresh sample exists, else
    requested with fresh=False (Fit-only for that node)."""
    names: List[str] = []
    alloc: List[int] = []
    requested: List[int] = []
    usage: List[int] = []
    fresh: List[bool] = []
    for name, a, r in nodes:
        names.append(name)
        alloc.extend(a)
        requested.extend(r)
        vec = (metrics or {}).get(name)
        if vec is not None and len(vec) == NUM_AXES:
            usage.extend(vec)
            fresh.append(True)
        else:
            usage.extend(r)
            fresh.append(False)
    return names, alloc, requested, usage, fresh


def build_sync(
    mirror: "ResidentMirror",
    delta: bool,
    names: List[str],
    alloc: List[int],
    requested: List[int],
    usage: List[int],
    fresh: List[bool],
    pod_name: str,
    pod_vec: List[int],
    priority: int,
) -> bytes:
    """buildSync: node tensors delta-encoded against the acked baseline
    (names omitted) on warm cycles; the single-pod table always full."""
    n = len(names)
    shape = [n, NUM_AXES]
    prev_alloc = prev_req = prev_usage = None
    wire_names = names
    if delta:
        prev_alloc, prev_req, prev_usage = (
            mirror.alloc,
            mirror.requested,
            mirror.usage,
        )
        wire_names = []
    req = {
        "nodes": {
            "names": wire_names,
            "allocatable": delta_tensor(shape, prev_alloc, alloc),
            "requested": delta_tensor(shape, prev_req, requested),
            "usage": delta_tensor(shape, prev_usage, usage),
            "metric_fresh": fresh,
        },
        "pods": {
            "names": [pod_name],
            "requests": {
                "shape": [1, NUM_AXES],
                "data": np.asarray(pod_vec, "<i8").tobytes(),
            },
            "estimated": {
                "shape": [1, NUM_AXES],
                "data": np.asarray(pod_vec, "<i8").tobytes(),
            },
            "priority": [priority],
            "gang_id": [-1],
            "quota_id": [-1],
        },
    }
    return wirecheck.encode_sync_request(req)


class ResidentMirror:
    """residentMirror: the last ACKED node table (delta baseline)."""

    def __init__(self):
        self.invalidate()

    def invalidate(self) -> None:
        self.names: List[str] = []
        self.alloc: List[int] = []
        self.requested: List[int] = []
        self.usage: List[int] = []
        self.gen = 0
        self.epoch = ""
        self.valid = False


class GoPluginSim:
    """Scorer (the plugin struct) over a raw-UDS connection."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self.mirror = ResidentMirror()
        # NodeMetricsProvider: node -> usage vector (fresh by presence;
        # staleness windows are the cache's concern, not the plugin's)
        self.metrics: Dict[str, Sequence[int]] = {}
        self._conn: Optional[socket.socket] = None
        # wire observability for tests: (method, payload_len) per frame
        self.sent_frames: List[Tuple[int, int]] = []

    def update_node_metric(self, node: str, payload: Dict) -> None:
        """The NodeMetric informer callback (the Go plugin wires the CR
        informer's add/update handler to NodeMetricCache.Set the same
        way): parse the koordlet report and refresh the node's usage
        sample; a payload without node usage keeps the previous one."""
        vec = usage_vector_from_node_metric(payload)
        if vec is not None:
            self.metrics[node] = vec

    # ensureClient / dropClient
    def _client(self) -> socket.socket:
        if self._conn is None:
            self._conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._conn.connect(self.socket_path)
        return self._conn

    def _drop_client(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _call(self, method: int, payload: bytes) -> bytes:
        conn = self._client()
        self.sent_frames.append((method, len(payload)))
        conn.sendall(struct.pack(">BI", method, len(payload)) + payload)
        head = conn.recv(5, socket.MSG_WAITALL)
        status, length = struct.unpack(">BI", head)
        body = b""
        while len(body) < length:
            chunk = conn.recv(length - len(body))
            if not chunk:
                raise ConnectionError("connection closed mid-reply")
            body += chunk
        if status != 0:
            raise RuntimeError(f"scorer error: {body.decode()}")
        return body

    def pre_score(
        self,
        nodes: Sequence[Tuple[str, Sequence[int], Sequence[int]]],
        pod_name: str,
        pod_vec: Sequence[int],
        priority: int = 0,
    ) -> Dict[str, int]:
        """Scorer.PreScore: returns {node name: combined score} (the
        CycleState row); raises on any seam failure, with the mirror
        invalidated exactly where the Go code invalidates it."""
        names, alloc, requested, usage, fresh = node_vectors(
            nodes, self.metrics
        )
        pod_vec = list(pod_vec)
        delta = self.mirror.valid and self.mirror.names == names

        def sync_once(as_delta: bool) -> Dict:
            return wirecheck.decode_sync_reply(
                self._call(
                    METHOD_SYNC,
                    build_sync(
                        self.mirror, as_delta, names, alloc, requested,
                        usage, fresh, pod_name, pod_vec, priority,
                    ),
                )
            )

        resynced_full = False
        try:
            reply = sync_once(delta)
        except Exception:
            if not delta:
                self.mirror.invalidate()
                self._drop_client()
                raise
            # delta-Sync failure is recoverable within the same cycle: a
            # restarted sidecar lost its resident tensors (and possibly
            # the connection) — re-dial and ship full state once before
            # surfacing an error (ADVICE r5)
            self._drop_client()
            try:
                reply = sync_once(False)
                resynced_full = True
            except Exception:
                self.mirror.invalidate()
                self._drop_client()
                raise
        epoch, gen = parse_snapshot_id(reply["snapshot_id"])
        if delta and not resynced_full and (
            epoch != self.mirror.epoch or gen != self.mirror.gen + 1
        ):
            # resident state displaced (foreign sync bumped the
            # generation, or a restart reset it under a fresh epoch —
            # the epoch comparison catches the restart even when the new
            # generation coincidentally continues ours): full re-sync
            # before trusting scores
            try:
                reply = sync_once(False)
            except Exception:
                self.mirror.invalidate()
                self._drop_client()
                raise
            epoch, gen = parse_snapshot_id(reply["snapshot_id"])
        self.mirror.names = names
        self.mirror.alloc = alloc
        self.mirror.requested = requested
        self.mirror.usage = usage
        self.mirror.gen = gen
        self.mirror.epoch = epoch
        self.mirror.valid = True
        try:
            score = wirecheck.decode_score_reply(
                self._call(
                    METHOD_SCORE,
                    wirecheck.encode_score_request(
                        {"snapshot_id": reply["snapshot_id"], "top_k": 0,
                         "flat": True}
                    ),
                )
            )
        except Exception:
            self.mirror.invalidate()
            self._drop_client()
            raise
        flat = score["flat"]
        if flat is None:
            raise RuntimeError("scorer did not return the flat layout")
        pod_index = np.frombuffer(flat["pod_index"], "<i4")
        counts = np.frombuffer(flat["counts"], "<i4")
        node_index = np.frombuffer(flat["node_index"], "<i4")
        scores_arr = np.frombuffer(flat["score"], "<i8")
        scores: Dict[str, int] = {}
        off = 0
        for g, p in enumerate(pod_index):
            c = int(counts[g])
            if p == 0:  # single-pod table: group 0 is our pod
                for i in range(off, off + c):
                    ni = int(node_index[i])
                    if ni < len(names):
                        scores[names[ni]] = int(scores_arr[i])
            off += c
        return scores
