"""protoc codegen shim: import the checked-in scorer_pb2, regenerating it
from scorer.proto when protoc is available and the proto is newer (the
image has protoc but not grpcio-tools; services use grpc generic handlers
so only message codegen is needed)."""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(__file__)
_PROTO = os.path.join(_DIR, "scorer.proto")
_PB2 = os.path.join(_DIR, "scorer_pb2.py")


def _regen_if_stale() -> None:
    try:
        if os.path.exists(_PB2) and os.path.getmtime(_PB2) >= os.path.getmtime(
            _PROTO
        ):
            return
        subprocess.run(
            ["protoc", f"--python_out={_DIR}", "scorer.proto"],
            cwd=_DIR,
            check=True,
            capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        # no protoc on this machine: use the checked-in scorer_pb2
        pass


_regen_if_stale()

from koordinator_tpu.bridge import scorer_pb2 as pb2  # noqa: E402,F401

SERVICE = "koordinator_tpu.bridge.BatchedScorer"


def method_path(name: str) -> str:
    return f"/{SERVICE}/{name}"
