"""protoc codegen shim: imports the checked-in scorer_pb2 unconditionally.
After editing scorer.proto, run ``regen()`` (or protoc by hand) and commit
the result — regeneration is never an import side effect.  The image has
protoc but not grpcio-tools; services use grpc generic handlers so only
message codegen is needed."""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(__file__)


def regen() -> None:
    """Regenerate scorer_pb2.py from scorer.proto.  Explicit dev tool —
    never run as an import side effect (a protoc skew or read-only
    install must not silently replace the tested checked-in pb2)."""
    subprocess.run(
        ["protoc", f"--python_out={_DIR}", "scorer.proto"],
        cwd=_DIR,
        check=True,
        capture_output=True,
    )


from koordinator_tpu.bridge import scorer_pb2 as pb2  # noqa: E402,F401

SERVICE = "koordinator_tpu.bridge.BatchedScorer"


def method_path(name: str) -> str:
    return f"/{SERVICE}/{name}"
