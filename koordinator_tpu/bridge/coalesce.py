"""Pipelined coalescing device dispatch for the BatchedScorer bridge.

ISSUE 5 built the coalescer: concurrent Score requests that arrive
while the device is busy (or within a small gather window) stack into
ONE batched launch against the resident snapshot, and the replies are
demultiplexed per caller.  But the engine still ran strictly one launch
at a time — the batch leader held the device critical section across
its *blocking* stacked readback, so the device sat idle for the entire
host-side ``device_get`` + demux of batch k before batch k+1 could
launch.

ISSUE 6 rebuilds the device section as a **double-buffered pipeline**.
The critical section now covers only the *launch* (snapshot capture +
async device dispatch — JAX returns as soon as the program is enqueued);
the blocking readback and the numpy demux run OFF the launch lock, so
the next leader launches batch k+1 while batch k's transfer is still in
flight.  A depth-``DEFAULT_DEPTH`` in-flight bound keeps memory
predictable (and ``depth=1`` reproduces the ISSUE-5 serial-readback
engine for baselines).

Executor protocol (the two-phase seam):

* ``launch_batch(entries)`` runs with the **launch lock held** and must
  only capture state and dispatch device work — never block on a
  device->host transfer (the ``lock-held-dispatch`` koordlint rule
  rejects blocking calls inside ``@launch_section`` functions
  statically).  It either finalizes entries in place (sets ``reply`` /
  ``error`` and returns ``None`` — the degenerate no-device path, e.g.
  every entry stale) or returns a **readback closure**.
* the readback closure runs with the launch lock *released*; it blocks
  on the stacked transfer, fills each entry's ``reply``/``error``, and
  may return a post-batch hook the leader runs after followers are
  notified (host bookkeeping must not extend any critical path).
* a closure carrying ``no_device = True`` (ISSUE 7: the Score memo's
  prefix assembly) also runs off the lock but put NOTHING on the
  device: no in-flight slot is taken, no launch is accounted (the
  device-idle gap closes only if queued work drains), and a donating
  ``run_exclusive(drain=True)`` never waits on it.

Concurrency contract (lock order is launch -> state, never state ->
launch while holding state):

* ``submit()`` enqueues and then either *leads* (first thread to take
  the launch lock with pipeline headroom drains up to ``max_batch``
  entries, launches them, then drains its own batch's readback off the
  lock) or *follows* (waits for a leader to publish its slot).  FIFO: a
  batch is always a prefix of the queue.  Every state transition
  (launch-lock release, readback completion, enqueue) notifies the
  shared condition — followers never poll.
* ``run_pipelined(fn)`` runs a non-coalescible launch (Assign's cycle)
  through the same pipeline: ``fn`` executes under the launch lock and
  returns a readback closure that runs outside it.
* ``run_exclusive(fn, drain=True)`` is the **donation barrier**: a
  warm Sync's delta scatter donates the pre-delta resident buffers, so
  it must not run while any launched-but-unread batch could still be
  holding python references that a deletion would invalidate.  With
  ``drain`` the section waits for the in-flight count to reach zero
  before running ``fn`` (launch lock held throughout, so nothing new
  launches).  Non-donating commits pass ``drain=False`` and keep the
  pipeline flowing.

The **gather window** is adaptive by default (ISSUE 6): instead of the
hand-tuned static ``gather_window_s``, :class:`AdaptiveGatherWindow`
tracks an EWMA of observed inter-arrival gaps (the same quantity the
``koord_scorer_coalesce_queue_delay_ms`` samples measure per entry) and
derives the wait from it — ``min(cap, ewma_gap * (max_batch - 1))``,
zero when traffic is too sparse for waiting to fill a batch.  A leader
only gather-waits when the pipeline is *empty*: with a batch already in
flight, launching immediately is free (the device is busy anyway).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from koordinator_tpu.obs.lockwitness import witness_condition, witness_lock

# One launch serves at most this many stacked Score requests; the Go
# scheduler dispatches 16 parallel Score workers, so a full worker burst
# coalesces into a single device program.
DEFAULT_MAX_BATCH = 16

# Launched-but-unread batches allowed at once.  Two is the double
# buffer: launch k+1 overlaps readback k; deeper queues buy nothing
# once the device is saturated and multiply in-flight result memory.
DEFAULT_DEPTH = 2


def launch_section(fn):
    """Marker for functions that run under the dispatcher's launch
    lock.  Identity at runtime; koordlint's ``lock-held-dispatch`` rule
    rejects blocking device->host transfers (``jax.device_get``,
    ``.block_until_ready()``, ``np.asarray``, ``.item()``) inside any
    function carrying this decorator — only the readback closure (a
    nested def, exempt) may block."""
    return fn


class SnapshotNotResident(ValueError):
    """A coalesced request named a snapshot that is no longer resident
    (the same condition ``ScorerServicer._check_generation`` rejects on
    the serial paths; callers translate to FAILED_PRECONDITION)."""


class DeadlineExpired(Exception):
    """A request's propagated deadline budget ran out before the device
    could serve it (ISSUE 13 deadline propagation).  ``stage`` says
    where the expiry was caught: ``queue`` = the request arrived with
    an already-exhausted budget (rejected at RPC entry, before it could
    deepen any queue), ``gather`` = it expired while queued and the
    batch leader evicted it at gather time — BEFORE it occupied a
    launch slot, so an expired request never costs a device launch.
    Transports map this to gRPC DEADLINE_EXCEEDED."""

    def __init__(self, method: str, stage: str, budget_ms: float):
        self.method = method
        self.stage = stage
        self.budget_ms = float(budget_ms)
        super().__init__(
            f"DEADLINE_EXCEEDED: {method} deadline budget "
            f"({self.budget_ms:.0f} ms) expired at stage={stage}; "
            "the request was never launched"
        )


class PendingRequest:
    """One caller's slot in a coalesced batch.  The executor fills
    ``reply`` (or ``error``); the dispatcher stamps queue/batch stats
    and flips ``done`` under the queue condition.  ``deadline_at`` is
    the absolute (dispatcher-clock) expiry the propagated per-RPC
    budget pins — None = no deadline; the batch leader evicts expired
    entries at gather time before they occupy a launch slot."""

    __slots__ = (
        "req", "enqueued_at", "reply", "error", "done",
        "queue_delay_ms", "batch_size", "deadline_at", "budget_ms",
        "trace_span",
    )

    def __init__(self, req, enqueued_at: float,
                 deadline_at: Optional[float] = None,
                 budget_ms: float = 0.0, trace_span=None):
        self.req = req
        self.enqueued_at = enqueued_at
        self.reply = None
        self.error: Optional[BaseException] = None
        self.done = False
        self.queue_delay_ms = 0.0
        self.batch_size = 0
        self.deadline_at = deadline_at
        self.budget_ms = budget_ms
        # this RPC's distributed-trace span (obs/spans.py TraceSpan,
        # ISSUE 14) or None: the batch leader fan-in links it to the
        # ONE launch span the coalesced batch shares — the span's
        # lifecycle (end/abort) stays with the submitting RPC body
        self.trace_span = trace_span


class ScoreMemo:
    """Host-side memo of one Score launch's padded top-k readback
    (ISSUE 7 satellite — the ROADMAP item-1 follow-on extending the
    PR 6 Assign memo to Score).

    Key: ``(snapshot id, CycleConfig)``; the entry records the
    ``k``-BUCKET it was launched at (``kb`` — the sticky power-of-two
    ``lax.top_k`` width) plus the host arrays of the stacked readback.
    A later batch whose every caller needs ``k <= kb`` serves sliced
    prefixes straight from the entry — no device launch, not even a
    lazy snapshot rebuild — and prefix slicing of the padded top-k is
    bit-identical to a fresh launch (``lax.top_k`` sorts descending
    with index tie-breaks).  A batch needing a LARGER k misses and its
    launch replaces the entry with the wider bucket.

    Thread contract: the caller serializes access (the servicer's
    ``_state_lock`` — lookups happen inside the launch section's state
    capture, publishes after the readback).  Invalidation is the same
    atomic clear-on-generation-bump the Assign memo uses: entries die
    with the snapshot id they certified, and because the id is IN the
    key, a stale publish racing the bump can never serve a future
    request (the caller also guards the publish on the current id, so
    the dict stays one-entry-deep per config).  Hit/miss accounting
    lives on the ``koord_scorer_score_memo_total`` telemetry family,
    fed by the servicer — not here.
    """

    __slots__ = ("_entries",)

    def __init__(self):
        self._entries = {}

    def get(self, sid, cfg):
        """The memo entry dict for (sid, cfg), or None.  Entry keys:
        ``kb`` (launched top-k bucket), ``N``/``P`` (node/pod
        capacities), ``ts``/``ti``/``feasible``/``valid`` (host-side
        stacked readback)."""
        return self._entries.get((sid, cfg))

    def put(self, sid, cfg, data) -> None:
        """Publish a readback; a narrower bucket never replaces a wider
        one (the wider entry already serves every prefix)."""
        prev = self._entries.get((sid, cfg))
        if prev is not None and prev["kb"] >= data["kb"]:
            return
        self._entries[(sid, cfg)] = data

    def invalidate(self) -> None:
        self._entries.clear()


class StaticGatherWindow:
    """The ISSUE-5 knob: a fixed straggler wait (0 = never wait)."""

    def __init__(self, seconds: float = 0.0):
        self._seconds = max(0.0, float(seconds))

    def observe_arrival(self, now_s: float) -> None:
        pass

    def window_s(self, max_batch: int) -> float:
        return self._seconds if max_batch > 1 else 0.0


class AdaptiveGatherWindow:
    """Gather window derived from the observed inter-arrival rate.

    ``observe_arrival`` feeds an EWMA of the gap between consecutive
    submits (callers hold the dispatcher's condition, so no lock here).
    The window is::

        0                                  while no gap was observed yet
        0                                  if ewma_gap >= lone_cutoff_ms
        min(cap_ms, ewma_gap*(max_batch-1))  otherwise

    Rationale: if requests arrive every ``g`` ms, an idle-device leader
    that waits ``g*(max_batch-1)`` gathers a full batch; the cap bounds
    the latency tax, and the lone cutoff turns the window off entirely
    when traffic is so sparse that a cap-length wait could not gather
    even one extra request (``lone_cutoff_ms`` defaults to ``cap_ms``:
    past it, cap/gap < 1).  Burst trains therefore converge onto wide
    batches while lone requests keep serial latency.
    """

    def __init__(self, alpha: float = 0.2, cap_ms: float = 5.0,
                 lone_cutoff_ms: Optional[float] = None):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.cap_ms = max(0.0, float(cap_ms))
        self.lone_cutoff_ms = (
            self.cap_ms if lone_cutoff_ms is None else float(lone_cutoff_ms)
        )
        self._last_arrival_s: Optional[float] = None
        self._ewma_gap_ms: Optional[float] = None

    def observe_arrival(self, now_s: float) -> None:
        last = self._last_arrival_s
        self._last_arrival_s = now_s
        if last is None:
            return
        gap_ms = max(0.0, (now_s - last) * 1000.0)
        if self._ewma_gap_ms is None:
            self._ewma_gap_ms = gap_ms
        else:
            self._ewma_gap_ms = (
                self.alpha * gap_ms + (1.0 - self.alpha) * self._ewma_gap_ms
            )

    def window_s(self, max_batch: int) -> float:
        if (
            max_batch <= 1
            or self._ewma_gap_ms is None
            or self._ewma_gap_ms >= self.lone_cutoff_ms
        ):
            return 0.0
        return min(self.cap_ms, self._ewma_gap_ms * (max_batch - 1)) / 1000.0


class CoalescingDispatcher:
    """Queue + pipelined launch section + per-caller demux.

    ``max_batch=1, depth=1`` degenerates to the pre-coalescing
    serialized behavior (every request pays its own launch and its own
    blocking readback) — the bench uses that as the speedup baseline,
    and ``depth=1`` alone reproduces the ISSUE-5 coalescer (shared
    launches, serial readbacks).
    """

    def __init__(
        self,
        launch_batch: Callable[[List[PendingRequest]], Optional[Callable]],
        max_batch: int = DEFAULT_MAX_BATCH,
        gather_window_s: float = 0.0,
        window=None,
        depth: int = DEFAULT_DEPTH,
        clock=time.perf_counter,
        sleep=time.sleep,
    ):
        self._launch_batch = launch_batch
        self.max_batch = max(1, int(max_batch))
        self.depth = max(1, int(depth))
        # ``window`` (a *GatherWindow object) wins; the float keeps the
        # ISSUE-5 signature for static callers (tests, bench baselines)
        self.window = (
            window if window is not None
            else StaticGatherWindow(gather_window_s)
        )
        self._clock = clock
        self._sleep = sleep
        # the launch critical section: snapshot capture + async device
        # dispatch only — blocking readbacks run off it (lock-held-
        # dispatch rejects them inside @launch_section code statically)
        self._launch_lock = witness_lock(
            "bridge.coalesce.CoalescingDispatcher._launch_lock")
        # one condition guards the queue, the in-flight count, entry
        # ``done`` flips and the lifetime stats; EVERY transition
        # notifies it, so followers wait, never poll
        self._cond = witness_condition(
            "bridge.coalesce.CoalescingDispatcher._cond")
        self._queue: List[PendingRequest] = []
        self._inflight = 0
        # device-idle bookkeeping: wall time where work was queued but
        # nothing was in flight (the quantity the pipeline exists to
        # drive to ~0; the bench publishes it as ``device_idle_ms``)
        self._idle_since: Optional[float] = None
        self._launched_once = False
        self.device_idle_s = 0.0
        # lifetime stats (under _cond): the bench's coalesce_batch_mean
        # and the parity tests read these
        self.batches = 0
        self.requests = 0
        self.max_occupancy = 0
        # launches that entered the device section while a previous
        # batch was still in flight — the pipeline actually pipelining
        self.launch_overlaps = 0
        # deadline eviction (ISSUE 13): entries whose propagated budget
        # expired while queued, evicted at gather time (never launched)
        self.deadline_evicted = 0
        # servicer seams: ``deadline_hook(n)`` observes gather-time
        # evictions (the stage="gather" telemetry feed);
        # ``launch_outcome_hook(outcome, exc)`` observes every launch
        # attempt's fate — "ok" (launch AND readback completed: with
        # async dispatch a failing device program usually surfaces at
        # the readback's device_get, so success is only known there),
        # "error" (either half raised), "none" (no device work:
        # all-stale/all-expired batch or a memo serve) — the circuit
        # breaker's failure feed (replication/admission.py
        # CircuitBreaker; the servicer filters request-level
        # rejections before counting)
        self.deadline_hook: Optional[Callable[[int], None]] = None
        self.launch_outcome_hook: Optional[Callable] = None

    # -- public API --
    def submit(self, req, deadline_at: Optional[float] = None,
               budget_ms: float = 0.0, trace_span=None) -> PendingRequest:
        """Enqueue ``req`` and block until a batch containing it ran.
        Returns the finished entry; raises its error if the executor
        (or the batch as a whole) failed.  ``deadline_at`` (dispatcher
        clock) arms gather-time eviction: an entry still queued past it
        fails with :class:`DeadlineExpired` instead of occupying a
        launch slot.  ``trace_span`` rides the entry for the executor's
        fan-in linking (ISSUE 14); the dispatcher never ends it."""
        entry = PendingRequest(
            req, self._clock(), deadline_at=deadline_at,
            budget_ms=budget_ms, trace_span=trace_span,
        )
        with self._cond:
            self.window.observe_arrival(entry.enqueued_at)
            self._queue.append(entry)
            if self._inflight == 0 and self._idle_since is None:
                self._idle_since = entry.enqueued_at
            # an idle leader may be parked: work just arrived
            self._cond.notify_all()
        while True:
            if self._try_lead() is None:
                with self._cond:
                    if entry.done:
                        break
                    if not self._can_lead_locked():
                        # Not a poll: every launch-lock release, readback
                        # completion and enqueue notifies this condition
                        # after flipping the state it guards, so the
                        # wakeup cannot be missed.  The timeout is a
                        # deadlock backstop only (a lost notify is a bug
                        # this recovers from at 1 Hz, not a latency tax
                        # on the hot path).
                        self._cond.wait(timeout=1.0)
            if entry.done:
                break
        if entry.error is not None:
            raise entry.error
        return entry

    def run_pipelined(self, launch_fn: Callable[[], Callable]):
        """Run a non-coalescible device section through the pipeline:
        ``launch_fn`` executes under the launch lock (with pipeline
        headroom reserved) and returns a readback closure; the closure
        runs with the lock released — so a coalesced Score batch can
        launch while this section's transfer is still in flight — and
        its return value is ``run_pipelined``'s."""
        self._launch_lock.acquire()
        launched = False
        try:
            with self._cond:
                # decrements come from readback threads only, so this
                # wait cannot race another launcher (we hold the lock)
                while self._inflight >= self.depth:
                    self._cond.wait(timeout=1.0)
                launch_at = self._clock()
            try:
                readback = launch_fn()
            except Exception as exc:
                # same breaker seam as the coalesced path: the servicer
                # filters request-level rejections (stale snapshot,
                # expired deadline) before a failure counts
                self._launch_outcome("error", exc)
                raise
            with self._cond:
                # accounted only now: a launch_fn that raised (e.g. a
                # displaced Assign's generation re-check) put nothing
                # on the device, so the idle gap must stay open and no
                # overlap may be counted
                self._note_launch_locked(launch_at)
                self._inflight += 1
                launched = True
        finally:
            self._launch_lock.release()
            with self._cond:
                self._cond.notify_all()
        try:
            try:
                result = readback()
            except Exception as exc:
                # readback-phase device fault: the breaker's failure
                # surface (async dispatch reports failing programs at
                # device_get, not at enqueue)
                self._launch_outcome("error", exc)
                raise
            self._launch_outcome("ok", None)
            return result
        finally:
            if launched:
                with self._cond:
                    self._dec_inflight_locked()
                    self._cond.notify_all()

    def run_exclusive(self, fn, drain=True):
        """Run a device section that must not overlap in-flight batches.

        With ``drain`` (the default — required for anything that
        DONATES resident buffers, e.g. a warm Sync's delta scatter) the
        section waits for every launched batch's readback to complete
        before running ``fn``; the launch lock is held throughout, so
        nothing launches concurrently either.  ``drain=False`` skips
        the barrier for sections that only need launch-ordering (a
        cold commit that drops residency: in-flight batches hold their
        own snapshot references, and deletion without donation cannot
        invalidate them).

        ``drain`` may also be a zero-arg callable, evaluated AFTER the
        launch lock is acquired: a drain decision that depends on
        launch-mutable state (e.g. whether the resident snapshot is
        warm — a concurrent Score's launch section can lazily
        cold-rebuild it) must be made where that state can no longer
        move, not at the call site."""
        self._launch_lock.acquire()
        try:
            if callable(drain):
                drain = drain()
            if drain:
                with self._cond:
                    while self._inflight > 0:
                        self._cond.wait(timeout=1.0)
            return fn()
        finally:
            self._launch_lock.release()
            with self._cond:
                self._cond.notify_all()

    def queue_depth(self) -> int:
        """Requests queued plus batches in flight — the depth the
        admission gate (ISSUE 8, replication/admission.py) bounds from
        upstream.  Cheap enough for a per-scrape gauge."""
        with self._cond:
            return len(self._queue) + self._inflight

    def stats(self) -> dict:
        with self._cond:
            return {
                "batches": self.batches,
                "requests": self.requests,
                "max_occupancy": self.max_occupancy,
                "batch_mean": (
                    self.requests / self.batches if self.batches else 0.0
                ),
                "inflight": self._inflight,
                "depth": self.depth,
                "launch_overlaps": self.launch_overlaps,
                "device_idle_ms": round(self.device_idle_s * 1000.0, 3),
                "window_ms": round(
                    self.window.window_s(self.max_batch) * 1000.0, 3
                ),
            }

    # -- leader path --
    def _can_lead_locked(self) -> bool:
        return (
            bool(self._queue)
            and self._inflight < self.depth
            and not self._launch_lock.locked()
        )

    def _try_lead(self):
        """Attempt to lead one batch end to end: launch under the lock,
        read back off it.  Returns the batch led, or None if leading was
        not possible (lock held, pipeline full, or empty queue)."""
        with self._cond:
            if not self._queue or self._inflight >= self.depth:
                return None
        if not self._launch_lock.acquire(blocking=False):
            return None
        batch: List[PendingRequest] = []
        readback = None
        launched = False
        try:
            with self._cond:
                headroom = self._inflight < self.depth
            if headroom:
                batch, readback, launched = self._launch_locked()
        finally:
            self._launch_lock.release()
            with self._cond:
                self._cond.notify_all()
        if not batch:
            return None
        if readback is not None:
            no_device = getattr(readback, "no_device", False)
            hook = None
            try:
                try:
                    hook = readback()
                    if launched:
                        # the device program actually completed (the
                        # stacked device_get drained): NOW the breaker
                        # may count a success
                        self._launch_outcome("ok", None)
                except BaseException as exc:
                    if launched and isinstance(exc, Exception):
                        # a readback-phase device fault (async
                        # dispatch surfaces failing programs at
                        # device_get, not at enqueue) counts exactly
                        # like a launch-half failure
                        self._launch_outcome("error", exc)
                    # a whole-readback failure is every unfilled caller's
                    # failure; per-entry errors the executor routed stay.
                    # BaseException too: a KeyboardInterrupt delivered
                    # mid-device_get must not leak the in-flight slot
                    # (finally below) or strand followers un-notified —
                    # two leaks and the depth is gone, deadlocking every
                    # submit() and run_exclusive(drain=True) forever
                    for e in batch:
                        if e.reply is None and e.error is None:
                            e.error = exc
                    if not isinstance(exc, Exception):
                        raise
            finally:
                self._finalize(batch, launched=launched, no_device=no_device)
            self._run_hook(hook)
        return batch

    def _launch_locked(self):
        """Launch phase (launch lock held).  Drains a FIFO prefix, runs
        the executor's launch half, and accounts the in-flight slot.
        Returns ``(batch, readback, launched)``; entries are finalized
        here only when there is nothing to read back."""
        if self.window.window_s(self.max_batch) > 0.0:
            self._gather_stragglers()
        with self._cond:
            batch = self._queue[: self.max_batch]
            del self._queue[: len(batch)]
            if not batch:
                return [], None, False
            now = self._clock()
            expired = 0
            for entry in batch:
                entry.queue_delay_ms = (now - entry.enqueued_at) * 1000.0
                entry.batch_size = len(batch)
                # deadline eviction (ISSUE 13): an entry whose
                # propagated budget ran out while it queued is answered
                # DEADLINE_EXCEEDED here — BEFORE the executor sees it,
                # so an expired request never occupies a launch slot,
                # and a batch whose every entry expired never launches
                if (
                    entry.deadline_at is not None
                    and now >= entry.deadline_at
                ):
                    entry.error = DeadlineExpired(
                        "score", "gather", entry.budget_ms
                    )
                    expired += 1
                    self.deadline_evicted += 1
        if expired and self.deadline_hook is not None:
            self.deadline_hook(expired)
        live = [e for e in batch if e.error is None]
        if not live:
            # every entry expired: nothing launches, the callers get
            # their DEADLINE_EXCEEDED immediately
            self._launch_outcome("none", None)
            self._finalize(batch, launched=False)
            return batch, None, False
        readback = None
        failed = False
        try:
            readback = self._launch_batch(live)
        except Exception as exc:
            failed = True
            self._launch_outcome("error", exc)
            for entry in live:
                if entry.reply is None and entry.error is None:
                    entry.error = exc
        if readback is None:
            # no device work in flight: the executor finalized (or
            # rejected) every entry during the launch phase — nothing
            # launched, so the device-idle gap stays open and no
            # overlap is counted
            if not failed:
                self._launch_outcome("none", None)
            self._finalize(batch, launched=False)
            return batch, None, False
        if getattr(readback, "no_device", False):
            # off-lock HOST work (memo prefix assembly): the closure
            # runs with the lock released like a readback, but nothing
            # is on the device — no in-flight slot, no launch
            # accounting, and a donating drain never waits on it
            self._launch_outcome("none", None)
            return batch, readback, False
        with self._cond:
            self._note_launch_locked(now)
            self._inflight += 1
        # no outcome yet: with async dispatch the launch half only
        # proves enqueue — success/failure is known at the readback
        # (_try_lead reports it after the closure runs)
        return batch, readback, True

    def _launch_outcome(self, outcome: str, exc) -> None:
        """Feed the launch-outcome seam (the circuit breaker); the hook
        must never fail the batch it observed."""
        hook = self.launch_outcome_hook
        if hook is None:
            return
        try:
            hook(outcome, exc)
        except Exception:  # an observability/breaker hook failing must not fail callers whose launch already resolved
            import logging

            logging.getLogger(__name__).exception(
                "launch outcome hook failed"
            )

    def _gather_stragglers(self) -> None:
        """Idle-pipeline straggler wait (launch lock held).  Only worth
        paying when nothing is in flight: with a batch already on the
        device, launching immediately costs no idle time, and waiting
        would."""
        with self._cond:
            if self._inflight > 0:
                return
            deadline = self._clock() + self.window.window_s(self.max_batch)
        while True:
            with self._cond:
                if len(self._queue) >= self.max_batch or self._inflight > 0:
                    return
                left = deadline - self._clock()
            if left <= 0.0:
                return
            self._sleep(min(left, 0.0005))

    def _note_launch_locked(self, launch_at: float) -> None:
        """Account a successful launch that began at ``launch_at``
        (_cond held): close any open device-idle gap and count
        pipelined overlaps.  Called only after the executor's launch
        half returned — a launch that raised put nothing on the device,
        so the idle gap stays open and no overlap is counted."""
        if self._idle_since is not None:
            if self._launched_once:
                self.device_idle_s += max(0.0, launch_at - self._idle_since)
            self._idle_since = None
        if self._inflight > 0:
            self.launch_overlaps += 1
        self._launched_once = True

    def _dec_inflight_locked(self) -> None:
        self._inflight -= 1
        if self._inflight == 0:
            self._idle_since = self._clock() if self._queue else None

    def _finalize(
        self,
        batch: List[PendingRequest],
        launched: bool,
        no_device: bool = False,
    ) -> None:
        """Publish a batch's results: lifetime stats, ``done`` flips and
        the wakeup, all under the condition.  Runs off the launch lock —
        followers and the next leader proceed immediately."""
        with self._cond:
            # count only entries the executor ACCEPTED (reply set, no
            # error): rejected entries (stale snapshot) and failed
            # batches performed no useful launch, and the stats here
            # must agree with the koord_scorer_coalesce_* counters,
            # which are fed per accepted request
            n_ok = sum(1 for entry in batch if entry.error is None)
            if n_ok:
                self.batches += 1
                self.requests += n_ok
                self.max_occupancy = max(self.max_occupancy, n_ok)
            for entry in batch:
                entry.done = True
            if launched:
                self._dec_inflight_locked()
            elif no_device and self._inflight == 0:
                # a memo-SERVED batch put nothing on the device but did
                # answer its callers; once it drains the queue, a long
                # quiet stretch must not count as device idle at the
                # next real launch (same bookkeeping as
                # _dec_inflight_locked).  Scoped to no_device batches
                # only: an executor-REJECTED batch (every entry stale)
                # served nobody, so its callers' queued time keeps the
                # documented idle-gap-stays-open semantics.
                self._idle_since = self._clock() if self._queue else None
            self._cond.notify_all()

    @staticmethod
    def _run_hook(hook) -> None:
        if not callable(hook):
            return
        try:
            hook()
        except Exception:  # post-batch bookkeeping must not fail callers whose replies already succeeded
            import logging

            logging.getLogger(__name__).exception("post-batch hook failed")
