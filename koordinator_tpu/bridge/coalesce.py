"""Coalescing device dispatch for the BatchedScorer bridge (ISSUE 5).

The daemon used to serialize every RPC under one servicer lock: the Go
scheduler's 16 parallel Score workers arrive over thread-per-connection
transports and then queued single-file, each paying its own device
launch and its own blocking readback.  This module is the continuous-
batching shape from inference serving applied to that seam: concurrent
Score requests that arrive while the device is busy (or within a small
gather window) are stacked into ONE batched launch against the resident
snapshot, and the replies are demultiplexed per caller.

The dispatcher is deliberately generic — it owns the queueing, the
device critical section, and per-request result/error routing, while the
*meaning* of a batch (the padded ``top_k`` launch, the single stacked
readback, the telemetry) stays in ``bridge/server.py`` where the
snapshot lives.  That split keeps this file unit-testable with a fake
executor (tests/test_coalesce.py) and keeps the servicer free to change
its device programs without touching the concurrency machinery.

Concurrency contract (the lock order is device -> state, never state ->
device while holding state):

* ``submit()`` enqueues and then either *leads* (first thread to take
  the device lock drains up to ``max_batch`` entries and executes them)
  or *follows* (waits for a leader to publish its result).  FIFO: a
  batch is always a prefix of the queue.
* ``run_exclusive(fn)`` runs a non-coalescible device section (Assign's
  cycle launch+readback, Sync's donating delta scatter) under the same
  device lock, so a donation can never invalidate a buffer a coalesced
  Score batch captured but has not yet read back.
* Queue delay and batch occupancy per entry are stamped by the leader;
  the executor forwards them to the ``koord_scorer_coalesce_*`` metric
  families (obs/scorer_metrics.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

# One launch serves at most this many stacked Score requests; the Go
# scheduler dispatches 16 parallel Score workers, so a full worker burst
# coalesces into a single device program.
DEFAULT_MAX_BATCH = 16


class SnapshotNotResident(ValueError):
    """A coalesced request named a snapshot that is no longer resident
    (the same condition ``ScorerServicer._check_generation`` rejects on
    the serial paths; callers translate to FAILED_PRECONDITION)."""


class PendingRequest:
    """One caller's slot in a coalesced batch.  The executor fills
    ``reply`` (or ``error``); the dispatcher stamps queue/batch stats
    and flips ``done`` under the queue condition."""

    __slots__ = (
        "req", "enqueued_at", "reply", "error", "done",
        "queue_delay_ms", "batch_size",
    )

    def __init__(self, req, enqueued_at: float):
        self.req = req
        self.enqueued_at = enqueued_at
        self.reply = None
        self.error: Optional[BaseException] = None
        self.done = False
        self.queue_delay_ms = 0.0
        self.batch_size = 0


class CoalescingDispatcher:
    """Queue + device critical section + per-caller demux.

    ``execute_batch(entries)`` runs with the device lock held and must
    set ``entry.reply`` or ``entry.error`` for every entry it accepts;
    an exception it raises becomes the error of every entry still
    unfilled.  It may return a callable: a post-batch hook the leader
    runs AFTER the device lock is released and followers are notified —
    host-side bookkeeping (telemetry) must not extend the device
    critical section every queued launch waits on; a hook failure is
    logged, never surfaced to callers whose replies already succeeded.
    ``max_batch=1`` degenerates to the pre-coalescing serialized
    behavior (every request pays its own launch) — the bench uses that
    as the speedup baseline.
    """

    def __init__(
        self,
        execute_batch: Callable[[List[PendingRequest]], None],
        max_batch: int = DEFAULT_MAX_BATCH,
        gather_window_s: float = 0.0,
        clock=time.perf_counter,
        sleep=time.sleep,
    ):
        self._execute_batch = execute_batch
        self.max_batch = max(1, int(max_batch))
        # > 0: a leader that finds the device idle waits this long for
        # stragglers before launching (trades a little lone-request
        # latency for occupancy under bursty clients).  The default 0
        # keeps serial latency untouched — "arrived while the device is
        # busy" is what forms batches under real concurrency.
        self.gather_window_s = max(0.0, float(gather_window_s))
        self._clock = clock
        self._sleep = sleep
        self._device = threading.Lock()
        self._cond = threading.Condition()
        self._queue: List[PendingRequest] = []
        # lifetime stats (under _cond): the bench's coalesce_batch_mean
        # and the parity tests read these
        self.batches = 0
        self.requests = 0
        self.max_occupancy = 0

    # -- public API --
    def submit(self, req) -> PendingRequest:
        """Enqueue ``req`` and block until a batch containing it ran.
        Returns the finished entry; raises its error if the executor
        (or the batch as a whole) failed."""
        entry = PendingRequest(req, self._clock())
        with self._cond:
            self._queue.append(entry)
        while True:
            if self._device.acquire(blocking=False):
                hook = None
                try:
                    if not entry.done:
                        hook = self._lead()
                finally:
                    self._device.release()
                with self._cond:
                    if self._queue:
                        self._cond.notify_all()
                if hook is not None:
                    try:
                        hook()
                    except Exception:  # koordlint: disable=broad-except(post-batch bookkeeping must not fail callers whose replies already succeeded)
                        import logging

                        logging.getLogger(__name__).exception(
                            "post-batch hook failed"
                        )
                if entry.done:
                    break
                continue  # batch cap left us queued: lead the next one
            with self._cond:
                # ``done`` flips under this condition, so the check and
                # the wait cannot race a leader's notify.  Device holders
                # notify under this condition only AFTER releasing, so
                # checking the device here closes the other wakeup race:
                # a release landing between our failed acquire above and
                # this block shows as an unlocked device — retry leading
                # immediately instead of sleeping a poll interval while
                # the device sits idle.
                if entry.done:
                    break
                if self._device.locked():
                    self._cond.wait(timeout=0.05)
            if entry.done:
                break
        if entry.error is not None:
            raise entry.error
        return entry

    def run_exclusive(self, fn):
        """Run a non-coalescible device section (Assign cycle, Sync's
        donating scatter) under the device-dispatch lock, then wake any
        Score waiters that queued behind it."""
        self._device.acquire()
        try:
            return fn()
        finally:
            self._device.release()
            with self._cond:
                if self._queue:
                    self._cond.notify_all()

    def stats(self) -> dict:
        with self._cond:
            return {
                "batches": self.batches,
                "requests": self.requests,
                "max_occupancy": self.max_occupancy,
                "batch_mean": (
                    self.requests / self.batches if self.batches else 0.0
                ),
            }

    # -- leader path (device lock held); returns the executor's
    #    post-batch hook (run by submit() after the lock drops) --
    def _lead(self):
        if self.gather_window_s > 0.0:
            deadline = self._clock() + self.gather_window_s
            while True:
                with self._cond:
                    n = len(self._queue)
                if n >= self.max_batch:
                    break
                left = deadline - self._clock()
                if left <= 0.0:
                    break
                self._sleep(min(left, 0.0005))
        with self._cond:
            batch = self._queue[: self.max_batch]
            del self._queue[: self.max_batch]
        if not batch:
            return None
        now = self._clock()
        for entry in batch:
            entry.queue_delay_ms = (now - entry.enqueued_at) * 1000.0
            entry.batch_size = len(batch)
        hook = None
        try:
            hook = self._execute_batch(batch)
        except Exception as exc:
            # a whole-batch failure is every unfilled caller's failure;
            # per-entry errors the executor already routed stay theirs
            for entry in batch:
                if entry.reply is None and entry.error is None:
                    entry.error = exc
        with self._cond:
            # count only entries the executor ACCEPTED (reply set, no
            # error): rejected entries (stale snapshot) and failed
            # batches performed no device launch, and the stats here
            # must agree with the koord_scorer_coalesce_* counters,
            # which are fed per accepted request
            n_ok = sum(1 for entry in batch if entry.error is None)
            if n_ok:
                self.batches += 1
                self.requests += n_ok
                self.max_occupancy = max(self.max_occupancy, n_ok)
            for entry in batch:
                entry.done = True
            self._cond.notify_all()
        return hook if callable(hook) else None
