"""Raw framed protobuf-over-UDS transport for native (C++) clients.

The image has C++ protobuf but no grpc++ toolchain, so the native side of
the bridge seam (SURVEY §7.5's host-scheduler shim; the reference proves
the same boundary style with its UDS CRI proxy,
reference ``pkg/runtimeproxy/server/cri/criserver.go:93``) speaks a
minimal length-prefixed framing instead of gRPC.  The RPC *bodies* are
the very same ``ScorerServicer`` methods the gRPC server serves
(bridge/server.py) — one servicer, two transports, identical placements.

Frame (both directions, all integers big-endian):

    request:  u8 method (1=Sync, 2=Score, 3=Assign), u32 length, payload
    reply:    u8 status (0=ok, 1=error), u32 length, payload
              (serialized reply message, or UTF-8 error string)

One connection may carry any number of sequential request/reply pairs.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
from typing import Optional, Tuple

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG

logger = logging.getLogger(__name__)

METHOD_SYNC = 1
METHOD_SCORE = 2
METHOD_ASSIGN = 3
# admin plane (ISSUE 11): method 4 = Promote — no protobuf body either
# way; the reply payload is the promoted daemon's new snapshot id
# (UTF-8), or an error frame when this daemon has no promote handler
# (a leader, or a follower daemon started without the seam wired).
# Registered through RawUdsServer(admin_handlers=...), never the
# servicer method table, so the scorer wire contract is untouched.
METHOD_PROMOTE = 4
# admin plane (ISSUE 19): method 5 = Profile — request payload is an
# optional ASCII window in milliseconds, reply payload is the capture
# directory path (UTF-8) where jax.profiler wrote the on-demand trace.
# Same seam as Promote: RawUdsServer(admin_handlers=...), never the
# protobuf wire contract.
METHOD_PROFILE = 5
_METHOD_NAMES = {METHOD_SYNC: "sync", METHOD_SCORE: "score",
                 METHOD_ASSIGN: "assign", METHOD_PROMOTE: "promote",
                 METHOD_PROFILE: "profile"}

# Sized to the largest realistic SyncRequest (10k pods x 2k nodes of i64
# request/capacity vectors serializes to a few MB); anything larger is a
# malformed or hostile frame, not a workload.
_MAX_FRAME = 64 << 20
# One thread per connection; bound concurrent connections so a local
# misbehaving client cannot spawn unbounded threads/buffers.  Sized
# above the bench's 64-client storm (ISSUE 6): the pipelined dispatcher
# is the funnel that turns a burst into a few launches, so the
# transport must admit the burst first.
_MAX_CONNS = 96


def _recv_or_eof(conn: socket.socket, n: int) -> Tuple[Optional[bytes], int]:
    """Read exactly ``n`` bytes; on EOF returns (None, bytes_read) so
    the caller can tell a clean between-frames close (0) from a
    truncated frame (> 0) — the latter is a protocol violation worth a
    counter and a log line, not a silent drop."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except OSError:
            chunk = b""  # reset mid-read counts as the EOF it is
        if not chunk:
            return None, len(buf)
        buf.extend(chunk)
    return bytes(buf), n


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    out, _ = _recv_or_eof(conn, n)
    return out


class RawUdsServer:
    """Serve a ScorerServicer over the raw framing on a unix socket."""

    def __init__(
        self,
        path: str,
        servicer: Optional[ScorerServicer] = None,
        cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
        mesh=None,
        admin_handlers=None,
    ):
        """``admin_handlers``: optional ``{method_byte: fn}`` map of
        admin-plane methods (``fn(payload: bytes) -> bytes``; raise to
        answer an error frame).  The daemon wires METHOD_PROMOTE here
        (scheduler/server.py) — admin methods never touch the protobuf
        wire contract."""
        self.path = path
        self.servicer = servicer or ScorerServicer(cfg, mesh=mesh)
        self.admin_handlers = dict(admin_handlers or {})
        if os.path.exists(path):
            os.unlink(path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        # backlog matches _MAX_CONNS: with the coalescing dispatcher the
        # intended client is a 16-way worker burst dialing at once, and
        # a listen(8) backlog was refusing dials the connection cap
        # would have accepted
        self._sock.listen(_MAX_CONNS)
        self._stop = threading.Event()
        self._conn_slots = threading.BoundedSemaphore(_MAX_CONNS)
        # live connections, closed on stop(): a stopped server must not
        # keep draining requests on established sockets — a client would
        # get one more successful RPC against dying resident state and
        # only see the restart on the call after (the warm-path recovery
        # protocol depends on the failure surfacing at the Sync)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._methods = {
            METHOD_SYNC: (pb2.SyncRequest, self.servicer.sync),
            METHOD_SCORE: (pb2.ScoreRequest, self.servicer.score),
            METHOD_ASSIGN: (pb2.AssignRequest, self.servicer.assign),
        }

    def start(self) -> "RawUdsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        finally:
            with self._conns_lock:
                conns = list(self._conns)
            for conn in conns:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            if os.path.exists(self.path):
                os.unlink(self.path)

    # -- internals --
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            if not self._conn_slots.acquire(timeout=1.0):
                conn.close()  # saturated: shed instead of queueing unbounded
                continue
            with self._conns_lock:
                self._conns.add(conn)
            # close the race with stop(): a connection accepted just
            # before the listener closed but registered after stop()
            # snapshotted _conns would otherwise keep serving the dying
            # resident state
            if self._stop.is_set():
                with self._conns_lock:
                    self._conns.discard(conn)
                conn.close()
                self._conn_slots.release()
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            self._serve_conn_inner(conn)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            self._conn_slots.release()

    def _metrics(self):
        """The servicer's scorer metric families (None-tolerant: a bare
        test servicer without telemetry still serves)."""
        telemetry = getattr(self.servicer, "telemetry", None)
        return getattr(telemetry, "metrics", None)

    def _count_malformed(self, reason: str, detail: str) -> None:
        """A malformed frame is COUNTED and LOGGED, never silently
        dropped: a misbehaving client (or codec drift) used to look like
        an ordinary disconnect, invisible until placements went wrong.
        Frames cut short by our OWN stop() closing live connections are
        not client violations — the shutdown path must not pollute the
        counter operators alert on."""
        if self._stop.is_set():
            return
        metrics = self._metrics()
        if metrics is not None:
            metrics.count_uds_malformed(reason)
        logger.warning("malformed UDS frame (%s): %s", reason, detail)

    def _serve_conn_inner(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                header, nread = _recv_or_eof(conn, 5)
                if header is None:
                    if nread:
                        self._count_malformed(
                            "truncated-header",
                            f"connection closed {nread} bytes into a "
                            "5-byte frame header",
                        )
                    return
                method, length = struct.unpack(">BI", header)
                if length > _MAX_FRAME:
                    self._count_malformed(
                        "oversized",
                        f"method {method} frame of {length} bytes exceeds "
                        f"the {_MAX_FRAME}-byte cap",
                    )
                    self._reply(conn, 1, b"frame too large")
                    return
                payload, nread = _recv_or_eof(conn, length)
                if payload is None:
                    self._count_malformed(
                        "truncated-payload",
                        f"connection closed {nread}/{length} bytes into "
                        f"a method-{method} payload",
                    )
                    return
                admin = self.admin_handlers.get(method)
                if admin is not None:
                    metrics = self._metrics()
                    if metrics is not None and method in _METHOD_NAMES:
                        metrics.count_uds_frame(_METHOD_NAMES[method])
                    try:
                        self._reply(conn, 0, admin(payload))
                    except Exception as exc:  # surfaced to the caller, not lost
                        if metrics is not None:
                            metrics.count_uds_error()
                        self._reply(conn, 1, str(exc).encode())
                    continue
                entry = self._methods.get(method)
                if entry is None:
                    self._count_malformed(
                        "unknown-method", f"method byte {method}"
                    )
                    self._reply(conn, 1, f"unknown method {method}".encode())
                    continue
                metrics = self._metrics()
                if metrics is not None:
                    metrics.count_uds_frame(_METHOD_NAMES[method])
                req_cls, fn = entry
                try:
                    req = req_cls.FromString(payload)
                    if method == METHOD_SYNC:
                        # hand the servicer the client's ORIGINAL frame
                        # bytes: the replication publisher streams them
                        # verbatim instead of re-encoding the decoded
                        # message on the one writer path (ISSUE 8)
                        reply = fn(req, None, wire_bytes=payload)
                    else:
                        reply = fn(req, None)
                    size = reply.ByteSize()
                    if size > _MAX_FRAME:
                        # every client enforces the same cap on replies; a
                        # full-matrix flat Score (top_k=0) at 10k x 2k is
                        # ~280 MB — fail with a real error instead of
                        # shipping a frame the peer must reject (and skip
                        # materializing the wire bytes entirely).
                        hint = (
                            "; request a smaller top_k"
                            if method == METHOD_SCORE
                            else ""
                        )
                        if metrics is not None:
                            metrics.count_uds_error()
                        self._reply(
                            conn,
                            1,
                            (
                                f"reply frame {size} bytes exceeds the "
                                f"{_MAX_FRAME}-byte transport cap{hint}"
                            ).encode(),
                        )
                        continue
                    self._reply(conn, 0, reply.SerializeToString())
                except Exception as exc:  # surfaced to the client, not lost
                    if metrics is not None:
                        metrics.count_uds_error()
                    self._reply(conn, 1, str(exc).encode())

    @staticmethod
    def _reply(conn: socket.socket, status: int, payload: bytes) -> None:
        """Write header+payload with one gathered ``sendmsg`` instead of
        concatenating (which copies the payload — a full-matrix flat
        Score reply is tens of MB) or two ``sendall`` calls (two
        syscalls per reply on the hot path).  Partial sends are resumed
        across the buffer list; stream UDS sockets rarely split small
        frames, so the common case is exactly one syscall."""
        bufs = [memoryview(struct.pack(">BI", status, len(payload))),
                memoryview(payload)]
        try:
            while bufs:
                sent = conn.sendmsg(bufs)
                while bufs and sent >= len(bufs[0]):
                    sent -= len(bufs[0])
                    bufs.pop(0)
                if bufs and sent:
                    bufs[0] = bufs[0][sent:]
        except OSError:
            pass


def serve_raw_uds(
    path: str, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG, mesh=None
) -> RawUdsServer:
    """Pass a ``mesh`` to serve the round-based sharded Assign
    (path="shard"), same as the gRPC serve_uds."""
    return RawUdsServer(path, cfg=cfg, mesh=mesh).start()
