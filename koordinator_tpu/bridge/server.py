"""BatchedScorer gRPC service (generic handlers; no grpcio-tools needed).

The service composition mirrors the reference's hook-server dispatch
(reference ``pkg/koordlet/runtimehooks/proxyserver``): one process owns
the device, callers talk UDS.  Score/Assign run the same device programs
as the in-process API (solver.run_cycle / solver.score_cycle), so bridge
clients get identical placements to embedded users.
"""

from __future__ import annotations

import threading
import time
import uuid
from concurrent import futures
from typing import Optional

import numpy as np

import grpc
import jax.numpy as jnp
from jax import lax

from koordinator_tpu.bridge.codegen import SERVICE, pb2
from koordinator_tpu.bridge.state import ResidentState
from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG
from koordinator_tpu.obs import CycleTelemetry
from koordinator_tpu.solver import run_cycle, score_cycle


class ScorerServicer:
    def __init__(
        self,
        cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
        mesh=None,
        state_dir=None,
        telemetry: Optional[CycleTelemetry] = None,
    ):
        """``mesh``: a ``jax.sharding.Mesh`` turns the ASSIGN RPC into
        the round-based multi-chip cycle (parallel/shard_assign.py
        greedy_assign_waves, bit-identical with the single-chip path);
        clients see ``path="shard"``.  Scope: Assign only — Sync and
        Score still materialize the snapshot on the default device, so
        the resident tensors must fit one device's memory; the mesh buys
        cycle wall-clock, not snapshot capacity.  A shard-path failure
        falls back to the single-chip cycle for that RPC (placements are
        bit-identical either way).

        ``state_dir``: where flight-recorder dumps land (obs/flight.py;
        the daemon passes its --state-dir).  ``telemetry`` injects a
        pre-built CycleTelemetry (tests); by default one is created with
        this servicer's epoch so cycle ids ("c<epoch>-<seq>") correlate
        with snapshot ids ("s<epoch>-<gen>")."""
        self.cfg = cfg
        self.mesh = mesh
        self.state = ResidentState()
        self._generation = 0
        # per-boot epoch in every snapshot id ("s<epoch>-<gen>"): a client
        # checking bare generation continuity (gen == mirror.gen+1) can
        # coincidentally pass after a sidecar restart reset the counter,
        # and would then delta-sync onto a foreign baseline; the epoch
        # makes the restart unmistakable (ADVICE r5)
        self._epoch = uuid.uuid4().hex[:8]
        self.telemetry = telemetry or CycleTelemetry(
            epoch=self._epoch, cfg=cfg, state_dir=state_dir
        )
        # one lock over state-mutating Sync and state-reading Score/Assign:
        # the server runs on a thread pool, and a Sync racing a Score would
        # otherwise let one cycle mix tensors from two generations
        # (telemetry rides under the same lock: cycle records never
        # interleave two RPCs' spans)
        self._lock = threading.Lock()

    def snapshot_id(self) -> str:
        return f"s{self._epoch}-{self._generation}"

    def _check_generation(self, req, ctx) -> None:
        want = getattr(req, "snapshot_id", "")
        # the FULL id must match, epoch included: accepting a bare
        # legacy "s<gen>" here would re-open for Score/Assign the very
        # restart-coincidence the epoch closes (clients echo the Sync
        # reply's id verbatim, so nothing legitimate constructs one)
        if want and want != self.snapshot_id():
            msg = (
                f"snapshot {want!r} is not resident "
                f"(current {self.snapshot_id()})"
            )
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, msg)
            raise ValueError(msg)

    # -- RPC bodies (request -> reply functions) --
    def sync(self, req: "pb2.SyncRequest", ctx=None) -> "pb2.SyncReply":
        with self._lock:
            self.telemetry.flush_backlog()
            try:
                info = self.state.apply_sync(req, spans=self.telemetry.spans)
            except Exception as exc:
                # ValueError = a frame validation REJECTED (bad delta
                # shape/index, missing first-sync tensors): the
                # CLIENT's bug, at the client's rate — error counter
                # only.  No flight record, no dump, and crucially no
                # commit of the pending cycle: another client's sync
                # spans may be on it awaiting THEIR Assign, and a
                # looping bad client must be able to churn neither the
                # 64-slot ring nor the dump directory.  Anything else
                # is an unexpected server-side failure: full
                # abort (ring record + disk dump).
                if isinstance(exc, ValueError):
                    self.telemetry.metrics.count_cycle_error("sync")
                else:
                    self.telemetry.abort_cycle("sync", exc)
                raise
            self._generation += 1
            self.telemetry.record_sync(
                info,
                snapshot_id=self.snapshot_id(),
                epoch=self._epoch,
                generation=self._generation,
            )
            # counts come from the host mirrors.  A warm frame lands its
            # deltas straight on the resident device tensors inside
            # apply_sync (state.last_sync_path == "warm"); only a cold
            # frame defers the full padded build to the next Score/Assign
            return pb2.SyncReply(
                snapshot_id=self.snapshot_id(),
                nodes=self.state.node_alloc.shape[0],
                pods=self.state.pod_requests.shape[0],
            )

    def score(self, req: "pb2.ScoreRequest", ctx=None) -> "pb2.ScoreReply":
        with self._lock:
            self._check_generation(req, ctx)
            spans = self.telemetry.spans
            # a pending cycle holds the Sync stages (sync_decode,
            # delta_scatter) waiting for the Assign that correlates
            # them under the client's cycle_id.  In the standard
            # Sync→Score→Assign flow Score must NOT commit it — the
            # assign flight record would lose exactly the sync spans
            # the correlation promises.  Score's spans ride along
            # (score_* names, no collision) and only a Score with no
            # pending cycle commits its own record.
            self.telemetry.flush_backlog()
            pending = spans.has_pending()
            spans.current(snapshot_id=self.snapshot_id())
            t_cycle = time.perf_counter()
            try:
                reply = self._score_body(req, spans)
            except Exception as exc:
                self.telemetry.abort_cycle("score", exc)
                raise
            latency_ms = (time.perf_counter() - t_cycle) * 1000.0
            if pending:
                self.telemetry.metrics.observe_cycle(
                    latency_ms, path="score", wave=self.cfg.wave
                )
            else:
                self.telemetry.commit_cycle(
                    latency_ms, path="score", wave=self.cfg.wave
                )
            return reply

    def _score_body(self, req: "pb2.ScoreRequest", spans) -> "pb2.ScoreReply":
        snap = self.state.snapshot()
        with spans.span("score_dispatch"):
            scores, feasible = score_cycle(snap, self.cfg)
            masked = jnp.where(
                feasible, scores, jnp.iinfo(jnp.int64).min
            )
            P = snap.pods.capacity
            k = int(req.top_k) or snap.nodes.capacity
            k = min(k, snap.nodes.capacity)
            top_scores, top_idx = lax.top_k(masked, k)
        reply = pb2.ScoreReply()
        with spans.span("score_readback"):
            # one device->host transfer, then numpy-only assembly
            top_scores = np.asarray(top_scores)
            top_idx = np.asarray(top_idx).astype(np.int32)
            ok = np.take_along_axis(
                np.asarray(feasible), top_idx, axis=1
            )
            valid = np.asarray(snap.pods.valid)[:P].astype(bool)
        t0 = time.perf_counter()
        if req.flat:
            # flat layout (round-3 review #8): O(1) Python calls —
            # boolean indexing + tobytes, no per-pod message building
            ok_v = ok[:P][valid]
            reply.flat.pod_index = (
                np.flatnonzero(valid).astype("<i4").tobytes()
            )
            reply.flat.counts = ok_v.sum(axis=1).astype("<i4").tobytes()
            reply.flat.node_index = (
                top_idx[:P][valid][ok_v].astype("<i4").tobytes()
            )
            reply.flat.score = (
                top_scores[:P][valid][ok_v].astype("<i8").tobytes()
            )
        else:
            # legacy per-pod lists: per-valid-pod Python loop
            for p in np.flatnonzero(valid):
                entry = reply.pods.add()
                m = ok[p]
                entry.node_index.extend(top_idx[p, m].tolist())
                entry.score.extend(top_scores[p, m].tolist())
        reply.build_ms = (time.perf_counter() - t0) * 1000.0
        return reply

    def assign(self, req: "pb2.AssignRequest", ctx=None) -> "pb2.AssignReply":
        with self._lock:
            self._check_generation(req, ctx)
            spans = self.telemetry.spans
            # adopt the client's correlation id when it sent one; the id
            # (ours or theirs) is echoed in the reply either way
            cycle = spans.current(
                snapshot_id=self.snapshot_id(),
                cycle_id=req.cycle_id or None,
            )
            t0 = time.perf_counter()
            try:
                result, rounds, eff_wave = self._assign_cycle(spans)
                with spans.span("readback"):
                    assignment = np.asarray(result.assignment)
                    status = np.asarray(result.status)
                    # same cached snapshot _assign_cycle ran against
                    # (no Sync can interleave: we hold the lock)
                    valid = np.asarray(
                        self.state.snapshot().pods.valid
                    ).astype(bool)
                ms = (time.perf_counter() - t0) * 1000.0
                reply = pb2.AssignReply(
                    cycle_ms=ms,
                    path=result.path or "",
                    cycle_id=cycle.cycle_id,
                )
                reply.assignment.extend(assignment[valid].tolist())
                reply.status.extend(status[valid].tolist())
            except Exception as exc:
                # count + flight-dump the bad cycle before surfacing it
                self.telemetry.abort_cycle("assign", exc)
                raise
            self.telemetry.commit_cycle(
                ms,
                path=result.path or "unknown",
                wave=eff_wave,
                rounds=rounds,
            )
            return reply

    def _assign_cycle(self, spans):
        """Run the device cycle (shard-first when a mesh is configured)
        and return ``(materialized CycleResult, rounds or None,
        effective wave width)`` — the shard path widens cfg.wave<=1 to
        its own default, and the telemetry labels must say what actually
        ran.  Caller holds the lock and owns error accounting."""
        snap = self.state.snapshot()
        result = None
        rounds = None
        eff_wave = self.cfg.wave
        if self.mesh is not None:
            from koordinator_tpu.parallel import greedy_assign_waves
            from koordinator_tpu.solver import (
                _demoted,
                _record_failure,
                _record_success,
            )

            # the CycleConfig wave knobs thread through to the
            # round-based sharded cycle; wave=1 (the per-pod default)
            # keeps the multichip path's own proven width
            wave = self.cfg.wave if self.cfg.wave > 1 else 32
            top_m = self.cfg.top_m
            bucket = (
                "shard",
                int(snap.nodes.allocatable.shape[0]),
                int(snap.pods.capacity),
                self.mesh.size,
                wave,
                top_m,
            )
            if not _demoted(bucket):
                try:
                    # distinct name from the fallback's "dispatch": a
                    # failed shard attempt followed by the single-chip
                    # cycle must not leave two same-named spans a
                    # post-mortem reader would double-count
                    with spans.span("dispatch_shard"):
                        result, nwaves = greedy_assign_waves(
                            snap, self.mesh, self.cfg,
                            wave=wave, top_m=top_m, spans=spans,
                        )
                        # materialize INSIDE the guard: with async
                        # dispatch a late device fault would otherwise
                        # surface at the reply assembly, outside this
                        # fallback (the same hazard run_cycle documents)
                        import dataclasses

                        result = dataclasses.replace(
                            result,
                            assignment=np.asarray(result.assignment),
                            status=np.asarray(result.status),
                        )
                    # device-derived stat, materialized AFTER the device
                    # program completed — one scalar transfer, no retrace
                    rounds = int(np.asarray(nwaves))
                    eff_wave = wave
                    _record_success(bucket)
                except Exception as exc:
                    # the run_cycle demotion philosophy, shared
                    # machinery: back off this shape bucket instead
                    # of re-paying a failed shard compile on every
                    # RPC; the single-chip cycle is bit-identical
                    # and path in the reply shows the degradation
                    _record_failure(bucket)
                    result = None
                    # the cycle record must say the shard attempt
                    # failed, not just show a closed dispatch_shard
                    # span next to the fallback's dispatch
                    spans.note("shard_error", f"{exc!r:.200}")
                    import logging

                    logging.getLogger(__name__).exception(
                        "sharded assign failed; serving single-chip "
                        "and backing off bucket %r",
                        bucket,
                    )
        if result is None:
            eff_wave = self.cfg.wave
            with spans.span("dispatch"):
                result = run_cycle(
                    snap, self.cfg, i32_ok=self.state.i32_fits()
                )
            if result.rounds is not None:
                rounds = int(np.asarray(result.rounds))
        return result, rounds, eff_wave


def _handler(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        lambda req, ctx: fn(req, ctx),
        request_deserializer=req_cls.FromString,
        response_serializer=lambda msg: msg.SerializeToString(),
    )


def make_server(
    servicer: Optional[ScorerServicer] = None,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    max_workers: int = 4,
    mesh=None,
) -> grpc.Server:
    servicer = servicer or ScorerServicer(cfg, mesh=mesh)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = {
        "Sync": _handler(servicer.sync, pb2.SyncRequest),
        "Score": _handler(servicer.score, pb2.ScoreRequest),
        "Assign": _handler(servicer.assign, pb2.AssignRequest),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    server._koord_servicer = servicer  # test/introspection seam
    return server


def serve_uds(
    path: str, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG, mesh=None
) -> grpc.Server:
    """Bind the scorer on a unix-domain socket (the reference's CRI proxy
    transport, criserver.go:93) and start it.  Pass a multi-device
    ``mesh`` to serve the round-based sharded cycle (path="shard")."""
    server = make_server(cfg=cfg, mesh=mesh)
    server.add_insecure_port(f"unix://{path}")
    server.start()
    return server
