"""BatchedScorer gRPC service (generic handlers; no grpcio-tools needed).

The service composition mirrors the reference's hook-server dispatch
(reference ``pkg/koordlet/runtimehooks/proxyserver``): one process owns
the device, callers talk UDS.  Score/Assign run the same device programs
as the in-process API (solver.run_cycle / solver.score_cycle), so bridge
clients get identical placements to embedded users.
"""

from __future__ import annotations

import threading
import time
import uuid
from concurrent import futures
from typing import Optional

import numpy as np

import grpc
import jax.numpy as jnp
from jax import lax

from koordinator_tpu.bridge.codegen import SERVICE, pb2
from koordinator_tpu.bridge.state import ResidentState
from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG
from koordinator_tpu.solver import run_cycle, score_cycle


class ScorerServicer:
    def __init__(self, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG, mesh=None):
        """``mesh``: a ``jax.sharding.Mesh`` turns the ASSIGN RPC into
        the round-based multi-chip cycle (parallel/shard_assign.py
        greedy_assign_waves, bit-identical with the single-chip path);
        clients see ``path="shard"``.  Scope: Assign only — Sync and
        Score still materialize the snapshot on the default device, so
        the resident tensors must fit one device's memory; the mesh buys
        cycle wall-clock, not snapshot capacity.  A shard-path failure
        falls back to the single-chip cycle for that RPC (placements are
        bit-identical either way)."""
        self.cfg = cfg
        self.mesh = mesh
        self.state = ResidentState()
        self._generation = 0
        # per-boot epoch in every snapshot id ("s<epoch>-<gen>"): a client
        # checking bare generation continuity (gen == mirror.gen+1) can
        # coincidentally pass after a sidecar restart reset the counter,
        # and would then delta-sync onto a foreign baseline; the epoch
        # makes the restart unmistakable (ADVICE r5)
        self._epoch = uuid.uuid4().hex[:8]
        # one lock over state-mutating Sync and state-reading Score/Assign:
        # the server runs on a thread pool, and a Sync racing a Score would
        # otherwise let one cycle mix tensors from two generations
        self._lock = threading.Lock()

    def snapshot_id(self) -> str:
        return f"s{self._epoch}-{self._generation}"

    def _check_generation(self, req, ctx) -> None:
        want = getattr(req, "snapshot_id", "")
        # the FULL id must match, epoch included: accepting a bare
        # legacy "s<gen>" here would re-open for Score/Assign the very
        # restart-coincidence the epoch closes (clients echo the Sync
        # reply's id verbatim, so nothing legitimate constructs one)
        if want and want != self.snapshot_id():
            msg = (
                f"snapshot {want!r} is not resident "
                f"(current {self.snapshot_id()})"
            )
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, msg)
            raise ValueError(msg)

    # -- RPC bodies (request -> reply functions) --
    def sync(self, req: "pb2.SyncRequest", ctx=None) -> "pb2.SyncReply":
        with self._lock:
            self.state.apply_sync(req)
            self._generation += 1
            # counts come from the host mirrors.  A warm frame lands its
            # deltas straight on the resident device tensors inside
            # apply_sync (state.last_sync_path == "warm"); only a cold
            # frame defers the full padded build to the next Score/Assign
            return pb2.SyncReply(
                snapshot_id=self.snapshot_id(),
                nodes=self.state.node_alloc.shape[0],
                pods=self.state.pod_requests.shape[0],
            )

    def score(self, req: "pb2.ScoreRequest", ctx=None) -> "pb2.ScoreReply":
        with self._lock:
            self._check_generation(req, ctx)
            snap = self.state.snapshot()
            scores, feasible = score_cycle(snap, self.cfg)
            masked = jnp.where(feasible, scores, jnp.iinfo(jnp.int64).min)
            P = snap.pods.capacity
            reply = pb2.ScoreReply()
            k = int(req.top_k) or snap.nodes.capacity
            k = min(k, snap.nodes.capacity)
            top_scores, top_idx = lax.top_k(masked, k)
            # one device->host transfer, then numpy-only reply assembly
            top_scores = np.asarray(top_scores)
            top_idx = np.asarray(top_idx).astype(np.int32)
            ok = np.take_along_axis(np.asarray(feasible), top_idx, axis=1)
            valid = np.asarray(snap.pods.valid)[:P].astype(bool)
            t0 = time.perf_counter()
            if req.flat:
                # flat layout (round-3 review #8): O(1) Python calls —
                # boolean indexing + tobytes, no per-pod message building
                ok_v = ok[:P][valid]
                reply.flat.pod_index = (
                    np.flatnonzero(valid).astype("<i4").tobytes()
                )
                reply.flat.counts = ok_v.sum(axis=1).astype("<i4").tobytes()
                reply.flat.node_index = (
                    top_idx[:P][valid][ok_v].astype("<i4").tobytes()
                )
                reply.flat.score = (
                    top_scores[:P][valid][ok_v].astype("<i8").tobytes()
                )
            else:
                # legacy per-pod lists: per-valid-pod Python loop
                for p in np.flatnonzero(valid):
                    entry = reply.pods.add()
                    m = ok[p]
                    entry.node_index.extend(top_idx[p, m].tolist())
                    entry.score.extend(top_scores[p, m].tolist())
            reply.build_ms = (time.perf_counter() - t0) * 1000.0
            return reply

    def assign(self, req: "pb2.AssignRequest", ctx=None) -> "pb2.AssignReply":
        with self._lock:
            self._check_generation(req, ctx)
            snap = self.state.snapshot()
            t0 = time.perf_counter()
            result = None
            if self.mesh is not None:
                from koordinator_tpu.parallel import greedy_assign_waves
                from koordinator_tpu.solver import (
                    _demoted,
                    _record_failure,
                    _record_success,
                )

                # the CycleConfig wave knobs thread through to the
                # round-based sharded cycle; wave=1 (the per-pod default)
                # keeps the multichip path's own proven width
                wave = self.cfg.wave if self.cfg.wave > 1 else 32
                top_m = self.cfg.top_m
                bucket = (
                    "shard",
                    int(snap.nodes.allocatable.shape[0]),
                    int(snap.pods.capacity),
                    self.mesh.size,
                    wave,
                    top_m,
                )
                if not _demoted(bucket):
                    try:
                        result, _rounds = greedy_assign_waves(
                            snap, self.mesh, self.cfg,
                            wave=wave, top_m=top_m,
                        )
                        # materialize INSIDE the guard: with async
                        # dispatch a late device fault would otherwise
                        # surface at the reply assembly, outside this
                        # fallback (the same hazard run_cycle documents)
                        import dataclasses

                        result = dataclasses.replace(
                            result,
                            assignment=np.asarray(result.assignment),
                            status=np.asarray(result.status),
                        )
                        _record_success(bucket)
                    except Exception:
                        # the run_cycle demotion philosophy, shared
                        # machinery: back off this shape bucket instead
                        # of re-paying a failed shard compile on every
                        # RPC; the single-chip cycle is bit-identical
                        # and path in the reply shows the degradation
                        _record_failure(bucket)
                        result = None
                        import logging

                        logging.getLogger(__name__).exception(
                            "sharded assign failed; serving single-chip "
                            "and backing off bucket %r",
                            bucket,
                        )
            if result is None:
                result = run_cycle(
                    snap, self.cfg, i32_ok=self.state.i32_fits()
                )
            assignment = np.asarray(result.assignment)
            status = np.asarray(result.status)
            ms = (time.perf_counter() - t0) * 1000.0
            valid = np.asarray(snap.pods.valid).astype(bool)
            reply = pb2.AssignReply(cycle_ms=ms, path=result.path or "")
            reply.assignment.extend(assignment[valid].tolist())
            reply.status.extend(status[valid].tolist())
            return reply


def _handler(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        lambda req, ctx: fn(req, ctx),
        request_deserializer=req_cls.FromString,
        response_serializer=lambda msg: msg.SerializeToString(),
    )


def make_server(
    servicer: Optional[ScorerServicer] = None,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    max_workers: int = 4,
    mesh=None,
) -> grpc.Server:
    servicer = servicer or ScorerServicer(cfg, mesh=mesh)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = {
        "Sync": _handler(servicer.sync, pb2.SyncRequest),
        "Score": _handler(servicer.score, pb2.ScoreRequest),
        "Assign": _handler(servicer.assign, pb2.AssignRequest),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    server._koord_servicer = servicer  # test/introspection seam
    return server


def serve_uds(
    path: str, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG, mesh=None
) -> grpc.Server:
    """Bind the scorer on a unix-domain socket (the reference's CRI proxy
    transport, criserver.go:93) and start it.  Pass a multi-device
    ``mesh`` to serve the round-based sharded cycle (path="shard")."""
    server = make_server(cfg=cfg, mesh=mesh)
    server.add_insecure_port(f"unix://{path}")
    server.start()
    return server
