"""BatchedScorer gRPC service (generic handlers; no grpcio-tools needed).

The service composition mirrors the reference's hook-server dispatch
(reference ``pkg/koordlet/runtimehooks/proxyserver``): one process owns
the device, callers talk UDS.  Score/Assign run the same device programs
as the in-process API (solver.run_cycle / solver.score_cycle), so bridge
clients get identical placements to embedded users.

Concurrency (ISSUE 5 — the coalescing dispatch engine; docs/PIPELINE.md
has the full picture).  The pre-PR daemon held ONE lock across every
RPC body, so the Go scheduler's 16 parallel Score workers queued
single-file, each paying its own device launch and blocking readback.
That lock is now split three ways:

* ``_sync_lock`` serializes Sync RPCs and pins the mirror baseline for
  the protobuf->numpy decode — which runs OUTSIDE the device critical
  section, so decode of Sync k+1 overlaps the (async) on-device delta
  scatter of cycle k (a depth-2 decode/scatter pipeline).
* ``_state_lock`` guards the resident mirrors, the generation counter
  and telemetry sequencing.  It is never held across a device dispatch
  or a blocking readback (koordlint's ``lock-held-dispatch`` rule
  rejects that statically).
* the **device-dispatch queue** (bridge/coalesce.py): Score requests
  that arrive while the device is busy (or within a small gather
  window) coalesce into one padded batched launch — ``top_k`` padded to
  the sticky power-of-two bucket so coalescing introduces zero jit
  cache misses on the warm path — with ONE stacked readback per launch
  and replies demultiplexed per caller.  Assign's cycle and Sync's
  donating delta scatter ride the same queue via ``run_exclusive`` so
  a donation can never invalidate a buffer a captured batch has not
  read back.

The wire contract is untouched: replies are byte-identical to the
serialized daemon's, only the internal concurrency changed.
"""

from __future__ import annotations

import threading
import time
import uuid
from concurrent import futures
from typing import List, Optional

import numpy as np

import grpc
import jax
import jax.numpy as jnp
from jax import lax

from koordinator_tpu.bridge.codegen import SERVICE, pb2
from koordinator_tpu.bridge.coalesce import (
    CoalescingDispatcher,
    PendingRequest,
    SnapshotNotResident,
)
from koordinator_tpu.bridge.state import ResidentState
from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG
from koordinator_tpu.model.snapshot import pad_bucket
from koordinator_tpu.obs import CycleTelemetry
from koordinator_tpu.solver import run_cycle, score_cycle


class ScorerServicer:
    def __init__(
        self,
        cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
        mesh=None,
        state_dir=None,
        telemetry: Optional[CycleTelemetry] = None,
        coalesce_max_batch: int = 16,
        coalesce_window_ms: float = 0.0,
    ):
        """``mesh``: a ``jax.sharding.Mesh`` turns the ASSIGN RPC into
        the round-based multi-chip cycle (parallel/shard_assign.py
        greedy_assign_waves, bit-identical with the single-chip path);
        clients see ``path="shard"``.  Scope: Assign only — Sync and
        Score still materialize the snapshot on the default device, so
        the resident tensors must fit one device's memory; the mesh buys
        cycle wall-clock, not snapshot capacity.  A shard-path failure
        falls back to the single-chip cycle for that RPC (placements are
        bit-identical either way).

        ``state_dir``: where flight-recorder dumps land (obs/flight.py;
        the daemon passes its --state-dir).  ``telemetry`` injects a
        pre-built CycleTelemetry (tests); by default one is created with
        this servicer's epoch so cycle ids ("c<epoch>-<seq>") correlate
        with snapshot ids ("s<epoch>-<gen>").

        ``coalesce_max_batch``: Score requests sharing one device launch
        at most (1 = the pre-coalescing serialized behavior, the bench
        baseline).  ``coalesce_window_ms``: how long an idle-device
        leader waits for stragglers before launching (0 keeps lone-
        request latency untouched; batches still form whenever requests
        arrive while the device is busy)."""
        self.cfg = cfg
        self.mesh = mesh
        self.state = ResidentState()
        self._generation = 0
        # per-boot epoch in every snapshot id ("s<epoch>-<gen>"): a client
        # checking bare generation continuity (gen == mirror.gen+1) can
        # coincidentally pass after a sidecar restart reset the counter,
        # and would then delta-sync onto a foreign baseline; the epoch
        # makes the restart unmistakable (ADVICE r5)
        self._epoch = uuid.uuid4().hex[:8]
        self.telemetry = telemetry or CycleTelemetry(
            epoch=self._epoch, cfg=cfg, state_dir=state_dir
        )
        # the lock split (module docstring): _sync_lock serializes Sync
        # decodes against the mirror baseline; _state_lock guards mirror
        # commits, the generation counter and telemetry sequencing — and
        # is NEVER held across a device dispatch or blocking readback;
        # the dispatcher's device lock serializes launches.  Lock order
        # where nesting happens: device -> state.
        self._sync_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self.dispatch = CoalescingDispatcher(
            self._score_execute_batch,
            max_batch=coalesce_max_batch,
            gather_window_s=coalesce_window_ms / 1000.0,
        )

    def snapshot_id(self) -> str:
        return f"s{self._epoch}-{self._generation}"

    def _stale_snapshot(
        self, want: str, sid: Optional[str] = None
    ) -> Optional[SnapshotNotResident]:
        """The ONE stale-snapshot test — serial ``_check_generation`` and
        the coalesced batch's per-entry validation share it, so the
        matching rule and the message can never drift apart.  The FULL id
        must match, epoch included: accepting a bare legacy "s<gen>"
        would re-open for Score/Assign the very restart-coincidence the
        epoch closes (clients echo the Sync reply's id verbatim, so
        nothing legitimate constructs one).  Returns the error to raise,
        or None."""
        sid = self.snapshot_id() if sid is None else sid
        if want and want != sid:
            return SnapshotNotResident(
                f"snapshot {want!r} is not resident (current {sid})"
            )
        return None

    def _check_generation(self, req, ctx) -> None:
        exc = self._stale_snapshot(getattr(req, "snapshot_id", ""))
        if exc is not None:
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))
            raise exc

    # -- RPC bodies (request -> reply functions) --
    def sync(self, req: "pb2.SyncRequest", ctx=None) -> "pb2.SyncReply":
        # Phase 1 under _sync_lock only: the protobuf->numpy decode +
        # validation runs while the device may still be scattering the
        # PREVIOUS sync's deltas (async dispatch) and while coalesced
        # Scores launch — the old single lock serialized all of that.
        with self._sync_lock:
            t0 = time.perf_counter()
            try:
                staged = self.state.stage_sync(req)
            except Exception as exc:
                # ValueError = a frame validation REJECTED (bad delta
                # shape/index, missing first-sync tensors): the
                # CLIENT's bug, at the client's rate — error counter
                # only.  No flight record, no dump, and crucially no
                # commit of the pending cycle: another client's sync
                # spans may be on it awaiting THEIR Assign, and a
                # looping bad client must be able to churn neither the
                # 64-slot ring nor the dump directory.  Anything else
                # is an unexpected server-side failure: full
                # abort (ring record + disk dump).
                with self._state_lock:
                    if isinstance(exc, ValueError):
                        self.telemetry.metrics.count_cycle_error("sync")
                    else:
                        self.telemetry.abort_cycle("sync", exc)
                raise
            decode_s = time.perf_counter() - t0

            # Phase 2 — atomic commit + the donating device scatter,
            # under device -> state: the donation must not invalidate
            # buffers a coalesced Score batch captured but has not read
            # back, and the mirrors/generation/telemetry move together.
            def commit() -> "pb2.SyncReply":
                with self._state_lock:
                    self.telemetry.flush_backlog()
                    spans = self.telemetry.spans
                    spans.add_measured("sync_decode", decode_s)
                    try:
                        info = self.state.commit_sync(staged, spans=spans)
                    except Exception as exc:
                        self.telemetry.abort_cycle("sync", exc)
                        raise
                    self._generation += 1
                    self.telemetry.record_sync(
                        info,
                        snapshot_id=self.snapshot_id(),
                        epoch=self._epoch,
                        generation=self._generation,
                    )
                    # counts come from the host mirrors.  A warm frame
                    # lands its deltas straight on the resident device
                    # tensors inside commit_sync (state.last_sync_path ==
                    # "warm"); only a cold frame defers the full padded
                    # build to the next Score/Assign
                    return pb2.SyncReply(
                        snapshot_id=self.snapshot_id(),
                        nodes=self.state.node_alloc.shape[0],
                        pods=self.state.pod_requests.shape[0],
                    )

            return self.dispatch.run_exclusive(commit)

    def score(self, req: "pb2.ScoreRequest", ctx=None) -> "pb2.ScoreReply":
        # the coalescer runs the batch in whichever caller leads; this
        # caller's slot carries its reply or its error back here
        try:
            entry = self.dispatch.submit(req)
        except SnapshotNotResident as exc:
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))
            raise
        return entry.reply

    # -- coalesced Score execution (leader thread, device lock held) --
    def _score_execute_batch(self, batch: List[PendingRequest]) -> None:
        # capture a consistent view under the state lock, then leave it:
        # the launch and the stacked readback must not serialize Syncs
        with self._state_lock:
            sid = self.snapshot_id()
            accepted = []
            for entry in batch:
                err = self._stale_snapshot(
                    getattr(entry.req, "snapshot_id", ""), sid
                )
                if err is not None:
                    entry.error = err
                else:
                    accepted.append(entry)
            if not accepted:
                return None
            try:
                snap = self.state.snapshot()
            except Exception as exc:
                # a failed cold rebuild is a server-side cycle failure
                # the serial path counted and flight-dumped; keep that
                # (abort_cycle under the state lock, as Sync does)
                self.telemetry.abort_cycle("score", exc)
                raise
        try:
            # execution clock starts HERE: the cycle-latency histogram
            # keeps the serialized daemon's semantics (device dispatch +
            # readback + assembly, no queue wait — queue wait has its
            # own koord_scorer_coalesce_queue_delay_ms family)
            t_exec = time.perf_counter()
            N = snap.nodes.capacity
            P = snap.pods.capacity
            ks = [
                min(int(e.req.top_k) or N, N) for e in accepted
            ]
            # ONE launch serves every caller: top_k runs at the batch
            # max, padded to the sticky power-of-two bucket so varying
            # batch composition cannot mint new compiled shapes (zero
            # jit cache misses on the warm path); each caller's k is a
            # prefix of the padded result (lax.top_k sorts descending
            # with index tie-breaks, so prefixes are exact)
            k_launch = min(pad_bucket(max(ks)), N)
            t0 = t_exec
            scores, feasible = score_cycle(snap, self.cfg)
            masked = jnp.where(
                feasible, scores, jnp.iinfo(jnp.int64).min
            )
            top_scores, top_idx = lax.top_k(masked, k_launch)
            dispatch_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            # one stacked device->host transfer for the whole batch
            # (the serialized daemon paid one blocking readback per
            # request), then numpy-only per-caller assembly
            top_scores, top_idx, feasible_np, valid_np = jax.device_get(
                (top_scores, top_idx, feasible, snap.pods.valid)
            )
            readback_s = time.perf_counter() - t0
            top_idx = top_idx.astype(np.int32)
            valid = valid_np[:P].astype(bool)
            # host-side assembly failures are per-entry: the launch
            # served everyone else, so one bad demux must not fail
            # callers whose replies are already built — and routing them
            # per-entry is what keeps the dispatcher's lifetime stats
            # (which count error-free entries) agreeing with the
            # koord_scorer_coalesce_* counters the hook below feeds
            assembled = []
            n_failed = 0
            for entry, k in zip(accepted, ks):
                try:
                    entry.reply = self._assemble_score_reply(
                        entry.req, k, top_scores, top_idx, feasible_np,
                        valid, P,
                    )
                    assembled.append(entry)
                except Exception as exc:  # koordlint: disable=broad-except(routed to the one caller as its RPC error; sibling replies stand)
                    entry.error = exc
                    n_failed += 1
            exec_ms = (time.perf_counter() - t_exec) * 1000.0
        except Exception as exc:
            with self._state_lock:
                self.telemetry.abort_cycle("score", exc)
            raise
        # returned as the post-batch hook: the dispatcher runs it after
        # the device lock drops, so telemetry never extends the device
        # critical section queued launches wait on
        return lambda: self._score_telemetry(
            assembled, sid, dispatch_s, readback_s, exec_ms, n_failed
        )

    def _assemble_score_reply(
        self, req, k, top_scores, top_idx, feasible_np, valid, P
    ) -> "pb2.ScoreReply":
        """Demux one caller's reply from the shared readback: slice the
        k-prefix of the padded top-k (bit-identical with a serial
        ``lax.top_k(masked, k)``), then the same flat/legacy assembly
        the serialized path used."""
        ts = top_scores[:, :k]
        ti = top_idx[:, :k]
        ok = np.take_along_axis(feasible_np, ti, axis=1)
        reply = pb2.ScoreReply()
        t0 = time.perf_counter()
        if req.flat:
            # flat layout (round-3 review #8): O(1) Python calls —
            # boolean indexing + tobytes, no per-pod message building
            ok_v = ok[:P][valid]
            reply.flat.pod_index = (
                np.flatnonzero(valid).astype("<i4").tobytes()
            )
            reply.flat.counts = ok_v.sum(axis=1).astype("<i4").tobytes()
            reply.flat.node_index = (
                ti[:P][valid][ok_v].astype("<i4").tobytes()
            )
            reply.flat.score = (
                ts[:P][valid][ok_v].astype("<i8").tobytes()
            )
        else:
            # legacy per-pod lists: per-valid-pod Python loop
            for p in np.flatnonzero(valid):
                entry = reply.pods.add()
                m = ok[p]
                entry.node_index.extend(ti[p, m].tolist())
                entry.score.extend(ts[p, m].tolist())
        reply.build_ms = (time.perf_counter() - t0) * 1000.0
        return reply

    def _score_telemetry(self, assembled, sid, dispatch_s, readback_s,
                         exec_ms, n_failed=0):
        """Per-batch telemetry, sequenced under the state lock.  The
        pending-cycle contract is unchanged from the serial daemon: a
        pending cycle holds Sync stages awaiting the Assign that
        correlates them, so a Score must NOT commit it — its spans ride
        along (score_* names) and only a pending-free batch commits one
        record.  The cycle-latency histogram gets ONE observation per
        request, all at the batch's shared execution time (dispatch +
        readback + assembly — the same quantity the serialized daemon
        observed per request), so serial and coalesced streams count
        identically and queue wait stays in its own
        koord_scorer_coalesce_queue_delay_ms family.  Runs as the
        dispatcher's post-batch hook — after the device lock dropped.
        ``assembled`` holds only the entries whose replies were delivered
        (per-entry assembly failures were routed as those callers' RPC
        errors and arrive here as ``n_failed``), so every family below
        counts exactly what the dispatcher's lifetime stats count."""
        with self._state_lock:
            tel = self.telemetry
            for _ in range(n_failed):
                tel.metrics.count_cycle_error("score")
            if not assembled:
                return
            tel.flush_backlog()
            spans = tel.spans
            pending = spans.has_pending()
            spans.current(snapshot_id=sid)
            spans.add_measured("score_dispatch", dispatch_s)
            spans.add_measured("score_readback", readback_s)
            if len(assembled) > 1:
                spans.note("coalesced", len(assembled))
            tel.metrics.record_coalesce(
                len(assembled), [e.queue_delay_ms for e in assembled]
            )
            n_observe = len(assembled) if pending else len(assembled) - 1
            if not pending:
                tel.commit_cycle(exec_ms, path="score", wave=self.cfg.wave)
            for _ in range(n_observe):
                tel.metrics.observe_cycle(
                    exec_ms, path="score", wave=self.cfg.wave
                )

    def assign(self, req: "pb2.AssignRequest", ctx=None) -> "pb2.AssignReply":
        # the cycle clock starts inside the device section (below), so
        # cycle_ms and the latency histogram keep the serialized
        # daemon's meaning — device cycle + readback, NOT time spent
        # queued behind other launches (the coalesce families carry
        # queueing)
        t0 = [0.0]
        with self._state_lock:
            self._check_generation(req, ctx)
            spans = self.telemetry.spans
            # adopt the client's correlation id when it sent one; the id
            # (ours or theirs) is echoed in the reply either way
            cycle = spans.current(
                snapshot_id=self.snapshot_id(),
                cycle_id=req.cycle_id or None,
            )
            cycle_id = cycle.cycle_id

        def launch():
            # capture INSIDE the device section: a pipelined Sync's
            # delta scatter DONATES the pre-delta resident buffers, so
            # a snapshot captured before this RPC held the device lock
            # could be deleted out from under the cycle (the stress
            # test in tests/test_coalesce.py reproduces exactly that).
            # The generation re-check keeps the serial semantics: if a
            # Sync committed while we queued, a pinned snapshot_id is
            # now stale and must FAILED_PRECONDITION, same as if the
            # RPCs had serialized Sync-first.
            t0[0] = time.perf_counter()
            with self._state_lock:
                self._check_generation(req, None)
                snap = self.state.snapshot()
                i32_ok = self.state.i32_fits()
            return self._assign_launch(snap, spans, i32_ok)

        try:
            # the device section (launch + the single stacked readback)
            # rides the dispatch queue: serialized against coalesced
            # Score launches and Sync's donating scatters, off the
            # state lock so neither blocks behind the transfer
            result, rounds, eff_wave, assignment, status, valid = (
                self.dispatch.run_exclusive(launch)
            )
        except SnapshotNotResident as exc:
            # displaced mid-queue by another client's Sync: a client
            # protocol condition (the Go client full-resyncs on it),
            # not a cycle failure — no flight dump
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))
            raise
        except Exception as exc:
            # count + flight-dump the bad cycle before surfacing it
            with self._state_lock:
                self.telemetry.abort_cycle("assign", exc)
            raise
        ms = (time.perf_counter() - t0[0]) * 1000.0
        with self._state_lock:
            reply = pb2.AssignReply(
                cycle_ms=ms,
                path=result.path or "",
                cycle_id=cycle_id,
            )
            reply.assignment.extend(assignment[valid].tolist())
            reply.status.extend(status[valid].tolist())
            self.telemetry.commit_cycle(
                ms,
                path=result.path or "unknown",
                wave=eff_wave,
                rounds=rounds,
            )
        return reply

    def _assign_launch(self, snap, spans, i32_ok):
        """Device section of Assign (device lock held, state lock NOT):
        run the cycle, then ONE stacked readback for assignment, status
        and the validity mask of the very snapshot the cycle ran
        against."""
        result, rounds, eff_wave = self._assign_cycle(snap, spans, i32_ok)
        with spans.span("readback"):
            assignment, status, valid = jax.device_get(
                (result.assignment, result.status, snap.pods.valid)
            )
        return result, rounds, eff_wave, assignment, status, valid.astype(bool)

    def _assign_cycle(self, snap, spans, i32_ok):
        """Run the device cycle (shard-first when a mesh is configured)
        and return ``(CycleResult, rounds or None, effective wave
        width)`` — the shard path widens cfg.wave<=1 to its own
        default, and the telemetry labels must say what actually ran.
        Caller holds the device lock and owns error accounting."""
        result = None
        rounds = None
        eff_wave = self.cfg.wave
        if self.mesh is not None:
            from koordinator_tpu.parallel import greedy_assign_waves
            from koordinator_tpu.solver import (
                _demoted,
                _record_failure,
                _record_success,
            )

            # the CycleConfig wave knobs thread through to the
            # round-based sharded cycle; wave=1 (the per-pod default)
            # keeps the multichip path's own proven width
            wave = self.cfg.wave if self.cfg.wave > 1 else 32
            top_m = self.cfg.top_m
            bucket = (
                "shard",
                int(snap.nodes.allocatable.shape[0]),
                int(snap.pods.capacity),
                self.mesh.size,
                wave,
                top_m,
            )
            if not _demoted(bucket):
                try:
                    # distinct name from the fallback's "dispatch": a
                    # failed shard attempt followed by the single-chip
                    # cycle must not leave two same-named spans a
                    # post-mortem reader would double-count
                    with spans.span("dispatch_shard"):
                        result, nwaves = greedy_assign_waves(
                            snap, self.mesh, self.cfg,
                            wave=wave, top_m=top_m, spans=spans,
                        )
                        # materialize INSIDE the guard: with async
                        # dispatch a late device fault would otherwise
                        # surface at the reply assembly, outside this
                        # fallback (the same hazard run_cycle documents)
                        import dataclasses

                        result = dataclasses.replace(
                            result,
                            assignment=np.asarray(result.assignment),
                            status=np.asarray(result.status),
                        )
                    # device-derived stat, materialized AFTER the device
                    # program completed — one scalar transfer, no retrace
                    rounds = int(np.asarray(nwaves))
                    eff_wave = wave
                    _record_success(bucket)
                except Exception as exc:
                    # the run_cycle demotion philosophy, shared
                    # machinery: back off this shape bucket instead
                    # of re-paying a failed shard compile on every
                    # RPC; the single-chip cycle is bit-identical
                    # and path in the reply shows the degradation
                    _record_failure(bucket)
                    result = None
                    # the cycle record must say the shard attempt
                    # failed, not just show a closed dispatch_shard
                    # span next to the fallback's dispatch
                    spans.note("shard_error", f"{exc!r:.200}")
                    import logging

                    logging.getLogger(__name__).exception(
                        "sharded assign failed; serving single-chip "
                        "and backing off bucket %r",
                        bucket,
                    )
        if result is None:
            eff_wave = self.cfg.wave
            with spans.span("dispatch"):
                result = run_cycle(snap, self.cfg, i32_ok=i32_ok)
            if result.rounds is not None:
                rounds = int(np.asarray(result.rounds))
        return result, rounds, eff_wave


def _handler(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        lambda req, ctx: fn(req, ctx),
        request_deserializer=req_cls.FromString,
        response_serializer=lambda msg: msg.SerializeToString(),
    )


def make_server(
    servicer: Optional[ScorerServicer] = None,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    max_workers: int = 16,
    mesh=None,
) -> grpc.Server:
    """``max_workers`` defaults to the reference scheduler's 16 parallel
    Score workers: with the coalescing dispatcher a full worker burst
    now shares one device launch instead of queueing on a lock, so the
    transport should not be the narrower funnel."""
    servicer = servicer or ScorerServicer(cfg, mesh=mesh)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = {
        "Sync": _handler(servicer.sync, pb2.SyncRequest),
        "Score": _handler(servicer.score, pb2.ScoreRequest),
        "Assign": _handler(servicer.assign, pb2.AssignRequest),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    server._koord_servicer = servicer  # test/introspection seam
    return server


def serve_uds(
    path: str, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG, mesh=None
) -> grpc.Server:
    """Bind the scorer on a unix-domain socket (the reference's CRI proxy
    transport, criserver.go:93) and start it.  Pass a multi-device
    ``mesh`` to serve the round-based sharded cycle (path="shard")."""
    server = make_server(cfg=cfg, mesh=mesh)
    server.add_insecure_port(f"unix://{path}")
    server.start()
    return server
