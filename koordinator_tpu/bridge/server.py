"""BatchedScorer gRPC service (generic handlers; no grpcio-tools needed).

The service composition mirrors the reference's hook-server dispatch
(reference ``pkg/koordlet/runtimehooks/proxyserver``): one process owns
the device, callers talk UDS.  Score/Assign run the same device programs
as the in-process API (solver.run_cycle / solver.score_cycle), so bridge
clients get identical placements to embedded users.

Concurrency (ISSUE 5 coalescing + ISSUE 6 pipelining; docs/PIPELINE.md
has the full picture).  The pre-PR-5 daemon held ONE lock across every
RPC body; PR 5 split it three ways and coalesced concurrent Scores into
shared batched launches; PR 6 made the device section a depth-2
pipeline:

* ``_sync_lock`` serializes Sync RPCs and pins the mirror baseline for
  the protobuf->numpy decode — which runs OUTSIDE the device critical
  section, so decode of Sync k+1 overlaps the (async) on-device delta
  scatter of cycle k (a depth-2 decode/scatter pipeline).
* ``_state_lock`` guards the resident mirrors, the generation counter,
  the Assign result memo and telemetry sequencing.  It is never held
  across a device dispatch or a blocking readback (koordlint's
  ``lock-held-dispatch`` rule rejects that statically).
* the **pipelined dispatch queue** (bridge/coalesce.py): the launch
  critical section covers only snapshot capture + async device
  dispatch; the blocking stacked readback and the numpy demux run OFF
  the launch lock, so batch k+1 launches while batch k's transfer is
  still in flight (double buffering — the device never idles between
  coalesced launches).  A warm Sync's donating delta scatter drains
  the pipeline first (``run_exclusive(drain=True)``) so a donation can
  never invalidate a buffer an in-flight batch has not read back;
  non-donating commits keep the pipeline flowing.

Concurrent Assigns against the SAME resident snapshot re-ran identical
device cycles under PR 5; they are now served from a result memo keyed
on (snapshot id, CycleConfig), invalidated atomically with every
generation bump — one cycle runs, its certified result fans out, and
the replies are bit-identical to serial execution (timing fields aside).

Warm Scores run an ENGINE LADDER (ISSUE 9): the (snapshot id, config,
k-bucket) prefix memo first (no device work at all), then the
incremental column/row rescore of the device-resident [P, N]
score/feasible tensors (only what the delta Syncs since the last
launch dirtied — solver/incremental.py, bit-identical by
construction), then the full ``score_cycle``.  The resident tensors
advance with every generation bump instead of being discarded; cold
Syncs, geometry changes, full-tensor re-uploads, a CycleConfig change
or a dirty ratio past ``--score-incr-max-ratio`` fall back to the
full rescore (docs/KERNEL.md "Incremental scoring" has the matrix).

The wire contract is untouched: replies are byte-identical to the
serialized daemon's, only the internal concurrency changed.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from concurrent import futures
from typing import List, Optional

import numpy as np

import grpc
import jax

from koordinator_tpu.bridge.codegen import SERVICE, pb2
from koordinator_tpu.bridge.coalesce import (
    AdaptiveGatherWindow,
    CoalescingDispatcher,
    DEFAULT_DEPTH,
    DeadlineExpired,
    PendingRequest,
    ScoreMemo,
    SnapshotNotResident,
    launch_section,
)
from koordinator_tpu.bridge.state import ResidentState
from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG
from koordinator_tpu.model.snapshot import pad_bucket
from koordinator_tpu.obs import CycleTelemetry
from koordinator_tpu.obs import devprof
from koordinator_tpu.obs import lockwitness
from koordinator_tpu.obs.lockwitness import witness_lock
from koordinator_tpu.replication.admission import (
    AdmissionGate,
    BreakerOpen,
    CircuitBreaker,
    ResourceExhausted,
)
from koordinator_tpu.solver import (
    CandidateOverflow,
    build_candidates,
    masked_top_k,
    refresh_candidates,
    run_cycle,
    score_candidates,
    score_cycle,
    score_upper_bound,
    sparse_top_k,
)
from koordinator_tpu.solver.candidates import check_candidate_overflow


def _devprof_span_attrs(span, notes) -> None:
    """Attach the launch ledger's notes (obs/devprof.py, drained on the
    thread that ran the jit boundaries) to a launch/RPC span: sampled
    device time, whether any boundary compiled (and its wall cost), and
    the launch's XLA-estimated flops — the host/device split the
    assemble waterfall renders.  No notes (devprof off, or an unsampled
    launch) = no attrs, so traces stay byte-identical to today."""
    if not notes:
        return
    dev = [n["device_us"] for n in notes if n.get("device_us") is not None]
    if dev:
        span.set_attr("device_us", round(sum(dev), 1))
    if any(n.get("compiled") for n in notes):
        span.set_attr("compiled", True)
        cms = [n["compile_ms"] for n in notes
               if n.get("compile_ms") is not None]
        if cms:
            span.set_attr("compile_ms", round(sum(cms), 2))
    fl = [n["flops"] for n in notes if n.get("flops") is not None]
    if fl:
        span.set_attr("flops", float(sum(fl)))


class _AssignMemo:
    """One (snapshot id, CycleConfig)'s certified Assign result.

    The owner (first RPC to miss) runs the device cycle and publishes
    under the servicer's ``_state_lock``; waiters block on ``done``
    OUTSIDE every lock.  ``result`` is a host-side tuple — the memo
    never pins device buffers, so it cannot interact with donation.
    A generation bump clears the memo dict atomically (same
    ``_state_lock`` hold that bumps), but an entry already handed to a
    waiter stays valid: that waiter passed its generation check before
    the bump, which is exactly the serial schedule where its Assign ran
    first."""

    __slots__ = ("done", "result", "error", "span_ref")

    def __init__(self):
        self.done = threading.Event()
        # (assignment, status, valid, path, rounds, eff_wave, cycle_ms)
        self.result = None
        self.error: Optional[BaseException] = None
        # the owner's (trace_id, span_id) when its RPC was traced
        # (ISSUE 14): memo-served Assigns fan-in link to the span that
        # certified the shared result
        self.span_ref = None


class ScorerServicer:
    def __init__(
        self,
        cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
        mesh=None,
        state_dir=None,
        telemetry: Optional[CycleTelemetry] = None,
        coalesce_max_batch: int = 16,
        coalesce_window_ms: Optional[float] = None,
        pipeline_depth: int = DEFAULT_DEPTH,
        mesh_resident: bool = False,
        coalesce_cap_ms: Optional[float] = None,
        score_memo: bool = True,
        max_inflight: int = 0,
        score_incr: bool = True,
        score_incr_max_ratio: Optional[float] = None,
        breaker_threshold: Optional[int] = None,
        breaker_cooldown_ms: Optional[float] = None,
        brownout_max_lag: Optional[int] = None,
        trace_export: Optional[str] = None,
        shed_fractions=None,
        devprof_sample: Optional[int] = None,
    ):
        """``mesh``: a ``jax.sharding.Mesh`` turns the ASSIGN RPC into
        the round-based multi-chip cycle (parallel/shard_assign.py
        greedy_assign_waves, bit-identical with the single-chip path);
        clients see ``path="shard"``.  By default the mesh buys cycle
        wall-clock only — Sync and Score still materialize the snapshot
        on the default device, so the resident tensors must fit one
        chip's memory.  A shard-path failure falls back to the
        single-chip cycle for that RPC (placements are bit-identical
        either way).

        ``mesh_resident`` (ISSUE 7): the SNAPSHOT ITSELF lives sharded
        over ``mesh`` — node tensors split along the mesh's node axis
        (the combined HBM is the cluster's capacity), pod rows and the
        gang/quota tables replicate, warm delta Syncs scatter into the
        owning shard only, and Score/Assign launch against the sharded
        tensors through the same pipelined dispatch seam (only each
        caller's top-k prefix is ever gathered to host).  Pass the 1-D
        ``parallel.cluster_mesh`` here; placements stay bit-identical
        to the single-chip oracle (the cross-shard top-M merge reuses
        the packed-key tie-break).

        ``score_memo``: memoize each (snapshot id, CycleConfig,
        k-bucket) Score readback so a Score storm against an unchanged
        snapshot serves sliced prefixes from ONE launch
        (bridge/coalesce.py ScoreMemo; invalidated atomically on every
        generation bump, the Assign-memo contract).  ``False`` disables
        it — the bench storms do, to keep measuring the dispatch
        engine itself.

        ``score_incr`` (ISSUE 9, the tentpole): keep the [P, N]
        score/feasible tensors device-resident across generations and
        serve a warm Score by rescoring ONLY the columns/rows the
        delta Syncs since the last launch dirtied
        (solver/incremental.py; O(P x d) + O(d_p x N) instead of
        O(P x N), bit-identical by the gather/scatter exactness
        contract).  Engine order per Score batch: prefix memo ->
        incremental -> full rescore.  ``False`` disables it — the
        bench storms do, mirroring ``score_memo=False``, so they keep
        measuring the dispatch engines rather than the short-circuit.

        ``score_incr_max_ratio``: dirty-cost fraction
        (``d_nodes/N + d_pods/P`` — the incremental arithmetic
        relative to a full rescore) above which a Score falls back to
        the full ``score_cycle`` (rescoring most of the tensor
        incrementally costs MORE: two scatters plus worse fusion).
        Default from ``KOORD_SCORE_INCR_MAX_RATIO``, else 0.5 — tuned
        with the trace harness against realistic delta-size mixes
        (ISSUE 13 / ROADMAP 5(b), docs/KERNEL.md "Tuning the ratio
        gate": the incr/full crossover measures at ~0.6 dirty
        fraction, and the old 0.25 default was refusing the 1.7-3x
        wins in the 0.25-0.5 band that usage-drift events actually
        produce); daemon flag ``--score-incr-max-ratio``.

        ``coalesce_cap_ms``: clamp of the adaptive gather window's
        straggler wait (AdaptiveGatherWindow cap_ms; default 5.0) —
        a daemon flag since ISSUE 7 so real-TPU tuning rounds need no
        code edits.  Ignored when ``coalesce_window_ms`` pins a static
        window.

        ``state_dir``: where flight-recorder dumps land (obs/flight.py;
        the daemon passes its --state-dir).  ``telemetry`` injects a
        pre-built CycleTelemetry (tests); by default one is created with
        this servicer's epoch so cycle ids ("c<epoch>-<seq>") correlate
        with snapshot ids ("s<epoch>-<gen>").

        ``max_inflight`` (ISSUE 8 admission control): read RPCs
        (Score/Assign) admitted-but-unfinished at once before new ones
        are shed with RESOURCE_EXHAUSTED + a retry-after hint
        (replication/admission.py; daemon flag ``--max-inflight`` /
        ``KOORD_MAX_INFLIGHT``).  0 (the default) disables the gate.
        Sync is never shed — the one-writer path must not degrade
        under a read storm.

        ``breaker_threshold`` / ``breaker_cooldown_ms`` (ISSUE 13):
        consecutive launch failures that trip the circuit breaker OPEN,
        and how long it stays open before admitting one half-open
        probe.  While open, Score degrades to the brownout cache
        (below) and Assign fails fast with UNAVAILABLE + retry-after
        instead of queueing behind a failing device.  Defaults from
        ``KOORD_BREAKER_THRESHOLD`` (3; 0 disables the breaker) and
        ``KOORD_BREAKER_COOLDOWN_MS`` (250); daemon flags
        ``--breaker-threshold`` / ``--breaker-cooldown-ms``.  Admission
        sheds and request-level rejections (stale snapshot, expired
        deadline) never feed the breaker.

        ``trace_export`` (ISSUE 14, distributed tracing): directory the
        span exporter appends OTLP-shaped JSON lines to ("1"/"true" =
        the default ``<state-dir>/traces``; None falls back to the
        ``KOORD_TRACE_EXPORT`` env).  Tracing itself is request-driven:
        a request carrying ``trace_id`` gets a server span (parented
        under the client's attempt span, echoed as ``server_span`` in
        the reply) whether or not an exporter persists it — an
        untraced request pays one string check.  Coalesced batches mint
        ONE launch span; every rider's RPC span fan-in links to it, as
        do memo and brownout serves to the launch that produced their
        cached bytes (docs/OBSERVABILITY.md "Distributed tracing").

        ``shed_fractions`` (ISSUE 14 satellite): per-band shed ladder
        overrides for the admission gate (``--shed-fraction-<band>`` /
        ``KOORD_SHED_FRACTION_*``; validated monotone across bands and
        in (0, 1] — replication/admission.py).

        ``brownout_max_lag`` (ISSUE 13): maximum generations behind the
        current snapshot a breaker-open Score may be served from the
        host-side brownout cache (the last launch's padded top-k
        readback).  A reply within the bound carries an explicit
        ``degraded`` flag; one past it is REFUSED (UNAVAILABLE +
        retry-after), never served — stale-but-bounded, by contract.
        Default from ``KOORD_BROWNOUT_MAX_LAG`` (2); daemon flag
        ``--brownout-max-lag``.  Assign never serves stale.

        ``coalesce_max_batch``: Score requests sharing one device launch
        at most (1 = the pre-coalescing serialized behavior, the bench
        baseline).  ``coalesce_window_ms``: ``None`` (the default)
        derives the gather window adaptively from the observed
        inter-arrival EWMA (bridge/coalesce.py AdaptiveGatherWindow —
        lone requests keep serial latency, burst trains converge onto
        wide batches); a float pins the ISSUE-5 static window (0 = never
        wait).  ``pipeline_depth``: launched-but-unread batches allowed
        in flight (2 = double buffering; 1 = the ISSUE-5 serial-readback
        engine, the pipeline bench baseline)."""
        self.cfg = cfg
        self.mesh = mesh
        self.mesh_resident = bool(mesh_resident and mesh is not None)
        self.state = ResidentState(mesh=mesh if self.mesh_resident else None)
        self._generation = 0
        # per-boot epoch in every snapshot id ("s<epoch>-<gen>"): a client
        # checking bare generation continuity (gen == mirror.gen+1) can
        # coincidentally pass after a sidecar restart reset the counter,
        # and would then delta-sync onto a foreign baseline; the epoch
        # makes the restart unmistakable (ADVICE r5)
        self._epoch = uuid.uuid4().hex[:8]
        self.telemetry = telemetry or CycleTelemetry(
            epoch=self._epoch, cfg=cfg, state_dir=state_dir,
            trace_export=trace_export,
        )
        if lockwitness.enabled():
            # witness mode: distinct observed/inversion edges feed
            # koord_scorer_lock_witness_edges_total (late attach replays)
            lockwitness.attach_metrics(self.telemetry.metrics)
        # the lock split (module docstring): _sync_lock serializes Sync
        # decodes against the mirror baseline; _state_lock guards mirror
        # commits, the generation counter, the Assign memo and telemetry
        # sequencing — and is NEVER held across a device dispatch or
        # blocking readback; the dispatcher's launch lock serializes
        # launches.  Lock order where nesting happens: launch -> state.
        self._sync_lock = witness_lock(
            "bridge.server.ScorerServicer._sync_lock")
        self._state_lock = witness_lock(
            "bridge.server.ScorerServicer._state_lock")
        # Assign result memo: (snapshot id, CycleConfig) -> _AssignMemo,
        # cleared atomically with every generation bump
        self._assign_memo = {}
        # Score top-k prefix memo (same invalidation; None = disabled)
        self._score_memo = ScoreMemo() if score_memo else None
        # incremental score engine (ISSUE 9): resident [P, N] tensors
        # advanced column-wise by warm deltas; the ratio gates when a
        # mostly-dirty tensor should just full-rescore
        self._score_incr = bool(score_incr)
        if score_incr_max_ratio is None:
            # `or`: an empty env value means unset (every KOORD_* knob's
            # convention), not a float('') crash at daemon startup
            # 0.5: tuned via the trace-harness sweep (ROADMAP 5(b) /
            # docs/KERNEL.md "Tuning the ratio gate") — the measured
            # incr-vs-full crossover sits at ~0.6 dirty fraction
            score_incr_max_ratio = float(
                os.environ.get("KOORD_SCORE_INCR_MAX_RATIO") or "0.5"
            )
        self._score_incr_max_ratio = float(score_incr_max_ratio)
        # admission gate in front of the dispatch queue (ISSUE 8):
        # Score/Assign reserve a slot before touching the coalescer,
        # overload sheds fast instead of queueing without bound
        # (band-aware ladder since ISSUE 13: free sheds first, prod
        # last, Sync never; fractions flag/env-tunable since ISSUE 14)
        self.admission = AdmissionGate(
            max_inflight, shed_fractions=shed_fractions
        )
        # circuit breaker + brownout ladder (ISSUE 13): consecutive
        # launch failures trip the breaker; while open, Score serves
        # stale-but-bounded from the host-side brownout cache and
        # Assign fails fast.  `or`: empty env value means unset (the
        # KOORD_* convention).
        if breaker_threshold is None:
            breaker_threshold = int(
                os.environ.get("KOORD_BREAKER_THRESHOLD") or "3"
            )
        if breaker_cooldown_ms is None:
            breaker_cooldown_ms = float(
                os.environ.get("KOORD_BREAKER_COOLDOWN_MS") or "250"
            )
        if brownout_max_lag is None:
            brownout_max_lag = int(
                os.environ.get("KOORD_BROWNOUT_MAX_LAG") or "2"
            )
        self.breaker = CircuitBreaker(
            threshold=breaker_threshold,
            cooldown_ms=breaker_cooldown_ms,
            on_transition=self._breaker_transition,
        )
        self._brownout_max_lag = max(0, int(brownout_max_lag))
        # ROADMAP 6(a): cache the launch's FULL [P, N] scores readback
        # alongside the padded top-k when the tensor is small enough
        # (cells <= KOORD_BROWNOUT_FULL_CELLS, default 4M = 32 MiB of
        # host i64) — a breaker-open Score wanting a WIDER top-k than
        # the cached launch computed is then ranked on host
        # (solver/topk.py masked_top_k_host, bit-identical) instead of
        # refused.  Past the gate only the padded top-k caches and the
        # wider-k refusal stands — the hot-path transfer cost must not
        # scale with P x N at headline scale.
        self._brownout_full_cells = int(
            os.environ.get("KOORD_BROWNOUT_FULL_CELLS") or str(1 << 22)
        )
        # fused scoring terms (ISSUE 15): enabled term names, counted
        # per device launch on koord_scorer_term_total{term}
        from koordinator_tpu.solver.terms import term_names

        self._term_names = term_names(cfg)
        # host-side brownout cache: the last Score launch's padded
        # top-k readback plus the (epoch, generation, cfg, geometry)
        # it certified.  Unlike the ScoreMemo it deliberately SURVIVES
        # generation bumps — that staleness, bounded by
        # --brownout-max-lag, is exactly what the breaker-open path
        # serves.  Guarded by _state_lock.
        self._brownout = None
        self.degraded_replies = 0  # lifetime stat (bench/tests)
        # replication seam (ISSUE 8): the leader's publisher sets this
        # to stream every committed Sync to the follower tier; called
        # under _sync_lock, so frames publish in generation order
        self.replication_hook = None
        # durability seam (ISSUE 11): the frame journal sets this to
        # append every committed frame's encoded bytes under
        # --state-dir.  Called BEFORE replication_hook (durability
        # first, then fan-out), same _sync_lock ordering guarantee.
        self.journal_hook = None
        self.dispatch = CoalescingDispatcher(
            self._score_launch_batch,
            max_batch=coalesce_max_batch,
            window=(
                AdaptiveGatherWindow(
                    **({} if coalesce_cap_ms is None
                       else {"cap_ms": coalesce_cap_ms})
                )
                if coalesce_window_ms is None else None
            ),
            gather_window_s=(coalesce_window_ms or 0.0) / 1000.0,
            depth=pipeline_depth,
        )
        # degradation-ladder seams into the dispatcher (ISSUE 13):
        # gather-time deadline evictions feed the stage="gather"
        # counter, launch outcomes feed the breaker (filtered below)
        self.dispatch.deadline_hook = self._count_gather_expired
        self.dispatch.launch_outcome_hook = self._launch_outcome
        self.telemetry.metrics.set_breaker_state(self.breaker.state())
        self.telemetry.metrics.set_candidate_width(self.cfg.candidate_width)
        # device-time truth (ISSUE 19): configure the process-global
        # launch ledger.  None = leave the ledger as-is (library
        # embedders/tests own it); the daemon forwards its
        # --devprof-sample.  The metrics sink is a weakref inside
        # devprof, so this servicer's lifetime is never extended.
        if devprof_sample is not None:
            devprof.configure(
                sample=devprof_sample,
                metrics=self.telemetry.metrics,
                state_dir=state_dir,
            )

    # -- degradation ladder seams (ISSUE 13) --
    def _breaker_transition(self, to: str) -> None:
        self.telemetry.metrics.set_breaker_state(to)
        self.telemetry.metrics.count_breaker_transition(to)

    def _count_gather_expired(self, n: int) -> None:
        self.telemetry.metrics.count_deadline_expired("gather", n)

    def _launch_outcome(self, outcome: str, exc) -> None:
        """The dispatcher's launch-outcome hook -> the breaker.  Only
        REAL launch faults count as failures: request-level rejections
        (a displaced snapshot, an expired deadline) say nothing about
        the device, and a batch that performed no device work releases
        a half-open probe slot without a verdict."""
        if outcome == "ok":
            self.breaker.record_success()
        elif outcome == "error":
            # CandidateOverflow is a config-vs-cluster-state refusal
            # (ISSUE 16: --candidate-width too narrow for the feasible
            # fan-out), not a device fault — tripping the breaker on it
            # would brown out a healthy device
            if isinstance(exc, (SnapshotNotResident, DeadlineExpired,
                                ResourceExhausted, CandidateOverflow)):
                self.breaker.release_probe()
            else:
                self.breaker.record_failure()
        else:  # "none": no device work happened, nothing was probed
            self.breaker.release_probe()

    def _deadline_budget_ms(self, req, ctx) -> float:
        """Effective remaining deadline budget for this RPC in ms: the
        wire field (``deadline_ms``, stamped by the client at send
        time) and the gRPC transport deadline, whichever is tighter.
        0 = no deadline; NEGATIVE = already expired on arrival (the
        stage="queue" rejection).  The raw-UDS framing has no
        transport deadline — the wire field is its only carrier."""
        budget = float(getattr(req, "deadline_ms", 0) or 0)
        if ctx is not None:
            try:
                remaining = ctx.time_remaining()
            except Exception:  # koordlint: disable=broad-except(a transport without deadline support must not fail the RPC; the wire field still applies)
                remaining = None
            if remaining is not None:
                # an exhausted transport deadline must read as expired,
                # not as "no deadline" — never let it round to 0
                remaining_ms = float(remaining) * 1000.0
                if remaining_ms <= 0.0:
                    remaining_ms = -1.0
                if budget <= 0.0:
                    budget = remaining_ms
                else:
                    budget = min(budget, remaining_ms)
        return budget

    def snapshot_id(self) -> str:
        return f"s{self._epoch}-{self._generation}"

    def rebase_epoch(self, epoch: Optional[str] = None) -> str:
        """Mint a fresh epoch while KEEPING the generation (ISSUE 11).
        Used when journal recovery truncated a torn/corrupt tail:
        the truncated frames may already have been published, so
        resuming the identical ``s<epoch>-<gen>`` chain could hand a
        follower/client generation numbers it already holds with
        different content — the one fork the epoch fence cannot see.
        A fresh epoch turns that into the ordinary fenced one-shot
        full resync.  The memos die with the old chain."""
        with self._sync_lock:
            with self._state_lock:
                return self._rebase_epoch_locked(epoch)

    def _rebase_epoch_locked(self, epoch: Optional[str] = None) -> str:
        """The bump itself (``_sync_lock`` + ``_state_lock`` held) —
        shared with FollowerServicer.promote, which composes it with
        its own promoted flag under one lock hold."""
        self._epoch = epoch or uuid.uuid4().hex[:8]
        self._assign_memo.clear()
        if self._score_memo is not None:
            self._score_memo.invalidate()
        return self.snapshot_id()

    def _stale_snapshot(
        self, want: str, sid: Optional[str] = None
    ) -> Optional[SnapshotNotResident]:
        """The ONE stale-snapshot test — serial ``_check_generation`` and
        the coalesced batch's per-entry validation share it, so the
        matching rule and the message can never drift apart.  The FULL id
        must match, epoch included: accepting a bare legacy "s<gen>"
        would re-open for Score/Assign the very restart-coincidence the
        epoch closes (clients echo the Sync reply's id verbatim, so
        nothing legitimate constructs one).  Returns the error to raise,
        or None."""
        sid = self.snapshot_id() if sid is None else sid
        if want and want != sid:
            return SnapshotNotResident(
                f"snapshot {want!r} is not resident (current {sid})"
            )
        return None

    def _check_generation(self, req, ctx) -> None:
        exc = self._stale_snapshot(getattr(req, "snapshot_id", ""))
        if exc is not None:
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))
            raise exc

    # -- distributed tracing (ISSUE 14) --
    def _start_rpc_span(self, name: str, req, **attrs):
        """The per-RPC server span, or None when the request carries no
        trace context (the untraced fast path: one truthiness check).
        Parented under the CLIENT's attempt span — the id the wire
        ``parent_span`` field names — so per-process exports assemble
        into one cross-process tree offline (obs/assemble.py)."""
        trace_id = getattr(req, "trace_id", "") or ""
        if not trace_id:
            return None
        return self.telemetry.spans.start_trace_span(
            name, trace_id,
            parent_id=getattr(req, "parent_span", "") or None,
            kind="server", attrs={k: v for k, v in attrs.items() if v},
        )

    # -- RPC bodies (request -> reply functions) --
    def sync(self, req: "pb2.SyncRequest", ctx=None,
             wire_bytes: Optional[bytes] = None) -> "pb2.SyncReply":
        """Tracing shell over :meth:`_sync_impl`: the server span
        covers decode + commit + journal/replication hooks, ends (or
        aborts, error visible) on every exit, and its id rides the
        reply so the client's attempt span can reference it."""
        tspan = self._start_rpc_span("sync", req)
        if tspan is None:
            return self._sync_impl(req, ctx, wire_bytes)
        try:
            reply = self._sync_impl(req, ctx, wire_bytes)
        except BaseException as exc:
            tspan.abort(exc)
            raise
        tspan.set_attr("snapshot_id", reply.snapshot_id)
        reply.server_span = tspan.span_id
        tspan.end()
        return reply

    def _sync_impl(self, req: "pb2.SyncRequest", ctx=None,
                   wire_bytes: Optional[bytes] = None) -> "pb2.SyncReply":
        # Phase 1 under _sync_lock only: the protobuf->numpy decode +
        # validation runs while the device may still be scattering the
        # PREVIOUS sync's deltas (async dispatch) and while coalesced
        # Scores launch — the old single lock serialized all of that.
        with self._sync_lock:
            t0 = time.perf_counter()
            try:
                staged = self.state.stage_sync(req)
            except Exception as exc:
                # ValueError = a frame validation REJECTED (bad delta
                # shape/index, missing first-sync tensors): the
                # CLIENT's bug, at the client's rate — error counter
                # only.  No flight record, no dump, and crucially no
                # commit of the pending cycle: another client's sync
                # spans may be on it awaiting THEIR Assign, and a
                # looping bad client must be able to churn neither the
                # 64-slot ring nor the dump directory.  Anything else
                # is an unexpected server-side failure: full
                # abort (ring record + disk dump).
                with self._state_lock:
                    if isinstance(exc, ValueError):
                        self.telemetry.metrics.count_cycle_error("sync")
                    else:
                        self.telemetry.abort_cycle("sync", exc)
                raise
            decode_s = time.perf_counter() - t0

            # Phase 2 — atomic commit + the donating device scatter,
            # under launch -> state: the donation must not invalidate
            # buffers an in-flight batch captured but has not read
            # back, and the mirrors/generation/telemetry move together.
            def commit() -> "pb2.SyncReply":
                with self._state_lock:
                    self.telemetry.flush_backlog()
                    spans = self.telemetry.spans
                    spans.add_measured("sync_decode", decode_s)
                    try:
                        info = self.state.commit_sync(
                            staged, spans=spans, plan=plan_cell[0]
                        )
                    except Exception as exc:
                        self.telemetry.abort_cycle("sync", exc)
                        raise
                    self._generation += 1
                    # the memos die with the generation they certified —
                    # atomically, under the same hold that bumps (an
                    # Assign/Score checking a memo also holds _state_lock)
                    self._assign_memo.clear()
                    if self._score_memo is not None:
                        self._score_memo.invalidate()
                    self.telemetry.record_sync(
                        info,
                        snapshot_id=self.snapshot_id(),
                        epoch=self._epoch,
                        generation=self._generation,
                    )
                    # counts come from the host mirrors.  A warm frame
                    # lands its deltas straight on the resident device
                    # tensors inside commit_sync (state.last_sync_path ==
                    # "warm"); only a cold frame defers the full padded
                    # build to the next Score/Assign
                    return pb2.SyncReply(
                        snapshot_id=self.snapshot_id(),
                        nodes=self.state.node_alloc.shape[0],
                        pods=self.state.pod_requests.shape[0],
                    )

            # the pipeline barrier is donation-scoped: only a warm
            # delta scatter (which donates the pre-delta buffers) must
            # wait for in-flight readbacks; cold/full commits keep the
            # pipeline flowing — in-flight batches hold their own
            # snapshot references, deletion without donation cannot
            # invalidate them.  The decision runs as run_exclusive's
            # drain CALLABLE — i.e. with the launch lock already held:
            # residency only flips inside a launch section (a Score's
            # lazy snapshot() cold rebuild), so a plan computed at the
            # call site could say "cold, no drain" and be warm-with-
            # donation by the time the lock is acquired.  commit()
            # then reuses the very plan the barrier was chosen on.
            plan_cell = [None]

            def _decide_drain() -> bool:
                plan_cell[0] = self.state.plan_commit(staged)
                return self.state.commit_donates(staged, plan=plan_cell[0])

            reply = self.dispatch.run_exclusive(commit, drain=_decide_drain)
            # replication (ISSUE 8): stream the committed frame to the
            # follower tier — still under _sync_lock, so publishes are
            # strictly generation-ordered; the publisher's per-follower
            # queues are non-blocking, so a slow follower can never
            # stall the one writer path
            # ``wire_bytes`` is the CLIENT's original frame when the
            # transport had it in hand (the raw-UDS server always
            # does): the publisher streams those bytes verbatim — no
            # re-encode on the one writer path.  A transport that only
            # has the decoded message (gRPC) passes None and the
            # publisher re-serializes, which is byte-identical (same
            # runtime both ends).
            jhook = self.journal_hook
            if jhook is not None:
                try:
                    jhook(req, reply.snapshot_id, wire_bytes)
                except Exception:  # the Sync IS committed in memory — a full disk must degrade durability, not fail the acked write; the journal logs and counts the miss
                    import logging

                    logging.getLogger(__name__).exception(
                        "journal append failed for %s",
                        reply.snapshot_id,
                    )
            hook = self.replication_hook
            if hook is not None:
                try:
                    hook(req, reply.snapshot_id, wire_bytes)
                except Exception:  # the Sync IS committed — a publisher fault must not fail the client's acked write; followers detect the gap and resync
                    import logging

                    logging.getLogger(__name__).exception(
                        "replication publish failed for %s",
                        reply.snapshot_id,
                    )
            return reply

    # -- replication seam (ISSUE 8; koordinator_tpu/replication/) --
    def export_replication_snapshot(self):
        """``(epoch, generation, payload)`` of the current resident
        state: the kind=full frame a new or resyncing subscriber
        receives.  ``payload`` is the full-state SyncRequest bytes
        (empty before the first Sync — the follower resets to the
        empty state at this generation).  Consistent under
        ``_state_lock``: mirrors and the generation move together at
        commit, so the pair read here is exactly one committed Sync's
        outcome."""
        with self._state_lock:
            epoch, gen = self._epoch, self._generation
            req = self.state.export_sync_request()
        return epoch, gen, (b"" if req is None else req.SerializeToString())

    def apply_replica_frame(self, frame, origin: str = "replica_apply") -> dict:
        """Apply one replication frame (replication/codec.py Frame) and
        adopt the LEADER's ``(epoch, generation)`` — the follower's
        snapshot ids mirror the leader's exactly, so a client holding
        the leader's Sync ack can Score against any caught-up follower.
        Continuity (gap/epoch fencing) is the caller's job
        (replication/follower.py ReplicaApplier); this method only
        applies:

        * a sequence (kind=delta) frame runs the SAME two-phase
          stage/commit seam a client Sync does — delta scatters, warm
          residency, donation barrier and all — so the warm follower
          apply path is the warm leader path, byte for byte;
        * a reset (kind=full) frame swaps in a FRESH ResidentState and
          applies the payload as a first Sync (the one-shot full
          resync).  The swap never donates buffers out of the old
          snapshot, so in-flight read batches keep their references
          and the pipeline keeps flowing (``drain=False``).

        A frame that fails validation raises WITHOUT mutating anything
        (stage-then-commit): the follower keeps serving its last good
        snapshot — never a torn one — and resyncs.

        Distributed tracing (ISSUE 14): a delta frame's payload is the
        client's ORIGINAL SyncRequest bytes, so the originating
        commit's ``trace_id``/``parent_span`` ride it verbatim — this
        apply opens a span in the SAME trace (``origin`` names it:
        "replica_apply" for a live follower frame, "journal_replay"
        for the boot replay), making replication lag and failover gaps
        per-frame measurable in the assembled tree instead of EWMA
        gauges."""
        from koordinator_tpu.replication import codec

        payload = frame.payload
        # an empty payload means two different things by kind: a FULL
        # frame with no bytes resets to the empty pre-first-Sync state
        # (req=None), while a DELTA frame with no bytes is a real
        # no-change client Sync (pb2.SyncRequest() serializes to b"")
        # that must APPLY — forcing a resync for it would replay the
        # full state export on every quiet-cluster Sync
        if frame.kind == codec.KIND_FULL:
            req = pb2.SyncRequest.FromString(payload) if payload else None
        else:
            req = pb2.SyncRequest.FromString(payload)
        aspan = None
        if req is not None and (getattr(req, "trace_id", "") or ""):
            aspan = self.telemetry.spans.start_trace_span(
                origin, req.trace_id,
                parent_id=getattr(req, "parent_span", "") or None,
                kind="consumer",
                attrs={
                    "epoch": frame.epoch,
                    "generation": int(frame.generation),
                    "frame_kind": (
                        "full" if frame.kind == codec.KIND_FULL
                        else "delta"
                    ),
                },
            )
        try:
            with self._sync_lock:
                if frame.kind == codec.KIND_FULL:
                    fresh = ResidentState(mesh=self.state.mesh)
                    staged = None if req is None else fresh.stage_sync(req)

                    def commit_full() -> dict:
                        with self._state_lock:
                            self.state = fresh
                            info = (
                                {"path": "cold", "delta_tensors": 0,
                                 "full_tensors": 0}
                                if staged is None
                                else fresh.commit_sync(staged)
                            )
                            self._adopt_replica_locked(frame, info)
                            return info

                    info = self.dispatch.run_exclusive(
                        commit_full, drain=False
                    )
                else:
                    staged = self.state.stage_sync(req)
                    plan_cell = [None]

                    def commit_seq() -> dict:
                        with self._state_lock:
                            info = self.state.commit_sync(
                                staged, plan=plan_cell[0]
                            )
                            self._adopt_replica_locked(frame, info)
                            return info

                    def _decide_drain() -> bool:
                        plan_cell[0] = self.state.plan_commit(staged)
                        return self.state.commit_donates(
                            staged, plan=plan_cell[0]
                        )

                    info = self.dispatch.run_exclusive(
                        commit_seq, drain=_decide_drain
                    )
        except BaseException as exc:
            # the span must say the apply FAILED (the follower resyncs;
            # the trace shows where the chain broke)
            if aspan is not None:
                aspan.abort(exc)
            raise
        if aspan is not None:
            aspan.set_attr("snapshot_id", self.snapshot_id())
            aspan.end()
        return info

    def _adopt_replica_locked(self, frame, info) -> None:
        """Adopt the leader's snapshot id after a replica apply
        (``_state_lock`` held): generation AND epoch move to the
        frame's, and the memos die exactly as on a client Sync — they
        certified the previous generation."""
        self._epoch = frame.epoch
        self._generation = frame.generation
        self._assign_memo.clear()
        if self._score_memo is not None:
            self._score_memo.invalidate()
        # same backlog valve as a client Sync: a follower applying an
        # endless frame stream with no Assign to correlate must commit
        # span backlog instead of growing one immortal pending cycle
        self.telemetry.flush_backlog()
        self.telemetry.record_sync(
            info,
            snapshot_id=self.snapshot_id(),
            epoch=frame.epoch,
            generation=frame.generation,
        )

    def score(self, req: "pb2.ScoreRequest", ctx=None) -> "pb2.ScoreReply":
        """Tracing shell over :meth:`_score_impl` (see :meth:`sync`);
        the span's error status makes a shed / expired-deadline /
        breaker fast-fail visible in the assembled tree, not just in
        counters."""
        tspan = self._start_rpc_span(
            "score", req,
            band=getattr(req, "band", "") or "",
            top_k=int(getattr(req, "top_k", 0) or 0),
        )
        if tspan is None:
            return self._score_impl(req, ctx, None)
        try:
            reply = self._score_impl(req, ctx, tspan)
        except BaseException as exc:
            tspan.abort(exc)
            raise
        if reply.degraded:
            tspan.set_attr("degraded", True)
        reply.server_span = tspan.span_id
        tspan.end()
        return reply

    def _score_impl(self, req: "pb2.ScoreRequest", ctx=None,
                    tspan=None) -> "pb2.ScoreReply":
        # the degradation ladder, in rung order (ISSUE 13 /
        # docs/REPLICATION.md "Degradation ladder"):
        #   1. admission sheds BEFORE the request can deepen the
        #      dispatch queue — band-aware since ISSUE 13 (free sheds
        #      first, prod last); everything admitted completes
        #   2. an already-exhausted deadline budget is refused here
        #      (stage="queue") — it must never cost a device launch
        #   3. an open breaker serves the brownout cache (bounded
        #      staleness, explicit degraded flag) or fails fast
        #   4. the coalescer evicts entries whose budget expires while
        #      queued (stage="gather") before they occupy a launch slot
        band = getattr(req, "band", "") or ""
        try:
            gate = self.admission.admit("score", band)
            gate.__enter__()
        except ResourceExhausted as exc:
            self.telemetry.metrics.count_shed("score", band)
            if ctx is not None:
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
            raise
        try:
            budget = self._deadline_budget_ms(req, ctx)
            if budget < 0.0:
                self.telemetry.metrics.count_deadline_expired("queue")
                exc = DeadlineExpired("score", "queue", budget)
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
                raise exc
            if not self.breaker.allow_launch():
                reply = self._serve_brownout(req, tspan)
                if reply is not None:
                    return reply
                self.telemetry.metrics.count_breaker_rejected("score")
                exc = BreakerOpen(
                    "score", self.breaker.retry_after_ms(),
                    "brownout cache cannot serve within "
                    f"--brownout-max-lag={self._brownout_max_lag}",
                )
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
                raise exc
            deadline_at = (
                time.perf_counter() + budget / 1000.0
                if budget > 0.0 else None
            )
            # the coalescer runs the batch in whichever caller leads;
            # this caller's slot carries its reply or its error back
            try:
                entry = self.dispatch.submit(
                    req, deadline_at=deadline_at, budget_ms=budget,
                    trace_span=tspan,
                )
            except SnapshotNotResident as exc:
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))
                raise
            except CandidateOverflow as exc:
                # sparse engine refusal (ISSUE 16): the configured
                # --candidate-width cannot hold every feasible node for
                # some pod — refusing beats silently serving a
                # truncated candidate set; the operator raises the
                # width (or turns the sparse path off)
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))
                raise
            except DeadlineExpired as exc:
                # already counted stage="gather" by the dispatcher hook
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
                raise
            return entry.reply
        finally:
            gate.__exit__(None, None, None)

    def _serve_brownout(self, req, tspan=None) -> Optional["pb2.ScoreReply"]:
        """Serve one breaker-open Score STALE from the brownout cache,
        or return None when the bound (or the cache's coverage) refuses
        it.  The reply carries ``degraded=True`` and certifies a
        generation at most ``--brownout-max-lag`` behind the id the
        client named — same epoch, same geometry, same CycleConfig, a
        top-k no wider than the cached launch.  Host numpy only: the
        whole point is answering without touching the failing device.
        A traced serve fan-in links ``tspan`` to the launch span that
        produced the cached bytes (ISSUE 14): the degraded reply's
        provenance is one link-hop away in the assembled tree."""
        with self._state_lock:
            # the id contract is unchanged: the client must name the
            # CURRENT snapshot (its Sync ack) — brownout changes which
            # GENERATION the scores certify, never which id is live
            if self._stale_snapshot(
                getattr(req, "snapshot_id", "")
            ) is not None:
                return None
            cache = self._brownout
            if cache is None or cache["epoch"] != self._epoch:
                return None
            lag = self._generation - cache["gen"]
            if lag < 0 or lag > self._brownout_max_lag:
                return None
            if cache["cfg"] != self.cfg:
                return None
            st = self.state
            if (
                st.node_alloc is None
                or st.pod_requests is None
                or st.node_alloc.shape[0] != cache["nodes"]
                or st.pod_requests.shape[0] != cache["pods"]
            ):
                return None  # geometry moved: the cached rows misalign
            k = min(int(req.top_k) or cache["N"], cache["N"])
            # snapshot ts/ti/kb UNDER the lock: the wide path below
            # decides on this consistent read, never on a re-read — a
            # concurrent widener bumping cache["kb"] mid-flight must
            # not make this thread skip the re-rank while still
            # holding the pre-widen narrow columns
            ts, ti, kb = cache["ts"], cache["ti"], cache["kb"]
            if k > kb and cache.get("scores") is None:
                # wider top-k than the cached launch computed and no
                # full [P, N] scores cached (past the cell gate): the
                # refusal stands — the cache cannot invent columns
                return None
        if k > kb:
            # ROADMAP 6(a): rank the cached full scores on host —
            # bit-identical to the launch that would have run.  The
            # inputs are immutable on the entry, so a concurrent
            # widener computes the identical result; memoization is
            # idempotent and only the SAME entry widens (a newer
            # launch's cache never inherits a stale ranking).
            from koordinator_tpu.solver.topk import masked_top_k_host

            ts, ti = masked_top_k_host(
                cache["scores"], cache["feasible"], k
            )
            with self._state_lock:
                if self._brownout is cache and k > cache["kb"]:
                    cache["ts"], cache["ti"], cache["kb"] = ts, ti, k
        reply = self._assemble_score_reply(
            req, k, ts, ti, cache["feasible"],
            cache["valid"], cache["P"], degraded=True,
            # sparse cache entries (ISSUE 16) carry the launch's ok
            # matrix instead of a dense feasible tensor; the wide
            # re-rank path above never runs for them (scores is None)
            ok_full=cache.get("ok"),
        )
        if tspan is not None:
            tspan.link_ref(cache.get("launch_span"))
            tspan.set_attr("brownout_lag", lag)
        with self._state_lock:
            self.degraded_replies += 1
            self.telemetry.metrics.count_degraded("score")
        return reply

    # -- coalesced Score execution: launch phase (leader thread, launch
    #    lock held) returning the readback closure the dispatcher runs
    #    OFF the lock — the pipeline seam --
    @launch_section
    def _score_launch_batch(self, batch: List[PendingRequest]):
        # capture a consistent view under the state lock, then leave it:
        # the launch must not serialize Syncs
        with self._state_lock:
            sid = self.snapshot_id()
            accepted = []
            for entry in batch:
                err = self._stale_snapshot(
                    getattr(entry.req, "snapshot_id", ""), sid
                )
                if err is not None:
                    entry.error = err
                else:
                    accepted.append(entry)
            if not accepted:
                return None
            # Score memo (ISSUE 7 satellite): an unchanged (snapshot
            # id, CycleConfig) whose memoized k-bucket covers every
            # caller serves sliced prefixes of the memoized readback —
            # no launch, and no lazy cold snapshot rebuild either
            memo = memo_ks = None
            if self._score_memo is not None:
                memo = self._score_memo.get(sid, self.cfg)
            if memo is not None:
                memo_ks = [
                    min(int(e.req.top_k) or memo["N"], memo["N"])
                    for e in accepted
                ]
                if max(memo_ks) > memo["kb"]:
                    memo = None  # needs a wider launch; it will replace
            incr = None
            # geometry AT LAUNCH for the brownout cache: serve-time
            # checks compare against it so a resize between this launch
            # and a breaker-open serve can never misalign cached rows
            mirror_rows = (
                self.state.node_alloc.shape[0]
                if self.state.node_alloc is not None else 0,
                self.state.pod_requests.shape[0]
                if self.state.pod_requests is not None else 0,
            )
            sparse = self.cfg.candidate_width > 0
            cres = None
            if memo is None:
                try:
                    snap = self.state.snapshot()
                except Exception as exc:
                    # a failed cold rebuild is a server-side cycle
                    # failure the serial path counted and flight-dumped;
                    # keep that (abort_cycle under the state lock, as
                    # Sync does)
                    self.telemetry.abort_cycle("score", exc)
                    raise
                if sparse:
                    # sparse candidate engine (ISSUE 16): the resident
                    # [P, C] candidate lists, if any, with the dirt the
                    # warm commits since their build accumulated.  Same
                    # wholesale CycleConfig invalidation as the score
                    # residency — the lists certify one feasibility
                    # program.
                    cres = self.state.candidate_residency()
                    if cres is not None and cres.cfg != self.cfg:
                        self.state.drop_candidate_residency()
                        cres = None
                elif self._score_incr:
                    # incremental engine (ISSUE 9): the resident score
                    # tensors, if any, with the dirt the warm commits
                    # since their launch accumulated.  A CycleConfig
                    # change invalidates them wholesale — the tensors
                    # certify a different scoring program.
                    incr = self.state.score_residency()
                    if incr is not None and incr.cfg != self.cfg:
                        self.state.drop_score_residency()
                        incr = None
        if memo is not None:
            # the prefix assembly is pure host work: hand it back as a
            # no-device closure so it runs OFF the launch lock (like a
            # readback) without taking an in-flight slot — a memo hit
            # must not stall the next real launch behind numpy slicing
            def _serve(accepted=accepted, ks=memo_ks, memo=memo, sid=sid):
                return self._score_serve_memo(accepted, ks, memo, sid)

            _serve.no_device = True
            return _serve
        # fan-in tracing (ISSUE 14): ONE launch span for the whole
        # coalesced batch, parented under the first traced rider's RPC
        # span; every traced rider LINKS to it instead of each minting
        # its own — the tree shows N RPCs converging on one device
        # launch.  The span ends in the readback closure (off the
        # launch lock) or aborts on either half's failure below.
        traced = [e.trace_span for e in accepted
                  if e.trace_span is not None]
        launch_span = None
        if traced:
            lead = traced[0]
            launch_span = self.telemetry.spans.start_trace_span(  # ends in the readback closure the dispatcher always runs off the launch lock; both failure paths abort it explicitly
                "score_launch", lead.trace_id, parent_id=lead.span_id,
                kind="internal",
                attrs={"batch": len(accepted), "snapshot_id": sid},
            )
            for t in traced:
                t.link_ref(launch_span.ref)
        launch_ref = None if launch_span is None else launch_span.ref
        if sparse:
            # sparse [P, C] engine (ISSUE 16): candidate build/refresh +
            # gathered scoring replaces the dense [P, N] ladder below —
            # same readback-closure contract, same memo/brownout/
            # telemetry seams
            return self._score_launch_sparse(
                accepted, snap, cres, sid, mirror_rows,
                launch_span, launch_ref,
            )
        try:
            # execution clock starts HERE: the cycle-latency histogram
            # keeps the serialized daemon's semantics (device dispatch +
            # readback + assembly, no queue wait — queue wait has its
            # own koord_scorer_coalesce_queue_delay_ms family)
            t_exec = time.perf_counter()
            devprof.drain_notes()  # discard notes a prior stage left on this thread
            N = snap.nodes.capacity
            P = snap.pods.capacity
            ks = [
                min(int(e.req.top_k) or N, N) for e in accepted
            ]
            # ONE launch serves every caller: top_k runs at the batch
            # max, padded to the sticky power-of-two bucket so varying
            # batch composition cannot mint new compiled shapes (zero
            # jit cache misses on the warm path); each caller's k is a
            # prefix of the padded result (lax.top_k sorts descending
            # with index tie-breaks, so prefixes are exact)
            k_launch = min(pad_bucket(max(ks)), N)
            # engine ordering (ISSUE 9): memo (handled above) ->
            # incremental column/row rescore of the resident tensors ->
            # full score_cycle.  All three hand bit-identical tensors
            # to the SAME masked top_k below.
            scores = feasible = None
            incr_result = None  # telemetry: incr | full | fallback
            incr_cols = 0
            if self._score_incr:
                if incr is not None:
                    ratio = (
                        len(incr.dirty_nodes) / N + len(incr.dirty_pods) / P
                    )
                    if ratio <= self._score_incr_max_ratio:
                        incr_cols = len(incr.dirty_nodes)
                        try:
                            scores, feasible = self._score_incremental(
                                snap, incr
                            )
                            incr_result = "incr"
                        except Exception:  # owner failure on the incremental launch: the full rescore below is the documented fallback; the residency was dropped so the torn tensor can never serve
                            # the kernel may have consumed the donated
                            # scores buffer mid-failure: the residency
                            # is poison — drop it and full-rescore;
                            # the reply this batch serves stays exact
                            import logging

                            logging.getLogger(__name__).exception(
                                "incremental rescore failed; falling "
                                "back to a full score_cycle"
                            )
                            self.state.drop_score_residency()
                            incr_result = "fallback"
                    else:
                        # mostly-dirty: a full rescore is cheaper than
                        # scattering most of the tensor incrementally
                        incr_result = "fallback"
                else:
                    incr_result = "full"  # nothing resident to advance
            if scores is None:
                scores, feasible = score_cycle(snap, self.cfg)
            if self._score_incr:
                # the tensors this launch certifies become (or refresh)
                # the residency; accumulated dirt clears with the store
                self.state.store_score_result(self.cfg, scores, feasible)
            # masked top-k via the packed-f64 fast path (solver/topk.py):
            # bit-identical to lax.top_k on the masked i64 tensor, ~30x
            # cheaper on CPU — the shared tail both engines pay, so the
            # incremental saving is not buried under an integer sort
            top_scores, top_idx = masked_top_k(
                scores, feasible, k=k_launch,
                hi=score_upper_bound(self.cfg),
            )
            # brownout full cache (ROADMAP 6(a)): a defensive device
            # COPY under the cell gate — the stored residency tensor is
            # DONATED by a subsequent pipelined incremental launch (the
            # very reason feasible is never donated), so the buffer
            # this readback will device_get must be its own
            cache_full = P * N <= self._brownout_full_cells
            scores_cache = None
            if cache_full:
                import jax.numpy as jnp

                scores_cache = jnp.copy(scores)
            # launch phase ends with the program ENQUEUED (async
            # dispatch); everything below blocks, so it lives in the
            # readback closure the dispatcher runs off the launch lock
            dispatch_s = time.perf_counter() - t_exec
            # this thread ran the registered jit boundaries above: the
            # ledger's launch notes attach to the span in the readback
            devprof_notes = devprof.drain_notes()
        except Exception as exc:
            if launch_span is not None:
                launch_span.abort(exc)
            with self._state_lock:
                self.telemetry.abort_cycle("score", exc)
            raise

        def _readback():
            try:
                t0 = time.perf_counter()
                # one stacked device->host transfer for the whole batch
                # (the serialized daemon paid one blocking readback per
                # request), overlapped with the NEXT batch's launch by
                # the pipelined dispatcher.  Small tensors also fetch
                # the full [P, N] scores (the launch-section copy) for
                # the brownout cache (ROADMAP 6(a)): a breaker-open
                # wider-k request is then ranked on host instead of
                # refused; past the cell gate the extra transfer is
                # skipped — the hot path must not pay O(P x N)
                # readback at headline scale.
                ts, ti, feasible_np, valid_np, scores_np = jax.device_get(
                    (top_scores, top_idx, feasible, snap.pods.valid,
                     scores_cache)
                )
                readback_s = time.perf_counter() - t0
                # device work is done: the launch span closes HERE (off
                # the launch lock), covering async dispatch + the
                # stacked transfer — per-entry assembly failures are
                # the individual RPC spans' errors, not the launch's
                if launch_span is not None:
                    launch_span.set_attr("k_bucket", k_launch)
                    _devprof_span_attrs(launch_span, devprof_notes)
                    launch_span.end()
                ti = ti.astype(np.int32)
                valid = valid_np[:P].astype(bool)
                # publish the padded readback for Score-storm reuse —
                # only while the snapshot it certified is still current
                # (the id is in the key, so even a racing publish could
                # never serve a future generation; the guard just keeps
                # the dict from carrying a dead entry until the next
                # bump's clear)
                with self._state_lock:
                    if (
                        self._score_memo is not None
                        and sid == self.snapshot_id()
                    ):
                        self._score_memo.put(sid, self.cfg, dict(
                            kb=k_launch, N=N, P=P, ts=ts, ti=ti,
                            feasible=feasible_np, valid=valid,
                            launch_span=launch_ref,
                        ))
                    # brownout cache (ISSUE 13): unlike the memo this
                    # SURVIVES generation bumps — bounded staleness is
                    # what the breaker-open path serves.  Pipelined
                    # readbacks may complete out of order, so an older
                    # launch never replaces a newer cache entry.
                    b_epoch, _, b_gen = sid[1:].rpartition("-")
                    try:
                        b_gen = int(b_gen)
                    except ValueError:
                        b_gen = -1
                    prev = self._brownout
                    # b_epoch must ALSO match the current epoch: a
                    # rebase keeps the generation, so an out-of-order
                    # pre-rebase readback could otherwise satisfy the
                    # gen comparison and clobber a fresh post-rebase
                    # entry with one the serve-time epoch check will
                    # forever refuse
                    if b_gen >= 0 and b_epoch == self._epoch and (
                        prev is None
                        or prev["epoch"] != self._epoch
                        or b_gen >= prev["gen"]
                    ):
                        self._brownout = dict(
                            epoch=b_epoch, gen=b_gen, cfg=self.cfg,
                            kb=k_launch, N=N, P=P,
                            nodes=mirror_rows[0], pods=mirror_rows[1],
                            ts=ts, ti=ti, feasible=feasible_np,
                            valid=valid, launch_span=launch_ref,
                            scores=scores_np,
                        )
                # host-side assembly failures are per-entry: the launch
                # served everyone else, so one bad demux must not fail
                # callers whose replies are already built — and routing
                # them per-entry is what keeps the dispatcher's lifetime
                # stats (which count error-free entries) agreeing with
                # the koord_scorer_coalesce_* counters the hook feeds
                assembled = []
                n_failed = 0
                for entry, k in zip(accepted, ks):
                    try:
                        entry.reply = self._assemble_score_reply(
                            entry.req, k, ts, ti, feasible_np, valid, P,
                        )
                        assembled.append(entry)
                    except Exception as exc:  # routed to the one caller as its RPC error; sibling replies stand
                        entry.error = exc
                        n_failed += 1
                exec_ms = (time.perf_counter() - t_exec) * 1000.0
            except Exception as exc:
                if launch_span is not None:
                    launch_span.abort(exc)
                with self._state_lock:
                    self.telemetry.abort_cycle("score", exc)
                raise
            # returned as the post-batch hook: the dispatcher runs it
            # after followers were notified, so telemetry never extends
            # the readback path either
            return lambda: self._score_telemetry(
                assembled, sid, dispatch_s, readback_s, exec_ms, n_failed,
                incr_result=incr_result, incr_cols=incr_cols,
            )

        return _readback

    @launch_section
    def _score_launch_sparse(self, accepted, snap, cres, sid, mirror_rows,
                             launch_span, launch_ref):
        """Sparse candidate-set Score launch (ISSUE 16): score [P, C]
        gathered cells instead of the dense [P, N] wall.  Caller is
        :meth:`_score_launch_batch` (launch lock held, riders already
        filtered, launch span already fanned in); returns the same
        readback-closure shape the dense path returns.

        Engine ladder: reuse the resident candidate lists when clean;
        lazily merge-refresh the entries the warm commits dirtied
        (reason "dirty"); force a full blocked rebuild past the
        staleness bound (reason "stale") or with nothing resident
        (reason "cold").  The gathered cells run the SAME cellwise
        term stack as the dense launch, so wherever every pod's
        feasible fan-out fits C the reply bytes are identical to
        dense; when some pod's exact feasible count exceeds C the
        readback raises :class:`CandidateOverflow` — the engine
        refuses rather than silently degrade to a truncated list."""
        try:
            t_exec = time.perf_counter()
            devprof.drain_notes()  # discard notes a prior stage left on this thread
            N = snap.nodes.capacity
            P = snap.pods.capacity
            C = int(self.cfg.candidate_width)
            # a pod holds at most min(C, N) real candidates, so every
            # caller's k (and the memoized "N") clamps there — the
            # same derivation the dense path runs with N.  Both are
            # powers of two, so the k bucket stays within C and the
            # top-k shape never crosses a jit boundary traced.
            k_cap = min(C, N)
            ks = [min(int(e.req.top_k) or k_cap, k_cap) for e in accepted]
            k_launch = min(pad_bucket(max(ks)), k_cap)
            refresh_reason = None
            merges = 0
            if cres is None:
                # cold: the pipelined build (ISSUE 20) engages past the
                # block threshold — the node mesh, when configured,
                # shards its counts pass over the block axis
                cand, count = build_candidates(
                    snap, self.cfg, node_mesh=self.mesh
                )
                refresh_reason = "cold"
            elif cres.dirty_nodes or cres.dirty_pods:
                if cres.merges >= self.cfg.candidate_max_stale:
                    # merge-chain bound hit: one full rebuild resets it
                    cand, count = build_candidates(
                        snap, self.cfg, node_mesh=self.mesh
                    )
                    refresh_reason = "stale"
                else:
                    cand, count = refresh_candidates(
                        snap, cres.idx, cres.count,
                        sorted(cres.dirty_nodes), sorted(cres.dirty_pods),
                        self.cfg,
                    )
                    refresh_reason = "dirty"
                    merges = cres.merges + 1
            else:
                cand, count = cres.idx, cres.count
            if refresh_reason is not None:
                # the lists this launch certifies become the residency;
                # accumulated dirt clears with the store
                self.state.store_candidates(self.cfg, cand, count, merges)
            scores, feasible = score_candidates(snap, cand, self.cfg)
            top_scores, top_idx, top_ok = sparse_top_k(
                scores, feasible, cand, k=k_launch,
                hi=score_upper_bound(self.cfg),
            )
            dispatch_s = time.perf_counter() - t_exec
            devprof_notes = devprof.drain_notes()
        except Exception as exc:
            if launch_span is not None:
                launch_span.abort(exc)
            with self._state_lock:
                self.telemetry.abort_cycle("score", exc)
            raise

        def _readback():
            try:
                t0 = time.perf_counter()
                # one stacked device->host transfer, like the dense
                # readback; the exact per-pod feasible counts ride
                # along for the overflow check
                ts, ti, ok_np, count_np, valid_np = jax.device_get(
                    (top_scores, top_idx, top_ok, count, snap.pods.valid)
                )
                readback_s = time.perf_counter() - t0
                try:
                    check_candidate_overflow(count_np, C)
                except CandidateOverflow:
                    # a truncating merge may have dropped real
                    # candidates: the lists must never refresh — drop
                    # them so the next sparse Score cold-rebuilds (and
                    # refuses again until the width is raised)
                    self.state.drop_candidate_residency()
                    raise
                if launch_span is not None:
                    launch_span.set_attr("k_bucket", k_launch)
                    launch_span.set_attr("candidate_width", C)
                    _devprof_span_attrs(launch_span, devprof_notes)
                    launch_span.end()
                ti = ti.astype(np.int32)
                ok_np = ok_np.astype(bool)
                valid = valid_np[:P].astype(bool)
                with self._state_lock:
                    if (
                        self._score_memo is not None
                        and sid == self.snapshot_id()
                    ):
                        # the precomputed ok matrix replaces the dense
                        # entries' [P, N] feasible tensor: the sparse
                        # feasibility is per-CELL, so take_along_axis
                        # against real node ids would misindex it
                        self._score_memo.put(sid, self.cfg, dict(
                            kb=k_launch, N=k_cap, P=P, ts=ts, ti=ti,
                            feasible=None, valid=valid, ok=ok_np,
                            launch_span=launch_ref,
                        ))
                    b_epoch, _, b_gen = sid[1:].rpartition("-")
                    try:
                        b_gen = int(b_gen)
                    except ValueError:
                        b_gen = -1
                    prev = self._brownout
                    if b_gen >= 0 and b_epoch == self._epoch and (
                        prev is None
                        or prev["epoch"] != self._epoch
                        or b_gen >= prev["gen"]
                    ):
                        # no full scores cached: a breaker-open
                        # wider-k request is refused (the cache cannot
                        # invent candidate columns this launch never
                        # scored); prefix serves within kb still work
                        self._brownout = dict(
                            epoch=b_epoch, gen=b_gen, cfg=self.cfg,
                            kb=k_launch, N=k_cap, P=P,
                            nodes=mirror_rows[0], pods=mirror_rows[1],
                            ts=ts, ti=ti, feasible=None, valid=valid,
                            ok=ok_np, launch_span=launch_ref,
                            scores=None,
                        )
                assembled = []
                n_failed = 0
                for entry, k in zip(accepted, ks):
                    try:
                        entry.reply = self._assemble_score_reply(
                            entry.req, k, ts, ti, None, valid, P,
                            ok_full=ok_np,
                        )
                        assembled.append(entry)
                    except Exception as exc:  # routed to the one caller as its RPC error; sibling replies stand
                        entry.error = exc
                        n_failed += 1
                exec_ms = (time.perf_counter() - t_exec) * 1000.0
            except Exception as exc:
                if launch_span is not None:
                    launch_span.abort(exc)
                with self._state_lock:
                    self.telemetry.abort_cycle("score", exc)
                raise
            return lambda: self._score_telemetry(
                assembled, sid, dispatch_s, readback_s, exec_ms, n_failed,
                cand_refresh=refresh_reason, cand_width=C,
            )

        return _readback

    @launch_section
    def _score_incremental(self, snap, res):
        """Advance the resident score tensors through the accumulated
        dirty columns/rows (solver/incremental.py ``rescore_dirty``) —
        the warm Score engine.  Caller holds the launch lock (commits
        that add dirt run under it too, so the sets cannot move) and
        owns the fallback on failure.

        The resident ``scores`` buffer is DONATED to the rescore: the
        residency's references are cleared FIRST so a mid-kernel
        failure can never leave a consumed buffer published — the
        caller re-stores the advanced tensors on success and drops the
        residency on failure.  With no dirt at all (a quota-only delta
        stream, or a wider-k relaunch over an unchanged snapshot) the
        tensors are already exact and pass through untouched — no
        kernel launches."""
        from koordinator_tpu.solver.incremental import rescore_dirty

        if not res.dirty_nodes and not res.dirty_pods:
            return res.scores, res.feasible
        scores, feasible = res.scores, res.feasible
        res.scores = res.feasible = None
        return rescore_dirty(
            snap, scores, feasible, res.dirty_nodes, res.dirty_pods,
            self.cfg, mesh=self.state.active_mesh(),
        )

    def _score_serve_memo(self, accepted, ks, memo, sid):
        """Serve a whole coalesced batch as sliced prefixes of the
        memoized padded top-k readback.  Host numpy only — no device
        launch, no snapshot capture — and bit-identical to a fresh
        launch (each caller's k is a prefix of the padded ``lax.top_k``
        the memo recorded, the same slice a live batch would take).
        Runs as the dispatcher's ``no_device`` closure: OFF the launch
        lock (assembly must not stall the next real launch) but with
        nothing entering the pipeline — no in-flight slot, no device
        idle charged.  Telemetry follows the Assign memo's contract:
        hits count on the score-memo family, feed the coalesce
        occupancy/queue-delay families, and observe the latency
        histogram under ``path="memo"`` — never ``path="score"``, so
        sub-millisecond prefix slices cannot skew the device-cycle
        percentiles.  A pending-free batch commits its own flight
        record (``path="memo"``, ``memo_hit`` note); with a pending
        Sync→Assign correlation open, only the counters move — a memo
        hit must not stamp the pending cycle."""
        t_exec = time.perf_counter()
        served = []
        n_failed = 0
        for entry, k in zip(accepted, ks):
            try:
                # traced memo hits fan-in link to the launch that
                # produced the cached readback (ISSUE 14): a prefix
                # slice's provenance is the ORIGINAL device launch,
                # possibly from another caller's trace
                if entry.trace_span is not None:
                    entry.trace_span.link_ref(memo.get("launch_span"))
                    entry.trace_span.set_attr("memo_hit", True)
                entry.reply = self._assemble_score_reply(
                    entry.req, k, memo["ts"], memo["ti"],
                    memo["feasible"], memo["valid"], memo["P"],
                    ok_full=memo.get("ok"),
                )
                served.append(entry)
            except Exception as exc:  # routed to the one caller as its RPC error; sibling replies stand
                entry.error = exc
                n_failed += 1
        exec_ms = (time.perf_counter() - t_exec) * 1000.0

        def _hook():
            # post-batch hook: sequenced under the state lock AFTER
            # followers were notified, exactly like _score_telemetry
            with self._state_lock:
                tel = self.telemetry
                for _ in range(n_failed):
                    tel.metrics.count_cycle_error("score")
                if not served:
                    return
                tel.metrics.count_score_memo("hit", len(served))
                tel.metrics.record_coalesce(
                    len(served), [e.queue_delay_ms for e in served]
                )
                pending = tel.spans.has_pending()
                n_observe = len(served) if pending else len(served) - 1
                if not pending:
                    tel.flush_backlog()
                    spans = tel.spans
                    # the record must say which snapshot the memoized
                    # readback certified — the correlation every other
                    # record type carries
                    spans.current(snapshot_id=sid)
                    if len(served) > 1:
                        spans.note("coalesced", len(served))
                    spans.note("memo_hit", True)
                    tel.commit_cycle(
                        exec_ms, path="memo", wave=self.cfg.wave
                    )
                for _ in range(n_observe):
                    tel.metrics.observe_cycle(
                        exec_ms, path="memo", wave=self.cfg.wave
                    )

        return _hook

    def _assemble_score_reply(
        self, req, k, top_scores, top_idx, feasible_np, valid, P,
        degraded: bool = False, ok_full=None,
    ) -> "pb2.ScoreReply":
        """Demux one caller's reply from the shared readback: slice the
        k-prefix of the padded top-k (bit-identical with a serial
        ``lax.top_k(masked, k)``), then the same flat/legacy assembly
        the serialized path used.  ``degraded`` stamps the brownout
        path's explicit staleness flag (ISSUE 13) — a fresh launch
        never sets it, so reply bytes off the breaker path are
        untouched.  ``ok_full``: the sparse engine (ISSUE 16) passes
        its precomputed [P, k_bucket] validity matrix instead of a
        dense [P, N] feasible tensor — sparse feasibility is per
        gathered CELL, so indexing it by real node id would misread
        it; the prefix slice keeps the bytes identical either way."""
        ts = top_scores[:, :k]
        ti = top_idx[:, :k]
        if ok_full is not None:
            ok = ok_full[:, :k]
        else:
            ok = np.take_along_axis(feasible_np, ti, axis=1)
        reply = pb2.ScoreReply()
        if degraded:
            reply.degraded = True
        t0 = time.perf_counter()
        if req.flat:
            # flat layout (round-3 review #8): O(1) Python calls —
            # boolean indexing + tobytes, no per-pod message building
            ok_v = ok[:P][valid]
            reply.flat.pod_index = (
                np.flatnonzero(valid).astype("<i4").tobytes()
            )
            reply.flat.counts = ok_v.sum(axis=1).astype("<i4").tobytes()
            reply.flat.node_index = (
                ti[:P][valid][ok_v].astype("<i4").tobytes()
            )
            reply.flat.score = (
                ts[:P][valid][ok_v].astype("<i8").tobytes()
            )
        else:
            # legacy per-pod lists: per-valid-pod Python loop
            for p in np.flatnonzero(valid):
                entry = reply.pods.add()
                m = ok[p]
                entry.node_index.extend(ti[p, m].tolist())
                entry.score.extend(ts[p, m].tolist())
        reply.build_ms = (time.perf_counter() - t0) * 1000.0
        return reply

    def _score_telemetry(self, assembled, sid, dispatch_s, readback_s,
                         exec_ms, n_failed=0, incr_result=None,
                         incr_cols=0, cand_refresh=None, cand_width=0):
        """Per-batch telemetry, sequenced under the state lock.  The
        pending-cycle contract is unchanged from the serial daemon: a
        pending cycle holds Sync stages awaiting the Assign that
        correlates them, so a Score must NOT commit it — its spans ride
        along (score_* names) and only a pending-free batch commits one
        record.  The cycle-latency histogram gets ONE observation per
        request, all at the batch's shared execution time (dispatch +
        readback + assembly — the same quantity the serialized daemon
        observed per request), so serial and coalesced streams count
        identically and queue wait stays in its own
        koord_scorer_coalesce_queue_delay_ms family.  Runs as the
        dispatcher's post-batch hook — after the device lock dropped.
        ``assembled`` holds only the entries whose replies were delivered
        (per-entry assembly failures were routed as those callers' RPC
        errors and arrive here as ``n_failed``), so every family below
        counts exactly what the dispatcher's lifetime stats count."""
        with self._state_lock:
            tel = self.telemetry
            for _ in range(n_failed):
                tel.metrics.count_cycle_error("score")
            if self._score_memo is not None and (assembled or n_failed):
                # every request in a LAUNCHED batch missed the memo
                tel.metrics.count_score_memo(
                    "miss", len(assembled) + n_failed
                )
            if incr_result is not None:
                # one observation per LAUNCH (the engines are per-batch
                # decisions, unlike the per-request memo counters)
                tel.metrics.count_score_incr(incr_result)
                if incr_result == "incr":
                    tel.metrics.observe_incr_cols(incr_cols)
            if cand_width:
                # sparse engine (ISSUE 16): the serving width gauge and
                # one refresh count per launch that rebuilt/re-merged
                # (a launch reusing clean lists counts nothing)
                tel.metrics.set_candidate_width(cand_width)
                if cand_refresh is not None:
                    tel.metrics.count_candidate_refresh(cand_refresh)
            if assembled or n_failed:
                # fused scoring terms (ISSUE 15): one count per DEVICE
                # launch per enabled term — the fused engine's "all
                # terms, one launch" claim made countable
                for term in self._term_names:
                    tel.metrics.count_term(term)
            if not assembled:
                return
            tel.flush_backlog()
            spans = tel.spans
            pending = spans.has_pending()
            spans.current(snapshot_id=sid)
            spans.add_measured("score_dispatch", dispatch_s)
            spans.add_measured("score_readback", readback_s)
            if len(assembled) > 1:
                spans.note("coalesced", len(assembled))
            tel.metrics.record_coalesce(
                len(assembled), [e.queue_delay_ms for e in assembled]
            )
            # pipeline health rides the same hook: the live adaptive
            # window and the cumulative device-idle wall time
            stats = self.dispatch.stats()
            tel.metrics.set_coalesce_window(stats["window_ms"])
            tel.metrics.set_device_idle(stats["device_idle_ms"])
            n_observe = len(assembled) if pending else len(assembled) - 1
            if not pending:
                tel.commit_cycle(exec_ms, path="score", wave=self.cfg.wave)
            for _ in range(n_observe):
                tel.metrics.observe_cycle(
                    exec_ms, path="score", wave=self.cfg.wave
                )

    def assign(self, req: "pb2.AssignRequest", ctx=None) -> "pb2.AssignReply":
        """Tracing shell over :meth:`_assign_impl` (see :meth:`sync`)."""
        tspan = self._start_rpc_span(
            "assign", req, band=getattr(req, "band", "") or "",
        )
        if tspan is None:
            return self._assign_impl(req, ctx, None)
        try:
            reply = self._assign_impl(req, ctx, tspan)
        except BaseException as exc:
            tspan.abort(exc)
            raise
        tspan.set_attr("cycle_id", reply.cycle_id)
        reply.server_span = tspan.span_id
        tspan.end()
        return reply

    def _assign_impl(self, req: "pb2.AssignRequest", ctx=None,
                     tspan=None) -> "pb2.AssignReply":
        # same admission gate as Score (ISSUE 8): Assign is read
        # traffic against the resident snapshot, so it sheds with the
        # same RESOURCE_EXHAUSTED-before-the-queue-drowns contract —
        # band-aware since ISSUE 13 (free sheds first, prod last)
        band = getattr(req, "band", "") or ""
        try:
            gate = self.admission.admit("assign", band)
            gate.__enter__()
        except ResourceExhausted as exc:
            self.telemetry.metrics.count_shed("assign", band)
            if ctx is not None:
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
            raise
        try:
            # deadline propagation (ISSUE 13): an already-exhausted
            # budget is refused before it can queue behind the device
            budget = self._deadline_budget_ms(req, ctx)
            if budget < 0.0:
                self.telemetry.metrics.count_deadline_expired("queue")
                exc = DeadlineExpired("assign", "queue", budget)
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
                raise exc
            # breaker (ISSUE 13): Assign must NOT serve stale — a
            # placement certifies the live snapshot — so an open
            # breaker fails fast with retry-after instead of queueing
            # this RPC behind a failing device
            if not self.breaker.allow_launch():
                self.telemetry.metrics.count_breaker_rejected("assign")
                exc = BreakerOpen(
                    "assign", self.breaker.retry_after_ms(),
                    "assign never serves stale",
                )
                if ctx is not None:
                    ctx.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
                raise exc
            deadline_at = (
                time.perf_counter() + budget / 1000.0
                if budget > 0.0 else None
            )
            # bounded retry: a waiter that inherited an OWNER's failure
            # re-runs the memo protocol (the failed entry was removed,
            # so one waiter promotes to owner); the last attempt
            # bypasses the memo entirely and computes its own cycle, so
            # a pathologically failing owner can never starve waiters
            for attempt in range(3):
                outcome = self._assign_once(
                    req, ctx, bypass_memo=attempt == 2,
                    deadline_at=deadline_at, budget_ms=budget,
                    tspan=tspan,
                )
                if outcome is not None:
                    return outcome
            raise RuntimeError(
                "unreachable: memo-bypass attempt returned None"
            )
        finally:
            gate.__exit__(None, None, None)

    def _assign_once(
        self, req: "pb2.AssignRequest", ctx, bypass_memo: bool = False,
        deadline_at: Optional[float] = None, budget_ms: float = 0.0,
        tspan=None,
    ) -> Optional["pb2.AssignReply"]:
        """One pass of the Assign memo protocol.  Returns the reply, or
        None when this thread waited on a memo owner that failed (the
        caller retries).  ``bypass_memo`` computes a cycle without
        consulting or publishing the memo."""
        t_rpc = time.perf_counter()
        with self._state_lock:
            self._check_generation(req, ctx)
            sid = self.snapshot_id()
            key = (sid, self.cfg)
            owner = False
            entry = None
            if not bypass_memo:
                entry = self._assign_memo.get(key)
                if entry is None:
                    entry = _AssignMemo()
                    self._assign_memo[key] = entry
                    owner = True
            # per-RPC span scope (the ISSUE-6 correlation fix): the
            # cycle OWNER — the RPC whose device cycle will close the
            # Sync→Score→Assign flow — adopts the pending cycle
            # atomically; memo waiters mint fresh cycles and can no
            # longer relabel the open one or land stray stamps on it
            scope = self.telemetry.begin_rpc_scope(
                snapshot_id=sid,
                cycle_id=req.cycle_id or None,
                adopt_pending=owner or bypass_memo,
                trace_id=getattr(req, "trace_id", "") or None,
            )
            if tspan is not None and (owner or bypass_memo):
                # the owner's RPC span is what memo waiters link to:
                # publish the ref on the entry the waiters hold
                if entry is not None:
                    entry.span_ref = tspan.ref
        if entry is not None and not owner:
            if tspan is not None:
                # memo-served: fan-in link to the owner's span — the
                # device cycle this RPC's result actually came from
                tspan.link_ref(entry.span_ref)
                tspan.set_attr("memo_hit", True)
            # no device work will happen on this RPC: if assign()'s
            # allow_launch() granted it the one half-open probe slot,
            # that slot must free for a caller that WILL launch —
            # a memo hit says nothing about the device, and a wedged
            # probe slot would hold the breaker half-open forever
            # (release_probe is a no-op outside half-open)
            self.breaker.release_probe()
            return self._assign_from_memo(entry, scope, t_rpc)
        try:
            reply = self._assign_compute(
                req, ctx, scope, memo=entry,
                deadline_at=deadline_at, budget_ms=budget_ms,
                tspan=tspan,
            )
        except BaseException as exc:
            if owner:
                # unpublish BEFORE waiters act on it: the entry leaves
                # the dict so the next attempt mints a fresh owner
                with self._state_lock:
                    if self._assign_memo.get(key) is entry:
                        del self._assign_memo[key]
                    entry.error = exc
                    entry.done.set()
            raise
        return reply

    def _assign_from_memo(
        self, entry: _AssignMemo, scope, t_rpc: float
    ) -> Optional["pb2.AssignReply"]:
        """Serve one Assign from a published (or in-flight) memo entry.
        Waits OUTSIDE every lock; returns None (caller retries) when the
        owner failed — its error class may have been specific to that
        RPC, and serial semantics are re-established by re-running."""
        # backstop timeout (unbounded-wait idiom): the owner always
        # sets done — success AND failure paths — so the loop exits on
        # the event; the 1 Hz re-check only bounds a bug's blast radius
        while not entry.done.wait(timeout=1.0):
            pass
        if entry.error is not None or entry.result is None:
            # the waiter's private scope must not be abandoned: commit
            # it to the flight ring (no disk dump, no error counter —
            # the failed OWNER's abort already did both for the actual
            # cycle) so the record trail shows this RPC inherited the
            # owner's failure and retried
            exc = entry.error or RuntimeError(
                "memo owner published no result"
            )
            with self._state_lock:
                scope.note("memo_owner_failed", True)
                self.telemetry.abort_scope(
                    scope, "assign-memo-wait", exc, dump=False
                )
            return None
        assignment, status, valid, path, rounds, eff_wave, cycle_ms = (
            entry.result
        )
        wait_ms = (time.perf_counter() - t_rpc) * 1000.0
        with self._state_lock:
            reply = pb2.AssignReply(
                # the cycle that certified this assignment cost
                # ``cycle_ms`` on the device — that is what the field
                # has always meant; the memo wait itself is this RPC's
                # latency, carried by the "memo" histogram label
                cycle_ms=cycle_ms,
                path=path or "",
                cycle_id=scope.cycle_id,
            )
            reply.assignment.extend(assignment[valid].tolist())
            reply.status.extend(status[valid].tolist())
            self.telemetry.metrics.count_assign_memo("hit")
            scope.note("memo_hit", True)
            self.telemetry.commit_scope(
                scope, wait_ms, path="memo", wave=eff_wave, rounds=rounds
            )
        return reply

    def _assign_compute(
        self, req: "pb2.AssignRequest", ctx, scope,
        memo: Optional[_AssignMemo] = None,
        deadline_at: Optional[float] = None, budget_ms: float = 0.0,
        tspan=None,
    ) -> "pb2.AssignReply":
        """Run one real device cycle through the pipelined dispatcher
        and (as memo owner) publish its certified result.  ``memo`` is
        the owner's OWN entry object — published directly, never by
        dict re-lookup: a Sync's generation bump clears the dict
        mid-flight, and waiters already blocked on this entry must
        still be released (their result is serially consistent with
        the generation check they passed)."""
        # the cycle clock starts inside the launch section (below), so
        # cycle_ms and the latency histogram keep the serialized
        # daemon's meaning — device cycle + readback, NOT time spent
        # queued behind other launches (the coalesce families carry
        # queueing)
        t0 = [0.0]
        devprof_notes: list = []

        @launch_section
        def launch():
            # capture INSIDE the launch section: a pipelined Sync's
            # delta scatter DONATES the pre-delta resident buffers, so
            # a snapshot captured before this RPC held the launch lock
            # could be deleted out from under the cycle (the stress
            # test in tests/test_coalesce.py reproduces exactly that).
            # The generation re-check is the pipeline seam's guard: if
            # a Sync committed while we queued, a pinned snapshot_id is
            # now stale and must FAILED_PRECONDITION, same as if the
            # RPCs had serialized Sync-first.  Once launched, the
            # in-flight slot keeps a donating Sync OUT (run_exclusive
            # drains) until the readback below completes.
            t0[0] = time.perf_counter()
            devprof.drain_notes()  # discard notes a prior stage left on this thread
            # gather-stage deadline check (ISSUE 13): the budget may
            # have drained while this RPC waited for pipeline headroom
            # and the launch lock — an expired Assign must fail HERE,
            # before the full device cycle it can no longer use
            if deadline_at is not None and t0[0] >= deadline_at:
                raise DeadlineExpired("assign", "gather", budget_ms)
            with self._state_lock:
                self._check_generation(req, None)
                snap = self.state.snapshot()
                i32_ok = self.state.i32_fits()
            result, rounds, eff_wave = self._assign_cycle(
                snap, scope, i32_ok
            )
            devprof_notes.extend(devprof.drain_notes())

            def _readback():
                # blocking stacked transfer — OFF the launch lock, so a
                # coalesced Score batch can launch while it drains.
                # ``rounds`` rides the same stacked device_get: it may
                # be a device scalar (single-chip wave path), a host int
                # (shard path, materialized inside its demotion guard)
                # or None — device_get passes the last two through.
                with scope.span("readback"):
                    assignment, status, valid, got_rounds = jax.device_get(
                        (result.assignment, result.status,
                         snap.pods.valid, rounds)
                    )
                return (
                    result,
                    None if got_rounds is None else int(got_rounds),
                    eff_wave,
                    assignment, status, valid.astype(bool),
                )

            return _readback

        try:
            # the launch rides the pipelined dispatch queue: ordered
            # against coalesced Score launches and Sync's donating
            # scatters, with the readback off the launch critical
            # section so neither blocks behind the transfer
            result, rounds, eff_wave, assignment, status, valid = (
                self.dispatch.run_pipelined(launch)
            )
        except SnapshotNotResident as exc:
            # displaced mid-queue by another client's Sync: a client
            # protocol condition (the Go client full-resyncs on it),
            # not a cycle failure — no flight dump, no error counter,
            # but the RPC's OWN record says what happened instead of
            # its stamps landing on the pending cycle (the ISSUE-6
            # correlation fix)
            with self._state_lock:
                scope.note("displaced", True)
                self.telemetry.abort_scope(scope, "assign", exc, dump=False)
            if ctx is not None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))
            raise
        except DeadlineExpired as exc:
            # the CLIENT's budget ran out while this RPC queued: a
            # protocol condition like displacement, not a cycle failure
            # — no flight dump, no error counter, the record says what
            # happened (and the device never launched)
            with self._state_lock:
                self.telemetry.metrics.count_deadline_expired("gather")
                scope.note("deadline_expired", True)
                self.telemetry.abort_scope(scope, "assign", exc, dump=False)
            if ctx is not None:
                ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
            raise
        except Exception as exc:
            # count + flight-dump the bad cycle before surfacing it
            with self._state_lock:
                self.telemetry.abort_scope(scope, "assign", exc)
            raise
        ms = (time.perf_counter() - t0[0]) * 1000.0
        if tspan is not None:
            # device-time truth on the assign RPC span: the ledger's
            # notes for the cycle this RPC's thread launched
            _devprof_span_attrs(tspan, devprof_notes)
        with self._state_lock:
            reply = pb2.AssignReply(
                cycle_ms=ms,
                path=result.path or "",
                cycle_id=scope.cycle_id,
            )
            reply.assignment.extend(assignment[valid].tolist())
            reply.status.extend(status[valid].tolist())
            # publish for concurrent waiters — on the OWNED entry: if a
            # Sync bumped the generation while the readback drained,
            # the dict slot is already gone (cleared under this very
            # lock) and stays gone, so future Assigns miss; waiters
            # blocked on this object still consume a result that is
            # serially consistent with the generation check they passed
            if memo is not None:
                memo.result = (
                    assignment, status, valid,
                    result.path or "", rounds, eff_wave, ms,
                )
                memo.done.set()
            self.telemetry.metrics.count_assign_memo("miss")
            self.telemetry.commit_scope(
                scope, ms,
                path=result.path or "unknown",
                wave=eff_wave,
                rounds=rounds,
            )
        return reply

    @launch_section
    def _assign_cycle(self, snap, spans, i32_ok):
        """Run the device cycle (shard-first when a mesh is configured)
        and return ``(CycleResult, rounds or None, effective wave
        width)`` — the shard path widens cfg.wave<=1 to its own
        default, and the telemetry labels must say what actually ran.
        Caller holds the launch lock and owns error accounting.

        ``spans`` is the RPC's CycleScope (obs/spans.py) — same span
        surface as the recorder, but private to this cycle."""
        result = None
        rounds = None
        eff_wave = self.cfg.wave
        # fused scoring terms (ISSUE 15): the multi-chip wave cycle has
        # no extras seam, so a term-enabled config serves Assign through
        # the single-chip run_cycle below (which folds the term tensors
        # into extra_mask/extra_scores) — bit-identical placements, the
        # reply's path field shows what ran.  Score keeps its full mesh
        # path either way: the terms live INSIDE score_all.
        if self.mesh is not None and not self._term_names:
            from koordinator_tpu.parallel import greedy_assign_waves
            from koordinator_tpu.solver import (
                _demoted,
                _record_failure,
                _record_success,
            )

            # the CycleConfig wave knobs thread through to the
            # round-based sharded cycle; wave=1 (the per-pod default)
            # keeps the multichip path's own proven width
            wave = self.cfg.wave if self.cfg.wave > 1 else 32
            top_m = self.cfg.top_m
            bucket = (
                "shard",
                int(snap.nodes.allocatable.shape[0]),
                int(snap.pods.capacity),
                self.mesh.size,
                wave,
                top_m,
            )
            if not _demoted(bucket):
                try:
                    # distinct name from the fallback's "dispatch": a
                    # failed shard attempt followed by the single-chip
                    # cycle must not leave two same-named spans a
                    # post-mortem reader would double-count
                    with spans.span("dispatch_shard"):
                        result, nwaves = greedy_assign_waves(
                            snap, self.mesh, self.cfg,
                            wave=wave, top_m=top_m, spans=spans,
                        )
                        # materialize INSIDE the guard: with async
                        # dispatch a late device fault would otherwise
                        # surface at the reply assembly, outside this
                        # fallback (the same hazard run_cycle documents).
                        # This is the ONE blocking transfer allowed in a
                        # launch section — the shard path trades a slot
                        # of pipeline depth for its demotion guard.
                        import dataclasses

                        result = dataclasses.replace(
                            result,
                            assignment=np.asarray(result.assignment),  # koordlint: disable=lock-held-dispatch(shard demotion guard: the fault must surface inside the fallback try, pipeline depth is traded deliberately)
                            status=np.asarray(result.status),  # koordlint: disable=lock-held-dispatch(shard demotion guard)
                        )
                    # device-derived stat, materialized AFTER the device
                    # program completed — one scalar transfer, no retrace
                    rounds = int(np.asarray(nwaves))  # koordlint: disable=lock-held-dispatch(shard demotion guard)
                    eff_wave = wave
                    _record_success(bucket)
                except Exception as exc:
                    # the run_cycle demotion philosophy, shared
                    # machinery: back off this shape bucket instead
                    # of re-paying a failed shard compile on every
                    # RPC; the single-chip cycle is bit-identical
                    # and path in the reply shows the degradation
                    _record_failure(bucket)
                    result = None
                    # the cycle record must say the shard attempt
                    # failed, not just show a closed dispatch_shard
                    # span next to the fallback's dispatch
                    spans.note("shard_error", f"{exc!r:.200}")
                    import logging

                    logging.getLogger(__name__).exception(
                        "sharded assign failed; serving single-chip "
                        "and backing off bucket %r",
                        bucket,
                    )
        if result is None:
            eff_wave = self.cfg.wave
            with spans.span("dispatch"):
                result = run_cycle(snap, self.cfg, i32_ok=i32_ok)
            # device-derived wave count: returned UN-materialized (a
            # device scalar) — blocking on it here would hold the
            # launch lock for the whole cycle; the pipelined readback
            # fetches it in the same stacked device_get as
            # assignment/status, off the lock
            rounds = result.rounds
        return result, rounds, eff_wave


def _handler(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        lambda req, ctx: fn(req, ctx),
        request_deserializer=req_cls.FromString,
        response_serializer=lambda msg: msg.SerializeToString(),
    )


def make_server(
    servicer: Optional[ScorerServicer] = None,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    max_workers: int = 16,
    mesh=None,
) -> grpc.Server:
    """``max_workers`` defaults to the reference scheduler's 16 parallel
    Score workers: with the coalescing dispatcher a full worker burst
    now shares one device launch instead of queueing on a lock, so the
    transport should not be the narrower funnel.  (Client-side, pass
    ``channels=N`` to ScorerClient so the burst actually arrives over
    parallel HTTP/2 connections — see bridge/client.py.)"""
    servicer = servicer or ScorerServicer(cfg, mesh=mesh)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        # unbounded frames: a sparse-scale cluster's first full Sync
        # (ISSUE 16 — node counts past the dense allocator's reach)
        # ships hundreds of MB of node tensors in one request, far
        # past gRPC's 4 MB default receive cap
        options=(
            ("grpc.max_receive_message_length", -1),
            ("grpc.max_send_message_length", -1),
        ),
    )
    handlers = {
        "Sync": _handler(servicer.sync, pb2.SyncRequest),
        "Score": _handler(servicer.score, pb2.ScoreRequest),
        "Assign": _handler(servicer.assign, pb2.AssignRequest),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    server._koord_servicer = servicer  # test/introspection seam
    return server


def serve_uds(
    path: str, cfg: CycleConfig = DEFAULT_CYCLE_CONFIG, mesh=None
) -> grpc.Server:
    """Bind the scorer on a unix-domain socket (the reference's CRI proxy
    transport, criserver.go:93) and start it.  Pass a multi-device
    ``mesh`` to serve the round-based sharded cycle (path="shard")."""
    server = make_server(cfg=cfg, mesh=mesh)
    server.add_insecure_port(f"unix://{path}")
    server.start()
    return server
